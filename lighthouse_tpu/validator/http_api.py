"""Validator-client keymanager HTTP API.

Parity surface: /root/reference/validator_client/src/http_api/ — the
standard keymanager endpoints:
  GET/POST/DELETE /eth/v1/keystores       (local keystore management,
                                           EIP-2335 import, slashing-
                                           protection export on delete)
  GET/POST/DELETE /eth/v1/remotekeys      (web3signer-backed keys)
  GET/POST       /eth/v1/validator/{pubkey}/feerecipient
  GET            /lighthouse/version
Auth: a bearer api-token (the reference writes api-token.txt; here the
token is generated per server and exposed as `.api_token`)."""

from __future__ import annotations

import json
import re
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..crypto import bls
from ..crypto.keystore import decrypt_keystore
from .web3signer import Web3Signer


class KeymanagerServer:
    def __init__(self, store, preparation=None, host="127.0.0.1", port=0):
        self.store = store
        self.preparation = preparation
        self.api_token = "api-token-" + secrets.token_hex(16)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            # -------------------------------------------------- plumbing

            def _authed(self) -> bool:
                auth = self.headers.get("Authorization", "")
                return auth == f"Bearer {outer.api_token}"

            def _json(self, payload, code=200):
                out = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def _body(self):
                ln = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(ln).decode()) if ln else {}

            def _route(self, method):
                if not self._authed():
                    return self._json({"message": "unauthorized"}, 401)
                path = self.path.split("?")[0]
                try:
                    if path == "/eth/v1/keystores":
                        return getattr(outer, f"{method}_keystores")(self)
                    if path == "/eth/v1/remotekeys":
                        return getattr(outer, f"{method}_remotekeys")(self)
                    m = re.match(r"^/eth/v1/validator/0x([0-9a-f]{96})/feerecipient$", path)
                    if m:
                        return getattr(outer, f"{method}_feerecipient")(
                            self, bytes.fromhex(m.group(1))
                        )
                    if path == "/lighthouse/version" and method == "get":
                        return self._json({"data": {"version": "lighthouse-tpu-vc"}})
                except AttributeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    return self._json({"message": str(e)}, 500)
                return self._json({"message": "not found"}, 404)

            def do_GET(self):
                self._route("get")

            def do_POST(self):
                self._route("post")

            def do_DELETE(self):
                self._route("delete")

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.url = f"http://{host}:{self.server.server_address[1]}"
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self._thread.start()
        self._remote_keys: set[bytes] = set()

    def close(self):
        self.server.shutdown()

    # ---------------------------------------------------------- keystores

    def get_keystores(self, rq):
        data = [
            {
                "validating_pubkey": "0x" + pk.hex(),
                "derivation_path": "",
                "readonly": pk in self._remote_keys,
            }
            for pk in self.store.voting_pubkeys()
        ]
        rq._json({"data": data})

    def post_keystores(self, rq):
        body = rq._body()
        statuses = []
        # EIP-3076 history travels WITH the keys (keymanager spec field) so
        # a moved validator can't double-sign at its new home
        sp = body.get("slashing_protection")
        if sp:
            try:
                interchange = json.loads(sp) if isinstance(sp, str) else sp
                self.store.slashing_db.import_interchange(
                    interchange, self.store.genesis_validators_root
                )
            except Exception as e:  # noqa: BLE001
                return rq._json(
                    {"message": f"bad slashing_protection: {e}"}, 400
                )
        for ks_json, password in zip(body.get("keystores", []), body.get("passwords", [])):
            try:
                ks = json.loads(ks_json) if isinstance(ks_json, str) else ks_json
                sk_bytes = decrypt_keystore(ks, password)
                sk = bls.SecretKey(int.from_bytes(sk_bytes, "big"))
                self.store.add_validator(sk)
                statuses.append({"status": "imported"})
            except Exception as e:  # noqa: BLE001
                statuses.append({"status": "error", "message": str(e)})
        rq._json({"data": statuses})

    def delete_keystores(self, rq):
        body = rq._body()
        statuses = []
        wanted = {bytes.fromhex(p[2:]) for p in body.get("pubkeys", [])}
        full = self.store.slashing_db.export_interchange(
            self.store.genesis_validators_root
        )
        full["data"] = [
            rec
            for rec in full.get("data", [])
            if bytes.fromhex(rec["pubkey"][2:]) in wanted
        ]
        for pk_hex in body.get("pubkeys", []):
            pk = bytes.fromhex(pk_hex[2:])
            if pk in self.store.validators:
                del self.store.validators[pk]
                statuses.append({"status": "deleted"})
            else:
                statuses.append({"status": "not_found"})
        rq._json({"data": statuses, "slashing_protection": json.dumps(full)})

    # ---------------------------------------------------------- remotekeys

    def get_remotekeys(self, rq):
        data = [
            {"pubkey": "0x" + pk.hex(), "url": getattr(
                self.store.validators[pk].signer, "url", ""
            ), "readonly": False}
            for pk in self.store.voting_pubkeys()
            if pk in self._remote_keys
        ]
        rq._json({"data": data})

    def post_remotekeys(self, rq):
        from .validator_store import InitializedValidator

        body = rq._body()
        statuses = []
        for item in body.get("remote_keys", []):
            pk = bytes.fromhex(item["pubkey"][2:])
            signer = Web3Signer(item["url"], pk)
            self.store.slashing_db.register_validator(pk)
            self.store.validators[pk] = InitializedValidator(pubkey=pk, signer=signer)
            self._remote_keys.add(pk)
            statuses.append({"status": "imported"})
        rq._json({"data": statuses})

    def delete_remotekeys(self, rq):
        body = rq._body()
        statuses = []
        for pk_hex in body.get("pubkeys", []):
            pk = bytes.fromhex(pk_hex[2:])
            if pk in self._remote_keys:
                self._remote_keys.discard(pk)
                self.store.validators.pop(pk, None)
                statuses.append({"status": "deleted"})
            else:
                statuses.append({"status": "not_found"})
        rq._json({"data": statuses})

    # ---------------------------------------------------------- fee recipient

    def get_feerecipient(self, rq, pk: bytes):
        if self.preparation is None:
            return rq._json({"message": "no preparation service"}, 500)
        addr = self.preparation.fee_recipients.get(
            pk, self.preparation.default_fee_recipient
        )
        rq._json(
            {"data": {"pubkey": "0x" + pk.hex(), "ethaddress": "0x" + addr.hex()}}
        )

    def post_feerecipient(self, rq, pk: bytes):
        if self.preparation is None:
            return rq._json({"message": "no preparation service"}, 500)
        body = rq._body()
        self.preparation.set_fee_recipient(
            pk, bytes.fromhex(body["ethaddress"][2:])
        )
        rq._json({}, 202)
