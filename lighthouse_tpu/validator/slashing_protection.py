"""Slashing-protection database: SQLite guards + EIP-3076 interchange.

Parity surface: /root/reference/validator_client/slashing_protection/src/
slashing_database.rs (per-pubkey min/max slot & epoch guards enforced in a
single transaction per signing) and interchange.rs (EIP-3076 import/export,
including minification semantics on import).
"""

from __future__ import annotations

import json
import sqlite3
import threading


class SlashingProtectionError(Exception):
    """Refusing to sign (slashable or below low-watermark)."""


class NotRegistered(SlashingProtectionError):
    pass


class SlashingDatabase:
    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._conn:
            self._conn.execute(
                """CREATE TABLE IF NOT EXISTS validators (
                       id INTEGER PRIMARY KEY,
                       public_key BLOB UNIQUE NOT NULL)"""
            )
            self._conn.execute(
                """CREATE TABLE IF NOT EXISTS signed_blocks (
                       validator_id INTEGER NOT NULL REFERENCES validators(id),
                       slot INTEGER NOT NULL,
                       signing_root BLOB,
                       UNIQUE (validator_id, slot))"""
            )
            self._conn.execute(
                """CREATE TABLE IF NOT EXISTS signed_attestations (
                       validator_id INTEGER NOT NULL REFERENCES validators(id),
                       source_epoch INTEGER NOT NULL,
                       target_epoch INTEGER NOT NULL,
                       signing_root BLOB,
                       UNIQUE (validator_id, target_epoch))"""
            )

    # ------------------------------------------------------------- admin

    def register_validator(self, pubkey: bytes) -> None:
        with self._lock, self._conn:
            self._conn.execute(
                "INSERT OR IGNORE INTO validators (public_key) VALUES (?)", (pubkey,)
            )

    def _validator_id(self, pubkey: bytes) -> int:
        row = self._conn.execute(
            "SELECT id FROM validators WHERE public_key = ?", (pubkey,)
        ).fetchone()
        if row is None:
            raise NotRegistered(f"validator {pubkey.hex()[:16]} not registered")
        return row[0]

    def is_registered(self, pubkey: bytes) -> bool:
        return (
            self._conn.execute(
                "SELECT 1 FROM validators WHERE public_key = ?", (pubkey,)
            ).fetchone()
            is not None
        )

    # ------------------------------------------------------------- blocks

    def check_and_insert_block_proposal(
        self, pubkey: bytes, slot: int, signing_root: bytes
    ) -> None:
        """Atomically check + record a proposal (slashing_database.rs
        check_and_insert_block_proposal)."""
        with self._lock, self._conn:
            vid = self._validator_id(pubkey)
            row = self._conn.execute(
                "SELECT signing_root FROM signed_blocks WHERE validator_id=? AND slot=?",
                (vid, slot),
            ).fetchone()
            if row is not None:
                if row[0] == signing_root:
                    return  # same block re-signed: fine
                raise SlashingProtectionError(f"double block proposal at slot {slot}")
            mx = self._conn.execute(
                "SELECT MAX(slot) FROM signed_blocks WHERE validator_id=?", (vid,)
            ).fetchone()[0]
            if mx is not None and slot <= mx:
                raise SlashingProtectionError(
                    f"slot {slot} not above low watermark {mx}"
                )
            self._conn.execute(
                "INSERT INTO signed_blocks (validator_id, slot, signing_root) VALUES (?,?,?)",
                (vid, slot, signing_root),
            )

    # ------------------------------------------------------------- attestations

    def check_and_insert_attestation(
        self, pubkey: bytes, source_epoch: int, target_epoch: int, signing_root: bytes
    ) -> None:
        if source_epoch > target_epoch:
            raise SlashingProtectionError("source > target")
        with self._lock, self._conn:
            vid = self._validator_id(pubkey)
            row = self._conn.execute(
                "SELECT signing_root FROM signed_attestations WHERE validator_id=? AND target_epoch=?",
                (vid, target_epoch),
            ).fetchone()
            if row is not None:
                if row[0] == signing_root:
                    return
                raise SlashingProtectionError(f"double vote at target {target_epoch}")
            # surround checks
            surrounding = self._conn.execute(
                """SELECT 1 FROM signed_attestations
                   WHERE validator_id=? AND source_epoch<? AND target_epoch>?""",
                (vid, source_epoch, target_epoch),
            ).fetchone()
            if surrounding:
                raise SlashingProtectionError("attestation would be surrounded")
            surrounded = self._conn.execute(
                """SELECT 1 FROM signed_attestations
                   WHERE validator_id=? AND source_epoch>? AND target_epoch<?""",
                (vid, source_epoch, target_epoch),
            ).fetchone()
            if surrounded:
                raise SlashingProtectionError("attestation would surround a prior vote")
            # low watermarks
            min_src = self._conn.execute(
                "SELECT MIN(source_epoch) FROM signed_attestations WHERE validator_id=?",
                (vid,),
            ).fetchone()[0]
            if min_src is not None and source_epoch < min_src:
                raise SlashingProtectionError("source below low watermark")
            max_tgt = self._conn.execute(
                "SELECT MAX(target_epoch) FROM signed_attestations WHERE validator_id=?",
                (vid,),
            ).fetchone()[0]
            if max_tgt is not None and target_epoch <= max_tgt:
                raise SlashingProtectionError("target not above low watermark")
            self._conn.execute(
                """INSERT INTO signed_attestations
                   (validator_id, source_epoch, target_epoch, signing_root)
                   VALUES (?,?,?,?)""",
                (vid, source_epoch, target_epoch, signing_root),
            )

    # ------------------------------------------------------------- interchange

    def export_interchange(self, genesis_validators_root: bytes) -> dict:
        """EIP-3076 export."""
        out = {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x" + genesis_validators_root.hex(),
            },
            "data": [],
        }
        with self._lock:
            for vid, pk in self._conn.execute("SELECT id, public_key FROM validators"):
                blocks = [
                    {
                        "slot": str(slot),
                        **({"signing_root": "0x" + sr.hex()} if sr else {}),
                    }
                    for slot, sr in self._conn.execute(
                        "SELECT slot, signing_root FROM signed_blocks WHERE validator_id=? ORDER BY slot",
                        (vid,),
                    )
                ]
                atts = [
                    {
                        "source_epoch": str(se),
                        "target_epoch": str(te),
                        **({"signing_root": "0x" + sr.hex()} if sr else {}),
                    }
                    for se, te, sr in self._conn.execute(
                        "SELECT source_epoch, target_epoch, signing_root FROM signed_attestations WHERE validator_id=? ORDER BY target_epoch",
                        (vid,),
                    )
                ]
                out["data"].append(
                    {
                        "pubkey": "0x" + pk.hex(),
                        "signed_blocks": blocks,
                        "signed_attestations": atts,
                    }
                )
        return out

    def import_interchange(self, interchange: dict, genesis_validators_root: bytes) -> None:
        """EIP-3076 import with minification: keep only the maximum slot /
        maximum (source, target) per validator, like the reference importer."""
        meta_root = interchange["metadata"]["genesis_validators_root"]
        if bytes.fromhex(meta_root[2:]) != genesis_validators_root:
            raise SlashingProtectionError("interchange genesis_validators_root mismatch")
        for record in interchange["data"]:
            pk = bytes.fromhex(record["pubkey"][2:])
            self.register_validator(pk)
            with self._lock, self._conn:
                vid = self._validator_id(pk)
                slots = [int(b["slot"]) for b in record.get("signed_blocks", [])]
                if slots:
                    mx = max(slots)
                    self._conn.execute(
                        "INSERT OR REPLACE INTO signed_blocks (validator_id, slot, signing_root) VALUES (?,?,NULL)",
                        (vid, mx),
                    )
                atts = record.get("signed_attestations", [])
                if atts:
                    max_source = max(int(a["source_epoch"]) for a in atts)
                    max_target = max(int(a["target_epoch"]) for a in atts)
                    self._conn.execute(
                        """INSERT OR REPLACE INTO signed_attestations
                           (validator_id, source_epoch, target_epoch, signing_root)
                           VALUES (?,?,?,NULL)""",
                        (vid, max_source, max_target),
                    )

    def close(self):
        self._conn.close()
