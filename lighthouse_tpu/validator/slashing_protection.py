"""Slashing-protection database: SQLite guards + EIP-3076 interchange +
a durable sign-intent journal.

Parity surface: /root/reference/validator_client/slashing_protection/src/
slashing_database.rs (per-pubkey min/max slot & epoch guards enforced in a
single transaction per signing) and interchange.rs (EIP-3076 import/export,
including minification semantics on import).

`SignIntentJournal` writes every sign intent as ONE CRC-framed record to a
`KeyValueStore`-shaped log BEFORE the key produces a signature, and
replays the surviving records into a fresh `SlashingDatabase` on restart
with EIP-3076 minification semantics (keep the max watermarks). Combined
with the ordering in `ValidatorStore` (guard check -> durable intent ->
sign), a crash at ANY point — including a torn journal write, proven by
the `loadgen/storefaults.py` fault matrix — can never permit a double-sign
after restart: either the intent survived (the restart refuses a
conflicting message) or it tore (no signature was ever produced).
"""

from __future__ import annotations

import json
import sqlite3
import threading


class SlashingProtectionError(Exception):
    """Refusing to sign (slashable or below low-watermark)."""


class NotRegistered(SlashingProtectionError):
    pass


class SlashingDatabase:
    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._conn:
            self._conn.execute(
                """CREATE TABLE IF NOT EXISTS validators (
                       id INTEGER PRIMARY KEY,
                       public_key BLOB UNIQUE NOT NULL)"""
            )
            self._conn.execute(
                """CREATE TABLE IF NOT EXISTS signed_blocks (
                       validator_id INTEGER NOT NULL REFERENCES validators(id),
                       slot INTEGER NOT NULL,
                       signing_root BLOB,
                       UNIQUE (validator_id, slot))"""
            )
            self._conn.execute(
                """CREATE TABLE IF NOT EXISTS signed_attestations (
                       validator_id INTEGER NOT NULL REFERENCES validators(id),
                       source_epoch INTEGER NOT NULL,
                       target_epoch INTEGER NOT NULL,
                       signing_root BLOB,
                       UNIQUE (validator_id, target_epoch))"""
            )

    # ------------------------------------------------------------- admin

    def register_validator(self, pubkey: bytes) -> None:
        with self._lock, self._conn:
            self._conn.execute(
                "INSERT OR IGNORE INTO validators (public_key) VALUES (?)", (pubkey,)
            )

    def _validator_id(self, pubkey: bytes) -> int:
        row = self._conn.execute(
            "SELECT id FROM validators WHERE public_key = ?", (pubkey,)
        ).fetchone()
        if row is None:
            raise NotRegistered(f"validator {pubkey.hex()[:16]} not registered")
        return row[0]

    def is_registered(self, pubkey: bytes) -> bool:
        return (
            self._conn.execute(
                "SELECT 1 FROM validators WHERE public_key = ?", (pubkey,)
            ).fetchone()
            is not None
        )

    # ------------------------------------------------------------- blocks

    def check_and_insert_block_proposal(
        self, pubkey: bytes, slot: int, signing_root: bytes
    ) -> None:
        """Atomically check + record a proposal (slashing_database.rs
        check_and_insert_block_proposal)."""
        with self._lock, self._conn:
            vid = self._validator_id(pubkey)
            row = self._conn.execute(
                "SELECT signing_root FROM signed_blocks WHERE validator_id=? AND slot=?",
                (vid, slot),
            ).fetchone()
            if row is not None:
                if row[0] == signing_root:
                    return  # same block re-signed: fine
                raise SlashingProtectionError(f"double block proposal at slot {slot}")
            mx = self._conn.execute(
                "SELECT MAX(slot) FROM signed_blocks WHERE validator_id=?", (vid,)
            ).fetchone()[0]
            if mx is not None and slot <= mx:
                raise SlashingProtectionError(
                    f"slot {slot} not above low watermark {mx}"
                )
            self._conn.execute(
                "INSERT INTO signed_blocks (validator_id, slot, signing_root) VALUES (?,?,?)",
                (vid, slot, signing_root),
            )

    # ------------------------------------------------------------- attestations

    def check_and_insert_attestation(
        self, pubkey: bytes, source_epoch: int, target_epoch: int, signing_root: bytes
    ) -> None:
        if source_epoch > target_epoch:
            raise SlashingProtectionError("source > target")
        with self._lock, self._conn:
            vid = self._validator_id(pubkey)
            row = self._conn.execute(
                "SELECT signing_root FROM signed_attestations WHERE validator_id=? AND target_epoch=?",
                (vid, target_epoch),
            ).fetchone()
            if row is not None:
                if row[0] == signing_root:
                    return
                raise SlashingProtectionError(f"double vote at target {target_epoch}")
            # surround checks
            surrounding = self._conn.execute(
                """SELECT 1 FROM signed_attestations
                   WHERE validator_id=? AND source_epoch<? AND target_epoch>?""",
                (vid, source_epoch, target_epoch),
            ).fetchone()
            if surrounding:
                raise SlashingProtectionError("attestation would be surrounded")
            surrounded = self._conn.execute(
                """SELECT 1 FROM signed_attestations
                   WHERE validator_id=? AND source_epoch>? AND target_epoch<?""",
                (vid, source_epoch, target_epoch),
            ).fetchone()
            if surrounded:
                raise SlashingProtectionError("attestation would surround a prior vote")
            # low watermarks
            min_src = self._conn.execute(
                "SELECT MIN(source_epoch) FROM signed_attestations WHERE validator_id=?",
                (vid,),
            ).fetchone()[0]
            if min_src is not None and source_epoch < min_src:
                raise SlashingProtectionError("source below low watermark")
            max_tgt = self._conn.execute(
                "SELECT MAX(target_epoch) FROM signed_attestations WHERE validator_id=?",
                (vid,),
            ).fetchone()[0]
            if max_tgt is not None and target_epoch <= max_tgt:
                raise SlashingProtectionError("target not above low watermark")
            self._conn.execute(
                """INSERT INTO signed_attestations
                   (validator_id, source_epoch, target_epoch, signing_root)
                   VALUES (?,?,?,?)""",
                (vid, source_epoch, target_epoch, signing_root),
            )

    # ------------------------------------------------------------- interchange

    def export_interchange(self, genesis_validators_root: bytes) -> dict:
        """EIP-3076 export."""
        out = {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x" + genesis_validators_root.hex(),
            },
            "data": [],
        }
        with self._lock:
            for vid, pk in self._conn.execute("SELECT id, public_key FROM validators"):
                blocks = [
                    {
                        "slot": str(slot),
                        **({"signing_root": "0x" + sr.hex()} if sr else {}),
                    }
                    for slot, sr in self._conn.execute(
                        "SELECT slot, signing_root FROM signed_blocks WHERE validator_id=? ORDER BY slot",
                        (vid,),
                    )
                ]
                atts = [
                    {
                        "source_epoch": str(se),
                        "target_epoch": str(te),
                        **({"signing_root": "0x" + sr.hex()} if sr else {}),
                    }
                    for se, te, sr in self._conn.execute(
                        "SELECT source_epoch, target_epoch, signing_root FROM signed_attestations WHERE validator_id=? ORDER BY target_epoch",
                        (vid,),
                    )
                ]
                out["data"].append(
                    {
                        "pubkey": "0x" + pk.hex(),
                        "signed_blocks": blocks,
                        "signed_attestations": atts,
                    }
                )
        return out

    def import_interchange(self, interchange: dict, genesis_validators_root: bytes) -> None:
        """EIP-3076 import with minification: keep only the maximum slot /
        maximum (source, target) per validator, like the reference importer."""
        meta_root = interchange["metadata"]["genesis_validators_root"]
        if bytes.fromhex(meta_root[2:]) != genesis_validators_root:
            raise SlashingProtectionError("interchange genesis_validators_root mismatch")
        for record in interchange["data"]:
            pk = bytes.fromhex(record["pubkey"][2:])
            slots = [int(b["slot"]) for b in record.get("signed_blocks", [])]
            atts = record.get("signed_attestations", [])
            self.import_watermarks(
                pk,
                max_block_slot=max(slots) if slots else None,
                max_source=(
                    max(int(a["source_epoch"]) for a in atts) if atts else None
                ),
                max_target=(
                    max(int(a["target_epoch"]) for a in atts) if atts else None
                ),
            )

    def import_watermarks(self, pubkey: bytes, max_block_slot: int | None = None,
                          max_source: int | None = None,
                          max_target: int | None = None) -> None:
        """Install minified low-watermark guards for one validator (the
        EIP-3076 import shape: only the maxima survive; signing roots are
        NULL, so even a same-root re-sign at the watermark is refused —
        conservative and safe). The journal replay path."""
        self.register_validator(pubkey)
        with self._lock, self._conn:
            vid = self._validator_id(pubkey)
            if max_block_slot is not None:
                self._conn.execute(
                    "INSERT OR REPLACE INTO signed_blocks "
                    "(validator_id, slot, signing_root) VALUES (?,?,NULL)",
                    (vid, int(max_block_slot)),
                )
            if max_target is not None:
                self._conn.execute(
                    """INSERT OR REPLACE INTO signed_attestations
                       (validator_id, source_epoch, target_epoch, signing_root)
                       VALUES (?,?,?,NULL)""",
                    (vid, int(max_source or 0), int(max_target)),
                )

    def close(self):
        self._conn.close()


# ---------------------------------------------------------------- journal


class SignIntentJournal:
    """Durable sign-intent log in front of a `SlashingDatabase`.

    Backed by any `KeyValueStore`-shaped object (`store/native_kv.py`
    PurePythonKVStore for a real datadir; `loadgen/storefaults.py`
    FaultyKVStore in the interruption tests) so every intent is ONE
    CRC-framed record write — the exact surface the torn-write fault
    matrix tears at every byte offset. Record, then sign: if the record
    write crashes, no signature exists; if it lands, the restart replay
    refuses anything conflicting."""

    def __init__(self, store):
        from ..store.kv import Column

        self.store = store
        self._col = Column.metadata

    # ------------------------------------------------------------- writes

    def record_block(self, pubkey: bytes, slot: int, signing_root: bytes) -> None:
        self.store.put(
            self._col,
            b"b:" + pubkey + int(slot).to_bytes(8, "big"),
            bytes(signing_root),
        )

    def record_attestation(self, pubkey: bytes, source: int, target: int,
                           signing_root: bytes) -> None:
        self.store.put(
            self._col,
            b"a:" + pubkey + int(target).to_bytes(8, "big"),
            int(source).to_bytes(8, "big") + bytes(signing_root),
        )

    # ------------------------------------------------------------- replay

    def replay_into(self, db: SlashingDatabase) -> dict:
        """Replay the crash-consistent journal prefix into `db` with
        minification semantics. Returns per-pubkey watermarks installed
        (diagnostics)."""
        marks: dict[bytes, dict] = {}
        for key, value in self.store.iter_column(self._col):
            kind, pk = key[:2], key[2:50]
            m = marks.setdefault(
                pk, {"block_slot": None, "source": None, "target": None}
            )
            if kind == b"b:":
                slot = int.from_bytes(key[50:58], "big")
                if m["block_slot"] is None or slot > m["block_slot"]:
                    m["block_slot"] = slot
            elif kind == b"a:":
                target = int.from_bytes(key[50:58], "big")
                source = int.from_bytes(value[:8], "big")
                if m["target"] is None or target > m["target"]:
                    m["target"] = target
                if m["source"] is None or source > m["source"]:
                    m["source"] = source
        for pk, m in marks.items():
            db.import_watermarks(
                pk, max_block_slot=m["block_slot"],
                max_source=m["source"], max_target=m["target"],
            )
        return {
            pk.hex()[:16]: m for pk, m in sorted(marks.items())
        }
