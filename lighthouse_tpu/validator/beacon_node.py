"""Beacon-node interface for the validator client + multi-node fallback.

Parity surface: the typed client boundary of /root/reference/common/eth2
(BeaconNodeHttpClient, src/lib.rs:156) and the VC's
BeaconNodeFallback health-ranked redundancy
(validator_client/src/beacon_node_fallback.rs). The VC talks to a small
duck-typed interface; `InProcessBeaconNode` implements it directly over a
BeaconChain (the simulator path — testing/simulator analog), and an HTTP
client implementing the same surface slots in for production (api/client).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..state_transition import accessors as acc
from ..state_transition.slot import types_for_slot
from ..types import helpers as h


@dataclass
class AttesterDuty:
    pubkey: bytes
    validator_index: int
    slot: int
    committee_index: int
    committee_length: int
    committee_position: int
    committees_at_slot: int


@dataclass
class ProposerDuty:
    pubkey: bytes
    validator_index: int
    slot: int


class BeaconNodeError(Exception):
    pass


class InProcessBeaconNode:
    """The VC-visible API implemented straight over a BeaconChain."""

    def __init__(self, chain):
        self.chain = chain
        self.healthy = True

    # -- node status -----------------------------------------------------

    def is_healthy(self) -> bool:
        return self.healthy

    def genesis_validators_root(self) -> bytes:
        return bytes(self.chain.head_state().genesis_validators_root)

    # -- duties ----------------------------------------------------------

    def attester_duties(self, epoch: int, indices: list[int]) -> list[AttesterDuty]:
        if not self.healthy:
            raise BeaconNodeError("node down")
        chain = self.chain
        state = chain.head_state()
        spec = chain.spec
        # advance a clone when the requested epoch is beyond the head's
        # shuffling horizon (the reference advances the state the same way
        # in its duties endpoint)
        if epoch > acc.get_current_epoch(state, spec) + 1:
            from ..testing.harness import clone_state
            from ..state_transition.slot import process_slots

            state = clone_state(state, spec)
            process_slots(state, spec, h.compute_start_slot_at_epoch(epoch, spec))
        cache = acc.build_committee_cache(state, spec, epoch)
        wanted = set(indices)
        duties = []
        for slot in range(
            h.compute_start_slot_at_epoch(epoch, spec),
            h.compute_start_slot_at_epoch(epoch + 1, spec),
        ):
            for cidx in range(cache.committees_per_slot):
                committee = cache.committee(slot, cidx)
                for pos, vi in enumerate(committee):
                    if vi in wanted:
                        duties.append(
                            AttesterDuty(
                                pubkey=bytes(state.validators[vi].pubkey),
                                validator_index=vi,
                                slot=slot,
                                committee_index=cidx,
                                committee_length=len(committee),
                                committee_position=pos,
                                committees_at_slot=cache.committees_per_slot,
                            )
                        )
        return duties

    def proposer_duties(self, epoch: int) -> list[ProposerDuty]:
        if not self.healthy:
            raise BeaconNodeError("node down")
        chain = self.chain
        spec = chain.spec
        from ..testing.harness import clone_state
        from ..state_transition.slot import process_slots

        state = clone_state(chain.head_state(), spec)
        start = h.compute_start_slot_at_epoch(epoch, spec)
        if state.slot < start:
            process_slots(state, spec, start)
        duties = []
        for slot in range(start, start + spec.preset.SLOTS_PER_EPOCH):
            proposer = acc.get_beacon_proposer_index(state, spec, slot)
            duties.append(
                ProposerDuty(
                    pubkey=bytes(state.validators[proposer].pubkey),
                    validator_index=proposer,
                    slot=slot,
                )
            )
        return duties

    # -- attestation flow ------------------------------------------------

    def attestation_data(self, slot: int, committee_index: int):
        if not self.healthy:
            raise BeaconNodeError("node down")
        chain = self.chain
        spec = chain.spec
        state = chain.head_state()
        types = types_for_slot(spec, slot)
        epoch = h.compute_epoch_at_slot(slot, spec)
        head_root = chain.head_root
        start_slot = h.compute_start_slot_at_epoch(epoch, spec)
        if state.slot <= start_slot:
            target_root = head_root
        else:
            target_root = state.block_roots[
                start_slot % spec.preset.SLOTS_PER_HISTORICAL_ROOT
            ]
        source = (
            state.current_justified_checkpoint
            if epoch == acc.get_current_epoch(state, spec)
            else state.previous_justified_checkpoint
        )
        return types.AttestationData.make(
            slot=slot,
            index=committee_index,
            beacon_block_root=head_root,
            source=source,
            target=types.Checkpoint.make(epoch=epoch, root=target_root),
        )

    def publish_attestations(self, attestations) -> int:
        """BN re-verifies and gossips; returns count accepted."""
        if not self.healthy:
            raise BeaconNodeError("node down")
        verified = self.chain.verify_unaggregated_attestations(attestations)
        for att, indices in verified:
            self.chain.apply_attestation_to_fork_choice(att, indices)
        return len(verified)

    # -- blocks ----------------------------------------------------------

    def publish_block(self, signed_block) -> bytes:
        if not self.healthy:
            raise BeaconNodeError("node down")
        root = self.chain.verify_block_for_gossip(signed_block)
        return self.chain.process_block(
            signed_block, block_root=root, proposal_already_verified=True
        )


class BeaconNodeFallback:
    """Health-ranked multi-node redundancy (beacon_node_fallback.rs)."""

    def __init__(self, nodes: list):
        self.nodes = list(nodes)

    def first_success(self, method: str, *args, **kwargs):
        errors = []
        ranked = sorted(self.nodes, key=lambda n: not n.is_healthy())
        for node in ranked:
            try:
                return getattr(node, method)(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — try next node
                errors.append((node, e))
        raise BeaconNodeError(f"all beacon nodes failed: {errors}")
