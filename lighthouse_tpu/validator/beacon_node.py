"""Beacon-node interface for the validator client + multi-node fallback.

Parity surface: the typed client boundary of /root/reference/common/eth2
(BeaconNodeHttpClient, src/lib.rs:156) and the VC's
BeaconNodeFallback health-ranked redundancy
(validator_client/src/beacon_node_fallback.rs). The VC talks to a small
duck-typed interface; `InProcessBeaconNode` implements it directly over a
BeaconChain (the simulator path — testing/simulator analog), and an HTTP
client implementing the same surface slots in for production (api/client).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..state_transition import accessors as acc
from ..state_transition.slot import types_for_slot
from ..types import helpers as h


@dataclass
class AttesterDuty:
    pubkey: bytes
    validator_index: int
    slot: int
    committee_index: int
    committee_length: int
    committee_position: int
    committees_at_slot: int


@dataclass
class ProposerDuty:
    pubkey: bytes
    validator_index: int
    slot: int


@dataclass
class SyncDuty:
    pubkey: bytes
    validator_index: int
    positions: list   # [(subcommittee_index, index_in_subcommittee)]


class BeaconNodeError(Exception):
    pass


class InProcessBeaconNode:
    """The VC-visible API implemented straight over a BeaconChain."""

    def __init__(self, chain):
        self.chain = chain
        self.healthy = True

    # -- node status -----------------------------------------------------

    def is_healthy(self) -> bool:
        return self.healthy

    def genesis_validators_root(self) -> bytes:
        return bytes(self.chain.head_state().genesis_validators_root)

    # -- duties ----------------------------------------------------------

    def attester_duties(self, epoch: int, indices: list[int]) -> list[AttesterDuty]:
        if not self.healthy:
            raise BeaconNodeError("node down")
        chain = self.chain
        state = chain.head_state()
        spec = chain.spec
        # advance a clone when the requested epoch is beyond the head's
        # shuffling horizon (the reference advances the state the same way
        # in its duties endpoint)
        if epoch > acc.get_current_epoch(state, spec) + 1:
            from ..testing.harness import clone_state
            from ..state_transition.slot import process_slots

            state = clone_state(state, spec)
            process_slots(state, spec, h.compute_start_slot_at_epoch(epoch, spec))
        cache = acc.build_committee_cache(state, spec, epoch)
        wanted = set(indices)
        duties = []
        for slot in range(
            h.compute_start_slot_at_epoch(epoch, spec),
            h.compute_start_slot_at_epoch(epoch + 1, spec),
        ):
            for cidx in range(cache.committees_per_slot):
                committee = cache.committee(slot, cidx)
                for pos, vi in enumerate(committee):
                    if vi in wanted:
                        duties.append(
                            AttesterDuty(
                                pubkey=bytes(state.validators[vi].pubkey),
                                validator_index=vi,
                                slot=slot,
                                committee_index=cidx,
                                committee_length=len(committee),
                                committee_position=pos,
                                committees_at_slot=cache.committees_per_slot,
                            )
                        )
        return duties

    def proposer_duties(self, epoch: int) -> list[ProposerDuty]:
        if not self.healthy:
            raise BeaconNodeError("node down")
        chain = self.chain
        spec = chain.spec
        from ..testing.harness import clone_state
        from ..state_transition.slot import process_slots

        state = clone_state(chain.head_state(), spec)
        start = h.compute_start_slot_at_epoch(epoch, spec)
        if state.slot < start:
            process_slots(state, spec, start)
        duties = []
        for slot in range(start, start + spec.preset.SLOTS_PER_EPOCH):
            proposer = acc.get_beacon_proposer_index(state, spec, slot)
            duties.append(
                ProposerDuty(
                    pubkey=bytes(state.validators[proposer].pubkey),
                    validator_index=proposer,
                    slot=slot,
                )
            )
        return duties

    # -- attestation flow ------------------------------------------------

    def attestation_data(self, slot: int, committee_index: int, types=None):
        if not self.healthy:
            raise BeaconNodeError("node down")
        chain = self.chain
        spec = chain.spec
        types = types_for_slot(spec, slot)
        epoch = h.compute_epoch_at_slot(slot, spec)

        # early-attester path: serve the block imported this slot straight
        # from the cache (populated only when it won fork choice) without
        # touching a full state (early_attester_cache.rs)
        early = chain.early_attester_cache.try_attest(slot, chain.head_root)
        if early is not None:
            return types.AttestationData.make(
                slot=slot,
                index=committee_index,
                beacon_block_root=early.beacon_block_root,
                source=types.Checkpoint.make(
                    epoch=early.source_epoch, root=early.source_root
                ),
                target=types.Checkpoint.make(
                    epoch=early.target_epoch, root=early.target_root
                ),
            )

        head_root = chain.head_root
        # attester cache: (epoch, head) -> (source, target_root) without
        # touching the full state (attester_cache.rs)
        cached = chain.attester_cache.get(epoch, head_root)
        if cached is not None:
            source, target_root = cached
        else:
            state = chain.head_state()
            start_slot = h.compute_start_slot_at_epoch(epoch, spec)
            if state.slot <= start_slot:
                target_root = head_root
            else:
                target_root = bytes(
                    state.block_roots[start_slot % spec.preset.SLOTS_PER_HISTORICAL_ROOT]
                )
            source = (
                state.current_justified_checkpoint
                if epoch == acc.get_current_epoch(state, spec)
                else state.previous_justified_checkpoint
            )
            chain.attester_cache.put(epoch, head_root, source, target_root)
        return types.AttestationData.make(
            slot=slot,
            index=committee_index,
            beacon_block_root=head_root,
            source=source,
            target=types.Checkpoint.make(epoch=epoch, root=target_root),
        )

    def publish_attestations(self, attestations, types=None) -> int:
        """BN re-verifies and gossips; returns count accepted."""
        if not self.healthy:
            raise BeaconNodeError("node down")
        verified = self.chain.verify_unaggregated_attestations(attestations)
        for att, indices in verified:
            self.chain.apply_attestation_to_fork_choice(att, indices)
        return len(verified)

    def aggregate_attestation(self, slot: int, data_root: bytes):
        """Serve an aggregate from the naive aggregation pool
        (GET /eth/v1/validator/aggregate_attestation)."""
        if not self.healthy:
            raise BeaconNodeError("node down")
        types = types_for_slot(self.chain.spec, slot)
        agg = self.chain.naive_attestation_pool.get_aggregate(slot, data_root, types)
        if agg is None:
            raise BeaconNodeError("no aggregate known")
        return agg

    def publish_aggregates(self, signed_aggregates, types=None) -> int:
        if not self.healthy:
            raise BeaconNodeError("node down")
        verified = self.chain.verify_aggregated_attestations(signed_aggregates)
        for att, indices in verified:
            self.chain.apply_attestation_to_fork_choice(att, indices)
        return len(verified)

    # -- sync committee flow ----------------------------------------------

    def sync_duties(self, epoch: int, indices: list[int]) -> list["SyncDuty"]:
        """Current-period sync-committee membership for the given validators
        (POST /eth/v1/validator/duties/sync)."""
        if not self.healthy:
            raise BeaconNodeError("node down")
        duties = []
        state = self.chain.head_state()
        if not hasattr(state, "current_sync_committee"):
            return duties
        for vi in indices:
            positions = self.chain.sync_subcommittee_positions(vi)
            if positions:
                duties.append(
                    SyncDuty(
                        pubkey=bytes(state.validators[vi].pubkey),
                        validator_index=vi,
                        positions=positions,
                    )
                )
        return duties

    def publish_sync_messages(self, msgs) -> int:
        if not self.healthy:
            raise BeaconNodeError("node down")
        return self.chain.process_sync_committee_messages(msgs)

    def sync_committee_contribution(self, slot: int, subcommittee_index: int, beacon_block_root: bytes):
        if not self.healthy:
            raise BeaconNodeError("node down")
        types = types_for_slot(self.chain.spec, slot)
        contrib = self.chain.naive_sync_pool.get_contribution(
            slot, beacon_block_root, subcommittee_index, types
        )
        if contrib is None:
            raise BeaconNodeError("no contribution known")
        return contrib

    def publish_contributions(self, signed_contributions) -> int:
        if not self.healthy:
            raise BeaconNodeError("node down")
        n = 0
        for sc in signed_contributions:
            if self.chain.verify_signed_contribution(sc):
                n += 1
        return n

    # -- preparation ------------------------------------------------------

    def prepare_beacon_proposer(self, preparations) -> int:
        """Record fee recipients (POST /eth/v1/validator/prepare_beacon_proposer)."""
        if not self.healthy:
            raise BeaconNodeError("node down")
        for p in preparations:
            self.chain.proposer_preparations[p["validator_index"]] = p["fee_recipient"]
        return len(preparations)

    # -- blocks ----------------------------------------------------------

    def publish_block(self, signed_block, types=None) -> bytes:
        if not self.healthy:
            raise BeaconNodeError("node down")
        root = self.chain.verify_block_for_gossip(signed_block)
        return self.chain.process_block(
            signed_block, block_root=root, proposal_already_verified=True
        )


class BeaconNodeFallback:
    """Health-ranked multi-node redundancy (beacon_node_fallback.rs)."""

    def __init__(self, nodes: list):
        self.nodes = list(nodes)

    def first_success(self, method: str, *args, **kwargs):
        errors = []
        ranked = sorted(self.nodes, key=lambda n: not n.is_healthy())
        for node in ranked:
            try:
                return getattr(node, method)(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — try next node
                errors.append((node, e))
        raise BeaconNodeError(f"all beacon nodes failed: {errors}")
