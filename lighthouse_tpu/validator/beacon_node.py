"""Beacon-node interface for the validator client + multi-node fallback.

Parity surface: the typed client boundary of /root/reference/common/eth2
(BeaconNodeHttpClient, src/lib.rs:156) and the VC's
BeaconNodeFallback health-ranked redundancy
(validator_client/src/beacon_node_fallback.rs). The VC talks to a small
duck-typed interface; `InProcessBeaconNode` implements it directly over a
BeaconChain (the simulator path — testing/simulator analog), and an HTTP
client implementing the same surface slots in for production (api/client).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from ..state_transition import accessors as acc
from ..state_transition.slot import types_for_slot
from ..types import helpers as h
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY

log = get_logger("vc_fallback")

VC_FALLBACK = REGISTRY.counter_vec(
    "vc_fallback_total",
    "validator-client beacon-node fallback calls, by method and outcome "
    "(success / error / timeout / rate_limited / retry / probe_up / "
    "all_failed)",
    ("method", "result"),
)
VC_NODE_HEALTH = REGISTRY.gauge_vec(
    "vc_node_health_score",
    "per-node fallback health score in [0,1] (1 = every recent call "
    "succeeded; failures halve it, timeouts quarter it, successes decay "
    "it back toward 1)",
    ("node",),
)

#: default per-call deadline in seconds (the VC analog of --rpc-timeout)
DEFAULT_CALL_TIMEOUT = 5.0
#: below this score a node is DEMOTED: it ranks behind every healthy
#: node and is only probed back, never retried first
DEMOTION_THRESHOLD = 0.5


def resolve_call_timeout(explicit: float | None = None) -> float:
    """Per-call deadline resolution: explicit arg / --vc-timeout >
    LIGHTHOUSE_TPU_VC_TIMEOUT > 5.0 (the --rpc-timeout pattern). A value
    <= 0 disables the deadline."""
    if explicit is not None:
        return float(explicit)
    env = os.environ.get("LIGHTHOUSE_TPU_VC_TIMEOUT")
    if env:
        try:
            return float(env)
        except ValueError:
            log.warn("bad LIGHTHOUSE_TPU_VC_TIMEOUT ignored", value=env)
    return DEFAULT_CALL_TIMEOUT


@dataclass
class AttesterDuty:
    pubkey: bytes
    validator_index: int
    slot: int
    committee_index: int
    committee_length: int
    committee_position: int
    committees_at_slot: int


@dataclass
class ProposerDuty:
    pubkey: bytes
    validator_index: int
    slot: int


@dataclass
class SyncDuty:
    pubkey: bytes
    validator_index: int
    positions: list   # [(subcommittee_index, index_in_subcommittee)]


class BeaconNodeError(Exception):
    pass


class NodeTimeout(BeaconNodeError):
    """A beacon-node call blew its deadline (the classified-timeout shape:
    socket timeout, injected silent peer, or a slow call measured past the
    per-call budget)."""


class NodeRateLimited(BeaconNodeError):
    """The node's token bucket refused the call (HTTP 429 shape)."""

    def __init__(self, msg: str, retry_after: float = 0.0):
        super().__init__(msg)
        self.retry_after = retry_after


class _NullLock:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class InProcessBeaconNode:
    """The VC-visible API implemented straight over a BeaconChain.

    Optional wiring makes it a full BN surface for the fleet harness:
    `op_pool` enables `produce_block`, `net` gossips published
    blocks/attestations to peers (what a real BN does after accepting a
    publish), and `lock` serializes chain mutations with the network
    node's handler threads."""

    def __init__(self, chain, op_pool=None, net=None, lock=None):
        self.chain = chain
        self.op_pool = op_pool
        self.net = net
        self.lock = lock if lock is not None else _NullLock()
        self.healthy = True

    # -- node status -----------------------------------------------------

    def is_healthy(self) -> bool:
        return self.healthy

    def genesis_validators_root(self) -> bytes:
        return bytes(self.chain.head_state().genesis_validators_root)

    # -- duties ----------------------------------------------------------

    def attester_duties(self, epoch: int, indices: list[int]) -> list[AttesterDuty]:
        if not self.healthy:
            raise BeaconNodeError("node down")
        chain = self.chain
        state = chain.head_state()
        spec = chain.spec
        # advance a clone when the requested epoch is beyond the head's
        # shuffling horizon (the reference advances the state the same way
        # in its duties endpoint)
        if epoch > acc.get_current_epoch(state, spec) + 1:
            from ..testing.harness import clone_state
            from ..state_transition.slot import process_slots

            state = clone_state(state, spec)
            process_slots(state, spec, h.compute_start_slot_at_epoch(epoch, spec))
        cache = acc.build_committee_cache(state, spec, epoch)
        wanted = set(indices)
        duties = []
        for slot in range(
            h.compute_start_slot_at_epoch(epoch, spec),
            h.compute_start_slot_at_epoch(epoch + 1, spec),
        ):
            for cidx in range(cache.committees_per_slot):
                committee = cache.committee(slot, cidx)
                for pos, vi in enumerate(committee):
                    if vi in wanted:
                        duties.append(
                            AttesterDuty(
                                pubkey=bytes(state.validators[vi].pubkey),
                                validator_index=vi,
                                slot=slot,
                                committee_index=cidx,
                                committee_length=len(committee),
                                committee_position=pos,
                                committees_at_slot=cache.committees_per_slot,
                            )
                        )
        return duties

    def proposer_duties(self, epoch: int) -> list[ProposerDuty]:
        if not self.healthy:
            raise BeaconNodeError("node down")
        chain = self.chain
        spec = chain.spec
        from ..testing.harness import clone_state
        from ..state_transition.slot import process_slots

        state = clone_state(chain.head_state(), spec)
        start = h.compute_start_slot_at_epoch(epoch, spec)
        if state.slot < start:
            process_slots(state, spec, start)
        duties = []
        for slot in range(start, start + spec.preset.SLOTS_PER_EPOCH):
            proposer = acc.get_beacon_proposer_index(state, spec, slot)
            duties.append(
                ProposerDuty(
                    pubkey=bytes(state.validators[proposer].pubkey),
                    validator_index=proposer,
                    slot=slot,
                )
            )
        return duties

    # -- attestation flow ------------------------------------------------

    def attestation_data(self, slot: int, committee_index: int, types=None):
        if not self.healthy:
            raise BeaconNodeError("node down")
        chain = self.chain
        spec = chain.spec
        types = types_for_slot(spec, slot)
        epoch = h.compute_epoch_at_slot(slot, spec)

        # early-attester path: serve the block imported this slot straight
        # from the cache (populated only when it won fork choice) without
        # touching a full state (early_attester_cache.rs)
        early = chain.early_attester_cache.try_attest(slot, chain.head_root)
        if early is not None:
            return types.AttestationData.make(
                slot=slot,
                index=committee_index,
                beacon_block_root=early.beacon_block_root,
                source=types.Checkpoint.make(
                    epoch=early.source_epoch, root=early.source_root
                ),
                target=types.Checkpoint.make(
                    epoch=early.target_epoch, root=early.target_root
                ),
            )

        head_root = chain.head_root
        # attester cache: (epoch, head) -> (source, target_root) without
        # touching the full state (attester_cache.rs)
        cached = chain.attester_cache.get(epoch, head_root)
        if cached is not None:
            source, target_root = cached
        else:
            state = chain.head_state()
            start_slot = h.compute_start_slot_at_epoch(epoch, spec)
            if state.slot <= start_slot:
                target_root = head_root
            else:
                target_root = bytes(
                    state.block_roots[start_slot % spec.preset.SLOTS_PER_HISTORICAL_ROOT]
                )
            source = (
                state.current_justified_checkpoint
                if epoch == acc.get_current_epoch(state, spec)
                else state.previous_justified_checkpoint
            )
            chain.attester_cache.put(epoch, head_root, source, target_root)
        return types.AttestationData.make(
            slot=slot,
            index=committee_index,
            beacon_block_root=head_root,
            source=source,
            target=types.Checkpoint.make(epoch=epoch, root=target_root),
        )

    #: attestation gossip fans out over this many subnet topics when a
    #: `net` is wired (the harness's subnet count; production parity is
    #: spec.attestation_subnet_count)
    subnet_count = 2

    def _att_subnet(self, att) -> int:
        cidx = int(att.data.index)
        cb = getattr(att, "committee_bits", None)
        if cb:
            cidx = next((i for i, b in enumerate(cb) if b), 0)
        return cidx % max(1, self.subnet_count)

    def publish_attestations(self, attestations, types=None) -> int:
        """BN re-verifies, imports and gossips; returns count accepted."""
        if not self.healthy:
            raise BeaconNodeError("node down")
        with self.lock:
            verified = self.chain.verify_unaggregated_attestations(
                attestations
            )
            for att, indices in verified:
                self.chain.apply_attestation_to_fork_choice(att, indices)
                if self.op_pool is not None:
                    self.op_pool.insert_attestation(
                        att, indices,
                        types or types_for_slot(self.chain.spec,
                                                att.data.slot),
                    )
        if self.net is not None:
            for att, _indices in verified:
                self.net.publish_attestation(att, self._att_subnet(att))
        return len(verified)

    def aggregate_attestation(self, slot: int, data_root: bytes):
        """Serve an aggregate from the naive aggregation pool
        (GET /eth/v1/validator/aggregate_attestation)."""
        if not self.healthy:
            raise BeaconNodeError("node down")
        types = types_for_slot(self.chain.spec, slot)
        agg = self.chain.naive_attestation_pool.get_aggregate(slot, data_root, types)
        if agg is None:
            raise BeaconNodeError("no aggregate known")
        return agg

    def publish_aggregates(self, signed_aggregates, types=None) -> int:
        if not self.healthy:
            raise BeaconNodeError("node down")
        with self.lock:
            verified = self.chain.verify_aggregated_attestations(
                signed_aggregates
            )
            for att, indices in verified:
                self.chain.apply_attestation_to_fork_choice(att, indices)
        if self.net is not None:
            # gossip only what verification ACCEPTED (the attestation path
            # above does the same): pushing a refused aggregate to mesh
            # peers earns this node their invalid-message penalties
            accepted = {id(att) for att, _indices in verified}
            for agg in signed_aggregates:
                if id(agg.message.aggregate) in accepted:
                    self.net.publish_aggregate(agg)
        return len(verified)

    # -- sync committee flow ----------------------------------------------

    def sync_duties(self, epoch: int, indices: list[int]) -> list["SyncDuty"]:
        """Current-period sync-committee membership for the given validators
        (POST /eth/v1/validator/duties/sync)."""
        if not self.healthy:
            raise BeaconNodeError("node down")
        duties = []
        state = self.chain.head_state()
        if not hasattr(state, "current_sync_committee"):
            return duties
        for vi in indices:
            positions = self.chain.sync_subcommittee_positions(vi)
            if positions:
                duties.append(
                    SyncDuty(
                        pubkey=bytes(state.validators[vi].pubkey),
                        validator_index=vi,
                        positions=positions,
                    )
                )
        return duties

    def publish_sync_messages(self, msgs) -> int:
        if not self.healthy:
            raise BeaconNodeError("node down")
        with self.lock:
            return self.chain.process_sync_committee_messages(msgs)

    def sync_committee_contribution(self, slot: int, subcommittee_index: int, beacon_block_root: bytes):
        if not self.healthy:
            raise BeaconNodeError("node down")
        types = types_for_slot(self.chain.spec, slot)
        contrib = self.chain.naive_sync_pool.get_contribution(
            slot, beacon_block_root, subcommittee_index, types
        )
        if contrib is None:
            raise BeaconNodeError("no contribution known")
        return contrib

    def publish_contributions(self, signed_contributions) -> int:
        if not self.healthy:
            raise BeaconNodeError("node down")
        n = 0
        for sc in signed_contributions:
            if self.chain.verify_signed_contribution(sc):
                n += 1
        return n

    # -- preparation ------------------------------------------------------

    def prepare_beacon_proposer(self, preparations) -> int:
        """Record fee recipients (POST /eth/v1/validator/prepare_beacon_proposer)."""
        if not self.healthy:
            raise BeaconNodeError("node down")
        for p in preparations:
            self.chain.proposer_preparations[p["validator_index"]] = p["fee_recipient"]
        return len(preparations)

    # -- blocks ----------------------------------------------------------

    def produce_block(self, slot: int, randao_reveal: bytes, types=None,
                      graffiti: bytes | None = None):
        """Unsigned block on the node's head (GET /eth/v3/validator/blocks).
        Requires an `op_pool` to pack operations from."""
        if not self.healthy:
            raise BeaconNodeError("node down")
        with self.lock:
            return self.chain.produce_block(
                slot, randao_reveal, op_pool=self.op_pool, graffiti=graffiti
            )

    def publish_block(self, signed_block, types=None) -> bytes:
        if not self.healthy:
            raise BeaconNodeError("node down")
        with self.lock:
            root = self.chain.verify_block_for_gossip(signed_block)
            out = self.chain.process_block(
                signed_block, block_root=root, proposal_already_verified=True
            )
        if self.net is not None:
            self.net.publish_block(signed_block)
        return out


class _Candidate:
    """Per-node fallback health state. Score lives in [0,1]: successes
    decay it back toward 1, errors halve it, timeouts quarter it; below
    DEMOTION_THRESHOLD the node ranks behind every healthy peer. `label`
    is a STABLE identity for metrics (the HTTP client's URL, a harness
    node's global index) — list position alone would alias every
    fallback instance's first node onto one series."""

    __slots__ = ("node", "index", "label", "score", "last_result",
                 "demotions")

    def __init__(self, node, index: int):
        self.node = node
        self.index = index
        ident = getattr(node, "base_url", None)
        if ident is None:
            ident = getattr(node, "index", None)
        self.label = str(ident if ident is not None else index)
        self.score = 1.0
        self.last_result = "untried"
        self.demotions = 0

    @property
    def demoted(self) -> bool:
        return self.score < DEMOTION_THRESHOLD

    def is_healthy(self) -> bool:
        try:
            return bool(self.node.is_healthy())
        except Exception:  # noqa: BLE001 — an unreachable node is unhealthy
            return False


def classify_failure(exc: Exception) -> str:
    """Map a node-call exception onto a fallback outcome: timeout-shaped
    failures (socket timeout, injected silent peer, NodeTimeout) sink the
    node hard; rate limiting is the node protecting itself and is retried
    without demotion; everything else is an error. Rate limiting is
    recognized by TYPE (NodeRateLimited — the HTTP client raises it for
    status 429) or an explicit phrase, never a bare '429' substring: an
    error mentioning epoch 429 must not exempt a broken node from
    demotion."""
    if isinstance(exc, NodeRateLimited):
        return "rate_limited"
    name = type(exc).__name__.lower()
    text = str(exc).lower()
    if "timeout" in name or "timeout" in text or "timed out" in text:
        return "timeout"
    if "rate limit" in text or "rate-limit" in text or (
        "too many requests" in text
    ):
        return "rate_limited"
    return "error"


class BeaconNodeFallback:
    """Health-ranked multi-node redundancy (beacon_node_fallback.rs), with
    per-call deadlines, failure-driven health scoring and bounded
    retry/backoff.

    Every call is measured against `call_timeout` on the injectable
    `clock` (a call that returns late still sinks its node: the next duty
    prefers a faster peer). Failures demote a node's score — errors halve
    it, timeouts quarter it — and a demoted node ranks behind every
    healthy one; it is probed back via `is_healthy()` every `probe_every`
    calls instead of being retried first forever. One duty gets at most
    `max_retries` extra rounds across the ranked nodes, separated by
    exponential backoff through the injectable `sleep_fn` (tests and the
    fleet harness record delays instead of sleeping). Outcomes land in
    `vc_fallback_total{method,result}`; demotions in the flight recorder;
    deterministic per-instance tallies in `stats`."""

    BACKOFF_BASE = 0.05
    BACKOFF_CAP = 2.0
    #: hard ceiling on how long a server's Retry-After may stretch the
    #: between-round backoff — with deadlines disabled (call_timeout 0)
    #: an unclamped header would be an unbounded server-controlled sleep
    RETRY_AFTER_CAP = 30.0

    def __init__(self, nodes: list, call_timeout: float | None = None,
                 clock=time.monotonic, sleep_fn=time.sleep,
                 max_retries: int = 2, probe_every: int = 8,
                 recorder=None):
        self._candidates = [_Candidate(n, i) for i, n in enumerate(nodes)]
        self.call_timeout = resolve_call_timeout(call_timeout)
        self.clock = clock
        self.sleep_fn = sleep_fn
        self.max_retries = int(max_retries)
        self.probe_every = int(probe_every)
        self.recorder = recorder
        self._calls = 0
        self.last_served: int | None = None
        #: deterministic per-instance tallies (scenario reports)
        self.stats = {
            "calls": 0, "successes": 0, "errors": 0, "timeouts": 0,
            "rate_limited": 0, "retries": 0, "failovers": 0,
            "probes_up": 0, "exhausted": 0,
            "retry_after_honored": 0, "retry_after_skipped": 0,
        }

    @property
    def nodes(self) -> list:
        return [c.node for c in self._candidates]

    def health_scores(self) -> dict[int, float]:
        return {c.index: round(c.score, 4) for c in self._candidates}

    # ---------------------------------------------------------- internals

    def _ranked(self, health: dict[int, bool] | None = None) -> list[_Candidate]:
        """Rank by (healthy, score, index). `health` is probed ONCE per
        duty call and reused across retry rounds — for an HTTP client
        is_healthy() is a real network GET, and re-probing every node
        every round would spend the duty deadline on health checks; the
        failure-driven scores are the intra-call freshness signal."""
        if health is None:
            health = {c.index: c.is_healthy() for c in self._candidates}
        return sorted(
            self._candidates,
            key=lambda c: (not health[c.index], -c.score, c.index),
        )

    def _set_score(self, cand: _Candidate, score: float, reason: str) -> None:
        was_demoted = cand.demoted
        cand.score = min(1.0, max(0.0, score))
        VC_NODE_HEALTH.labels(cand.label).set(cand.score)
        if cand.demoted and not was_demoted:
            cand.demotions += 1
            log.warn("beacon node demoted", node=cand.label,
                     score=f"{cand.score:.3f}", reason=reason)
            if self.recorder is not None:
                self.recorder.record(
                    "vc_node_demoted", severity="warn", node=cand.label,
                    score=round(cand.score, 4), reason=reason,
                )

    def _record_failure(self, cand: _Candidate, method: str, outcome: str,
                        exc: Exception | None = None) -> None:
        VC_FALLBACK.labels(method, outcome).inc()
        self.stats[
            "timeouts" if outcome == "timeout"
            else "rate_limited" if outcome == "rate_limited"
            else "errors"
        ] += 1
        cand.last_result = outcome
        if outcome == "rate_limited":
            return   # the node is healthy, just busy: never demote for 429s
        factor = 0.25 if outcome == "timeout" else 0.5
        self._set_score(cand, cand.score * factor, outcome)

    def _record_success(self, cand: _Candidate, method: str) -> None:
        VC_FALLBACK.labels(method, "success").inc()
        self.stats["successes"] += 1
        cand.last_result = "success"
        self._set_score(cand, 0.5 * cand.score + 0.5, "success")

    def _probe_demoted(self) -> None:
        """Probe every demoted node's health endpoint; a live answer lifts
        it back to the demotion boundary so ranking can try it again."""
        for cand in self._candidates:
            if not cand.demoted:
                continue
            if cand.is_healthy():
                self.stats["probes_up"] += 1
                VC_FALLBACK.labels("probe", "probe_up").inc()
                self._set_score(cand, DEMOTION_THRESHOLD, "probe_up")

    # -------------------------------------------------------------- calls

    def first_success(self, method: str, *args, **kwargs):
        result, _node, _attempts = self.call_detailed(method, *args, **kwargs)
        return result

    def call_detailed(self, method: str, *args, **kwargs):
        """Like first_success but returns (result, serving_node_index,
        attempts) — the fleet harness attributes work to the node that
        actually served it."""
        self._calls += 1
        self.stats["calls"] += 1
        if self.probe_every and self._calls % self.probe_every == 0:
            self._probe_demoted()
        errors: list[tuple[int, str]] = []
        attempts = 0
        t_begin = self.clock()
        retry_floor = 0.0  # max Retry-After seen in the previous round
        health = {c.index: c.is_healthy() for c in self._candidates}
        for round_no in range(self.max_retries + 1):
            if round_no:
                delay = min(self.BACKOFF_CAP,
                            self.BACKOFF_BASE * (2 ** (round_no - 1)))
                if retry_floor > 0.0:
                    # honor Retry-After as the backoff FLOOR — unless
                    # honoring it would sleep past the remaining duty
                    # deadline, in which case the round proceeds on plain
                    # exponential backoff (failing over beats out-sleeping
                    # the slot; the limiting node was already skipped
                    # within the round)
                    remaining = (
                        self.call_timeout - (self.clock() - t_begin)
                        if self.call_timeout > 0 else float("inf")
                    )
                    if retry_floor <= remaining:
                        delay = max(delay, retry_floor)
                        self.stats["retry_after_honored"] += 1
                        VC_FALLBACK.labels(method, "retry_after_honored").inc()
                    else:
                        self.stats["retry_after_skipped"] += 1
                        VC_FALLBACK.labels(method, "retry_after_skipped").inc()
                retry_floor = 0.0
                self.stats["retries"] += 1
                VC_FALLBACK.labels(method, "retry").inc()
                self.sleep_fn(delay)
            for pos, cand in enumerate(self._ranked(health)):
                attempts += 1
                start = self.clock()
                try:
                    result = getattr(cand.node, method)(*args, **kwargs)
                except Exception as e:  # noqa: BLE001 — fail over
                    outcome = classify_failure(e)
                    if outcome == "rate_limited":
                        ra = float(getattr(e, "retry_after", 0.0) or 0.0)
                        retry_floor = max(
                            retry_floor, min(ra, self.RETRY_AFTER_CAP)
                        )
                    self._record_failure(cand, method, outcome, e)
                    errors.append((cand.index,
                                   f"{type(e).__name__}: {e}"))
                    continue
                if (self.call_timeout > 0
                        and self.clock() - start > self.call_timeout):
                    # the answer arrived past the deadline: use it (it is
                    # real), but sink the node so the next duty routes to
                    # a faster peer first
                    self._record_failure(cand, method, "timeout")
                else:
                    self._record_success(cand, method)
                if pos or round_no:
                    self.stats["failovers"] += 1
                self.last_served = cand.index
                return result, cand.index, attempts
        self.stats["exhausted"] += 1
        VC_FALLBACK.labels(method, "all_failed").inc()
        raise BeaconNodeError(
            f"all beacon nodes failed {method} after "
            f"{self.max_retries + 1} rounds: {errors}"
        )
