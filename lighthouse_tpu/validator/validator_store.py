"""ValidatorStore — the signing façade every VC service goes through.

Parity surface: /root/reference/validator_client/src/validator_store.rs —
every signature is produced here and GATED by slashing protection and
doppelganger status; signing methods are pluggable (local keystore vs
remote signer, signing_method.rs:80).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import bls
from ..types import helpers as h
from ..types.spec import (
    ChainSpec,
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_VOLUNTARY_EXIT,
)
from .slashing_protection import SlashingDatabase, SlashingProtectionError


class DoppelgangerProtected(Exception):
    """Signing refused: validator still in doppelganger quarantine."""


class LocalSigner:
    """SigningMethod::LocalKeystore analog."""

    def __init__(self, sk: bls.SecretKey):
        self._sk = sk

    def sign(self, signing_root: bytes) -> bls.Signature:
        return bls.sign(self._sk, signing_root)


@dataclass
class InitializedValidator:
    pubkey: bytes
    signer: object
    index: int | None = None
    doppelganger_safe: bool = True


class ValidatorStore:
    def __init__(
        self,
        spec: ChainSpec,
        genesis_validators_root: bytes,
        slashing_db: SlashingDatabase | None = None,
        journal=None,
        record_signed: bool = False,
    ):
        """`journal` (slashing_protection.SignIntentJournal) makes sign
        intents DURABLE before any signature exists: the slashing-DB check
        passes, the intent lands on disk, THEN the key signs — a crash at
        any byte of that sequence can never permit a double-sign after
        restart. `record_signed=True` keeps an in-memory log of every
        slashable message signed (fleet post-hoc replay proof); leave it
        off for long-running processes."""
        self.spec = spec
        self.genesis_validators_root = genesis_validators_root
        self.slashing_db = slashing_db or SlashingDatabase()
        self.journal = journal
        self.signed_log: list | None = [] if record_signed else None
        self.validators: dict[bytes, InitializedValidator] = {}
        self.fork_version: bytes = spec.fork_version(spec.fork_name_at_epoch(0))

    # ------------------------------------------------------------- admin

    def add_validator(self, sk: bls.SecretKey, index: int | None = None) -> bytes:
        pk = sk.public_key().serialize()
        self.slashing_db.register_validator(pk)
        self.validators[pk] = InitializedValidator(pubkey=pk, signer=LocalSigner(sk), index=index)
        return pk

    def voting_pubkeys(self) -> list[bytes]:
        return list(self.validators)

    def set_index(self, pubkey: bytes, index: int) -> None:
        self.validators[pubkey].index = index

    def set_doppelganger_safe(self, pubkey: bytes, safe: bool) -> None:
        self.validators[pubkey].doppelganger_safe = safe

    def update_fork(self, fork_version: bytes) -> None:
        self.fork_version = fork_version

    def _validator(self, pubkey: bytes) -> InitializedValidator:
        v = self.validators[pubkey]
        if not v.doppelganger_safe:
            raise DoppelgangerProtected(pubkey.hex()[:16])
        return v

    def _domain(self, domain_type: bytes) -> bytes:
        return h.compute_domain(domain_type, self.fork_version, self.genesis_validators_root)

    # ------------------------------------------------------------- signing

    def sign_block(self, pubkey: bytes, block, types) -> bytes:
        v = self._validator(pubkey)
        domain = self._domain(DOMAIN_BEACON_PROPOSER)
        root = h.compute_signing_root(types.BeaconBlock, block, domain)
        slot = int(block.slot)
        self.slashing_db.check_and_insert_block_proposal(pubkey, slot, root)
        if self.journal is not None:
            # durable intent BEFORE the signature exists: a torn journal
            # write crashes here, so no signature was ever produced
            self.journal.record_block(pubkey, slot, root)
        if self.signed_log is not None:
            self.signed_log.append(
                ("block", pubkey, slot, bytes(root))
            )
        return v.signer.sign(root).serialize()

    def sign_attestation(self, pubkey: bytes, data, types) -> bytes:
        v = self._validator(pubkey)
        domain = self._domain(DOMAIN_BEACON_ATTESTER)
        root = h.compute_signing_root(types.AttestationData, data, domain)
        source, target = int(data.source.epoch), int(data.target.epoch)
        self.slashing_db.check_and_insert_attestation(
            pubkey, source, target, root
        )
        if self.journal is not None:
            self.journal.record_attestation(pubkey, source, target, root)
        if self.signed_log is not None:
            self.signed_log.append(
                ("attestation", pubkey, source, target, bytes(root))
            )
        return v.signer.sign(root).serialize()

    def sign_randao(self, pubkey: bytes, epoch: int) -> bytes:
        from ..ssz.core import uint64

        v = self._validator(pubkey)
        domain = self._domain(DOMAIN_RANDAO)
        root = h.compute_signing_root(uint64, epoch, domain)
        return v.signer.sign(root).serialize()

    def sign_selection_proof(self, pubkey: bytes, slot: int) -> bytes:
        from ..ssz.core import uint64

        v = self._validator(pubkey)
        domain = self._domain(DOMAIN_SELECTION_PROOF)
        root = h.compute_signing_root(uint64, slot, domain)
        return v.signer.sign(root).serialize()

    def sign_aggregate_and_proof(self, pubkey: bytes, agg_and_proof, types) -> bytes:
        v = self._validator(pubkey)
        domain = self._domain(DOMAIN_AGGREGATE_AND_PROOF)
        root = h.compute_signing_root(types.AggregateAndProof, agg_and_proof, domain)
        return v.signer.sign(root).serialize()

    def sign_sync_committee_message(self, pubkey: bytes, block_root: bytes) -> bytes:
        v = self._validator(pubkey)
        domain = self._domain(DOMAIN_SYNC_COMMITTEE)
        root = h.compute_signing_root_from_root(block_root, domain)
        return v.signer.sign(root).serialize()

    def sign_sync_selection_proof(self, pubkey: bytes, slot: int, subcommittee_index: int, types) -> bytes:
        from ..types.spec import DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF

        v = self._validator(pubkey)
        domain = self._domain(DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF)
        data = types.SyncAggregatorSelectionData.make(
            slot=slot, subcommittee_index=subcommittee_index
        )
        root = h.compute_signing_root(types.SyncAggregatorSelectionData, data, domain)
        return v.signer.sign(root).serialize()

    def sign_contribution_and_proof(self, pubkey: bytes, contrib_and_proof, types) -> bytes:
        from ..types.spec import DOMAIN_CONTRIBUTION_AND_PROOF

        v = self._validator(pubkey)
        domain = self._domain(DOMAIN_CONTRIBUTION_AND_PROOF)
        root = h.compute_signing_root(
            types.ContributionAndProof, contrib_and_proof, domain
        )
        return v.signer.sign(root).serialize()

    def sign_voluntary_exit(self, pubkey: bytes, exit_msg, types) -> bytes:
        # exits are NOT slashable; no protection needed
        v = self.validators[pubkey]  # doppelganger does not block exits
        domain = self._domain(DOMAIN_VOLUNTARY_EXIT)
        root = h.compute_signing_root(types.VoluntaryExit, exit_msg, domain)
        return v.signer.sign(root).serialize()
