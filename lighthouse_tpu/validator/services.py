"""Validator-client services: duties polling, attestations, proposals,
doppelganger quarantine.

Parity surface: /root/reference/validator_client/src/ — DutiesService
(duties_service.rs:208: per-epoch attester/proposer duty maps keyed by
dependent root, selection-proof precompute), AttestationService
(attestation_service.rs:176-493: slot+1/3 produce/sign/publish, slot+2/3
aggregate), BlockService (block_service.rs), DoppelgangerService
(doppelganger_service.rs: 2-epoch liveness quarantine before signing).

Scheduling is tick-driven and synchronous (`on_slot(slot, phase)`) so the
same code runs under the deterministic in-process simulator (manual clock)
or a wall-clock loop — logical time is the testing idiom the reference gets
from TestingSlotClock (SURVEY §4).
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field

from ..types import helpers as h
from ..types.spec import ChainSpec
from ..state_transition.slot import types_for_slot
from .beacon_node import BeaconNodeFallback
from .slashing_protection import SlashingProtectionError
from .validator_store import DoppelgangerProtected, ValidatorStore


@dataclass
class DutiesService:
    spec: ChainSpec
    store: ValidatorStore
    nodes: BeaconNodeFallback
    attester_duties: dict = field(default_factory=dict)   # epoch -> [AttesterDuty]
    proposer_duties: dict = field(default_factory=dict)   # epoch -> [ProposerDuty]

    def poll(self, current_epoch: int) -> None:
        """Refresh duty maps for current and next epoch (duties_service.rs
        poll loop)."""
        my_pubkeys = set(self.store.voting_pubkeys())
        # resolve indices
        indices = [
            v.index for v in self.store.validators.values() if v.index is not None
        ]
        for epoch in (current_epoch, current_epoch + 1):
            duties = self.nodes.first_success("attester_duties", epoch, indices)
            self.attester_duties[epoch] = [
                d for d in duties if d.pubkey in my_pubkeys
            ]
            proposals = self.nodes.first_success("proposer_duties", epoch)
            self.proposer_duties[epoch] = [
                d for d in proposals if d.pubkey in my_pubkeys
            ]
        # prune old epochs
        for e in list(self.attester_duties):
            if e < current_epoch:
                del self.attester_duties[e]
        for e in list(self.proposer_duties):
            if e < current_epoch:
                del self.proposer_duties[e]

    def attesters_at_slot(self, slot: int):
        epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
        return [d for d in self.attester_duties.get(epoch, []) if d.slot == slot]

    def proposers_at_slot(self, slot: int):
        epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
        return [d for d in self.proposer_duties.get(epoch, []) if d.slot == slot]


@dataclass
class AttestationService:
    spec: ChainSpec
    store: ValidatorStore
    duties: DutiesService
    nodes: BeaconNodeFallback
    published: int = 0
    failed: int = 0

    def attest(self, slot: int) -> int:
        """Produce+sign+publish attestations for all duties at `slot`
        (the slot+1/3 phase of attestation_service.rs)."""
        duties = self.duties.attesters_at_slot(slot)
        if not duties:
            return 0
        types = types_for_slot(self.spec, slot)
        by_committee: dict[int, list] = defaultdict(list)
        for d in duties:
            by_committee[d.committee_index].append(d)
        produced = 0
        for cidx, ds in by_committee.items():
            data = self.nodes.first_success("attestation_data", slot, cidx)
            atts = []
            for d in ds:
                bits = [False] * d.committee_length
                bits[d.committee_position] = True
                try:
                    sig = self.store.sign_attestation(d.pubkey, data, types)
                except (SlashingProtectionError, DoppelgangerProtected):
                    self.failed += 1
                    continue
                atts.append(
                    types.Attestation.make(
                        aggregation_bits=bits, data=data, signature=sig
                    )
                )
            if atts:
                produced += self.nodes.first_success("publish_attestations", atts)
        self.published += produced
        return produced


@dataclass
class BlockService:
    spec: ChainSpec
    store: ValidatorStore
    duties: DutiesService
    nodes: BeaconNodeFallback
    produce_block_fn: object = None   # (slot, randao_reveal) -> unsigned block
    published: int = 0

    def propose(self, slot: int) -> int:
        duties = self.duties.proposers_at_slot(slot)
        count = 0
        for d in duties:
            types = types_for_slot(self.spec, slot)
            epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
            randao = self.store.sign_randao(d.pubkey, epoch)
            block = self.produce_block_fn(slot, randao)
            try:
                sig = self.store.sign_block(d.pubkey, block, types)
            except (SlashingProtectionError, DoppelgangerProtected):
                continue
            signed = types.SignedBeaconBlock.make(message=block, signature=sig)
            self.nodes.first_success("publish_block", signed)
            count += 1
        self.published += count
        return count


@dataclass
class DoppelgangerService:
    """Quarantine new validators for N epochs while watching for their
    signatures on the network (doppelganger_service.rs)."""

    spec: ChainSpec
    store: ValidatorStore
    epochs_to_watch: int = 2
    _watch_until: dict = field(default_factory=dict)   # pubkey -> epoch

    def register(self, pubkey: bytes, current_epoch: int) -> None:
        self._watch_until[pubkey] = current_epoch + self.epochs_to_watch
        self.store.set_doppelganger_safe(pubkey, False)

    def observe_liveness(self, pubkey: bytes) -> None:
        """Another instance signed with this key: NEVER enable it."""
        if pubkey in self._watch_until:
            self._watch_until[pubkey] = 2**63  # poisoned
        self.store.set_doppelganger_safe(pubkey, False)

    def on_epoch(self, current_epoch: int) -> None:
        for pk, until in list(self._watch_until.items()):
            if current_epoch >= until:
                self.store.set_doppelganger_safe(pk, True)
                del self._watch_until[pk]
