"""Validator-client services: duties polling, attestations, proposals,
doppelganger quarantine.

Parity surface: /root/reference/validator_client/src/ — DutiesService
(duties_service.rs:208: per-epoch attester/proposer duty maps keyed by
dependent root, selection-proof precompute), AttestationService
(attestation_service.rs:176-493: slot+1/3 produce/sign/publish, slot+2/3
aggregate), BlockService (block_service.rs), DoppelgangerService
(doppelganger_service.rs: 2-epoch liveness quarantine before signing).

Scheduling is tick-driven and synchronous (`on_slot(slot, phase)`) so the
same code runs under the deterministic in-process simulator (manual clock)
or a wall-clock loop — logical time is the testing idiom the reference gets
from TestingSlotClock (SURVEY §4).
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field

from ..types import helpers as h
from ..types.spec import ChainSpec
from ..state_transition.slot import types_for_slot
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY
from .beacon_node import BeaconNodeError, BeaconNodeFallback
from .slashing_protection import SlashingProtectionError
from .validator_store import DoppelgangerProtected, ValidatorStore

log = get_logger("vc_services")

VC_DUTIES = REGISTRY.counter_vec(
    "vc_duty_total",
    "validator duties by kind (attestation / proposal / aggregation / "
    "sync_message / sync_contribution) and outcome (performed, or "
    "missed_<reason>: node_error / rate_limited / slashing_protection / "
    "doppelganger / no_aggregate / no_contribution / rejected)",
    ("duty", "result"),
)
VC_DUTY_ERRORS = REGISTRY.counter_vec(
    "vc_duty_errors_total",
    "validator-client service errors by pipeline stage (duties_poll / "
    "attestation_data / attestation_publish / aggregate_fetch / "
    "aggregate_publish / block_produce / block_publish / sync_publish / "
    "sync_contribution_fetch / sync_contribution_publish)",
    ("stage",),
)


class DutyAccountant:
    """Duty conservation ledger: `scheduled == performed + Σmissed{reason}`
    per duty kind — a missed duty is COUNTED with a reason, never silently
    swallowed. One instance is shared by all of a VC's services; `counts`
    is deterministic and lands in fleet reports. Verdicts also feed the
    SLO epoch window through the validator_monitor path when an accountant
    is bound (`slo.record_validator_epoch`)."""

    def __init__(self, slo=None):
        self.slo = slo
        self.counts: dict[str, dict] = {}

    def _bucket(self, duty: str) -> dict:
        b = self.counts.get(duty)
        if b is None:
            b = self.counts[duty] = {
                "scheduled": 0, "performed": 0, "missed": {},
            }
        return b

    def scheduled(self, duty: str, n: int = 1) -> None:
        self._bucket(duty)["scheduled"] += n

    def performed(self, duty: str, n: int = 1) -> None:
        if n <= 0:
            return
        self._bucket(duty)["performed"] += n
        VC_DUTIES.labels(duty, "performed").inc(n)
        if self.slo is not None:
            # epoch window via the validator_monitor path, slot window as
            # the TIMELY "vc_duty" kind — burn rates see duty misses
            self.slo.record_validator_epoch(n, 0)
            self.slo.record_admitted("vc_duty", n)
            self.slo.record_processed("vc_duty", n)

    def missed(self, duty: str, reason: str, n: int = 1) -> None:
        if n <= 0:
            return
        b = self._bucket(duty)
        b["missed"][reason] = b["missed"].get(reason, 0) + n
        VC_DUTIES.labels(duty, f"missed_{reason}").inc(n)
        if self.slo is not None:
            self.slo.record_validator_epoch(0, n)
            self.slo.record_admitted("vc_duty", n)
            self.slo.record_shed("vc_duty", f"duty_{reason}", n)

    def conserved(self) -> bool:
        return all(
            b["scheduled"] == b["performed"] + sum(b["missed"].values())
            for b in self.counts.values()
        )

    def summary(self) -> dict:
        out = {
            duty: {
                "scheduled": b["scheduled"],
                "performed": b["performed"],
                "missed": dict(sorted(b["missed"].items())),
            }
            for duty, b in sorted(self.counts.items())
        }
        out["conserved"] = self.conserved()
        return out

    def totals(self) -> tuple[int, int, int]:
        s = sum(b["scheduled"] for b in self.counts.values())
        p = sum(b["performed"] for b in self.counts.values())
        m = sum(sum(b["missed"].values()) for b in self.counts.values())
        return s, p, m


def _miss_reason(exc: Exception) -> str:
    """Why a node-facing duty step failed, as a conservation reason."""
    from .beacon_node import classify_failure

    kind = classify_failure(exc)
    return "rate_limited" if kind == "rate_limited" else "node_error"


@dataclass
class DutiesService:
    spec: ChainSpec
    store: ValidatorStore
    nodes: BeaconNodeFallback
    attester_duties: dict = field(default_factory=dict)   # epoch -> [AttesterDuty]
    proposer_duties: dict = field(default_factory=dict)   # epoch -> [ProposerDuty]
    accountant: DutyAccountant = field(default_factory=DutyAccountant)
    poll_failures: int = 0

    def poll(self, current_epoch: int) -> bool:
        """Refresh duty maps for current and next epoch (duties_service.rs
        poll loop). Returns False (keeping any stale maps, which still
        cover the current epoch on a healthy cadence) when every node
        refused — the caller keeps ticking; duties missed because of a
        stale map are accounted by the per-duty services."""
        my_pubkeys = set(self.store.voting_pubkeys())
        # resolve indices
        indices = [
            v.index for v in self.store.validators.values() if v.index is not None
        ]
        ok = True
        for epoch in (current_epoch, current_epoch + 1):
            try:
                duties = self.nodes.first_success(
                    "attester_duties", epoch, indices
                )
                self.attester_duties[epoch] = [
                    d for d in duties if d.pubkey in my_pubkeys
                ]
                proposals = self.nodes.first_success("proposer_duties", epoch)
                self.proposer_duties[epoch] = [
                    d for d in proposals if d.pubkey in my_pubkeys
                ]
            except BeaconNodeError as e:
                ok = False
                self.poll_failures += 1
                VC_DUTY_ERRORS.labels("duties_poll").inc()
                log.warn("duties poll failed", epoch=epoch,
                         error=f"{type(e).__name__}: {e}")
        # prune old epochs
        for e in list(self.attester_duties):
            if e < current_epoch:
                del self.attester_duties[e]
        for e in list(self.proposer_duties):
            if e < current_epoch:
                del self.proposer_duties[e]
        return ok

    def attesters_at_slot(self, slot: int):
        epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
        return [d for d in self.attester_duties.get(epoch, []) if d.slot == slot]

    def proposers_at_slot(self, slot: int):
        epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
        return [d for d in self.proposer_duties.get(epoch, []) if d.slot == slot]


@dataclass
class AttestationService:
    spec: ChainSpec
    store: ValidatorStore
    duties: DutiesService
    nodes: BeaconNodeFallback
    accountant: DutyAccountant = field(default_factory=DutyAccountant)
    published: int = 0
    failed: int = 0
    #: validator indices whose attestation the serving node accepted in
    #: the LAST attest() call (the fleet harness's fan-out bookkeeping)
    last_published: list = field(default_factory=list)

    def attest(self, slot: int) -> int:
        """Produce+sign+publish attestations for all duties at `slot`
        (the slot+1/3 phase of attestation_service.rs). Every duty is
        accounted: performed, or missed with a reason."""
        duties = self.duties.attesters_at_slot(slot)
        self.last_published = []
        if not duties:
            return 0
        acct = self.accountant
        types = types_for_slot(self.spec, slot)
        by_committee: dict[int, list] = defaultdict(list)
        for d in duties:
            by_committee[d.committee_index].append(d)
        produced = 0
        for cidx, ds in by_committee.items():
            acct.scheduled("attestation", len(ds))
            try:
                data = self.nodes.first_success(
                    "attestation_data", slot, cidx, types
                )
            except BeaconNodeError as e:
                VC_DUTY_ERRORS.labels("attestation_data").inc()
                log.warn("attestation data fetch failed", slot=slot,
                         committee=cidx, error=f"{type(e).__name__}: {e}")
                acct.missed("attestation", _miss_reason(e), len(ds))
                self.failed += len(ds)
                continue
            atts = []
            signers = []
            for d in ds:
                bits = [False] * d.committee_length
                bits[d.committee_position] = True
                try:
                    sig = self.store.sign_attestation(d.pubkey, data, types)
                except SlashingProtectionError:
                    acct.missed("attestation", "slashing_protection")
                    self.failed += 1
                    continue
                except DoppelgangerProtected:
                    acct.missed("attestation", "doppelganger")
                    self.failed += 1
                    continue
                atts.append(
                    types.Attestation.make(
                        aggregation_bits=bits, data=data, signature=sig
                    )
                )
                signers.append(d.validator_index)
            if not atts:
                continue
            try:
                accepted = self.nodes.first_success(
                    "publish_attestations", atts, types
                )
            except BeaconNodeError as e:
                VC_DUTY_ERRORS.labels("attestation_publish").inc()
                log.warn("attestation publish failed", slot=slot,
                         committee=cidx, error=f"{type(e).__name__}: {e}")
                acct.missed("attestation", _miss_reason(e), len(atts))
                self.failed += len(atts)
                continue
            produced += accepted
            acct.performed("attestation", accepted)
            if accepted < len(atts):
                # the node rejected some (already-observed attester, bad
                # sig): count the shortfall so conservation still holds
                acct.missed("attestation", "rejected", len(atts) - accepted)
                self.failed += len(atts) - accepted
            else:
                self.last_published.extend(signers)
        self.published += produced
        return produced


def _is_aggregator(selection_proof: bytes, committee_len: int, target: int) -> bool:
    """Spec is_aggregator: hash(proof)[0:8] LE mod max(1, len // target) == 0."""
    import hashlib

    modulo = max(1, committee_len // target)
    return int.from_bytes(hashlib.sha256(selection_proof).digest()[:8], "little") % modulo == 0


@dataclass
class AggregationService:
    """The slot+2/3 aggregate phase of attestation_service.rs:493 — for each
    duty where we are the selected aggregator, fetch the naive-pool
    aggregate from the BN, wrap and sign AggregateAndProof, publish."""

    spec: ChainSpec
    store: ValidatorStore
    duties: DutiesService
    nodes: BeaconNodeFallback
    accountant: DutyAccountant = field(default_factory=DutyAccountant)
    published: int = 0

    def aggregate(self, slot: int) -> int:
        duties = self.duties.attesters_at_slot(slot)
        if not duties:
            return 0
        acct = self.accountant
        types = types_for_slot(self.spec, slot)
        count = 0
        for d in duties:
            try:
                proof = self.store.sign_selection_proof(d.pubkey, slot)
            except (SlashingProtectionError, DoppelgangerProtected):
                continue
            if not _is_aggregator(
                proof, d.committee_length, self.spec.target_aggregators_per_committee
            ):
                continue
            # selected: from here on the aggregation duty is accounted
            acct.scheduled("aggregation")
            try:
                data = self.nodes.first_success(
                    "attestation_data", slot, d.committee_index
                )
                data_root = types.AttestationData.hash_tree_root(data)
                agg = self.nodes.first_success(
                    "aggregate_attestation", slot, data_root
                )
            except BeaconNodeError as e:
                # "no aggregate known" is an empty naive pool (nobody
                # attested to that data root), not a node failure
                reason = (
                    "no_aggregate" if "no aggregate" in str(e).lower()
                    else _miss_reason(e)
                )
                VC_DUTY_ERRORS.labels("aggregate_fetch").inc()
                log.warn("aggregate fetch failed", slot=slot,
                         committee=d.committee_index, reason=reason,
                         error=f"{type(e).__name__}: {e}")
                acct.missed("aggregation", reason)
                continue
            msg = types.AggregateAndProof.make(
                aggregator_index=d.validator_index,
                aggregate=agg,
                selection_proof=proof,
            )
            sig = self.store.sign_aggregate_and_proof(d.pubkey, msg, types)
            signed = types.SignedAggregateAndProof.make(message=msg, signature=sig)
            try:
                accepted = self.nodes.first_success(
                    "publish_aggregates", [signed]
                ) or 0
            except BeaconNodeError as e:
                VC_DUTY_ERRORS.labels("aggregate_publish").inc()
                log.warn("aggregate publish failed", slot=slot,
                         error=f"{type(e).__name__}: {e}")
                acct.missed("aggregation", _miss_reason(e))
                continue
            count += accepted
            if accepted:
                acct.performed("aggregation")
            else:
                acct.missed("aggregation", "rejected")
        self.published += count
        return count


@dataclass
class SyncCommitteeService:
    """Sync-committee duty flow (sync_committee_service.rs): each slot sign
    the head root as a SyncCommitteeMessage per duty; at the aggregation
    phase produce SignedContributionAndProof for selected aggregators."""

    spec: ChainSpec
    store: ValidatorStore
    nodes: BeaconNodeFallback
    duties: list = field(default_factory=list)     # [SyncDuty]
    accountant: DutyAccountant = field(default_factory=DutyAccountant)
    published_messages: int = 0
    published_contributions: int = 0

    def poll(self, epoch: int) -> bool:
        indices = [
            v.index for v in self.store.validators.values() if v.index is not None
        ]
        my_pubkeys = set(self.store.voting_pubkeys())
        try:
            duties = self.nodes.first_success("sync_duties", epoch, indices)
        except BeaconNodeError as e:
            VC_DUTY_ERRORS.labels("duties_poll").inc()
            log.warn("sync duties poll failed", epoch=epoch,
                     error=f"{type(e).__name__}: {e}")
            return False
        self.duties = [d for d in duties if d.pubkey in my_pubkeys]
        return True

    def sign_and_publish(self, slot: int, head_root: bytes) -> int:
        if not self.duties:
            return 0
        acct = self.accountant
        acct.scheduled("sync_message", len(self.duties))
        types = types_for_slot(self.spec, slot)
        msgs = []
        for d in self.duties:
            try:
                sig = self.store.sign_sync_committee_message(d.pubkey, head_root)
            except DoppelgangerProtected:
                acct.missed("sync_message", "doppelganger")
                continue
            msgs.append(
                types.SyncCommitteeMessage.make(
                    slot=slot,
                    beacon_block_root=head_root,
                    validator_index=d.validator_index,
                    signature=sig,
                )
            )
        if not msgs:
            return 0
        try:
            n = self.nodes.first_success("publish_sync_messages", msgs)
        except BeaconNodeError as e:
            VC_DUTY_ERRORS.labels("sync_publish").inc()
            log.warn("sync message publish failed", slot=slot,
                     error=f"{type(e).__name__}: {e}")
            acct.missed("sync_message", _miss_reason(e), len(msgs))
            return 0
        acct.performed("sync_message", n)
        if n < len(msgs):
            acct.missed("sync_message", "rejected", len(msgs) - n)
        self.published_messages += n
        return n

    def aggregate(self, slot: int, head_root: bytes) -> int:
        if not self.duties:
            return 0
        acct = self.accountant
        types = types_for_slot(self.spec, slot)
        sub_size = (
            self.spec.preset.SYNC_COMMITTEE_SIZE
            // self.spec.sync_committee_subnet_count
        )
        count = 0
        for d in self.duties:
            for sub_idx in sorted({s for s, _ in d.positions}):
                try:
                    proof = self.store.sign_sync_selection_proof(
                        d.pubkey, slot, sub_idx, types
                    )
                except DoppelgangerProtected:
                    continue
                if not _is_aggregator(
                    proof, sub_size, self.spec.target_aggregators_per_sync_subcommittee
                ):
                    continue
                acct.scheduled("sync_contribution")
                try:
                    contrib = self.nodes.first_success(
                        "sync_committee_contribution", slot, sub_idx, head_root
                    )
                except BeaconNodeError as e:
                    reason = (
                        "no_contribution"
                        if "no contribution" in str(e).lower()
                        else _miss_reason(e)
                    )
                    VC_DUTY_ERRORS.labels("sync_contribution_fetch").inc()
                    log.warn("sync contribution fetch failed", slot=slot,
                             subcommittee=sub_idx, reason=reason,
                             error=f"{type(e).__name__}: {e}")
                    acct.missed("sync_contribution", reason)
                    continue
                msg = types.ContributionAndProof.make(
                    aggregator_index=d.validator_index,
                    contribution=contrib,
                    selection_proof=proof,
                )
                sig = self.store.sign_contribution_and_proof(d.pubkey, msg, types)
                signed = types.SignedContributionAndProof.make(message=msg, signature=sig)
                try:
                    accepted = self.nodes.first_success(
                        "publish_contributions", [signed]
                    )
                except BeaconNodeError as e:
                    VC_DUTY_ERRORS.labels("sync_contribution_publish").inc()
                    log.warn("sync contribution publish failed", slot=slot,
                             error=f"{type(e).__name__}: {e}")
                    acct.missed("sync_contribution", _miss_reason(e))
                    continue
                count += accepted
                if accepted:
                    acct.performed("sync_contribution")
                else:
                    acct.missed("sync_contribution", "rejected")
        self.published_contributions += count
        return count


@dataclass
class PreparationService:
    """Proposer preparation (preparation_service.rs): push fee recipients to
    the BN every epoch so payload building can attribute fees."""

    spec: ChainSpec
    store: ValidatorStore
    nodes: BeaconNodeFallback
    fee_recipients: dict = field(default_factory=dict)   # pubkey -> address(20B)
    default_fee_recipient: bytes = b"\x00" * 20

    def set_fee_recipient(self, pubkey: bytes, address: bytes) -> None:
        self.fee_recipients[pubkey] = address

    def prepare(self, _epoch: int) -> int:
        preparations = [
            {
                "validator_index": v.index,
                "fee_recipient": self.fee_recipients.get(
                    pk, self.default_fee_recipient
                ),
            }
            for pk, v in self.store.validators.items()
            if v.index is not None
        ]
        if not preparations:
            return 0
        return self.nodes.first_success("prepare_beacon_proposer", preparations)


@dataclass
class BlockService:
    spec: ChainSpec
    store: ValidatorStore
    duties: DutiesService
    nodes: BeaconNodeFallback
    produce_block_fn: object = None   # (slot, randao_reveal) -> unsigned block
    graffiti: bytes | None = None     # per-VC graffiti (--graffiti)
    accountant: DutyAccountant = field(default_factory=DutyAccountant)
    published: int = 0

    def propose(self, slot: int) -> int:
        count = 0
        for d in self.duties.proposers_at_slot(slot):
            if self.propose_duty(d) is not None:
                count += 1
        return count

    def propose_duty(self, d) -> bytes | None:
        """Perform ONE proposer duty end to end: produce (via fn or node),
        sign under slashing protection, publish. Returns the block root on
        success, None on an accounted miss."""
        acct = self.accountant
        acct.scheduled("proposal")
        slot = d.slot
        types = types_for_slot(self.spec, slot)
        epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
        try:
            randao = self.store.sign_randao(d.pubkey, epoch)
        except DoppelgangerProtected:
            acct.missed("proposal", "doppelganger")
            return None
        try:
            if self.produce_block_fn is not None:
                block = self.produce_block_fn(slot, randao)
            else:
                block = self.nodes.first_success(
                    "produce_block", slot, randao, types, self.graffiti
                )
        except Exception as e:  # noqa: BLE001 — production failed
            VC_DUTY_ERRORS.labels("block_produce").inc()
            log.warn("block production failed", slot=slot,
                     error=f"{type(e).__name__}: {e}")
            acct.missed("proposal", _miss_reason(e))
            return None
        try:
            sig = self.store.sign_block(d.pubkey, block, types)
        except SlashingProtectionError:
            acct.missed("proposal", "slashing_protection")
            return None
        except DoppelgangerProtected:
            acct.missed("proposal", "doppelganger")
            return None
        signed = types.SignedBeaconBlock.make(message=block, signature=sig)
        try:
            self.nodes.first_success("publish_block", signed, types)
        except BeaconNodeError as e:
            VC_DUTY_ERRORS.labels("block_publish").inc()
            log.warn("block publish failed", slot=slot,
                     error=f"{type(e).__name__}: {e}")
            acct.missed("proposal", _miss_reason(e))
            return None
        acct.performed("proposal")
        self.published += 1
        return types.BeaconBlock.hash_tree_root(block)


@dataclass
class DoppelgangerService:
    """Quarantine new validators for N epochs while watching for their
    signatures on the network (doppelganger_service.rs)."""

    spec: ChainSpec
    store: ValidatorStore
    epochs_to_watch: int = 2
    _watch_until: dict = field(default_factory=dict)   # pubkey -> epoch

    def register(self, pubkey: bytes, current_epoch: int) -> None:
        self._watch_until[pubkey] = current_epoch + self.epochs_to_watch
        self.store.set_doppelganger_safe(pubkey, False)

    def observe_liveness(self, pubkey: bytes) -> None:
        """Another instance signed with this key: NEVER enable it."""
        if pubkey in self._watch_until:
            self._watch_until[pubkey] = 2**63  # poisoned
        self.store.set_doppelganger_safe(pubkey, False)

    def on_epoch(self, current_epoch: int) -> None:
        for pk, until in list(self._watch_until.items()):
            if current_epoch >= until:
                self.store.set_doppelganger_safe(pk, True)
                del self._watch_until[pk]
