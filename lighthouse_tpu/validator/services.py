"""Validator-client services: duties polling, attestations, proposals,
doppelganger quarantine.

Parity surface: /root/reference/validator_client/src/ — DutiesService
(duties_service.rs:208: per-epoch attester/proposer duty maps keyed by
dependent root, selection-proof precompute), AttestationService
(attestation_service.rs:176-493: slot+1/3 produce/sign/publish, slot+2/3
aggregate), BlockService (block_service.rs), DoppelgangerService
(doppelganger_service.rs: 2-epoch liveness quarantine before signing).

Scheduling is tick-driven and synchronous (`on_slot(slot, phase)`) so the
same code runs under the deterministic in-process simulator (manual clock)
or a wall-clock loop — logical time is the testing idiom the reference gets
from TestingSlotClock (SURVEY §4).
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field

from ..types import helpers as h
from ..types.spec import ChainSpec
from ..state_transition.slot import types_for_slot
from .beacon_node import BeaconNodeFallback
from .slashing_protection import SlashingProtectionError
from .validator_store import DoppelgangerProtected, ValidatorStore


@dataclass
class DutiesService:
    spec: ChainSpec
    store: ValidatorStore
    nodes: BeaconNodeFallback
    attester_duties: dict = field(default_factory=dict)   # epoch -> [AttesterDuty]
    proposer_duties: dict = field(default_factory=dict)   # epoch -> [ProposerDuty]

    def poll(self, current_epoch: int) -> None:
        """Refresh duty maps for current and next epoch (duties_service.rs
        poll loop)."""
        my_pubkeys = set(self.store.voting_pubkeys())
        # resolve indices
        indices = [
            v.index for v in self.store.validators.values() if v.index is not None
        ]
        for epoch in (current_epoch, current_epoch + 1):
            duties = self.nodes.first_success("attester_duties", epoch, indices)
            self.attester_duties[epoch] = [
                d for d in duties if d.pubkey in my_pubkeys
            ]
            proposals = self.nodes.first_success("proposer_duties", epoch)
            self.proposer_duties[epoch] = [
                d for d in proposals if d.pubkey in my_pubkeys
            ]
        # prune old epochs
        for e in list(self.attester_duties):
            if e < current_epoch:
                del self.attester_duties[e]
        for e in list(self.proposer_duties):
            if e < current_epoch:
                del self.proposer_duties[e]

    def attesters_at_slot(self, slot: int):
        epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
        return [d for d in self.attester_duties.get(epoch, []) if d.slot == slot]

    def proposers_at_slot(self, slot: int):
        epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
        return [d for d in self.proposer_duties.get(epoch, []) if d.slot == slot]


@dataclass
class AttestationService:
    spec: ChainSpec
    store: ValidatorStore
    duties: DutiesService
    nodes: BeaconNodeFallback
    published: int = 0
    failed: int = 0

    def attest(self, slot: int) -> int:
        """Produce+sign+publish attestations for all duties at `slot`
        (the slot+1/3 phase of attestation_service.rs)."""
        duties = self.duties.attesters_at_slot(slot)
        if not duties:
            return 0
        types = types_for_slot(self.spec, slot)
        by_committee: dict[int, list] = defaultdict(list)
        for d in duties:
            by_committee[d.committee_index].append(d)
        produced = 0
        for cidx, ds in by_committee.items():
            data = self.nodes.first_success("attestation_data", slot, cidx, types)
            atts = []
            for d in ds:
                bits = [False] * d.committee_length
                bits[d.committee_position] = True
                try:
                    sig = self.store.sign_attestation(d.pubkey, data, types)
                except (SlashingProtectionError, DoppelgangerProtected):
                    self.failed += 1
                    continue
                atts.append(
                    types.Attestation.make(
                        aggregation_bits=bits, data=data, signature=sig
                    )
                )
            if atts:
                produced += self.nodes.first_success(
                    "publish_attestations", atts, types
                )
        self.published += produced
        return produced


def _is_aggregator(selection_proof: bytes, committee_len: int, target: int) -> bool:
    """Spec is_aggregator: hash(proof)[0:8] LE mod max(1, len // target) == 0."""
    import hashlib

    modulo = max(1, committee_len // target)
    return int.from_bytes(hashlib.sha256(selection_proof).digest()[:8], "little") % modulo == 0


@dataclass
class AggregationService:
    """The slot+2/3 aggregate phase of attestation_service.rs:493 — for each
    duty where we are the selected aggregator, fetch the naive-pool
    aggregate from the BN, wrap and sign AggregateAndProof, publish."""

    spec: ChainSpec
    store: ValidatorStore
    duties: DutiesService
    nodes: BeaconNodeFallback
    published: int = 0

    def aggregate(self, slot: int) -> int:
        duties = self.duties.attesters_at_slot(slot)
        if not duties:
            return 0
        types = types_for_slot(self.spec, slot)
        count = 0
        for d in duties:
            try:
                proof = self.store.sign_selection_proof(d.pubkey, slot)
            except (SlashingProtectionError, DoppelgangerProtected):
                continue
            if not _is_aggregator(
                proof, d.committee_length, self.spec.target_aggregators_per_committee
            ):
                continue
            data = self.nodes.first_success("attestation_data", slot, d.committee_index)
            data_root = types.AttestationData.hash_tree_root(data)
            try:
                agg = self.nodes.first_success("aggregate_attestation", slot, data_root)
            except Exception:
                continue
            msg = types.AggregateAndProof.make(
                aggregator_index=d.validator_index,
                aggregate=agg,
                selection_proof=proof,
            )
            sig = self.store.sign_aggregate_and_proof(d.pubkey, msg, types)
            signed = types.SignedAggregateAndProof.make(message=msg, signature=sig)
            count += self.nodes.first_success("publish_aggregates", [signed]) or 0
        self.published += count
        return count


@dataclass
class SyncCommitteeService:
    """Sync-committee duty flow (sync_committee_service.rs): each slot sign
    the head root as a SyncCommitteeMessage per duty; at the aggregation
    phase produce SignedContributionAndProof for selected aggregators."""

    spec: ChainSpec
    store: ValidatorStore
    nodes: BeaconNodeFallback
    duties: list = field(default_factory=list)     # [SyncDuty]
    published_messages: int = 0
    published_contributions: int = 0

    def poll(self, epoch: int) -> None:
        indices = [
            v.index for v in self.store.validators.values() if v.index is not None
        ]
        my_pubkeys = set(self.store.voting_pubkeys())
        duties = self.nodes.first_success("sync_duties", epoch, indices)
        self.duties = [d for d in duties if d.pubkey in my_pubkeys]

    def sign_and_publish(self, slot: int, head_root: bytes) -> int:
        if not self.duties:
            return 0
        types = types_for_slot(self.spec, slot)
        msgs = []
        for d in self.duties:
            try:
                sig = self.store.sign_sync_committee_message(d.pubkey, head_root)
            except DoppelgangerProtected:
                continue
            msgs.append(
                types.SyncCommitteeMessage.make(
                    slot=slot,
                    beacon_block_root=head_root,
                    validator_index=d.validator_index,
                    signature=sig,
                )
            )
        if not msgs:
            return 0
        n = self.nodes.first_success("publish_sync_messages", msgs)
        self.published_messages += n
        return n

    def aggregate(self, slot: int, head_root: bytes) -> int:
        if not self.duties:
            return 0
        types = types_for_slot(self.spec, slot)
        sub_size = (
            self.spec.preset.SYNC_COMMITTEE_SIZE
            // self.spec.sync_committee_subnet_count
        )
        count = 0
        for d in self.duties:
            for sub_idx in sorted({s for s, _ in d.positions}):
                try:
                    proof = self.store.sign_sync_selection_proof(
                        d.pubkey, slot, sub_idx, types
                    )
                except DoppelgangerProtected:
                    continue
                if not _is_aggregator(
                    proof, sub_size, self.spec.target_aggregators_per_sync_subcommittee
                ):
                    continue
                try:
                    contrib = self.nodes.first_success(
                        "sync_committee_contribution", slot, sub_idx, head_root
                    )
                except Exception:
                    continue
                msg = types.ContributionAndProof.make(
                    aggregator_index=d.validator_index,
                    contribution=contrib,
                    selection_proof=proof,
                )
                sig = self.store.sign_contribution_and_proof(d.pubkey, msg, types)
                signed = types.SignedContributionAndProof.make(message=msg, signature=sig)
                count += self.nodes.first_success("publish_contributions", [signed])
        self.published_contributions += count
        return count


@dataclass
class PreparationService:
    """Proposer preparation (preparation_service.rs): push fee recipients to
    the BN every epoch so payload building can attribute fees."""

    spec: ChainSpec
    store: ValidatorStore
    nodes: BeaconNodeFallback
    fee_recipients: dict = field(default_factory=dict)   # pubkey -> address(20B)
    default_fee_recipient: bytes = b"\x00" * 20

    def set_fee_recipient(self, pubkey: bytes, address: bytes) -> None:
        self.fee_recipients[pubkey] = address

    def prepare(self, _epoch: int) -> int:
        preparations = [
            {
                "validator_index": v.index,
                "fee_recipient": self.fee_recipients.get(
                    pk, self.default_fee_recipient
                ),
            }
            for pk, v in self.store.validators.items()
            if v.index is not None
        ]
        if not preparations:
            return 0
        return self.nodes.first_success("prepare_beacon_proposer", preparations)


@dataclass
class BlockService:
    spec: ChainSpec
    store: ValidatorStore
    duties: DutiesService
    nodes: BeaconNodeFallback
    produce_block_fn: object = None   # (slot, randao_reveal) -> unsigned block
    graffiti: bytes | None = None     # per-VC graffiti (--graffiti)
    published: int = 0

    def propose(self, slot: int) -> int:
        duties = self.duties.proposers_at_slot(slot)
        count = 0
        for d in duties:
            types = types_for_slot(self.spec, slot)
            epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
            randao = self.store.sign_randao(d.pubkey, epoch)
            if self.produce_block_fn is not None:
                block = self.produce_block_fn(slot, randao)
            else:
                block = self.nodes.first_success(
                    "produce_block", slot, randao, types, self.graffiti
                )
            try:
                sig = self.store.sign_block(d.pubkey, block, types)
            except (SlashingProtectionError, DoppelgangerProtected):
                continue
            signed = types.SignedBeaconBlock.make(message=block, signature=sig)
            self.nodes.first_success("publish_block", signed, types)
            count += 1
        self.published += count
        return count


@dataclass
class DoppelgangerService:
    """Quarantine new validators for N epochs while watching for their
    signatures on the network (doppelganger_service.rs)."""

    spec: ChainSpec
    store: ValidatorStore
    epochs_to_watch: int = 2
    _watch_until: dict = field(default_factory=dict)   # pubkey -> epoch

    def register(self, pubkey: bytes, current_epoch: int) -> None:
        self._watch_until[pubkey] = current_epoch + self.epochs_to_watch
        self.store.set_doppelganger_safe(pubkey, False)

    def observe_liveness(self, pubkey: bytes) -> None:
        """Another instance signed with this key: NEVER enable it."""
        if pubkey in self._watch_until:
            self._watch_until[pubkey] = 2**63  # poisoned
        self.store.set_doppelganger_safe(pubkey, False)

    def on_epoch(self, current_epoch: int) -> None:
        for pk, until in list(self._watch_until.items()):
            if current_epoch >= until:
                self.store.set_doppelganger_safe(pk, True)
                del self._watch_until[pk]
