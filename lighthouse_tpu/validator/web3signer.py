"""Remote-signer signing method (web3signer).

Parity surface: /root/reference/validator_client/src/signing_method.rs:80 —
SigningMethod::Web3Signer posts the signing root (plus typed context) to
{url}/api/v1/eth2/sign/{pubkey} and parses the returned signature. The VC
treats local-keystore and remote signers identically behind the
ValidatorStore facade; tests run against an in-process mock signer exactly
like the reference's testing/web3signer_tests rig runs a real binary."""

from __future__ import annotations

import json
import urllib.request


class Web3SignerError(Exception):
    pass


class Web3Signer:
    """Signer duck-type (same .sign(root) surface as LocalSigner)."""

    def __init__(self, url: str, pubkey: bytes, timeout: float = 5.0):
        self.url = url.rstrip("/")
        self.pubkey = bytes(pubkey)
        self.timeout = timeout

    def sign(self, signing_root: bytes):
        from ..crypto import bls

        body = json.dumps(
            {
                "type": "BEACON_BLOCK_ROOT",   # generic root-signing envelope
                "signingRoot": "0x" + signing_root.hex(),
            }
        ).encode()
        req = urllib.request.Request(
            f"{self.url}/api/v1/eth2/sign/0x{self.pubkey.hex()}",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001 — surfaced as signer failure
            raise Web3SignerError(f"remote signing failed: {e}") from e
        sig_hex = payload["signature"] if isinstance(payload, dict) else payload
        return bls.Signature.deserialize(bytes.fromhex(sig_hex[2:]))


class MockWeb3SignerServer:
    """In-process web3signer double: signs with held keys over HTTP
    (the testing/web3signer_tests analog without the Java binary)."""

    def __init__(self, keypairs, host: str = "127.0.0.1", port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        import threading

        from ..crypto import bls

        sks = {kp.pk.serialize(): kp.sk for kp in keypairs}

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                import re

                m = re.match(r"^/api/v1/eth2/sign/0x([0-9a-f]{96})$", self.path)
                if not m:
                    self.send_error(404)
                    return
                pk = bytes.fromhex(m.group(1))
                sk = sks.get(pk)
                if sk is None:
                    self.send_error(404, "unknown key")
                    return
                ln = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(ln).decode())
                root = bytes.fromhex(body["signingRoot"][2:])
                sig = bls.sign(sk, root).serialize()
                out = json.dumps({"signature": "0x" + sig.hex()}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.url = f"http://{host}:{self.server.server_address[1]}"
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self._thread.start()

    def close(self):
        self.server.shutdown()
