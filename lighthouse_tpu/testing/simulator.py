"""In-process multi-node simulation over real TCP sockets.

The analog of /root/reference/testing/simulator/src/basic_sim.rs: N full
nodes (BeaconChain + NetworkNode + op pool) in one process, connected over
localhost TCP, the validator set split across nodes. Each simulated slot:
the owning node produces/signs/publishes the block over gossip, every node
publishes single-bit attestations for its own validators to subnet topics,
and the sim asserts convergence (shared head) and — over enough epochs —
advancing finalization (checks.rs)."""

from __future__ import annotations

import time

from ..chain.beacon_chain import BeaconChain
from ..chain.op_pool import OperationPool
from ..crypto import bls
from ..network import gossip as gs
from ..network.node import NetworkNode
from ..state_transition import accessors as acc
from ..state_transition.slot import process_slots, types_for_slot
from ..types import helpers as h
from ..types.spec import DOMAIN_BEACON_ATTESTER, ForkName
from .harness import StateHarness, _sign, clone_state


class SimNode:
    def __init__(self, sim, index: int, validator_indices: list[int]):
        self.sim = sim
        self.index = index
        self.validators = set(validator_indices)
        self.chain = BeaconChain(
            sim.spec, clone_state(sim.harness.state, sim.spec)
        )
        self.op_pool = OperationPool(sim.spec)
        self.net = NetworkNode(
            self.chain,
            f"node{index}",
            heartbeat_interval=0.1,
            subnets=sim.subnets,
            op_pool=self.op_pool,
        )


class Simulator:
    def __init__(
        self,
        spec,
        n_nodes: int = 4,
        n_validators: int = 64,
        subnets: int = 4,
    ):
        self.spec = spec
        self.subnets = subnets
        self.harness = StateHarness.new(spec, n_validators)
        per = n_validators // n_nodes
        self.nodes = [
            SimNode(
                self,
                i,
                list(range(i * per, (i + 1) * per if i < n_nodes - 1 else n_validators)),
            )
            for i in range(n_nodes)
        ]
        # full mesh (the reference sim connects all nodes on localhost too)
        for i, a in enumerate(self.nodes):
            for b in self.nodes[i + 1 :]:
                a.net.connect(b.net)
        self._wait(lambda: all(
            len(n.net.host.connections) == n_nodes - 1 for n in self.nodes
        ), 20.0, "node connections")
        # Subscription announcements ride the connections asynchronously;
        # publishing before every peer KNOWS every other peer subscribes
        # races the flood-publish fallback (a message can miss a node with
        # no mesh to relay it yet). Wait until the block topic is mutually
        # known — the real node tolerates this via IHAVE recovery windows,
        # the lock-step sim must not start with a partitioned view.
        block_topic = gs.topic_name(self.nodes[0].net.fork_digest, "beacon_block")
        self._wait(lambda: all(
            block_topic in a.net.gossipsub.peer_topics.get(b.net.node_id, set())
            for a in self.nodes
            for b in self.nodes
            if a is not b
        ), 20.0, "subscription propagation")

    # ------------------------------------------------------------ helpers

    @staticmethod
    def _wait(cond, timeout: float, what: str) -> None:
        deadline = time.monotonic() + timeout
        while not cond():
            if time.monotonic() > deadline:
                raise TimeoutError(f"timed out waiting for {what}")
            time.sleep(0.01)

    def node_for_validator(self, vi: int) -> SimNode:
        for n in self.nodes:
            if vi in n.validators:
                return n
        raise KeyError(vi)

    # ------------------------------------------------------------ slot driving

    def run_slot(self) -> bytes:
        spec = self.spec
        slot = self.nodes[0].chain.head_state().slot + 1
        for n in self.nodes:
            n.chain.slot_clock.set_slot(slot)
            n.chain.per_slot_task()

        # 1. proposer's node produces + publishes the block
        ref = self.nodes[0].chain
        pre = clone_state(ref.head_state(), spec)
        if pre.slot < slot:
            process_slots(pre, spec, slot)
        proposer = acc.get_beacon_proposer_index(pre, spec)
        owner = self.node_for_validator(proposer)
        epoch = h.compute_epoch_at_slot(slot, spec)
        reveal = self.harness.randao_reveal(pre, proposer, epoch)
        types = types_for_slot(spec, slot)
        block = owner.chain.produce_block(slot, reveal, op_pool=owner.op_pool)
        signed = self.harness.sign_block(block, types)
        root = types.BeaconBlock.hash_tree_root(block)
        # import locally, then gossip to the rest
        owner.chain.process_block(signed, block_root=root)
        owner.net.publish_block(signed)
        self._wait(
            lambda: all(n.chain.head_root == root for n in self.nodes),
            60.0,
            f"block propagation at slot {slot}",
        )

        # 2. every node attests for its own validators (single-bit gossip)
        post = owner.chain.head_state()
        cache = acc.build_committee_cache(post, spec, epoch)
        start_slot = h.compute_start_slot_at_epoch(epoch, spec)
        if slot == start_slot:
            target_root = root
        else:
            target_root = post.block_roots[
                start_slot % spec.preset.SLOTS_PER_HISTORICAL_ROOT
            ]
        source = post.current_justified_checkpoint
        domain = h.get_domain(post, spec, DOMAIN_BEACON_ATTESTER, epoch)
        electra = spec.fork_name_at_slot(slot) >= ForkName.electra
        published = 0
        for cidx in range(cache.committees_per_slot):
            committee = cache.committee(slot, cidx)
            data = types.AttestationData.make(
                slot=slot,
                index=0 if electra else cidx,
                beacon_block_root=root,
                source=source,
                target=types.Checkpoint.make(epoch=epoch, root=target_root),
            )
            signing_root = h.compute_signing_root(types.AttestationData, data, domain)
            subnet = gs.compute_subnet_for_attestation(
                cache.committees_per_slot, slot, cidx, spec
            ) % self.subnets
            for pos, vi in enumerate(committee):
                node = self.node_for_validator(vi)
                bits = [p == pos for p in range(len(committee))]
                sig = _sign(self.harness.sk(vi), signing_root).serialize()
                kwargs = dict(aggregation_bits=bits, data=data, signature=sig)
                if electra:
                    cb = [False] * spec.preset.MAX_COMMITTEES_PER_SLOT
                    cb[cidx] = True
                    kwargs["committee_bits"] = cb
                att = types.Attestation.make(**kwargs)
                # verify + pool locally, then gossip
                with node.net._lock:
                    results = node.chain.verify_unaggregated_attestations([att])
                    for a, idxs in results:
                        node.chain.apply_attestation_to_fork_choice(a, idxs)
                        node.op_pool.insert_attestation(a, idxs, types)
                node.net.publish_attestation(att, subnet)
                published += 1
        # wait for attestation fan-out: every node should have pooled
        # (close to) all attesting validators for this slot
        want = int(published * 0.95)

        def pooled(n):
            seen = set()
            for bucket in n.op_pool.attestations.values():
                for e in bucket:
                    if e.data.slot == slot:
                        seen |= e.attesting_indices
            return len(seen)

        self._wait(
            lambda: all(pooled(n) >= want for n in self.nodes),
            60.0,
            f"attestation propagation at slot {slot}",
        )
        return root

    def run_epochs(self, n_epochs: int) -> None:
        for _ in range(n_epochs * self.spec.preset.SLOTS_PER_EPOCH):
            self.run_slot()

    # ------------------------------------------------------------ checks

    def finalized_epoch(self) -> int:
        return self.nodes[0].chain.fork_choice.store.finalized_checkpoint[0]

    def heads_agree(self) -> bool:
        heads = {n.chain.head_root for n in self.nodes}
        return len(heads) == 1

    def close(self) -> None:
        for n in self.nodes:
            n.net.close()
