"""In-process multi-node simulation over real TCP sockets.

The analog of /root/reference/testing/simulator/src/basic_sim.rs: N full
nodes (BeaconChain + NetworkNode + op pool) in one process, connected over
localhost TCP, the validator set split across nodes. Each simulated slot:
the owning node produces/signs/publishes the block over gossip, every node
publishes single-bit attestations for its own validators to subnet topics,
and the sim asserts convergence (shared head) and — over enough epochs —
advancing finalization (checks.rs).

The slot-driving machinery now lives in `loadgen/multinode.py`
(`MultiNodeHarness`), which generalizes it with fork-aware cluster
production and network fault injection (partitions, churn, equivocation —
the `bn loadtest` multi-node scenario families). `Simulator` is the
happy-path specialization that the original basic-sim tests consume: no
injector, gossip batching through the real BeaconProcessor, and a
wall-clock heartbeat thread like a live node."""

from __future__ import annotations

from ..loadgen.multinode import MultiNode, MultiNodeHarness

# re-export: SimNode was this module's node container before the promotion
SimNode = MultiNode


class Simulator(MultiNodeHarness):
    def __init__(
        self,
        spec,
        n_nodes: int = 4,
        n_validators: int = 64,
        subnets: int = 4,
    ):
        super().__init__(
            spec,
            n_nodes,
            n_validators,
            subnets=subnets,
            # the happy-path sim keeps the live-node wiring the fault
            # harness trades away for determinism: gossip batched through
            # the BeaconProcessor, heartbeats on their own timer thread
            batch_gossip=True,
            heartbeat_interval=0.1,
        )

    def run_epochs(self, n_epochs: int) -> None:
        for _ in range(n_epochs * self.spec.preset.SLOTS_PER_EPOCH):
            self.run_slot()

    def finalized_epoch(self) -> int:
        return self.nodes[0].chain.fork_choice.store.finalized_checkpoint[0]
