"""EF consensus-spec-tests vector runner.

Parity surface: /root/reference/testing/ef_tests/src/handler.rs:10-32 — a
Handler walks `tests/{config}/{fork}/{runner}/{handler}/{suite}/{case}/`
directories and dispatches each case directory to a typed runner; every
file in a consumed case must be read (check_all_files_accessed.py analog:
`run_case` records accesses and `assert_all_files_accessed` fails on
leftovers).

Vector format is the official one (pre/post.ssz_snappy, meta.yaml,
blocks_N.ssz_snappy, data.yaml ...), so official tarballs dropped under the
vector root run unchanged. The environment has no network egress, so the
committed vectors under tests/ef/vectors are regression vectors generated
by scripts/gen_ef_vectors.py from this implementation (frozen at
generation time — they pin behavior across refactors exactly like the
reference pins against upstream vectors).

Case runners implemented: ssz_static, shuffling, sanity/slots,
sanity/blocks, operations/*, epoch_processing/*, finality, bls/*, kzg/*.
"""

from __future__ import annotations

import os
from pathlib import Path

import yaml

from ..crypto import bls
from ..network import snappy
from ..state_transition import accessors as acc
from ..state_transition import block as blk
from ..state_transition import epoch as ep
from ..state_transition.block import BlockProcessingError, SignatureStrategy, per_block_processing
from ..state_transition.slot import process_slots, types_for_slot, upgrade_state
from ..types.containers import spec_types
from ..types.helpers import compute_shuffled_index
from ..types.spec import ForkName, mainnet_spec, minimal_spec


class EfTestError(AssertionError):
    pass


class VectorAccess:
    """Tracks file reads so unconsumed vector files fail the run."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self.accessed: set[Path] = set()

    def read(self, case_dir: Path, name: str) -> bytes | None:
        p = case_dir / name
        if not p.exists():
            return None
        self.accessed.add(p)
        return p.read_bytes()

    def read_ssz(self, case_dir: Path, name: str) -> bytes | None:
        raw = self.read(case_dir, name)
        if raw is None:
            return None
        return snappy.decompress(raw)

    def read_yaml(self, case_dir: Path, name: str):
        raw = self.read(case_dir, name)
        if raw is None:
            return None
        return yaml.safe_load(raw.decode())

    def assert_all_files_accessed(self) -> None:
        all_files = {p for p in self.root.rglob("*") if p.is_file()}
        left = all_files - self.accessed
        if left:
            raise EfTestError(
                f"{len(left)} vector files never consumed, e.g. "
                f"{sorted(left)[:5]}"
            )


_FORK_ORDER = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra"]


def spec_at_fork(config: str, fork: str, fork_epoch_overrides: dict | None = None):
    """A spec with `fork` active from genesis and LATER forks disabled —
    vectors under {config}/{fork}/ must run with that fork's rules (the
    reference monomorphizes per fork; we pin the runtime spec instead)."""
    overrides: dict = {}
    for i, f in enumerate(_FORK_ORDER[1:], start=1):
        overrides[f + "_fork_epoch"] = 0 if i <= _FORK_ORDER.index(fork) else None
    if fork_epoch_overrides:
        overrides.update(fork_epoch_overrides)
    if config == "minimal":
        return minimal_spec(**overrides)
    import dataclasses

    return dataclasses.replace(mainnet_spec(), **overrides)


def _spec_for(config: str, fork: str = "deneb"):
    return spec_at_fork(config, fork)


def _fork_types(spec, fork: str):
    return spec_types(spec.preset, ForkName[fork])


def _verify_now(batch_sets: list) -> None:
    if batch_sets and not bls.verify_signature_sets(batch_sets):
        raise BlockProcessingError("signature invalid")


def _op_attestation(st, sp, t, op, f):
    sets: list = []
    blk.process_attestation(st, sp, t, op, f, sets.append, _pkg(st), {})
    _verify_now(sets)


def _op_attester_slashing(st, sp, t, op, f):
    sets: list = []
    blk.process_attester_slashing(st, sp, t, op, f, sets.append, _pkg(st))
    _verify_now(sets)


def _op_proposer_slashing(st, sp, t, op, f):
    sets: list = []
    blk.process_proposer_slashing(st, sp, t, op, f, sets.append, _pkg(st))
    _verify_now(sets)


def _op_voluntary_exit(st, sp, t, op, f):
    sets: list = []
    blk.process_voluntary_exit(st, sp, t, op, sets.append, _pkg(st))
    _verify_now(sets)


def _op_bls_change(st, sp, t, op, f):
    sets: list = []
    blk.process_bls_to_execution_change(st, sp, t, op, sets.append)
    _verify_now(sets)


def _op_sync_aggregate(st, sp, t, op, f):
    import types as _pytypes

    shim = _pytypes.SimpleNamespace(
        slot=st.slot, body=_pytypes.SimpleNamespace(sync_aggregate=op)
    )
    sets: list = []
    blk.process_sync_aggregate(st, sp, t, shim, sets.append, _pkg(st))
    _verify_now(sets)


def _electra_op(fn):
    def run(st, sp, t, op, f):
        from ..state_transition import electra as el

        getattr(el, fn)(st, sp, t, op)

    return run


OPERATION_RUNNERS = {
    # handler name -> (input file stem, apply(state, spec, types, op, fork))
    "attestation": ("attestation", _op_attestation),
    "attester_slashing": ("attester_slashing", _op_attester_slashing),
    "proposer_slashing": ("proposer_slashing", _op_proposer_slashing),
    "deposit": ("deposit", lambda st, sp, t, op, f: blk.process_deposit(st, sp, t, op, f)),
    "voluntary_exit": ("voluntary_exit", _op_voluntary_exit),
    "bls_to_execution_change": ("address_change", _op_bls_change),
    "sync_aggregate": ("sync_aggregate", _op_sync_aggregate),
    # electra execution requests (EIP-6110/7002/7251)
    "deposit_request": ("deposit_request", _electra_op("process_deposit_request")),
    "withdrawal_request": (
        "withdrawal_request", _electra_op("process_withdrawal_request"),
    ),
    "consolidation_request": (
        "consolidation_request", _electra_op("process_consolidation_request"),
    ),
}

def _rewards_and_penalties(st, sp, t, f):
    if f == ForkName.phase0:
        ep._process_rewards_and_penalties_phase0(st, sp, t)
    else:
        ep.process_rewards_and_penalties_altair(st, sp, f)


def _registry_updates(st, sp, t, f):
    if f >= ForkName.electra:
        from ..state_transition import electra as el

        el.process_registry_updates_electra(st, sp)
    else:
        ep.process_registry_updates(st, sp)


def _slashings(st, sp, t, f):
    if f >= ForkName.electra:
        from ..state_transition import electra as el

        el.process_slashings_electra(st, sp)
    else:
        ep.process_slashings(st, sp, f)


def _effective_balances(st, sp, t, f):
    if f >= ForkName.electra:
        from ..state_transition import electra as el

        el.process_effective_balance_updates_electra(st, sp)
    else:
        ep.process_effective_balance_updates(st, sp)


def _participation_records(st, sp, t, f):
    # phase0: rotate the pending-attestation records
    st.previous_epoch_attestations = st.current_epoch_attestations
    st.current_epoch_attestations = []


def _pending_deposits(st, sp, t, f):
    from ..state_transition import electra as el

    el.process_pending_deposits(st, sp, t)


def _pending_consolidations(st, sp, t, f):
    from ..state_transition import electra as el

    el.process_pending_consolidations(st, sp)


EPOCH_RUNNERS = {
    # handler -> fn(state, spec, types, fork); fork-dispatching where the
    # spec's transition differs per fork
    "justification_and_finalization": lambda st, sp, t, f: ep.process_justification_and_finalization(st, sp, t, f),
    "inactivity_updates": lambda st, sp, t, f: ep.process_inactivity_updates(st, sp),
    "rewards_and_penalties": _rewards_and_penalties,
    "registry_updates": _registry_updates,
    "slashings": _slashings,
    "effective_balance_updates": _effective_balances,
    "eth1_data_reset": lambda st, sp, t, f: ep.process_eth1_data_reset(st, sp),
    "slashings_reset": lambda st, sp, t, f: ep.process_slashings_reset(st, sp),
    "randao_mixes_reset": lambda st, sp, t, f: ep.process_randao_mixes_reset(st, sp),
    "historical_roots_update": lambda st, sp, t, f: ep.process_historical_roots_update(st, sp, t),
    "historical_summaries_update": lambda st, sp, t, f: ep.process_historical_summaries_update(st, sp, t),
    "participation_flag_updates": lambda st, sp, t, f: ep.process_participation_flag_updates(st),
    "participation_record_updates": _participation_records,
    "sync_committee_updates": lambda st, sp, t, f: ep.process_sync_committee_updates(st, sp, t),
    "pending_deposits": _pending_deposits,
    "pending_consolidations": _pending_consolidations,
}


def _pkg(state):
    """Pubkey getter over the state registry (EF vectors carry no cache)."""
    cache: dict[int, object] = {}

    def get(i: int):
        if i not in cache:
            cache[i] = bls.PublicKey.deserialize(bytes(state.validators[i].pubkey))
        return cache[i]

    return get


def run_case(va: VectorAccess, config: str, fork: str, runner: str,
             handler: str, case_dir: Path) -> None:
    """Dispatch one case directory. Raises EfTestError on mismatch."""
    spec = _spec_for(config, fork)
    types = _fork_types(spec, fork)

    if runner == "ssz_static":
        _run_ssz_static(va, types, handler, case_dir)
    elif runner == "shuffling":
        _run_shuffling(va, spec, case_dir)
    elif runner == "sanity" and handler == "slots":
        _run_sanity_slots(va, spec, types, case_dir)
    elif runner == "sanity" and handler == "blocks":
        _run_sanity_blocks(va, spec, types, fork, case_dir)
    elif runner == "finality":
        _run_sanity_blocks(va, spec, types, fork, case_dir)
    elif runner == "operations":
        _run_operation(va, spec, types, fork, handler, case_dir)
    elif runner == "epoch_processing":
        _run_epoch(va, spec, types, fork, handler, case_dir)
    elif runner == "rewards":
        _run_rewards(va, spec, types, fork, case_dir)
    elif runner == "fork":
        _run_fork_upgrade(va, spec, fork, case_dir)
    elif runner == "transition":
        _run_transition(va, config, fork, case_dir)
    elif runner == "fork_choice":
        _run_fork_choice(va, spec, fork, case_dir)
    elif runner == "bls":
        _run_bls(va, handler, case_dir)
    elif runner == "kzg":
        _run_kzg(va, handler, case_dir)
    else:
        raise EfTestError(f"no runner for {runner}/{handler}")


# ------------------------------------------------------------ case runners


def _state_pair(va, types, case_dir):
    pre = types.BeaconState.deserialize(va.read_ssz(case_dir, "pre.ssz_snappy"))
    post_raw = va.read_ssz(case_dir, "post.ssz_snappy")
    post = (
        types.BeaconState.deserialize(post_raw) if post_raw is not None else None
    )
    return pre, post


def _check_post(types, got_state, post, changed: bool) -> None:
    if post is None:
        if changed:
            raise EfTestError("expected failure but processing succeeded")
        return
    got = types.BeaconState.hash_tree_root(got_state)
    want = types.BeaconState.hash_tree_root(post)
    if got != want:
        raise EfTestError(f"post-state root mismatch: {got.hex()} != {want.hex()}")


def _run_ssz_static(va, types, handler, case_dir):
    roots = va.read_yaml(case_dir, "roots.yaml")
    ssz = va.read_ssz(case_dir, "serialized.ssz_snappy")
    ctype = getattr(types, handler, None)
    if ctype is None:
        raise EfTestError(f"unknown container {handler}")
    value = ctype.deserialize(ssz)
    if ctype.serialize(value) != ssz:
        raise EfTestError("non-roundtripping serialization")
    got = "0x" + ctype.hash_tree_root(value).hex()
    if got != roots["root"]:
        raise EfTestError(f"root mismatch {got} != {roots['root']}")


def _run_shuffling(va, spec, case_dir):
    meta = va.read_yaml(case_dir, "mapping.yaml")
    seed = bytes.fromhex(meta["seed"][2:])
    count = int(meta["count"])
    mapping = [int(x) for x in meta["mapping"]]
    rounds = spec.preset.SHUFFLE_ROUND_COUNT
    got = [compute_shuffled_index(i, count, seed, rounds) for i in range(count)]
    if got != mapping:
        raise EfTestError("shuffling mismatch")


def _run_sanity_slots(va, spec, types, case_dir):
    pre, post = _state_pair(va, types, case_dir)
    n = int(va.read_yaml(case_dir, "slots.yaml"))
    process_slots(pre, spec, pre.slot + n)
    _check_post(types, pre, post, True)


def _run_sanity_blocks(va, spec, types, fork, case_dir):
    meta = va.read_yaml(case_dir, "meta.yaml") or {}
    n_blocks = int(meta.get("blocks_count", 0))
    pre, post = _state_pair(va, types, case_dir)
    try:
        for i in range(n_blocks):
            raw = va.read_ssz(case_dir, f"blocks_{i}.ssz_snappy")
            sb = types.SignedBeaconBlock.deserialize(raw)
            bt = types_for_slot(spec, sb.message.slot)
            if pre.slot < sb.message.slot:
                process_slots(pre, spec, sb.message.slot)
            per_block_processing(
                pre, sb, spec, bt,
                strategy=SignatureStrategy.VERIFY_BULK, verify_block_root=True,
            )
    except Exception as e:  # noqa: BLE001 — any rejection counts for invalid cases
        if post is None:
            return
        raise EfTestError(f"valid block rejected: {e}") from e
    _check_post(types, pre, post, True)


def _run_operation(va, spec, types, fork, handler, case_dir):
    if handler not in OPERATION_RUNNERS:
        raise EfTestError(f"unknown operation {handler}")
    stem, apply = OPERATION_RUNNERS[handler]
    pre, post = _state_pair(va, types, case_dir)
    op_ssz = va.read_ssz(case_dir, f"{stem}.ssz_snappy")
    op_type = {
        "attestation": "Attestation",
        "attester_slashing": "AttesterSlashing",
        "proposer_slashing": "ProposerSlashing",
        "deposit": "Deposit",
        "voluntary_exit": "SignedVoluntaryExit",
        "bls_to_execution_change": "SignedBLSToExecutionChange",
        "sync_aggregate": "SyncAggregate",
        "deposit_request": "DepositRequest",
        "withdrawal_request": "WithdrawalRequest",
        "consolidation_request": "ConsolidationRequest",
    }[handler]
    op = getattr(types, op_type).deserialize(op_ssz)
    try:
        apply(pre, spec, types, op, ForkName[fork])
    except Exception as e:  # noqa: BLE001 — invalid-op cases expect failure
        if post is None:
            return
        raise EfTestError(f"valid op rejected: {e}") from e
    _check_post(types, pre, post, True)


def _run_epoch(va, spec, types, fork, handler, case_dir):
    if handler not in EPOCH_RUNNERS:
        raise EfTestError(f"unknown epoch transition {handler}")
    pre, post = _state_pair(va, types, case_dir)
    try:
        EPOCH_RUNNERS[handler](pre, spec, types, ForkName[fork])
    except Exception as e:  # noqa: BLE001
        if post is None:
            return
        raise EfTestError(f"epoch transition failed: {e}") from e
    _check_post(types, pre, post, True)


def _deltas_type(spec):
    from ..ssz.core import Container, List as SSZList, uint64

    limit = spec.preset.VALIDATOR_REGISTRY_LIMIT
    return Container(
        "Deltas",
        [("rewards", SSZList(uint64, limit)), ("penalties", SSZList(uint64, limit))],
    )


def _run_rewards(va, spec, types, fork, case_dir):
    """Official rewards vectors: per-component (rewards, penalties) lists
    (ef_tests/src/cases/rewards.rs). Altair+ flags map to
    source/target/head deltas plus the inactivity penalty deltas."""
    if ForkName[fork] == ForkName.phase0:
        raise EfTestError("phase0 rewards runner not implemented")
    pre = types.BeaconState.deserialize(va.read_ssz(case_dir, "pre.ssz_snappy"))
    D = _deltas_type(spec)
    names = ["source_deltas", "target_deltas", "head_deltas"]
    for flag_index, name in enumerate(names):
        want = D.deserialize(va.read_ssz(case_dir, f"{name}.ssz_snappy"))
        rewards, penalties = ep.get_flag_index_deltas(
            pre, spec, flag_index, ForkName[fork]
        )
        if list(want.rewards) != rewards or list(want.penalties) != penalties:
            raise EfTestError(f"{name} mismatch")
    want = D.deserialize(
        va.read_ssz(case_dir, "inactivity_penalty_deltas.ssz_snappy")
    )
    rewards, penalties = ep.get_inactivity_penalty_deltas(pre, spec, ForkName[fork])
    if list(want.rewards) != rewards or list(want.penalties) != penalties:
        raise EfTestError("inactivity_penalty_deltas mismatch")


def _run_transition(va, config, fork, case_dir):
    """Official transition vectors: blocks crossing a fork boundary
    (ef_tests/src/cases/transition.rs). `fork` is the POST fork; meta gives
    the activation epoch; pre is a PRE-fork state."""
    meta = va.read_yaml(case_dir, "meta.yaml")
    post_fork = meta.get("post_fork", fork)
    fork_epoch = int(meta["fork_epoch"])
    n_blocks = int(meta["blocks_count"])
    pre_fork = _FORK_ORDER[_FORK_ORDER.index(post_fork) - 1]
    spec = spec_at_fork(config, pre_fork, {post_fork + "_fork_epoch": fork_epoch})
    pre_types = spec_types(spec.preset, ForkName[pre_fork])
    post_types = spec_types(spec.preset, ForkName[post_fork])
    state = pre_types.BeaconState.deserialize(va.read_ssz(case_dir, "pre.ssz_snappy"))
    for i in range(n_blocks):
        raw = va.read_ssz(case_dir, f"blocks_{i}.ssz_snappy")
        bt = types_for_slot(spec, fork_epoch * spec.preset.SLOTS_PER_EPOCH)
        # block fork is decided by its slot (the transition block itself is
        # a post-fork block)
        # peek slot: first 8 bytes of the message after the 100-byte
        # envelope is fork-agnostic; simpler: try post types then pre
        try:
            sb = post_types.SignedBeaconBlock.deserialize(raw)
            bt = types_for_slot(spec, sb.message.slot)
            sb = bt.SignedBeaconBlock.deserialize(raw)
        except Exception:
            sb = pre_types.SignedBeaconBlock.deserialize(raw)
            bt = pre_types
        if state.slot < sb.message.slot:
            process_slots(state, spec, sb.message.slot)
        per_block_processing(
            state, sb, spec, bt,
            strategy=SignatureStrategy.VERIFY_BULK, verify_block_root=True,
        )
    post = post_types.BeaconState.deserialize(va.read_ssz(case_dir, "post.ssz_snappy"))
    _check_post(post_types, state, post, True)


def _run_fork_choice(va, spec, fork, case_dir):
    """Official fork-choice vectors: a step script driving an anchored
    store (ef_tests/src/cases/fork_choice.rs). Supported steps: tick,
    block (+ optional `valid: false`), attestation, checks {head,
    justified_checkpoint, finalized_checkpoint, proposer_boost_root}."""
    from ..fork_choice.fork_choice import ForkChoice
    from ..types.state_util import clone_state

    types = _fork_types(spec, fork)
    anchor_state = types.BeaconState.deserialize(
        va.read_ssz(case_dir, "anchor_state.ssz_snappy")
    )
    anchor_block = types.BeaconBlock.deserialize(
        va.read_ssz(case_dir, "anchor_block.ssz_snappy")
    )
    anchor_root = types.BeaconBlock.hash_tree_root(anchor_block)
    fc = ForkChoice(spec, anchor_root, anchor_block.slot, anchor_state)
    states = {anchor_root: anchor_state}
    genesis_time = int(anchor_state.genesis_time)
    steps = va.read_yaml(case_dir, "steps.yaml")

    def current_head():
        return fc.get_head()

    for step in steps:
        if "tick" in step:
            slot = (int(step["tick"]) - genesis_time) // spec.seconds_per_slot
            fc.on_tick(slot)
        elif "block" in step:
            raw = va.read_ssz(case_dir, f"{step['block']}.ssz_snappy")
            bt = types_for_slot(spec, 0)
            sb = bt.SignedBeaconBlock.deserialize(raw)
            bt = types_for_slot(spec, sb.message.slot)
            sb = bt.SignedBeaconBlock.deserialize(raw)
            root = bt.BeaconBlock.hash_tree_root(sb.message)
            parent = bytes(sb.message.parent_root)
            try:
                if parent not in states:
                    raise EfTestError("unknown parent")
                st = clone_state(states[parent], spec)
                if st.slot < sb.message.slot:
                    process_slots(st, spec, sb.message.slot)
                per_block_processing(
                    st, sb, spec, bt,
                    strategy=SignatureStrategy.VERIFY_BULK, verify_block_root=True,
                )
                fc.on_block(sb, root, st)
                states[root] = st
            except Exception as e:  # noqa: BLE001
                if step.get("valid", True):
                    raise EfTestError(f"valid block rejected: {e}") from e
                continue
            if not step.get("valid", True):
                raise EfTestError("invalid block accepted")
        elif "attestation" in step:
            raw = va.read_ssz(case_dir, f"{step['attestation']}.ssz_snappy")
            att = types.Attestation.deserialize(raw)
            target_root = bytes(att.data.target.root)
            st = states.get(target_root) or states.get(
                bytes(att.data.beacon_block_root)
            )
            if st is None:
                raise EfTestError("attestation references unknown state")
            indices = acc.get_attesting_indices(
                st, spec, att.data, att.aggregation_bits, None
            )
            fc.on_attestation(
                att.data.slot, indices, bytes(att.data.beacon_block_root),
                att.data.target.epoch,
            )
        elif "checks" in step:
            checks = step["checks"]
            if "head" in checks:
                head = current_head()
                want = checks["head"]
                if "0x" + head.hex() != want["root"]:
                    raise EfTestError(
                        f"head mismatch: 0x{head.hex()} != {want['root']}"
                    )
                got_slot = int(states[head].latest_block_header.slot)
                if got_slot != int(want["slot"]):
                    raise EfTestError(f"head slot {got_slot} != {want['slot']}")
            if "justified_checkpoint" in checks:
                je, jr = fc.store.justified_checkpoint
                want = checks["justified_checkpoint"]
                if int(want["epoch"]) != je or want["root"] != "0x" + jr.hex():
                    raise EfTestError("justified checkpoint mismatch")
            if "finalized_checkpoint" in checks:
                fe, fr = fc.store.finalized_checkpoint
                want = checks["finalized_checkpoint"]
                if int(want["epoch"]) != fe or want["root"] != "0x" + fr.hex():
                    raise EfTestError("finalized checkpoint mismatch")
            if "proposer_boost_root" in checks:
                got = fc.proto.proposer_boost_root
                if checks["proposer_boost_root"] != "0x" + got.hex():
                    raise EfTestError("proposer boost root mismatch")
        else:
            raise EfTestError(f"unknown fork-choice step {sorted(step)}")


def _run_fork_upgrade(va, spec, fork, case_dir):
    meta = va.read_yaml(case_dir, "meta.yaml")
    post_fork = meta["fork"]
    pre_fork_name = {
        "altair": ForkName.phase0, "bellatrix": ForkName.altair,
        "capella": ForkName.bellatrix, "deneb": ForkName.capella,
        "electra": ForkName.deneb,
    }[post_fork]
    pre_types = spec_types(spec.preset, pre_fork_name)
    post_types = spec_types(spec.preset, ForkName[post_fork])
    pre = pre_types.BeaconState.deserialize(va.read_ssz(case_dir, "pre.ssz_snappy"))
    post = post_types.BeaconState.deserialize(va.read_ssz(case_dir, "post.ssz_snappy"))
    upgrade_state(pre, spec, pre_fork_name, ForkName[post_fork])
    _check_post(post_types, pre, post, True)


def _run_bls(va, handler, case_dir):
    data = va.read_yaml(case_dir, "data.yaml")
    inp, expect = data["input"], data["output"]

    def sig(hexstr):
        return bls.Signature.deserialize(bytes.fromhex(hexstr[2:]))

    def pk(hexstr):
        return bls.PublicKey.deserialize(bytes.fromhex(hexstr[2:]))

    if handler == "sign":
        sk = bls.SecretKey(int(inp["privkey"], 16))
        got = "0x" + bls.sign(sk, bytes.fromhex(inp["message"][2:])).serialize().hex()
        ok = got == expect
    elif handler == "verify":
        try:
            got = bls.verify(pk(inp["pubkey"]), bytes.fromhex(inp["message"][2:]), sig(inp["signature"]))
        except Exception:  # noqa: BLE001 — malformed points fail verification
            got = False
        ok = got == expect
    elif handler == "aggregate":
        try:
            agg = bls.AggregateSignature.empty()
            for s in inp:
                agg.add_assign(sig(s))
            got = "0x" + agg.serialize().hex()
            ok = got == expect
        except Exception:  # noqa: BLE001
            ok = expect is None
    elif handler == "fast_aggregate_verify":
        try:
            got = bls.fast_aggregate_verify(
                [pk(p) for p in inp["pubkeys"]],
                bytes.fromhex(inp["message"][2:]),
                sig(inp["signature"]),
            )
        except Exception:  # noqa: BLE001
            got = False
        ok = got == expect
    elif handler == "aggregate_verify":
        try:
            got = bls.aggregate_verify(
                [pk(p) for p in inp["pubkeys"]],
                [bytes.fromhex(m[2:]) for m in inp["messages"]],
                sig(inp["signature"]),
            )
        except Exception:  # noqa: BLE001
            got = False
        ok = got == expect
    elif handler == "eth_fast_aggregate_verify":
        try:
            got = bls.eth_fast_aggregate_verify(
                [pk(p) for p in inp["pubkeys"]],
                bytes.fromhex(inp["message"][2:]),
                sig(inp["signature"]),
            )
        except Exception:  # noqa: BLE001
            got = False
        ok = got == expect
    elif handler == "batch_verify":
        try:
            sets = [
                bls.SignatureSet(sig(s), (pk(p),), bytes.fromhex(m[2:]))
                for p, m, s in zip(inp["pubkeys"], inp["messages"], inp["signatures"])
            ]
            got = bls.verify_signature_sets(sets)
        except Exception:  # noqa: BLE001
            got = False
        ok = got == expect
    else:
        raise EfTestError(f"unknown bls handler {handler}")
    if not ok:
        raise EfTestError(f"bls/{handler} mismatch in {case_dir.name}")


def _run_kzg(va, handler, case_dir):
    from ..crypto import kzg as ckzg
    from ..crypto.bls381 import serde

    data = va.read_yaml(case_dir, "data.yaml")
    inp, expect = data["input"], data["output"]
    setup = ckzg.TrustedSetup.insecure_dev_setup(
        len(bytes.fromhex(inp["blob"][2:])) // 32 if "blob" in inp else 4096
    )

    def run():
        if handler == "blob_to_kzg_commitment":
            c = ckzg.blob_to_kzg_commitment(bytes.fromhex(inp["blob"][2:]), setup)
            return "0x" + serde.g1_compress(c).hex()
        if handler == "compute_blob_kzg_proof":
            p = ckzg.compute_blob_kzg_proof(
                bytes.fromhex(inp["blob"][2:]),
                bytes.fromhex(inp["commitment"][2:]), setup,
            )
            return "0x" + serde.g1_compress(p).hex()
        if handler == "verify_blob_kzg_proof":
            return ckzg.verify_blob_kzg_proof(
                bytes.fromhex(inp["blob"][2:]),
                bytes.fromhex(inp["commitment"][2:]),
                bytes.fromhex(inp["proof"][2:]), setup,
            )
        if handler == "verify_blob_kzg_proof_batch":
            return ckzg.verify_blob_kzg_proof_batch(
                [bytes.fromhex(b[2:]) for b in inp["blobs"]],
                [bytes.fromhex(c[2:]) for c in inp["commitments"]],
                [bytes.fromhex(p[2:]) for p in inp["proofs"]], setup,
            )
        raise EfTestError(f"unknown kzg handler {handler}")

    try:
        got = run()
    except Exception:  # noqa: BLE001 — invalid inputs expect null output
        got = None
    if got != expect:
        raise EfTestError(f"kzg/{handler} mismatch: {got} != {expect}")


def discover_cases(vector_root: str):
    """Yield (config, fork, runner, handler, case_dir) for every case under
    the root (layout: {config}/{fork}/{runner}/{handler}/{suite}/{case})."""
    root = Path(vector_root)
    if not root.exists():
        return
    for config_dir in sorted(root.iterdir()):
        if not config_dir.is_dir():
            continue
        for fork_dir in sorted(config_dir.iterdir()):
            for runner_dir in sorted(p for p in fork_dir.iterdir() if p.is_dir()):
                for handler_dir in sorted(p for p in runner_dir.iterdir() if p.is_dir()):
                    for suite_dir in sorted(p for p in handler_dir.iterdir() if p.is_dir()):
                        for case_dir in sorted(p for p in suite_dir.iterdir() if p.is_dir()):
                            yield (
                                config_dir.name, fork_dir.name, runner_dir.name,
                                handler_dir.name, case_dir,
                            )
