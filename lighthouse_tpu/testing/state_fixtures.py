"""Synthetic validator-scale BeaconStates for benches and loadgen.

One builder shared by scripts/bench_state_root.py, the `bn loadtest
state_root` scenario (loadgen/state_root.py) and the jaxhash tests, so
the state-root workload every harness measures is the SAME shape:
an n-validator deneb state on the minimal spec (pubkeys are opaque bytes
for hashing purposes — no key derivation), optionally with seeded
participation/inactivity so the epoch-transition vectors have real work.
"""

from __future__ import annotations

import random


def build_synthetic_state(n: int, *, participation_seed: int | None = None,
                          slot: int | None = None):
    """(spec, types, state) with n validators. With `participation_seed`
    the participation flags / inactivity scores / balances are seeded
    non-trivial (the epoch-transition workload); `slot` defaults to 0
    (pass an epoch-boundary-minus-one slot to bench process_epoch)."""
    from ..state_transition.slot import types_for_slot
    from ..types.spec import FAR_FUTURE_EPOCH, minimal_spec

    spec = minimal_spec()
    types = types_for_slot(spec, 0)
    validators = [
        types.Validator.make(
            pubkey=i.to_bytes(48, "big"),
            withdrawal_credentials=i.to_bytes(32, "big"),
            effective_balance=32 * 10**9,
            slashed=False,
            activation_eligibility_epoch=0,
            activation_epoch=0,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH,
        )
        for i in range(n)
    ]
    state = types.BeaconState.default()
    state.validators = validators
    state.balances = [32 * 10**9] * n
    state.previous_epoch_participation = [0] * n
    state.current_epoch_participation = [0] * n
    state.inactivity_scores = [0] * n
    if participation_seed is not None:
        rng = random.Random(participation_seed)
        state.previous_epoch_participation = [
            rng.randrange(0, 8) for _ in range(n)
        ]
        state.current_epoch_participation = [
            rng.randrange(0, 8) for _ in range(n)
        ]
        state.inactivity_scores = [rng.randrange(0, 8) for _ in range(n)]
        state.balances = [
            32 * 10**9 + rng.randrange(-10**9, 10**9) for _ in range(n)
        ]
    if slot is not None:
        state.slot = slot
    return spec, types, state


def uncached_state_root(types, state) -> bytes:
    """Ground-truth root: a from-scratch rehash of a deep copy with every
    cache defeated — memoized container roots stripped, a FRESH list tree
    cache, and the host hash backend — so a cached/device root can be
    proven against it."""
    import copy

    from ..jaxhash import router as _router
    from ..ssz import tree_cache as _tc

    st = copy.deepcopy(state)
    for v in st.validators:
        if hasattr(v, "_htr"):
            object.__delattr__(v, "_htr")
    prev_cache = _tc.GLOBAL_LIST_CACHE
    prev_backend = _router._state["backend"]
    _tc.GLOBAL_LIST_CACHE = _tc.ListTreeCache()
    try:
        _router.set_hash_backend("host")
        return types.BeaconState.hash_tree_root(st)
    finally:
        _router._state["backend"] = prev_backend
        _tc.GLOBAL_LIST_CACHE = prev_cache
