"""Synthetic validator-scale BeaconStates for benches and loadgen.

One builder shared by scripts/bench_state_root.py, the `bn loadtest
state_root` scenario (loadgen/state_root.py) and the jaxhash tests, so
the state-root workload every harness measures is the SAME shape:
an n-validator deneb state on the minimal spec (pubkeys are opaque bytes
for hashing purposes — no key derivation), optionally with seeded
participation/inactivity so the epoch-transition vectors have real work.

Two scale features ride here:

  - The big per-validator fields come back as `ssz/cow.py` CowLists when
    the registry is at least cow_min_len() (override with `cow=`), built
    chunk-wise — the 1M-validator fixture never materializes a second
    flat copy of anything.
  - Fixtures persist to disk keyed by (validator_count, seed, fork): an
    npz holding the seeded arrays AND the per-validator memoized roots,
    so repeat 1M builds skip the ~1M-element RNG replay and — the real
    cost — the from-scratch per-validator hashing of the first root.
    Default dir is `<repo>/.fixture_cache` (gitignored);
    LIGHTHOUSE_TPU_FIXTURE_CACHE overrides it (a path) or disables
    caching entirely (0/off). Auto-caching starts at CACHE_MIN_N
    validators; pass `cache=True/False` to force either way.
"""

from __future__ import annotations

import os

import numpy as np

#: below this, building from scratch is faster than touching disk
CACHE_MIN_N = 65536

_DISABLED = ("0", "off", "false", "no", "disabled")


def fixture_cache_dir() -> str | None:
    """Cache directory, or None when caching is disabled by env."""
    raw = os.environ.get("LIGHTHOUSE_TPU_FIXTURE_CACHE", "").strip()
    if raw.lower() in _DISABLED and raw:
        return None
    if raw:
        return raw
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(pkg_root, ".fixture_cache")


def _cache_path(n: int, seed: int | None, fork: str) -> str | None:
    d = fixture_cache_dir()
    if d is None:
        return None
    seed_label = "none" if seed is None else str(seed)
    return os.path.join(d, f"state_n{n}_s{seed_label}_{fork}.npz")


def _seeded_arrays(n: int, seed: int):
    """Deterministic per-seed field arrays, vectorized (the per-element
    random.Random loop was most of a 1M fixture build)."""
    rng = np.random.default_rng(seed)
    prev_part = rng.integers(0, 8, n, dtype=np.uint8)
    cur_part = rng.integers(0, 8, n, dtype=np.uint8)
    inact = rng.integers(0, 8, n, dtype=np.uint64)
    balances = (
        32 * 10**9 + rng.integers(-(10**9), 10**9, n, dtype=np.int64)
    ).astype(np.uint64)
    return prev_part, cur_part, inact, balances


def build_synthetic_state(n: int, *, participation_seed: int | None = None,
                          slot: int | None = None, cow: bool | None = None,
                          cache: bool | None = None):
    """(spec, types, state) with n validators. With `participation_seed`
    the participation flags / inactivity scores / balances are seeded
    non-trivial (the epoch-transition workload); `slot` defaults to 0
    (pass an epoch-boundary-minus-one slot to bench process_epoch).
    `cow`/`cache` override the CowList-backing and disk-cache defaults
    (see module docstring)."""
    from ..ssz.cow import CowList, cow_chunk_elems, cow_min_len
    from ..state_transition.slot import types_for_slot
    from ..types.spec import FAR_FUTURE_EPOCH, minimal_spec

    spec = minimal_spec()
    types = types_for_slot(spec, 0)
    fork = types.fork.value
    use_cow = cow if cow is not None else (
        cow_min_len() > 0 and n >= cow_min_len()
    )
    use_cache = cache if cache is not None else n >= CACHE_MIN_N
    path = _cache_path(n, participation_seed, fork) if use_cache else None

    cached = None
    if path is not None and os.path.exists(path):
        try:
            with np.load(path) as f:
                cached = {k: f[k] for k in f.files}
            if cached.get("validator_roots") is not None and len(
                cached["validator_roots"]
            ) != n:
                cached = None
        except Exception:
            cached = None  # unreadable cache rebuilds from scratch

    validators = [
        types.Validator.make(
            pubkey=i.to_bytes(48, "big"),
            withdrawal_credentials=i.to_bytes(32, "big"),
            effective_balance=32 * 10**9,
            slashed=False,
            activation_eligibility_epoch=0,
            activation_epoch=0,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH,
        )
        for i in range(n)
    ]
    if cached is not None:
        # pre-seed the memoized roots: the first state root skips the
        # from-scratch per-validator hashing (the dominant cold cost)
        roots = cached["validator_roots"]
        for i, v in enumerate(validators):
            object.__setattr__(v, "_htr", roots[i].tobytes())

    if participation_seed is not None:
        if cached is not None:
            prev_part = cached["prev_part"]
            cur_part = cached["cur_part"]
            inact = cached["inact"]
            balances = cached["balances"]
        else:
            prev_part, cur_part, inact, balances = _seeded_arrays(
                n, participation_seed
            )
        prev_list = prev_part.tolist()
        cur_list = cur_part.tolist()
        inact_list = inact.tolist()
        bal_list = balances.tolist()
    else:
        prev_list = [0] * n
        cur_list = [0] * n
        inact_list = [0] * n
        bal_list = [32 * 10**9] * n

    state = types.BeaconState.default()
    if use_cow:
        bs = types.BeaconState
        ft = {f.name: f.type for f in bs.fields}
        state.validators = CowList.from_list(
            validators, cow_chunk_elems(ft["validators"]), name="validators"
        )
        state.balances = CowList.from_list(
            bal_list, cow_chunk_elems(ft["balances"]), name="balances"
        )
        state.previous_epoch_participation = CowList.from_list(
            prev_list, cow_chunk_elems(ft["previous_epoch_participation"]),
            name="previous_epoch_participation",
        )
        state.current_epoch_participation = CowList.from_list(
            cur_list, cow_chunk_elems(ft["current_epoch_participation"]),
            name="current_epoch_participation",
        )
        state.inactivity_scores = CowList.from_list(
            inact_list, cow_chunk_elems(ft["inactivity_scores"]),
            name="inactivity_scores",
        )
    else:
        state.validators = validators
        state.balances = bal_list
        state.previous_epoch_participation = prev_list
        state.current_epoch_participation = cur_list
        state.inactivity_scores = inact_list

    if path is not None and cached is None:
        _save_fixture(path, types, validators, participation_seed, n)
    if slot is not None:
        state.slot = slot
    return spec, types, state


def _save_fixture(path: str, types, validators, seed: int | None,
                  n: int) -> None:
    """Write the npz: seeded arrays + per-validator roots. Computing the
    roots here is the same work the first state root would do — paid once
    per (n, seed, fork) instead of per process."""
    try:
        roots = np.empty((n, 32), np.uint8)
        vt = None
        for f in types.BeaconState.fields:
            if f.name == "validators":
                vt = f.type.element
        for i, v in enumerate(validators):
            roots[i] = np.frombuffer(vt.hash_tree_root(v), np.uint8)
        arrays = {"validator_roots": roots}
        if seed is not None:
            prev_part, cur_part, inact, balances = _seeded_arrays(n, seed)
            arrays.update(prev_part=prev_part, cur_part=cur_part,
                          inact=inact, balances=balances)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            np.savez(fh, **arrays)
        os.replace(tmp, path)
    except Exception:
        # a failed cache write must never fail a fixture build
        try:
            if os.path.exists(tmp):
                os.unlink(tmp)
        except Exception:
            pass


def uncached_state_root(types, state) -> bytes:
    """Ground-truth root: a from-scratch rehash of a deep copy with every
    cache defeated — memoized container roots stripped, a FRESH list tree
    cache, CowList fields flattened to plain lists (their per-instance
    hash state must not serve), and the host hash backend — so a
    cached/device root can be proven against it."""
    import copy

    from ..jaxhash import router as _router
    from ..ssz import tree_cache as _tc
    from ..ssz.cow import CowList

    st = copy.deepcopy(state)
    for f in st.__class__.ssz_type.fields:
        v = getattr(st, f.name)
        if isinstance(v, CowList):
            setattr(st, f.name, v.to_list())
    for v in st.validators:
        if hasattr(v, "_htr"):
            object.__delattr__(v, "_htr")
    prev_cache = _tc.GLOBAL_LIST_CACHE
    prev_backend = _router._state["backend"]
    _tc.GLOBAL_LIST_CACHE = _tc.ListTreeCache()
    try:
        _router.set_hash_backend("host")
        return types.BeaconState.hash_tree_root(st)
    finally:
        _router._state["backend"] = prev_backend
        _tc.GLOBAL_LIST_CACHE = prev_cache
