"""Structural diffing of SSZ container values (compare_fields analog).

Parity surface: /root/reference/common/compare_fields(+derive) — the
reference derives CompareFields on consensus containers so tests can
pinpoint WHICH field diverged instead of eyeballing two giant states.
Here: a recursive runtime walk over the generated value classes."""

from __future__ import annotations


def compare_fields(a, b, path: str = "", max_diffs: int = 32) -> list[tuple[str, object, object]]:
    """Recursive field-by-field diff; returns [(path, a_val, b_val)]."""
    diffs: list[tuple[str, object, object]] = []

    def walk(x, y, p):
        if len(diffs) >= max_diffs:
            return
        if hasattr(x, "ssz_type") and hasattr(y, "ssz_type"):
            for f in x.ssz_type.fields:
                walk(getattr(x, f.name), getattr(y, f.name), f"{p}.{f.name}" if p else f.name)
            return
        if isinstance(x, (list, tuple)) and isinstance(y, (list, tuple)):
            if len(x) != len(y):
                diffs.append((f"{p}.len", len(x), len(y)))
                return
            for i, (xi, yi) in enumerate(zip(x, y)):
                walk(xi, yi, f"{p}[{i}]")
            return
        if isinstance(x, (bytes, bytearray)) or isinstance(y, (bytes, bytearray)):
            if bytes(x) != bytes(y):
                diffs.append((p, bytes(x), bytes(y)))
            return
        if x != y:
            diffs.append((p, x, y))

    walk(a, b, path)
    return diffs


def assert_equal(a, b, what: str = "values") -> None:
    """Assert with a field-level report on mismatch."""
    diffs = compare_fields(a, b)
    if diffs:
        lines = "\n".join(
            f"  {p}: {x!r} != {y!r}" for p, x, y in diffs[:16]
        )
        raise AssertionError(f"{what} differ in {len(diffs)} field(s):\n{lines}")
