"""StateHarness — deterministic block production over the state transition.

The core of the reference's BeaconChainHarness
(/root/reference/beacon_node/beacon_chain/src/test_utils.rs:610): interop
keypairs, logical time, extend-chain with full attestation participation.
This harness drives the pure state transition; chain/test_utils wraps it
with a full BeaconChain (store + fork choice) later.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field

from ..crypto import bls
from ..types import helpers as h
from ..types.spec import (
    ChainSpec,
    ForkName,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
)
from ..types.containers import spec_types
from ..state_transition import accessors as acc
from ..state_transition.block import SignatureStrategy
from ..state_transition.genesis import interop_genesis_state
from ..state_transition.slot import process_slots, state_transition, types_for_slot


# Re-export: clone_state is production consensus code and lives with the
# type layer; the harness keeps the historical import path for tests.
from ..types.state_util import clone_state  # noqa: F401


def _sign(sk, root: bytes) -> "bls.Signature":
    """Sign, but under the fake backend return a cheap deterministic dummy —
    the fake backend never checks signatures, and skipping the g2_mul makes
    the plumbing test lanes ~20x faster (the reference gets the same effect
    from fake_crypto's no-op sign)."""
    if bls.get_backend().name == "fake":
        return _DummySig()
    return bls.sign(sk, root)


class _DummySig:
    """Stand-in signature under the fake backend: a fixed VALID G2 point
    (the generator) so signature-set constructors can still decode it."""

    _cached = None

    def __init__(self):
        if _DummySig._cached is None:
            from ..crypto.bls381 import curve as _cv, serde as _serde

            _DummySig._cached = _serde.g2_compress(_cv.G2_GEN)
        self.point = None

    def serialize(self) -> bytes:
        return _DummySig._cached

    def is_infinity(self) -> bool:
        return False


@dataclass
class StateHarness:
    spec: ChainSpec
    keypairs: list
    state: object = None
    genesis_time: int = 1_600_000_000

    def __post_init__(self):
        if self.state is None:
            self.state = interop_genesis_state(self.keypairs, self.genesis_time, self.spec)

    @classmethod
    def new(cls, spec: ChainSpec, validator_count: int):
        return cls(spec=spec, keypairs=bls.interop_keypairs(validator_count))

    # -- signing helpers --------------------------------------------------

    def sk(self, validator_index: int) -> bls.SecretKey:
        return self.keypairs[validator_index].sk

    def sign_block(self, block, types):
        # Domain from the SPEC's fork schedule at the block's epoch, not
        # from self.state: the pre-block state still carries the old fork
        # at an upgrade boundary, and the verifier's advanced state would
        # use the new one (a real-crypto-only mismatch the fake lane never
        # sees).
        epoch = h.compute_epoch_at_slot(block.slot, self.spec)
        version = self.spec.fork_version(self.spec.fork_name_at_epoch(epoch))
        domain = h.compute_domain(
            DOMAIN_BEACON_PROPOSER, version,
            bytes(self.state.genesis_validators_root),
        )
        root = h.compute_signing_root(types.BeaconBlock, block, domain)
        sig = _sign(self.sk(block.proposer_index), root)
        return types.SignedBeaconBlock.make(message=block, signature=sig.serialize())

    def randao_reveal(self, state, proposer_index: int, epoch: int) -> bytes:
        from ..ssz.core import uint64

        domain = h.get_domain(state, self.spec, DOMAIN_RANDAO, epoch)
        root = h.compute_signing_root(uint64, epoch, domain)
        return _sign(self.sk(proposer_index), root).serialize()

def _build_attestations(self, state, slot, head_root):
    spec = self.spec
    types = types_for_slot(spec, slot)
    epoch = h.compute_epoch_at_slot(slot, spec)
    cache = acc.build_committee_cache(state, spec, epoch)
    start_slot = h.compute_start_slot_at_epoch(epoch, spec)
    if slot == start_slot:
        target_root = head_root
    else:
        target_root = state.block_roots[start_slot % spec.preset.SLOTS_PER_HISTORICAL_ROOT]
    source = (
        state.current_justified_checkpoint
        if epoch == acc.get_current_epoch(state, spec)
        else state.previous_justified_checkpoint
    )
    domain = h.get_domain(state, spec, DOMAIN_BEACON_ATTESTER, epoch)
    atts = []
    from ..crypto.bls381 import curve as cv

    electra = spec.fork_name_at_slot(slot) >= ForkName.electra
    for index in range(cache.committees_per_slot):
        committee = cache.committee(slot, index)
        data = types.AttestationData.make(
            slot=slot,
            index=0 if electra else index,
            beacon_block_root=head_root,
            source=source,
            target=types.Checkpoint.make(epoch=epoch, root=target_root),
        )
        root = h.compute_signing_root(types.AttestationData, data, domain)
        if bls.get_backend().name == "fake":
            sig_bytes = _sign(self.sk(committee[0]), root).serialize()
        else:
            agg_point = None
            for vi in committee:
                s = bls.sign(self.sk(vi), root)
                agg_point = cv.g2_add(agg_point, s.point)
            sig_bytes = bls.Signature(agg_point).serialize()
        if electra:
            # EIP-7549: one attestation per committee, committee_bits set
            committee_bits = [False] * spec.preset.MAX_COMMITTEES_PER_SLOT
            committee_bits[index] = True
            atts.append(
                types.Attestation.make(
                    aggregation_bits=[True] * len(committee),
                    data=data,
                    signature=sig_bytes,
                    committee_bits=committee_bits,
                )
            )
        else:
            atts.append(
                types.Attestation.make(
                    aggregation_bits=[True] * len(committee),
                    data=data,
                    signature=sig_bytes,
                )
            )
    return atts


def _sync_aggregate(self, state, block_slot: int):
    """Fully-participating sync aggregate signing the parent block root."""
    spec = self.spec
    types = types_for_slot(spec, block_slot)
    prev_slot = max(block_slot, 1) - 1
    epoch = h.compute_epoch_at_slot(prev_slot, spec)
    domain = h.get_domain(state, spec, DOMAIN_SYNC_COMMITTEE, epoch)
    root = acc.get_block_root_at_slot(state, spec, prev_slot)
    signing_root = h.compute_signing_root_from_root(root, domain)
    sk_by_pk = {kp.pk.serialize(): kp.sk for kp in self.keypairs}
    from ..crypto.bls381 import curve as cv

    fake = bls.get_backend().name == "fake"
    agg_point = None
    bits = []
    any_signer = None
    for pk in state.current_sync_committee.pubkeys:
        sk = sk_by_pk.get(bytes(pk))
        if sk is None:
            bits.append(False)
            continue
        bits.append(True)
        any_signer = sk
        if not fake:
            s = bls.sign(sk, signing_root)
            agg_point = cv.g2_add(agg_point, s.point)
    if fake and any_signer is not None:
        sig_bytes = _sign(any_signer, signing_root).serialize()
    elif agg_point is not None:
        sig_bytes = bls.Signature(agg_point).serialize()
    else:
        sig_bytes = bls.INFINITY_SIGNATURE_BYTES
    return types.SyncAggregate.make(
        sync_committee_bits=bits,
        sync_committee_signature=sig_bytes,
    )


def _produce_block(self, slot: int, attestations=(), full_sync: bool = True):
    """Produce a signed block for `slot` on top of the current state."""
    spec = self.spec
    types = types_for_slot(spec, slot)
    fork = spec.fork_name_at_slot(slot)
    state = clone_state(self.state, spec)
    process_slots(state, spec, slot)

    proposer = acc.get_beacon_proposer_index(state, spec)
    epoch = h.compute_epoch_at_slot(slot, spec)
    # process_slots filled latest_block_header.state_root at the parent slot
    parent_root = types.BeaconBlockHeader.hash_tree_root(state.latest_block_header)

    # drop attestations whose container shape doesn't match the block's fork
    # (at the electra boundary, pre-fork attestations can't be included —
    # EIP-7549 changed the Attestation container)
    electra_block = fork >= ForkName.electra
    attestations = [
        a for a in attestations if hasattr(a, "committee_bits") == electra_block
    ]
    body_kwargs = dict(
        randao_reveal=self.randao_reveal(state, proposer, epoch),
        eth1_data=state.eth1_data,
        graffiti=b"\x00" * 32,
        proposer_slashings=[],
        attester_slashings=[],
        attestations=list(attestations),
        deposits=[],
        voluntary_exits=[],
    )
    if fork >= ForkName.altair:
        if full_sync:
            body_kwargs["sync_aggregate"] = _sync_aggregate(self, state, slot)
        else:
            body_kwargs["sync_aggregate"] = types.SyncAggregate.make(
                sync_committee_bits=[False] * spec.preset.SYNC_COMMITTEE_SIZE,
                sync_committee_signature=bls.INFINITY_SIGNATURE_BYTES,
            )
    if fork >= ForkName.bellatrix:
        body_kwargs["execution_payload"] = types.ExecutionPayload.default()
    if fork >= ForkName.capella:
        body_kwargs["bls_to_execution_changes"] = []
    if fork >= ForkName.deneb:
        body_kwargs["blob_kzg_commitments"] = []
    if fork >= ForkName.electra:
        body_kwargs["execution_requests"] = types.ExecutionRequests.default()

    block = types.BeaconBlock.make(
        slot=slot,
        proposer_index=proposer,
        parent_root=parent_root,
        state_root=b"\x00" * 32,
        body=types.BeaconBlockBody.make(**body_kwargs),
    )
    # compute state root by applying the unsigned block without checks
    trial = types.SignedBeaconBlock.make(message=block, signature=b"\x00" * 96)
    post = clone_state(self.state, spec)
    state_transition(
        post,
        trial,
        spec,
        strategy=SignatureStrategy.NO_VERIFICATION,
        verify_state_root=False,
    )
    block = block.copy_with(state_root=types.BeaconState.hash_tree_root(post))
    return self.sign_block(block, types), post


def _apply_block(self, signed_block, strategy=SignatureStrategy.VERIFY_BULK):
    state_transition(self.state, signed_block, self.spec, strategy=strategy)
    return signed_block


def _extend_chain(self, num_blocks: int, attest: bool = True):
    """Produce+apply `num_blocks` blocks with full attestation participation
    (attestations from slot s included in the block at s+1)."""
    spec = self.spec
    blocks = []
    pending_atts = []
    for _ in range(num_blocks):
        slot = self.state.slot + 1
        signed, post = _produce_block(self, slot, attestations=pending_atts)
        _apply_block(self, signed)
        blocks.append(signed)
        if attest:
            types = types_for_slot(spec, slot)
            head_root = types.BeaconBlock.hash_tree_root(signed.message)
            att_state = clone_state(self.state, spec)
            pending_atts = _build_attestations(self, att_state, slot, head_root)
        else:
            pending_atts = []
    return blocks


StateHarness.build_attestations = _build_attestations
StateHarness.sync_aggregate = _sync_aggregate
StateHarness.produce_block = _produce_block
StateHarness.apply_block = _apply_block
StateHarness.extend_chain = _extend_chain
