"""Admission control: priority classes, slot deadlines, shed accounting.

Parity surface: the reference's Work taxonomy orders every work kind
explicitly (beacon_processor/src/lib.rs:955-1090) and bounds each queue;
what it does NOT do is refuse work early — a flooded queue sheds on push.
Here the `AdmissionController` sits in front of `BeaconProcessor.submit`
and adds two things the reference gets from tokio back-pressure:

  - priority classes: bulk work (chain segments, P1 API requests) is
    refused once its queue crosses a watermark, and backfill earlier still,
    so a gossip flood cannot starve block import by filling the executor
    with low-value work first;
  - slot deadlines: batchable gossip work is stamped with the last slot at
    which processing it still matters (an attestation is only propagatable
    within ATTESTATION_PROPAGATION_SLOT_RANGE slots of its own slot, spec
    p2p-interface). Expiry is checked at POP time — the item already spent
    its queue residency, so it is counted `expired`, not `dropped`.

Every lost work item lands in `qos_shed_total{kind,reason}` exactly once:
reason="queue_full" (bounded-queue shed, oldest-first for batchable kinds),
reason="expired" (deadline passed at pop), reason="admission" (refused at
submit by class watermark). Deadlines are in SLOT units and read through
the chain's slot clock, so a ManualSlotClock makes every decision
deterministic under test.
"""

from __future__ import annotations

from enum import IntEnum

from ..utils.metrics import REGISTRY

# spec p2p-interface: beacon_attestation_{subnet_id} messages are only
# propagated while attestation.data.slot + ATTESTATION_PROPAGATION_SLOT_RANGE
# >= current_slot — past that the work is unactionable
ATTESTATION_PROPAGATION_SLOT_RANGE = 32

SHED_TOTAL = REGISTRY.counter_vec(
    "qos_shed_total",
    "work items lost to QoS decisions, by work kind and reason "
    "(queue_full / expired / admission)",
    ("kind", "reason"),
)


def count_shed(kind_name: str, reason: str) -> None:
    """One lost work item. The shed path is never hot (losing work is the
    exceptional outcome), so the family lookup per event is fine."""
    SHED_TOTAL.labels(kind_name, reason).inc()


class PriorityClass(IntEnum):
    """Coarse admission classes over the WorkKind priority order."""

    CRITICAL = 0   # block import, reprocess, proposal-path API
    TIMELY = 1     # slot-deadlined gossip (attestations, aggregates, sync)
    BULK = 2       # rpc blocks, chain segments, P1 API, pool ops
    BACKFILL = 3   # historical backfill — always the first to yield


# keyed by WorkKind.name (string) so this module never imports the
# processor (which imports this module)
_CLASS_BY_KIND = {
    "chain_reprocess": PriorityClass.CRITICAL,
    "gossip_block": PriorityClass.CRITICAL,
    "api_request_p0": PriorityClass.CRITICAL,
    "gossip_aggregate": PriorityClass.TIMELY,
    "gossip_attestation": PriorityClass.TIMELY,
    "gossip_sync_contribution": PriorityClass.TIMELY,
    "gossip_sync_signature": PriorityClass.TIMELY,
    "rpc_block": PriorityClass.BULK,
    "chain_segment": PriorityClass.BULK,
    "api_request_p1": PriorityClass.BULK,
    "gossip_voluntary_exit": PriorityClass.BULK,
    "gossip_proposer_slashing": PriorityClass.BULK,
    "gossip_attester_slashing": PriorityClass.BULK,
    "gossip_bls_change": PriorityClass.BULK,
    "backfill_segment": PriorityClass.BACKFILL,
}


class AdmissionController:
    """Submit-time admission + pop-time expiry decisions.

    Stateless apart from the slot clock reference: all queue state lives in
    the processor, which passes (depth, cap) in. Watermarks are fractions
    of each kind's own queue bound — bulk work yields at 75% of ITS queue,
    backfill at 50%, so the thresholds track whatever bounds the autotune
    plan or CLI configured.

    Reach note: today's live submit() producers are the gossip handlers
    (CRITICAL/TIMELY kinds only — sync still imports chain segments
    directly), so the BULK/BACKFILL watermarks currently engage only for
    loadgen/tests and for whatever future work routes rpc/backfill
    segments through the processor. The classes exist so that routing
    change is a one-liner, not a redesign."""

    def __init__(self, slot_clock=None, *, bulk_watermark: float = 0.75,
                 backfill_watermark: float = 0.5):
        self.slot_clock = slot_clock
        # LIVE watermarks: the capacity scheduler (chain/scheduler.py)
        # retunes these between [0.25, configured base] from the rolling
        # burn rate — tightened while timely work is burning error budget
        # (bulk yields earlier), relaxed back as it recovers. The
        # constructor values are the bases it relaxes toward; the live
        # values are exported as scheduler_admission_watermark{klass}.
        self.bulk_watermark = bulk_watermark
        self.backfill_watermark = backfill_watermark

    # ------------------------------------------------------------- clocks

    def current_slot(self):
        """Current slot via the chain's clock, or None (no clock / before
        genesis) — with no time source nothing ever expires."""
        if self.slot_clock is None:
            return None
        return self.slot_clock.now()

    # ---------------------------------------------------------- decisions

    @staticmethod
    def classify(kind) -> PriorityClass:
        name = getattr(kind, "name", str(kind))
        return _CLASS_BY_KIND.get(name, PriorityClass.TIMELY)

    def admit(self, kind, depth: int, cap: int) -> bool:
        """Submit-time decision for one work item given its queue's current
        depth and bound. CRITICAL/TIMELY are always admitted here — their
        bounded queues (and oldest-first shedding) do the protecting."""
        cls = self.classify(kind)
        if cls <= PriorityClass.TIMELY:
            return True
        watermark = (
            self.backfill_watermark
            if cls == PriorityClass.BACKFILL
            else self.bulk_watermark
        )
        return depth < cap * watermark

    def is_expired(self, item) -> bool:
        """Pop-time deadline check: True once the current slot is PAST the
        item's deadline slot (the deadline slot itself still processes)."""
        deadline = getattr(item, "deadline_slot", None)
        if deadline is None:
            return False
        now = self.current_slot()
        return now is not None and now > deadline

    @staticmethod
    def attestation_deadline_slot(att_slot: int) -> int:
        """Last slot at which gossip attestation/aggregate work for
        `att_slot` is still propagatable (spec propagation window)."""
        return int(att_slot) + ATTESTATION_PROPAGATION_SLOT_RANGE
