"""QoS: admission control, overload shedding, rate limiting, circuit breaking.

PR 1 taught the node to size batches per device and PR 2 made every stage
observable; this package is the layer that *protects* the pipeline when the
measured numbers go bad. The reference client treats overload as a design
concern — a priority-ordered work taxonomy, oldest-first shedding on the
batchable gossip queues (LIFO-queue semantics in
beacon_processor/src/lib.rs:301-372), and explicit backfill rate limiting —
and this package gives the TPU port the same spine:

  - `admission`: per-WorkKind priority classes consulted by
    `BeaconProcessor.submit`, slot-deadline stamping so an attestation that
    can no longer be attested is shed at pop time (counted `expired`, not
    `dropped`), and the `qos_shed_total{kind,reason}` family that accounts
    for every lost work item.
  - `ratelimit`: deterministic token buckets wrapping the HTTP API (429 +
    Retry-After instead of unbounded queued work) and gossip ingest.
  - `breaker`: a closed/open/half-open circuit breaker formalizing the
    hybrid BLS router's device-health handling; a stalled device degrades
    to the host path within one budget window, and recovery is probe-driven
    (`bls_device_circuit_state`).

The companion `lighthouse_tpu/loadgen` package proves all of it under
synthetic mainnet-shaped floods and injected faults.

Importing this package imports every submodule so the global metrics
registry is fully populated (scripts/lint_metrics.py relies on that).
"""

from .admission import (  # noqa: F401
    ATTESTATION_PROPAGATION_SLOT_RANGE,
    AdmissionController,
    PriorityClass,
    SHED_TOTAL,
    count_shed,
)
from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker  # noqa: F401
from .ratelimit import RateLimiter, TokenBucket  # noqa: F401
