"""Circuit breaker: closed / open / half-open with probe-driven recovery.

Formalizes what `HybridBackend` previously did ad hoc (count three device
errors, mark the device down, re-arm a probe thread): a breaker trips OPEN
after `failure_threshold` consecutive failures — where a failure is either
a raised dispatch or a verify slower than the caller's budget window — and
every request while open is refused in O(1), no per-call timeout spent.
After `reset_timeout` seconds the next `allow()` transitions to HALF_OPEN
and admits exactly one probe request; its recorded outcome either closes
the circuit or re-opens it for another cooldown.

The state is exported through a caller-supplied gauge (the hybrid router
wires `bls_device_circuit_state`: 0=closed, 1=open, 2=half_open) and every
transition lands in `qos_circuit_transitions_total{breaker,to}`, so the
closed→open→half_open→closed cycle is scrape-observable. Every transition
is also handed to the flight recorder (observability/flight_recorder.py)
AFTER the breaker lock is released — a transition to OPEN is an incident
trigger that may write a dump, and that IO must never block concurrent
`allow()` callers. The time source is injectable for deterministic tests
and the loadgen fault injector.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

STATE_VALUES = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

TRANSITIONS = REGISTRY.counter_vec(
    "qos_circuit_transitions_total",
    "circuit breaker state transitions, by breaker name and target state",
    ("breaker", "to"),
)

# the unified per-tenant state family: the plain per-breaker gauges
# (bls_device_circuit_state, tree_hash_circuit_state) predate the device
# ledger's workload naming and stay exported as DEPRECATED aliases so
# existing dashboards keep working; new consumers read this one
CIRCUIT_STATE = REGISTRY.gauge_vec(
    "circuit_state",
    "circuit state per tenant workload (0=closed, 1=open, 2=half_open); "
    "supersedes the per-breaker *_circuit_state gauges, which remain as "
    "deprecated aliases",
    ("workload",),
)


class CircuitBreaker:
    def __init__(self, name: str, *, failure_threshold: int = 3,
                 reset_timeout: float = 10.0, time_fn=time.monotonic,
                 state_gauge=None, workload=None):
        self.name = name
        # tenant identity in the unified circuit_state{workload} family;
        # breakers constructed without one only export their legacy gauge
        self.workload = None if workload is None else str(workload)
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout = float(reset_timeout)
        self._time = time_fn
        self._gauge = state_gauge
        self._log = get_logger(f"qos.breaker.{name}")
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        # inspection/test surface; bounded — a breaker flapping for the
        # life of a degraded node must not grow memory (the durable count
        # lives in qos_circuit_transitions_total)
        self.transitions: deque = deque([CLOSED], maxlen=64)
        # transitions awaiting flight-recorder notification (lock released);
        # _notify_lock serializes delivery so racing flushers cannot
        # reorder transitions (stale breaker_states would pin health at 206)
        self._pending_notify: list = []
        self._notify_lock = threading.Lock()
        if self._gauge is not None:
            self._gauge.set(STATE_VALUES[CLOSED])
        if self.workload is not None:
            CIRCUIT_STATE.labels(self.workload).set(STATE_VALUES[CLOSED])

    # ------------------------------------------------------------ internals

    def _transition_locked(self, to: str) -> None:
        if to == self._state:
            return
        self._state = to
        self.transitions.append(to)
        TRANSITIONS.labels(self.name, to).inc()
        if self._gauge is not None:
            self._gauge.set(STATE_VALUES[to])
        if self.workload is not None:
            CIRCUIT_STATE.labels(self.workload).set(STATE_VALUES[to])
        self._log.info("circuit transition", to=to,
                       failures=self._failures)
        # flight-recorder notification is DEFERRED: a transition to OPEN
        # triggers an incident dump, and that must run after the caller
        # releases self._lock (see module docstring)
        self._pending_notify.append((to, self._failures))

    def _flush_notify(self) -> None:
        """Hand collected transitions to the flight recorder; call with
        self._lock RELEASED. Items are popped under self._lock (two racing
        flushers must not IndexError on the shared list) and delivered
        under _notify_lock (oldest-first, never reordered). Lock order is
        strictly _notify_lock -> _lock; no path holds _lock while taking
        _notify_lock. The unguarded empty check keeps the common case —
        allow()/record_success() with nothing pending — from ever waiting
        behind a flusher that is busy writing an incident dump (a missed
        item here is delivered by the flusher that queued it)."""
        if not self._pending_notify:
            return
        with self._notify_lock:
            while True:
                with self._lock:
                    if not self._pending_notify:
                        return
                    to, failures = self._pending_notify.pop(0)
                try:
                    from ..observability.flight_recorder import RECORDER

                    RECORDER.note_breaker(self.name, to, failures=failures)
                except Exception:  # the black box must never break the breaker
                    pass

    # ------------------------------------------------------------- surface

    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a request use the protected path right now? In OPEN past the
        cooldown this transitions to HALF_OPEN and admits exactly one probe
        (further allow() calls refuse until the probe's outcome lands)."""
        try:
            with self._lock:
                if self._state == CLOSED:
                    return True
                if self._state == OPEN:
                    if self._time() - self._opened_at < self.reset_timeout:
                        return False
                    self._transition_locked(HALF_OPEN)
                    self._probe_inflight = True
                    return True
                # HALF_OPEN: one probe at a time
                if self._probe_inflight:
                    return False
                self._probe_inflight = True
                return True
        finally:
            self._flush_notify()

    def record_success(self) -> None:
        try:
            with self._lock:
                if self._state == OPEN:
                    # a straggler dispatched BEFORE the trip completed while
                    # open: it is not evidence of recovery (the pipelined
                    # flap: stall -> 3 failures -> open -> pre-trip handle
                    # lands fine -> circuit must stay open until the cooldown
                    # + half-open probe, or the refusal guarantee never holds)
                    return
                self._failures = 0
                self._probe_inflight = False
                if self._state != CLOSED:
                    self._transition_locked(CLOSED)
        finally:
            self._flush_notify()

    def record_failure(self) -> None:
        try:
            with self._lock:
                self._probe_inflight = False
                if self._state == HALF_OPEN:
                    # failed probe: straight back to open, fresh cooldown
                    self._opened_at = self._time()
                    self._transition_locked(OPEN)
                    return
                self._failures += 1
                if self._state == CLOSED and self._failures >= self.failure_threshold:
                    self._opened_at = self._time()
                    self._transition_locked(OPEN)
        finally:
            self._flush_notify()
