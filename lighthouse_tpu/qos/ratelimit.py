"""Token-bucket rate limiting for the ingest edges (HTTP API, gossip).

The reference rate-limits its Req/Resp server per protocol
(lighthouse_network/src/rpc/rate_limiter.rs: one token bucket per protocol,
requests over quota answered with a busy error instead of queued) and
rate-limits backfill sync as a batch-per-epoch-fraction budget. Here the
same primitive guards the two unbounded producers feeding the beacon
processor: HTTP submission routes answer 429 with Retry-After, and gossip
ingest drops over-quota messages as IGNOREs before they reach the queues.

Buckets are continuous-refill (classic token bucket: `rate` tokens/sec up
to `burst`), with an injectable time source so tests — and the loadgen
fault injector — drive them deterministically.
"""

from __future__ import annotations

import math
import threading
import time

from ..utils.metrics import REGISTRY

RATE_LIMITED = REGISTRY.counter_vec(
    "qos_rate_limited_total",
    "requests or gossip messages refused by a QoS token bucket, by scope",
    ("scope",),
)


class TokenBucket:
    """`rate` tokens/second, capacity `burst`; starts full."""

    def __init__(self, rate: float, burst: float | None = None,
                 time_fn=time.monotonic):
        if rate < 0:
            raise ValueError("rate must be >= 0")
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(1.0, rate))
        self._time = time_fn
        self._tokens = self.burst
        self._last = self._time()
        self._lock = threading.Lock()

    def _refill_locked(self, now: float) -> None:
        elapsed = max(0.0, now - self._last)
        self._last = now
        self._tokens = min(self.burst, self._tokens + elapsed * self.rate)

    def allow(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill_locked(self._time())
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until `n` tokens will be available (0 if they already
        are). With rate 0 the deficit never refills; report a long hold."""
        with self._lock:
            self._refill_locked(self._time())
            deficit = n - self._tokens
            if deficit <= 0:
                return 0.0
            if self.rate <= 0:
                return 3600.0
            return deficit / self.rate


class RateLimiter:
    """Named token buckets. An unconfigured scope always allows — callers
    wire scopes explicitly (`--http-rate-limit`, `--gossip-ingest-rate`)
    and everything else stays untouched."""

    def __init__(self, time_fn=time.monotonic):
        self._time = time_fn
        self._buckets: dict[str, TokenBucket] = {}
        self._denied: dict[str, int] = {}
        self._lock = threading.Lock()

    def configure(self, scope: str, rate: float,
                  burst: float | None = None) -> "RateLimiter":
        with self._lock:
            self._buckets[scope] = TokenBucket(rate, burst, self._time)
        return self

    def allow(self, scope: str, n: float = 1.0) -> bool:
        bucket = self._buckets.get(scope)
        if bucket is None:
            return True
        if bucket.allow(n):
            return True
        with self._lock:
            self._denied[scope] = self._denied.get(scope, 0) + 1
        RATE_LIMITED.labels(scope).inc()
        return False

    def retry_after(self, scope: str, n: float = 1.0) -> float:
        bucket = self._buckets.get(scope)
        return 0.0 if bucket is None else bucket.retry_after(n)

    def retry_after_secs(self, scope: str) -> int:
        """Retry-After header value: whole seconds, at least 1."""
        return max(1, math.ceil(self.retry_after(scope)))

    def denied(self, scope: str) -> int:
        with self._lock:
            return self._denied.get(scope, 0)
