"""Pipeline stage-timing snapshot + startup trace probe.

`snapshot()` backs the `/lighthouse_tpu/pipeline` ops endpoint: aggregate
per-stage timings (from the tracer's histogram family), live scheduler
state (from any registered BeaconProcessor), and summaries of the most
recent completed traces. Everything is read-only over data the hot path
already maintains — a snapshot never touches a lock the dispatch path
holds.

`run_probe()` pushes a small synthetic batch through a real
BeaconProcessor so a freshly started (or quiet) node still produces spans
for every pipeline stage — the `bn --trace-out` bring-up path uses it, and
the e2e scrape test rides the same code.
"""

from __future__ import annotations

import weakref

from . import perf
from .trace import PIPELINE_STAGES, STAGE_SECONDS, TRACER

_processors: list = []  # weakrefs to registered BeaconProcessors


def register_processor(proc) -> None:
    """Expose a BeaconProcessor's live queue state to snapshots. Weakly
    referenced: a stopped/collected processor drops out on its own."""
    _processors.append(weakref.ref(proc))


def _live_processors():
    out = []
    stale = []
    for ref in _processors:
        p = ref()
        if p is None:
            stale.append(ref)
        else:
            out.append(p)
    for ref in stale:
        _processors.remove(ref)
    return out


def _stage_stats() -> dict:
    """Per-(stage, kind) timing summary from the histogram family."""
    out: dict = {}
    for key, child in STAGE_SECONDS.children():
        stage, kind = key
        if child.n == 0:
            continue
        out.setdefault(stage, {})[kind] = {
            "count": child.n,
            "total_seconds": round(child.total, 6),
            "mean_seconds": round(child.total / child.n, 6),
        }
    return out


def snapshot() -> dict:
    stats = _stage_stats()
    procs = []
    for p in _live_processors():
        procs.append(p.stats())
    recent = []
    for tr in TRACER.snapshot_ring()[-32:]:
        recent.append(
            {
                "kind": tr.kind,
                "items": tr.n_items,
                "duration_seconds": round(tr.duration(), 6),
                "spans": [
                    {"stage": name, "seconds": round(t1 - t0, 6)}
                    for name, t0, t1, _ in tr.spans
                ],
                **({"meta": {k: str(v) for k, v in tr.meta.items()}}
                   if tr.meta else {}),
            }
        )
    out = {
        "stages": [s for s in PIPELINE_STAGES if s in stats],
        "stage_timings": stats,
        "processors": procs,
        "traces_completed": TRACER.completed,
        "recent_traces": recent,
    }
    # bench trend aggregate (observability/perf.py): latest headline round
    # with its carried-forward flag + the regression verdict, so the ops
    # endpoint answers "did we get slower" without shell access. Cached,
    # best-effort, absent when no BENCH artifacts ship with this install.
    trend = perf.trend_summary()
    if trend is not None:
        out["perf_trend"] = trend
    return out


def run_probe(n_items: int = 8) -> int:
    """Drive a synthetic attestation-shaped batch through a REAL
    BeaconProcessor end to end (enqueue -> coalesce -> marshal -> async
    verify handle -> device wait -> continuation) using the active BLS
    backend. Returns the number of work units executed.

    The sets are generator-point placeholders (verify False on real
    backends, True on fake) — the result is discarded; the trace is the
    point. Kept tiny so even the pure-Python backend finishes in ~a second.
    """
    from ..chain.beacon_processor import (
        BeaconProcessor,
        BeaconProcessorConfig,
        WorkItem,
        WorkKind,
    )
    from ..crypto import bls
    from ..crypto.bls381 import curve as cv
    from .slo import SlotAccountant

    pk = bls.PublicKey(cv.G1_GEN)
    sig = bls.Signature(cv.G2_GEN)

    def run_batch(payloads):
        sets = [
            bls.SignatureSet(sig, [pk], i.to_bytes(4, "little") * 8)
            for i in range(len(payloads))
        ]
        handle = bls.verify_signature_sets_async(sets)
        return handle, lambda ok: None

    proc = BeaconProcessor(
        BeaconProcessorConfig(max_attestation_batch=max(2, n_items))
    )
    # synthetic probe work must not pollute the node's production SLI (a
    # cold first dispatch reading as 8 deadline misses could trip the
    # burn-rate incident on a healthy node): throwaway accountant
    proc.slo = SlotAccountant(export_metrics=False)
    for i in range(n_items):
        proc.submit(
            WorkItem(
                kind=WorkKind.gossip_attestation, payload=i,
                run_batch=run_batch,
            )
        )
    return proc.run_until_idle()
