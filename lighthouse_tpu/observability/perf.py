"""Compiled-program analytics, roofline attribution, and the bench trend
gate.

Three jobs, one module, zero jax at import time:

1. **Program analytics** — at stage compile time (warm_stages, or the
   first attributed dispatch per bucket) capture the compiled XLA
   program's `cost_analysis()` / `memory_analysis()`: flops, bytes
   accessed, and the HBM footprint split (argument/output/temp/generated
   code). Each capture lands as labeled `xla_program_*` gauges, in the
   autotune profiler's per-bucket recorders (so the persisted device
   profile carries the program shape next to the measured timings —
   autotune/profile.py `programs`), and in the in-memory snapshot
   bench.py writes into BENCH artifacts. The `.lower().compile()` pair
   rides the persistent XLA compilation cache (utils/jaxcfg.py), so a
   stage that already compiled via the normal call path re-traces but
   never re-compiles.

2. **Roofline** — `roofline(stats, secs, device_kind)` turns a program's
   flops/bytes plus a measured stage time into achieved-FLOP/s and
   achieved-bytes/s against an ESTIMATED peak for the device kind
   (`PEAK_ESTIMATES`, overridable via LIGHTHOUSE_TPU_PEAK_FLOPS /
   LIGHTHOUSE_TPU_PEAK_HBM_GBPS). The verdict decomposes "0.143x est
   blst" into per-stage utilization: a stage at 2% of peak flops and 60%
   of HBM bandwidth is memory-bound and wants layout work, not math.
   Peaks are estimates — every roofline dict says so.

3. **Bench trend** — `trend_report()` parses the checked-in
   `BENCH_r*.json` / `MULTICHIP_r*.json` round series plus the current
   `BENCH_MATRIX.json`, renders carried-forward rounds distinctly
   (a round whose record is skipped — `"skipped": true`, a zero value,
   or a tunnel-UNAVAILABLE marker — inherits the latest fresh value,
   flagged, so a stale number is never read as a fresh measurement),
   computes fresh-to-fresh deltas, and flags >threshold regressions.
   `check()` is the gate: nonzero on regression. `bn perf report` and
   `scripts/perf_trend.py` are thin CLIs over `run_report()`; the
   aggregate also surfaces on `/lighthouse_tpu/pipeline` via
   `trend_summary()`. All stdlib — runs on CPU with no device attached.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading

from ..utils.metrics import REGISTRY

# ------------------------------------------------------------------ metrics

XLA_PROGRAM_FLOPS = REGISTRY.gauge_vec(
    "xla_program_flops",
    "XLA cost_analysis flop count of the compiled stage program, by jit "
    "stage and padding bucket",
    ("stage", "n_sets", "n_pks"),
)
XLA_PROGRAM_BYTES_ACCESSED = REGISTRY.gauge_vec(
    "xla_program_bytes_accessed",
    "XLA cost_analysis bytes-accessed estimate of the compiled stage "
    "program, by jit stage and padding bucket",
    ("stage", "n_sets", "n_pks"),
)
XLA_PROGRAM_HBM_BYTES = REGISTRY.gauge_vec(
    "xla_program_hbm_bytes",
    "compiled-program memory footprint from XLA memory_analysis, by jit "
    "stage, padding bucket and region (argument/output/temp/generated_code)",
    ("stage", "n_sets", "n_pks", "region"),
)

_lock = threading.Lock()
_programs: dict = {}       # (stage, (n, m)) -> stats dict
_analytics_override: bool | None = None

#: rough peak (flops/s, HBM bytes/s) per device kind PREFIX — estimates
#: for roofline context, not measurements (v5e: ~197 TFLOP/s bf16,
#: ~819 GB/s HBM; v4: ~275/1228; v5p: ~459/2765; CPU numbers are a
#: placeholder for dry runs). Longest matching prefix wins.
PEAK_ESTIMATES = {
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5e": (197e12, 819e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v6": (918e12, 1640e9),
    "cpu": (2e11, 8e10),
}


def set_analytics(on: bool | None) -> bool | None:
    """Force program-analytics capture on/off; returns the previous
    override so scoped callers can restore it."""
    global _analytics_override
    prev = _analytics_override
    _analytics_override = None if on is None else bool(on)
    return prev


def analytics_enabled() -> bool:
    if _analytics_override is not None:
        return _analytics_override
    env = os.environ.get("LIGHTHOUSE_TPU_PROGRAM_ANALYTICS", "").lower()
    return env in ("1", "on", "yes", "true")


def maybe_capture_program(stage: str, jitted_fn, args, bucket: tuple):
    """capture_program once per (stage, bucket); later calls are free."""
    key = (stage, (int(bucket[0]), int(bucket[1])))
    with _lock:
        if key in _programs:
            return _programs[key]
    return capture_program(stage, jitted_fn, args, bucket)


def capture_program(stage: str, jitted_fn, args, bucket: tuple) -> dict | None:
    """Lower+compile one jit stage at concrete args and record its cost/
    memory analysis. Best-effort: any failure returns None and records
    nothing (a node on an exotic backend must not lose the verify path
    to a diagnostics call)."""
    n, m = int(bucket[0]), int(bucket[1])
    try:
        compiled = jitted_fn.lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        stats = {
            "flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        }
        ma = compiled.memory_analysis()
        if ma is not None:
            stats.update(
                argument_bytes=int(getattr(ma, "argument_size_in_bytes", 0)),
                output_bytes=int(getattr(ma, "output_size_in_bytes", 0)),
                temp_bytes=int(getattr(ma, "temp_size_in_bytes", 0)),
                generated_code_bytes=int(
                    getattr(ma, "generated_code_size_in_bytes", 0)
                ),
            )
    except Exception:
        return None
    record_program(stage, bucket, stats)
    return stats


def record_program(stage: str, bucket: tuple, stats: dict) -> None:
    """Publish one program's stats: gauges, snapshot, autotune recorder."""
    n, m = int(bucket[0]), int(bucket[1])
    XLA_PROGRAM_FLOPS.labels(stage, n, m).set(stats.get("flops", 0.0))
    XLA_PROGRAM_BYTES_ACCESSED.labels(stage, n, m).set(
        stats.get("bytes_accessed", 0.0)
    )
    for region in ("argument", "output", "temp", "generated_code"):
        v = stats.get(f"{region}_bytes")
        if v is not None:
            XLA_PROGRAM_HBM_BYTES.labels(stage, n, m, region).set(v)
    with _lock:
        _programs[(stage, (n, m))] = dict(stats)
    try:
        from ..autotune import profiler

        profiler.observe_program(n, m, stage, stats)
    except Exception:
        pass  # diagnostics must never raise into the dispatch path


def program_stats(stage: str, bucket: tuple) -> dict | None:
    with _lock:
        st = _programs.get((stage, (int(bucket[0]), int(bucket[1]))))
    return dict(st) if st else None


def program_snapshot() -> dict:
    """{"<n>x<m>": {stage: stats}} for everything captured so far."""
    with _lock:
        items = list(_programs.items())
    out: dict = {}
    for (stage, (n, m)), stats in items:
        out.setdefault(f"{n}x{m}", {})[stage] = dict(stats)
    return out


def reset_programs() -> None:
    """Drop captured program stats (tests)."""
    with _lock:
        _programs.clear()


# ----------------------------------------------------------------- roofline


def peak_for(device_kind: str | None) -> tuple | None:
    """(peak flops/s, peak HBM bytes/s) ESTIMATE for a device kind.
    Env overrides (LIGHTHOUSE_TPU_PEAK_FLOPS teraflops/s,
    LIGHTHOUSE_TPU_PEAK_HBM_GBPS gigabytes/s) beat the table."""
    env_f = os.environ.get("LIGHTHOUSE_TPU_PEAK_FLOPS")
    env_b = os.environ.get("LIGHTHOUSE_TPU_PEAK_HBM_GBPS")
    if env_f and env_b:
        return float(env_f) * 1e12, float(env_b) * 1e9
    if not device_kind:
        return None
    best = None
    for prefix, peaks in PEAK_ESTIMATES.items():
        if device_kind.lower().startswith(prefix.lower()):
            if best is None or len(prefix) > best[0]:
                best = (len(prefix), peaks)
    if best is None:
        return None
    pf, pb = best[1]
    if env_f:
        pf = float(env_f) * 1e12
    if env_b:
        pb = float(env_b) * 1e9
    return pf, pb


def roofline(stats: dict, secs: float, device_kind: str | None) -> dict | None:
    """Achieved vs estimated-peak throughput for one stage execution.

    `stats` is a capture_program dict; `secs` a measured wall time for
    one execution of that program. Returns achieved flops/s + bytes/s,
    utilization fractions where a peak estimate exists, and which wall
    the stage is closer to ("compute" vs "memory")."""
    if not secs or secs <= 0:
        return None
    flops = float(stats.get("flops") or 0.0)
    byts = float(stats.get("bytes_accessed") or 0.0)
    out = {
        "seconds": round(secs, 6),
        "achieved_gflops_per_sec": round(flops / secs / 1e9, 3),
        "achieved_gbytes_per_sec": round(byts / secs / 1e9, 3),
        "peak_note": "peaks are ESTIMATES (PEAK_ESTIMATES / env overrides)",
    }
    peaks = peak_for(device_kind)
    if peaks is not None:
        pf, pb = peaks
        fu = flops / secs / pf if pf else 0.0
        bu = byts / secs / pb if pb else 0.0
        out.update(
            flops_utilization=round(fu, 6),
            hbm_utilization=round(bu, 6),
            bound="memory" if bu > fu else "compute",
            device_kind=device_kind,
        )
    return out


# ------------------------------------------------------------- bench trend

#: every vs_est_* denominator in bench.py is an estimate; the report
#: header must say so (BASELINE.md / bench.py baseline_note)
EST_CAVEAT = (
    "vs_est_*/vs_baseline ratios divide by ESTIMATED single-core "
    "blst/c-kzg throughputs (EST_* constants in bench.py) — "
    "estimated, not measured"
)

DEFAULT_REGRESSION_THRESHOLD = 0.10


def default_root() -> str:
    """Repo root (where the BENCH_r*/MULTICHIP_r* artifacts live)."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _round_files(root: str, pattern: str) -> list:
    out = []
    for path in glob.glob(os.path.join(root, pattern)):
        m = re.search(r"_r(\d+)\.json$", path)
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


def load_bench_rounds(root: str | None = None) -> list:
    """BENCH_r*.json -> round dicts, oldest first, with skipped rounds
    carrying forward the latest fresh value (flagged, never silently)."""
    root = root or default_root()
    rounds = []
    for n, path in _round_files(root, "BENCH_r*.json"):
        name = os.path.basename(path)
        try:
            with open(path) as f:
                parsed = (json.load(f) or {}).get("parsed") or {}
        except (OSError, json.JSONDecodeError, AttributeError):
            parsed = {}
        metric = str(parsed.get("metric", ""))
        try:
            value = float(parsed.get("value") or 0.0)
        except (TypeError, ValueError):
            value = 0.0
        vs_est = parsed.get("vs_baseline")
        # a round is FRESH only when it measured something: an explicit
        # skipped flag, a zero value, or a tunnel-outage marker in the
        # metric string all mean "no measurement this run"
        skipped = (
            bool(parsed.get("skipped"))
            or value <= 0.0
            or "UNAVAILABLE" in metric.upper()
            or "SKIPPED" in metric.upper()
        )
        try:
            c1_p50 = float(parsed.get("config1_p50_ms") or 0.0) or None
        except (TypeError, ValueError):
            c1_p50 = None
        rounds.append(
            {
                "round": n,
                "source": name,
                "fresh": not skipped and bool(parsed),
                "value": value if not skipped else (value or None),
                "vs_est": vs_est if not skipped else None,
                "raw_vs_est": vs_est,
                "note": parsed.get("note"),
                # the urgent-path latency series (bench.py config 1,
                # recorded in the headline JSON since r8): only a FRESH
                # round's p50 may enter the latency trend
                "config1_p50_ms": c1_p50 if not skipped else None,
                # executor config of the run (depth/donation/msm window)
                "pipeline": parsed.get("pipeline"),
            }
        )
    last_fresh = None
    for r in rounds:
        if r["fresh"]:
            last_fresh = r
            r["carried"] = False
            continue
        if r["value"]:
            # the artifact itself carried a value forward (bench.py
            # _tunnel_down since r5): keep its value AND vs ratio, and
            # name the source round it cites (falling back to the latest
            # fresh round we saw)
            r["carried"] = True
            m = re.search(r"BENCH_r\d+\.json", r.get("note") or "")
            r["carried_from"] = m.group(0) if m else (
                last_fresh["source"] if last_fresh else "artifact carry-forward"
            )
            if r["vs_est"] is None:
                r["vs_est"] = r["raw_vs_est"]
        elif last_fresh is not None:
            r["carried"] = True
            r["carried_from"] = last_fresh["source"]
            r["value"] = last_fresh["value"]
            r["vs_est"] = last_fresh["vs_est"]
        else:
            r["carried"] = False
    for r in rounds:
        r.pop("raw_vs_est", None)
    return rounds


def load_multichip_rounds(root: str | None = None) -> list:
    root = root or default_root()
    rounds = []
    for n, path in _round_files(root, "MULTICHIP_r*.json"):
        try:
            with open(path) as f:
                d = json.load(f) or {}
        except (OSError, json.JSONDecodeError):
            d = {}
        rounds.append(
            {
                "round": n,
                "source": os.path.basename(path),
                "skipped": bool(d.get("skipped")),
                "ok": bool(d.get("ok")),
                "n_devices": d.get("n_devices"),
            }
        )
    return rounds


_RATE_KEYS = (
    "sets_per_sec", "verifies_per_sec", "blocks_per_sec", "blobs_per_sec",
    "roots_per_sec", "epochs_per_sec",
)

#: key families write_loadtest_rows accepts: loadtest_* rows come from
#: `bn loadtest` snapshots; state_root / epoch_transition rows from
#: scripts/bench_state_root.py --bench-matrix — the second workload's
#: bench rows beside the BLS configs
WORKLOAD_ROW_PREFIXES = ("loadtest_", "state_root", "epoch_transition")

#: bounded per-row measurement history (the state-root p50 trend series
#: reads it — every appended entry is a fresh measurement by construction)
MAX_ROW_HISTORY = 12


def write_loadtest_rows(rows: dict, smoke: bool = True,
                        root: str | None = None) -> str:
    """Merge measured workload rows into the BENCH_MATRIX schema — the
    tunnel-proof bench seam: `bn loadtest` (flood / the --mesh-devices
    sweep, and any future on-TPU soak) snapshots its measured sets/s +
    p50 here, and `bench_state_root.py --bench-matrix` lands the
    state_root / epoch_transition rows of the second device workload the
    same way — so any soak or host-provable bench doubles as a bench
    round and the trend gate reads the rows as FRESH measurements.
    Read-merge-write: bench.py's configs are preserved; only
    WORKLOAD_ROW_PREFIXES keys are touched, and rows carrying a p50
    accumulate a bounded `history` of fresh entries (the fresh-to-fresh
    series the state-root p50 trend gate checks). Smoke runs land in the
    gitignored-by-convention *_SMOKE variant, same rule as bench.py — a
    CPU harness must never clobber the on-chip artifact of record."""
    root = root or default_root()
    name = "BENCH_MATRIX_SMOKE.json" if smoke else "BENCH_MATRIX.json"
    path = os.path.join(root, name)
    try:
        with open(path) as f:
            matrix = json.load(f) or {}
    except (OSError, json.JSONDecodeError):
        matrix = {}
    for key, row in rows.items():
        key = str(key)
        if not key.startswith(WORKLOAD_ROW_PREFIXES):
            raise ValueError(
                "workload matrix rows must be keyed "
                f"{'/'.join(WORKLOAD_ROW_PREFIXES)}*: {key!r}"
            )
        row = dict(row, source=row.get("source", "loadtest"))
        if (
            row.get("p50_ms") is not None
            or row.get("scheduler_ratio") is not None
        ):
            prev = matrix.get(key)
            history = list(prev.get("history") or []) if isinstance(
                prev, dict
            ) else []
            entry = {
                "measured_unix": row.get("measured_unix"),
                "fresh": True,
            }
            if row.get("p50_ms") is not None:
                entry["p50_ms"] = row["p50_ms"]
            if row.get("scheduler_ratio") is not None:
                # the capacity-control proof's controller-vs-static-optimal
                # ratio (loadgen/capacity.py): the capacity_ratio trend
                # series reads this history fresh-to-fresh
                entry["scheduler_ratio"] = row["scheduler_ratio"]
            # measurement config rides each entry so the trend gate only
            # compares like with like — a host-vs-device (or resized)
            # re-measurement, or a different harness (bench_state_root vs
            # a loadtest soak), is a configuration change, not a regression
            for k in ("hash_backend", "validators", "source", "scenario"):
                if row.get(k) is not None:
                    entry[k] = row[k]
            history.append(entry)
            row["history"] = history[-MAX_ROW_HISTORY:]
        matrix[key] = row
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(matrix, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_matrix(root: str | None = None, name: str = "BENCH_MATRIX.json") -> dict:
    """Per-config summary of the current measurement matrix, with
    config*_skipped / config*_error flags kept distinct from measured
    configs (a skipped config must never read as a measured one).
    loadtest_* rows (write_loadtest_rows) parse like configs and carry
    their `source: loadtest` tag through — they are fresh by
    construction (the writer stamps them at measurement time)."""
    root = root or default_root()
    try:
        with open(os.path.join(root, name)) as f:
            matrix = json.load(f) or {}
    except (OSError, json.JSONDecodeError):
        return {}
    out: dict = {}
    for key, val in matrix.items():
        m = re.match(
            r"^(config\d+|loadtest_\w+|state_root\w*|epoch_transition\w*)"
            r"(?:_(skipped|error))?$",
            key,
        )
        if not m:
            m = re.match(r"^(config\d+)(?:_(skipped|error))?", key)
        if not m:
            continue
        config, flag = m.group(1), m.group(2)
        entry = out.setdefault(config, {})
        if flag:
            entry[flag] = val
            continue
        if not isinstance(val, dict):
            continue
        entry["name"] = key
        for rk in _RATE_KEYS:
            # a null rate (hand-edited or legacy artifact) must degrade to
            # "no measurement", not crash every later trend read
            if val.get(rk) is not None:
                entry["rate"] = float(val[rk])
                entry["rate_unit"] = rk
                break
        for k in ("p50_ms", "p99_ms"):
            if k in val:
                entry[k] = val[k]
        for k in ("source", "n_devices", "measured_unix", "history",
                  "scheduler_ratio"):
            if k in val:
                entry[k] = val[k]
        for k, v in val.items():
            if k.startswith("vs_est"):
                entry["vs_est"] = v
                entry["vs_est_key"] = k
    return out


def trend_report(
    root: str | None = None,
    threshold: float = DEFAULT_REGRESSION_THRESHOLD,
) -> dict:
    """The full per-config trend + regression verdict over the checked-in
    artifacts. Regressions compare FRESH values only — carried-forward
    rounds can neither cause nor mask one."""
    root = root or default_root()
    bench = load_bench_rounds(root)
    multichip = load_multichip_rounds(root)
    matrix = load_matrix(root)
    regressions = []

    fresh = [r for r in bench if r["fresh"]]
    deltas = []
    for prev, cur in zip(fresh, fresh[1:]):
        delta = (cur["value"] - prev["value"]) / prev["value"]
        deltas.append(
            {
                "config": "headline",
                "from": prev["source"],
                "to": cur["source"],
                "delta_pct": round(delta * 100.0, 2),
            }
        )
        if delta < -threshold:
            regressions.append(
                {
                    "config": "headline",
                    "prev": prev["value"],
                    "cur": cur["value"],
                    "from": prev["source"],
                    "to": cur["source"],
                    "delta_pct": round(delta * 100.0, 2),
                }
            )

    # config1 urgent-path p50 (ms, LOWER is better): a fresh-to-fresh
    # latency increase past the threshold gates CI exactly like a
    # throughput drop — raw speed regressions on the urgent lane must
    # not hide behind a healthy headline
    lat_fresh = [r for r in fresh if r.get("config1_p50_ms")]
    lat_deltas = []
    for prev, cur in zip(lat_fresh, lat_fresh[1:]):
        delta = (
            cur["config1_p50_ms"] - prev["config1_p50_ms"]
        ) / prev["config1_p50_ms"]
        lat_deltas.append(
            {
                "config": "config1_p50",
                "from": prev["source"],
                "to": cur["source"],
                "delta_pct": round(delta * 100.0, 2),
            }
        )
        if delta > threshold:
            regressions.append(
                {
                    "config": "config1_p50",
                    "prev": prev["config1_p50_ms"],
                    "cur": cur["config1_p50_ms"],
                    "from": prev["source"],
                    "to": cur["source"],
                    "delta_pct": round(delta * 100.0, 2),
                }
            )

    # state-root p50 (ms, LOWER is better) — the second workload's trend
    # series, read from the bounded histories of EVERY state_root* row
    # (the 16k row keeps the historic unsuffixed key; scale variants like
    # state_root_1m land beside it — same-config gating below already
    # separates them by validator count). Every entry written by
    # bench_state_root.py --bench-matrix is a fresh measurement; entries
    # marked fresh=false — a hand-carried or legacy value — render as
    # carried and can neither cause nor mask a regression, the
    # config1_p50 contract.
    # row histories are append-ordered (write_loadtest_rows), which IS the
    # chronology within a row; rows never share a config key (validators
    # differ), so concatenation order across rows cannot create a
    # cross-row pair below — no re-sort by measured_unix (tests use it as
    # an opaque stamp, not a clock)
    sr_entries = [
        e
        for key in sorted(matrix)
        if key == "state_root" or key.startswith("state_root_")
        for e in ((matrix.get(key) or {}).get("history") or [])
        if isinstance(e, dict)
    ]
    sr_fresh = [
        e for e in sr_entries if e.get("fresh", True) and e.get("p50_ms")
    ]
    sr_deltas = []
    # each fresh entry compares against the MOST RECENT prior fresh entry
    # of the SAME measurement config (backend/validators/harness) — a
    # config flip (host->device, resized run, bench vs loadtest) is not a
    # regression, and an interleaved flip must not mask the next
    # same-config comparison either
    _last_by_config: dict = {}
    for cur in sr_fresh:
        cfg = tuple(
            cur.get(k) for k in ("hash_backend", "validators", "source")
        )
        prev = _last_by_config.get(cfg)
        _last_by_config[cfg] = cur
        if prev is None:
            continue
        delta = (cur["p50_ms"] - prev["p50_ms"]) / prev["p50_ms"]
        sr_deltas.append(
            {"config": "state_root_p50", "delta_pct": round(delta * 100.0, 2)}
        )
        if delta > threshold:
            regressions.append(
                {
                    "config": "state_root_p50",
                    "prev": prev["p50_ms"],
                    "cur": cur["p50_ms"],
                    "from": f"history@{prev.get('measured_unix')}",
                    "to": f"history@{cur.get('measured_unix')}",
                    "delta_pct": round(delta * 100.0, 2),
                }
            )

    # capacity controller-vs-static-optimal ratio (HIGHER is better) — the
    # closed-loop scheduler's trend series, read from the loadtest_* rows'
    # histories (loadgen/driver.py _drive_capacity writes them). A
    # fresh-to-fresh DROP past the threshold gates CI: a scheduler change
    # that loses ground against the same static-optimal reference is a
    # controller regression even while the absolute gate still passes.
    # Same-config comparison only (scenario/validators/source stamped per
    # entry), the state_root_p50 contract.
    cap_entries = []
    cap_deltas = []
    for cfg_key in sorted(matrix):
        if not cfg_key.startswith("loadtest_"):
            continue
        hist = [
            e for e in (matrix[cfg_key].get("history") or [])
            if isinstance(e, dict) and e.get("scheduler_ratio") is not None
        ]
        if not hist:
            continue
        cap_entries.extend(dict(e, row=cfg_key) for e in hist)
        _last: dict = {}
        for cur in hist:
            if not cur.get("fresh", True):
                continue
            cfg = tuple(
                cur.get(k) for k in ("scenario", "validators", "source")
            )
            prev = _last.get(cfg)
            _last[cfg] = cur
            if prev is None or not prev.get("scheduler_ratio"):
                continue
            delta = (
                cur["scheduler_ratio"] - prev["scheduler_ratio"]
            ) / prev["scheduler_ratio"]
            cap_deltas.append(
                {
                    "config": "capacity_ratio",
                    "row": cfg_key,
                    "delta_pct": round(delta * 100.0, 2),
                }
            )
            if delta < -threshold:
                regressions.append(
                    {
                        "config": "capacity_ratio",
                        "prev": prev["scheduler_ratio"],
                        "cur": cur["scheduler_ratio"],
                        "from": f"{cfg_key}@{prev.get('measured_unix')}",
                        "to": f"{cfg_key}@{cur.get('measured_unix')}",
                        "delta_pct": round(delta * 100.0, 2),
                    }
                )

    mc_fresh = [r for r in multichip if not r["skipped"]]
    if mc_fresh and not mc_fresh[-1]["ok"] and any(r["ok"] for r in mc_fresh[:-1]):
        last_ok = [r for r in mc_fresh[:-1] if r["ok"]][-1]
        regressions.append(
            {
                "config": "multichip",
                "prev": "ok",
                "cur": "failed",
                "from": last_ok["source"],
                "to": mc_fresh[-1]["source"],
                "delta_pct": None,
            }
        )

    return {
        "caveat": EST_CAVEAT,
        "threshold_pct": round(threshold * 100.0, 1),
        "headline": {"rounds": bench, "deltas": deltas},
        "config1_p50": {
            "rounds": [
                {
                    "round": r["round"],
                    "source": r["source"],
                    "p50_ms": r["config1_p50_ms"],
                }
                for r in lat_fresh
            ],
            "deltas": lat_deltas,
        },
        "state_root_p50": {"entries": sr_entries, "deltas": sr_deltas},
        "capacity_ratio": {"entries": cap_entries, "deltas": cap_deltas},
        "multichip": {"rounds": multichip},
        "matrix": matrix,
        "regressions": regressions,
        "ok": not regressions,
    }


def check(
    root: str | None = None,
    threshold: float = DEFAULT_REGRESSION_THRESHOLD,
) -> tuple:
    """(exit_code, report): nonzero when any fresh-to-fresh delta drops
    more than `threshold` (the CI gate behind scripts/perf_trend.py
    --check and the lint gate)."""
    report = trend_report(root, threshold)
    return (0 if report["ok"] else 1), report


_trend_cache: dict = {}  # root -> (monotonic deadline, summary)


def trend_summary(root: str | None = None) -> dict | None:
    """Small cached aggregate for /lighthouse_tpu/pipeline: the latest
    headline round (with its carried-forward flag), the regression
    verdict, and the estimate caveat. None when no artifacts exist."""
    import time

    root = root or default_root()
    now = time.monotonic()
    hit = _trend_cache.get(root)
    if hit is not None and hit[0] > now:
        return hit[1]
    try:
        report = trend_report(root)
    except Exception:
        return None
    rounds = report["headline"]["rounds"]
    if not rounds and not report["matrix"]:
        return None
    latest = rounds[-1] if rounds else None
    summary = {
        "caveat": report["caveat"],
        "regressions": len(report["regressions"]),
        "ok": report["ok"],
    }
    if latest is not None:
        summary["headline_latest"] = {
            "source": latest["source"],
            "value_sets_per_sec": latest["value"],
            "vs_est_blst": latest["vs_est"],
            "fresh": latest["fresh"],
            **(
                {"carried_from": latest.get("carried_from")}
                if latest.get("carried")
                else {}
            ),
        }
    _trend_cache[root] = (now + 30.0, summary)
    return summary


# ------------------------------------------------------------ report render


def render_report(report: dict) -> str:
    """Human-readable trend report (bn perf report / scripts/perf_trend.py).
    Carried-forward rounds and skipped matrix configs render unmistakably
    distinct from fresh measurements."""
    lines = [
        "lighthouse-tpu perf trend",
        f"  CAVEAT: {report['caveat']}",
        "",
        "headline (BENCH_r*.json, sets/s):",
    ]
    for r in report["headline"]["rounds"]:
        val = f"{r['value']:.2f}" if r["value"] else "—"
        vs = f"  vs_est_blst={r['vs_est']}" if r.get("vs_est") is not None else ""
        if r["fresh"]:
            tag = "fresh"
        elif r.get("carried"):
            tag = (
                f"CARRIED FORWARD from {r['carried_from']} — "
                "not a fresh measurement"
            )
        else:
            tag = "SKIPPED (no measurement, nothing to carry)"
        lines.append(f"  r{r['round']:02d}  {val:>10s}{vs}  [{tag}]")
    for d in report["headline"]["deltas"]:
        lines.append(
            f"  delta {d['from']} -> {d['to']}: {d['delta_pct']:+.2f}%"
        )
    lat = report.get("config1_p50") or {}
    if lat.get("rounds"):
        lines.append("")
        lines.append(
            "config1 urgent-path p50 (ms, lower is better; fresh rounds "
            "only):"
        )
        for r in lat["rounds"]:
            lines.append(f"  r{r['round']:02d}  {r['p50_ms']:>10.2f}")
        for d in lat["deltas"]:
            lines.append(
                f"  delta {d['from']} -> {d['to']}: {d['delta_pct']:+.2f}%"
            )
    sr = report.get("state_root_p50") or {}
    if sr.get("entries"):
        lines.append("")
        lines.append(
            "state_root p50 (ms, lower is better; BENCH_MATRIX "
            "state_root row history):"
        )
        for e in sr["entries"]:
            if e.get("fresh", True) and e.get("p50_ms"):
                tag = "fresh"
            else:
                tag = "CARRIED FORWARD — not a fresh measurement"
            val = f"{e['p50_ms']:.2f}" if e.get("p50_ms") else "—"
            lines.append(
                f"  @{e.get('measured_unix')}  {val:>10s}  [{tag}]"
            )
        for d in sr["deltas"]:
            lines.append(f"  delta: {d['delta_pct']:+.2f}%")
    cap = report.get("capacity_ratio") or {}
    if cap.get("entries"):
        lines.append("")
        lines.append(
            "capacity controller vs static-optimal (ratio, higher is "
            "better; loadtest_* row histories):"
        )
        for e in cap["entries"]:
            tag = "fresh" if e.get("fresh", True) else (
                "CARRIED FORWARD — not a fresh measurement"
            )
            lines.append(
                f"  {e.get('row')}@{e.get('measured_unix')}  "
                f"{e.get('scheduler_ratio')}  [{tag}]"
            )
        for d in cap["deltas"]:
            lines.append(
                f"  delta ({d['row']}): {d['delta_pct']:+.2f}%"
            )
    lines.append("")
    lines.append("multichip (MULTICHIP_r*.json):")
    for r in report["multichip"]["rounds"]:
        if r["skipped"]:
            tag = "SKIPPED"
        else:
            tag = "ok" if r["ok"] else "FAILED"
        lines.append(
            f"  r{r['round']:02d}  {tag}  (n_devices={r['n_devices']})"
        )
    if report["matrix"]:
        lines.append("")
        lines.append("current matrix (BENCH_MATRIX.json):")
        for config in sorted(report["matrix"]):
            e = report["matrix"][config]
            if "skipped" in e:
                lines.append(
                    f"  {config}: SKIPPED ({e['skipped']}) — no measurement"
                )
                continue
            if "error" in e and "rate" not in e:
                lines.append(f"  {config}: ERROR ({e['error']})")
                continue
            bits = []
            if "rate" in e:
                bits.append(f"{e['rate']} {e['rate_unit']}")
            if "p50_ms" in e:
                bits.append(f"p50={e['p50_ms']}ms")
            if e.get("vs_est") is not None:
                bits.append(f"{e['vs_est_key']}={e['vs_est']} (estimated)")
            if e.get("source") == "loadtest":
                nd = e.get("n_devices")
                bits.append(
                    "source=loadtest (fresh soak snapshot"
                    + (f", {nd} device(s))" if nd else ")")
                )
            lines.append(f"  {config}: " + ", ".join(bits))
    lines.append("")
    if report["regressions"]:
        lines.append(
            f"REGRESSION: {len(report['regressions'])} config(s) dropped "
            f">{report['threshold_pct']}% between fresh rounds:"
        )
        for r in report["regressions"]:
            lines.append(
                f"  {r['config']}: {r['prev']} -> {r['cur']} "
                f"({r['from']} -> {r['to']}, {r['delta_pct']}%)"
            )
    else:
        lines.append(
            f"verdict: OK — no fresh-to-fresh drop exceeds "
            f"{report['threshold_pct']}%"
        )
    return "\n".join(lines)


def run_report(
    root: str | None = None,
    check_mode: bool = False,
    threshold: float = DEFAULT_REGRESSION_THRESHOLD,
    as_json: bool = False,
) -> int:
    """Shared driver behind `bn perf report` and scripts/perf_trend.py."""
    rc, report = check(root, threshold)
    if as_json:
        print(json.dumps(report, indent=1))
    else:
        print(render_report(report))
    return rc if check_mode else 0
