"""Span tracer for the verification dataflow.

Model: a `Trace` is one work unit's journey through the pipeline — a
coalesced gossip batch, a single work item, a device dispatch. Stages are
recorded as closed spans (name, t0, t1, args); the processor owns the
canonical stage names (PIPELINE_STAGES) but producers may add sub-spans
(the jaxbls backend annotates marshalled bytes and its dispatch split).

Every finished span feeds the `pipeline_stage_seconds{stage,kind}`
histogram family; the finished trace lands in a bounded ring. The ring
serves two consumers:

  - `/lighthouse_tpu/pipeline` (observability/pipeline.py): recent-trace
    summaries next to the aggregate stage timings;
  - Chrome trace-event export (`bn --trace-out`): `chrome_trace_events`
    renders the ring in the trace-event JSON schema Perfetto/chrome://
    tracing load directly — one "thread" row per pipeline lane, complete
    ("ph": "X") events with microsecond timestamps. Spans named
    `device:<stage>` (the per-stage attribution sub-spans from
    observability/device.py) are routed onto dedicated, named device
    lanes so host pipeline stages and device stage execution read as one
    timeline; sampled queue depths export as counter events ("ph": "C")
    so backlog renders next to the spans.

Cost model: the hot path pays one Trace alloc + a span tuple append per
stage per BATCH (not per attestation), and one histogram observe per span
— dict lookups and float math, no syscalls, no locks beyond the metric's.
Timestamps are time.perf_counter() (monotonic); the export rebases them so
t=0 is the oldest event in the ring.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
from collections import deque
from time import perf_counter

from ..utils.metrics import REGISTRY

#: canonical stage order of the verification dataflow; the acceptance
#: surface for exports (docs/OBSERVABILITY.md "Trace stages")
PIPELINE_STAGES = ("enqueue", "coalesce", "marshal", "device", "continuation")

# spans range from sub-ms queue pops to multi-minute cold compiles
_STAGE_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0, 60.0, 300.0,
)

STAGE_SECONDS = REGISTRY.histogram_vec(
    "pipeline_stage_seconds",
    "verification dataflow stage wall time, by stage and work kind",
    ("stage", "kind"),
    buckets=_STAGE_BUCKETS,
)

TRACES_TOTAL = REGISTRY.counter_vec(
    "pipeline_traces_total",
    "completed pipeline traces, by work kind",
    ("kind",),
)


#: process-wide monotonic trace ids — the correlation key the flight
#: recorder stamps on events recorded while a trace is current, so an
#: incident dump's event list joins against its recent-trace list
_next_trace_id = itertools.count(1)


def next_trace_id() -> int:
    """Allocate one id from the process-wide trace-id sequence (publish
    contexts built outside any Trace still need a unique causal key)."""
    return next(_next_trace_id)


class Trace:
    """One work unit's spans. Append-only; finished via Tracer.finish."""

    __slots__ = ("kind", "n_items", "t0", "spans", "meta", "trace_id", "ctx")

    def __init__(self, kind: str, n_items: int = 1):
        self.kind = kind
        self.n_items = n_items
        self.t0 = perf_counter()
        self.trace_id = next(_next_trace_id)
        self.spans: list = []        # (name, t0, t1, args|None)
        self.meta: dict = {}
        # wire-propagated origin context (observability/propagation.py):
        # set on the producer side at publish and ADOPTED on every
        # consumer, so a block's publish span and its remote
        # validate/import spans share one causal id — the merged Perfetto
        # export links them with flow events keyed on it
        self.ctx = None

    def add_span(self, name: str, t0: float, t1: float, **args) -> None:
        self.spans.append((name, t0, t1, args or None))

    def adopt(self, ctx) -> None:
        """Adopt a WireTraceContext into this trace (cross-node causal
        join): the context becomes the trace's flow key and its origin
        fields land in the exported span args."""
        self.ctx = ctx
        self.meta.update(
            causal=ctx.causal_id(), origin=ctx.origin,
            origin_slot=ctx.slot, origin_seq=ctx.seq,
        )

    def annotate(self, **kv) -> None:
        """Attach key/values to the whole trace (bucket, bytes, ...)."""
        self.meta.update(kv)

    def duration(self) -> float:
        if not self.spans:
            return 0.0
        return max(t1 for _, _, t1, _ in self.spans) - min(
            t0 for _, t0, _, _ in self.spans
        )


# wire-context thread-local (set by the transport's CREQ serve path):
# traces begun on a thread with a bound wire context auto-adopt it, so a
# served request's spans join the caller's causal chain without plumbing
# a context argument through every handler signature
_wire_tls = threading.local()


def set_current_wire_ctx(ctx) -> None:
    """Bind the wire context of the request being served to this thread
    (transport `Connection._serve`); `Tracer.begin` adopts it."""
    _wire_tls.ctx = ctx


def current_wire_ctx():
    return getattr(_wire_tls, "ctx", None)


class Tracer:
    """Bounded ring of completed traces + per-stage histogram feed."""

    def __init__(self, ring_size: int = 256, counter_ring_size: int = 2048):
        self.ring: deque = deque(maxlen=ring_size)
        # sampled counter values (t, name, {series: value}) — queue depths
        # today; exported as "ph": "C" rows next to the spans
        self.counter_ring: deque = deque(maxlen=counter_ring_size)
        self._lock = threading.Lock()
        self.completed = 0
        self.out_path: str | None = None  # bn --trace-out destination
        # optional () -> [(t_mono, name, args)] provider of instant-event
        # markers for the export; the flight recorder wires itself onto
        # the global TRACER at import (test-local Tracer instances export
        # only their own spans)
        self.instants_source = None
        # optional () -> [(track, name, t0, t1, args)] provider of the
        # device ledger's merged per-workload occupancy timeline; the
        # ledger wires itself onto the global TRACER at import, the same
        # contract as instants_source
        self.device_timeline_source = None

    def begin(self, kind: str, n_items: int = 1) -> Trace:
        tr = Trace(kind, n_items)
        ctx = current_wire_ctx()
        if ctx is not None:
            tr.adopt(ctx)
        return tr

    def finish(self, trace: Trace | None) -> None:
        if trace is None:
            return
        for name, t0, t1, _args in trace.spans:
            STAGE_SECONDS.labels(name, trace.kind).observe(t1 - t0)
        TRACES_TOTAL.labels(trace.kind).inc()
        with self._lock:
            self.ring.append(trace)
            self.completed += 1

    def sample_counters(self, name: str, values: dict) -> None:
        """Record one sample of a counter track (e.g. per-WorkKind queue
        depth at batch-formation time); bounded, lock-guarded, cheap."""
        with self._lock:
            self.counter_ring.append((perf_counter(), name, dict(values)))

    def snapshot_ring(self) -> list[Trace]:
        with self._lock:
            return list(self.ring)

    def snapshot_counters(self) -> list[tuple]:
        with self._lock:
            return list(self.counter_ring)

    def reset(self) -> None:
        with self._lock:
            self.ring.clear()
            self.counter_ring.clear()
            self.completed = 0

    # ------------------------------------------------------------- export

    def write_chrome_trace(self, path: str) -> int:
        """Write the ring as Chrome trace-event JSON; returns event count.
        With an instants_source wired (the flight recorder on the global
        TRACER), its events render as instant markers on a dedicated lane
        of the same timeline."""
        events = chrome_trace_events(
            self.snapshot_ring(), counters=self.snapshot_counters(),
            instants=self.instants_source() if self.instants_source else None,
            device_timeline=self.device_timeline_source()
            if self.device_timeline_source else None,
        )
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "lighthouse-tpu pipeline tracer"},
        }
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(events)


#: spans named `device:<stage>` render on dedicated lanes starting here
#: (host pipeline lanes recycle tid 0..HOST_LANES-1)
DEVICE_LANE_BASE = 1000

#: host pipeline lane count; tids recycle mod this. ONE owner — both the
#: span export and the flow-link synthesis derive a trace's lane from it,
#: and a divergence would detach every flow arrow from its slice
HOST_LANES = 32


def _host_tid(trace_index: int) -> int:
    return trace_index % HOST_LANES

#: flight-recorder instant events render on this dedicated lane
INSTANT_LANE = 900

#: trace kinds that ANCHOR a cross-node flow (the producer end of the
#: arrow): gossip publishes and HTTP client requests; everything else
#: carrying a wire context is a consumer (`http_serve`, imports, ...)
_FLOW_ANCHOR_KINDS = ("gossip_publish", "http_client")

#: the device ledger's per-workload occupancy/waiting tracks render on
#: dedicated lanes starting here (one tid per track, deterministically
#: ordered by track name)
DEVICE_LEDGER_LANE_BASE = 2000


def _device_timeline_events(timeline, pid: int, base: float) -> list[dict]:
    """Render the device ledger's merged timeline — (track, name, t0, t1,
    args) spans from DeviceLedger.perfetto_device_timeline() — as "X"
    rows on per-track lanes plus thread_name metadata. Tracks are
    assigned tids in sorted order so the export is deterministic: each
    workload's occupancy track (`ledger:<workload>`) sits beside its
    waiting-marker track (`ledger:<workload>:wait`)."""
    tracks = sorted({t for t, _, _, _, _ in timeline})
    tids = {t: DEVICE_LEDGER_LANE_BASE + i for i, t in enumerate(tracks)}
    events: list[dict] = [
        {
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tids[t],
            "args": {"name": f"ledger:{t}"},
        }
        for t in tracks
    ]
    for track, name, t0, t1, args in timeline:
        ev = {
            "name": name,
            "cat": "device_ledger",
            "ph": "X",
            "ts": (t0 - base) * 1e6,
            "dur": max(0.0, (t1 - t0) * 1e6),
            "pid": pid,
            "tid": tids[track],
        }
        if args:
            ev["args"] = {k: str(v) for k, v in args.items()}
        events.append(ev)
    return events


def chrome_trace_events(
    traces: list[Trace], counters: list[tuple] | None = None,
    instants: list[tuple] | None = None, pid: int | None = None,
    base: float | None = None, device_timeline: list[tuple] | None = None,
) -> list[dict]:
    """Trace-event ("X" complete events, µs) rows for a list of traces.

    Each trace gets its own tid so overlapping pipeline lanes (up to
    max_inflight device batches) render as parallel rows; tids recycle
    mod 32 to keep the track count readable. Spans whose name starts
    with `device:` (per-stage device attribution sub-spans) are routed
    to one dedicated lane per stage (tid >= DEVICE_LANE_BASE) with a
    thread_name metadata row, so host pipeline and device stages show as
    distinct lanes of ONE timeline. `counters` — (t, name, {series:
    value}) samples from Tracer.sample_counters — export as "ph": "C"
    counter rows. `instants` — (t, name, args) markers from the flight
    recorder (breaker transitions, incidents, deadline misses) — export as
    "ph": "i" instant events on the dedicated INSTANT_LANE, so the black
    box's view lines up against the pipeline spans. `device_timeline` —
    (track, name, t0, t1, args) spans from the device ledger — render as
    per-workload occupancy/waiting lanes (tid >= DEVICE_LEDGER_LANE_BASE,
    deterministic track order). Timestamps are rebased
    so the oldest event is t=0 (`base` overrides the rebase origin so the
    cluster merge can put N tracers on one shared axis; `pid` overrides
    the process id so each node renders as its own process group).

    Cross-node flow events are NOT emitted here — they need the whole
    cluster's traces at once (one distinct s/f pair per consumer, or the
    trace-event flow model chains sibling importers into false causality);
    `merge_chrome_traces` synthesizes them."""
    counters = counters or []
    instants = instants or []
    device_timeline = device_timeline or []
    if not traces and not counters and not instants and not device_timeline:
        return []
    span_starts = [
        t0
        for tr in traces
        for _, t0, _, _ in tr.spans or [("", tr.t0, tr.t0, None)]
    ]
    if base is None:
        base = min(
            span_starts
            + [t for t, _, _ in counters]
            + [t for t, _, _ in instants]
            + [t0 for _, _, t0, _, _ in device_timeline]
        )
    if pid is None:
        pid = os.getpid()
    events = []
    device_lanes: dict = {}  # span name -> dedicated tid
    for i, tr in enumerate(traces):
        host_tid = _host_tid(i)
        for name, t0, t1, args in tr.spans:
            if name.startswith("device:"):
                tid = device_lanes.get(name)
                if tid is None:
                    tid = DEVICE_LANE_BASE + len(device_lanes)
                    device_lanes[name] = tid
            else:
                tid = host_tid
            ev = {
                "name": name,
                "cat": tr.kind,
                "ph": "X",
                "ts": (t0 - base) * 1e6,
                "dur": max(0.0, (t1 - t0) * 1e6),
                "pid": pid,
                "tid": tid,
            }
            merged = dict(tr.meta)
            if args:
                merged.update(args)
            if merged:
                ev["args"] = {k: str(v) for k, v in merged.items()}
            events.append(ev)
    for name, tid in device_lanes.items():
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": name},
            }
        )
    for t, name, values in counters:
        events.append(
            {
                "name": name,
                "ph": "C",
                "ts": (t - base) * 1e6,
                "pid": pid,
                "tid": 0,
                "args": {k: float(v) for k, v in values.items()},
            }
        )
    if instants:
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": INSTANT_LANE,
                "args": {"name": "flight_recorder"},
            }
        )
        for t, name, args in instants:
            ev = {
                "name": name,
                "ph": "i",
                "s": "p",          # process-scope marker: full-height line
                "ts": (t - base) * 1e6,
                "pid": pid,
                "tid": INSTANT_LANE,
            }
            if args:
                ev["args"] = {k: str(v) for k, v in args.items()}
            events.append(ev)
    if device_timeline:
        events.extend(_device_timeline_events(device_timeline, pid, base))
    return events


def _flow_links(snaps, base: float) -> list[dict]:
    """Cross-node flow pairs for the cluster merge: ONE distinct (s, f)
    id per (publish, consumer trace). The trace-event flow model treats
    same-id events as a single sequential chain, so a fan-out publish with
    three importers keyed on one id would render import1 -> import2 —
    false causality between siblings; per-consumer ids give the documented
    publish -> each-import arrows. Consumers whose context has no publish
    anchor in the merged set (e.g. an rpc_serve adopting a non-publish
    caller context) emit nothing."""
    from .propagation import flow_id

    # pass 1: producer anchors — fid -> (pid, tid, mid-span time). Gossip
    # publishes and HTTP client requests both originate causal chains;
    # a gossip publish wins when both carry the same context (the HTTP
    # call is then itself a consumer of the publish's chain).
    anchors: dict = {}
    for i, (_name, traces, _c) in enumerate(snaps):
        for j, tr in enumerate(traces):
            if (tr.kind in _FLOW_ANCHOR_KINDS and tr.ctx is not None
                    and tr.spans):
                fid = flow_id(tr.ctx)
                if tr.kind != "gossip_publish" and fid in anchors:
                    continue
                first = tr.spans[0]
                anchors[fid] = (
                    i + 1, _host_tid(j), (first[1] + first[2]) / 2.0
                )
    # pass 2: one unique flow per consumer trace with a matching anchor
    events: list[dict] = []
    for i, (_name, traces, _c) in enumerate(snaps):
        pid = i + 1
        for j, tr in enumerate(traces):
            if (tr.ctx is None or tr.kind in _FLOW_ANCHOR_KINDS
                    or not tr.spans):
                continue
            fid = flow_id(tr.ctx)
            anchor = anchors.get(fid)
            if anchor is None:
                continue
            # digest-derived per-consumer id (NOT an arithmetic pack of
            # pid/index — wrapped indices or >31 pids would collide and
            # re-chain sibling flows)
            uid = int.from_bytes(
                hashlib.sha256(f"{fid}:{pid}:{j}".encode()).digest()[:6],
                "big",
            )
            apid, atid, ats = anchor
            events.append({
                "name": "propagation", "cat": "net", "ph": "s", "id": uid,
                "ts": (ats - base) * 1e6, "pid": apid, "tid": atid,
            })
            first = tr.spans[0]
            events.append({
                "name": "propagation", "cat": "net", "ph": "f", "bp": "e",
                "id": uid, "ts": (first[1] - base) * 1e6,
                "pid": pid, "tid": _host_tid(j),
            })
    return events


def merge_chrome_traces(named_tracers, path: str, instants=None,
                        device_timeline="auto") -> int:
    """Merge N nodes' tracers into ONE Chrome-trace file: each node is a
    distinct process group (pid = position + 1, named via process_name
    metadata), every timestamp rebased against one shared origin, and
    cross-node flow events link each publish span to the remote import
    spans that adopted its wire context. `named_tracers` is an iterable of
    (name, Tracer); `instants` — (t_mono, name, args) markers (the flight
    recorder's `perfetto_instants()`, which is process-global and so
    cluster-wide in an in-process harness) render as a dedicated
    `flight_recorder` process group (pid 0). The device ledger's merged
    per-workload timeline (process-global, like the recorder) renders as
    its own `device_ledger` process group after the node groups —
    `device_timeline="auto"` pulls it from the global TRACER's wired
    source, an explicit list overrides, None suppresses. Returns the
    event count written."""
    snaps = [
        (name, tr.snapshot_ring(), tr.snapshot_counters())
        for name, tr in named_tracers
    ]
    instants = list(instants) if instants else []
    if device_timeline == "auto":
        src = TRACER.device_timeline_source
        device_timeline = src() if src else []
    device_timeline = list(device_timeline) if device_timeline else []
    starts = [
        t0
        for _, traces, counters in snaps
        for tr in traces
        for _, t0, _, _ in tr.spans or [("", tr.t0, tr.t0, None)]
    ] + [t for _, _, counters in snaps for t, _, _ in counters] + [
        t for t, _, _ in instants
    ] + [t0 for _, _, t0, _, _ in device_timeline]
    base = min(starts) if starts else 0.0
    events: list[dict] = []
    if instants:
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": 0,
                "tid": 0,
                "args": {"name": "flight_recorder"},
            }
        )
        events.extend(
            chrome_trace_events([], instants=instants, pid=0, base=base)
        )
    for i, (name, traces, counters) in enumerate(snaps):
        pid = i + 1
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": name},
            }
        )
        events.extend(
            chrome_trace_events(traces, counters=counters, pid=pid,
                                base=base)
        )
    if device_timeline:
        dl_pid = len(snaps) + 1
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": dl_pid,
                "tid": 0,
                "args": {"name": "device_ledger"},
            }
        )
        events.extend(
            _device_timeline_events(device_timeline, dl_pid, base)
        )
    events.extend(_flow_links(snaps, base))
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "lighthouse-tpu cluster trace merge"},
    }
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(events)


TRACER = Tracer()

# ------------------------------------------------------- context propagation

_tls = threading.local()


def set_current_trace(trace: Trace | None) -> None:
    """Bind the in-progress trace to this thread so layers below the
    processor (jaxbls marshal/dispatch) can add sub-spans without plumbing
    a trace argument through every call signature."""
    _tls.trace = trace


def current_trace() -> Trace | None:
    return getattr(_tls, "trace", None)
