"""Cross-node causal observability: wire trace context + propagation SLIs.

PR 2's tracer ends at the node boundary — `Trace.trace_id` is a node-local
counter, so a block's producer-side publish and its consumer-side
validate/import spans on another node share nothing. This module makes
message causality a first-class observable:

  - `WireTraceContext`: the compact origin context every gossip publish
    (and Req/Resp request, transport frame CREQ) carries on the wire —
    origin node id, the origin's trace id, the slot, a logical publish
    offset (`seq`, the origin's per-process publish counter) and the
    origin clock reading at publish (`sent_at`). The receiving node adopts
    it into its local `Trace` (`Trace.adopt`), so the publish span and
    every remote validate/import span share one causal id — and the merged
    Perfetto export (`trace.merge_chrome_traces`) links them with flow
    events.
  - `PropagationTracker`: one per node. First-delivery latencies feed the
    labeled `net_propagation_seconds{topic}` histogram and a bounded
    per-topic sample list; block time-to-head (publish -> this node's
    fork-choice head update) feeds `net_time_to_head_seconds{role}`.
    Latency = receiver clock minus `sent_at` on the SAME clock surface
    (`SlotClock._time()`): wall seconds on a live node (cross-node NTP
    skew is the usual caveat), LOGICAL slot-time under the deterministic
    multinode harness's ManualSlotClocks — so harness distributions are a
    pure function of the seed.
  - Propagation-stall trigger: `close_slot()` (driven per slot by the
    harness / the bn slot timer) counts consecutive slots in which the
    node had >= 1 connected peer but received NOTHING over gossip; at
    `stall_slots` it fires the flight recorder's `propagation_stall`
    incident (hysteresis: re-armed by the first delivery, like the
    breaker/burn triggers) — the partitioned minority's view of a
    partition window becomes a durable, schema-valid dump.
  - `build_cluster_report`: the deterministic cluster rollup the multinode
    and fleet scenario reports embed — cluster deadline-hit ratio over
    every node's SLO accountant, per-node outliers, per-topic propagation
    p50/p95 merged across nodes, stall counts. Everything in it derives
    from logical clocks and integer counters, so it is bit-identical
    across reruns of one seed.
"""

from __future__ import annotations

import hashlib
import struct
import threading
import time
from dataclasses import dataclass

from ..utils.metrics import REGISTRY

#: propagation spans link ranges: sub-ms localhost hops to multi-slot
#: delayed links (logical seconds under the harness clamp to slot grid)
_PROP_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0,
    16.0, 32.0,
)

NET_PROPAGATION = REGISTRY.histogram_vec(
    "net_propagation_seconds",
    "gossip first-delivery latency (origin publish to this node's first "
    "receipt, sender/receiver clock surfaces), by topic",
    ("topic",),
    buckets=_PROP_BUCKETS,
)
NET_TIME_TO_HEAD = REGISTRY.histogram_vec(
    "net_time_to_head_seconds",
    "block publish to this node's fork-choice head update, by role "
    "(remote = a propagated block became our head)",
    ("role",),
    buckets=_PROP_BUCKETS,
)
NET_CTX = REGISTRY.counter_vec(
    "net_trace_context_total",
    "wire trace-context lifecycle events, by event (sent / delivered / "
    "missing = a gossip first delivery carried no context / req_sent / "
    "req_adopted)",
    ("event",),
)

#: consecutive delivery-free slots (with peers connected) before the
#: propagation_stall incident fires
DEFAULT_STALL_SLOTS = 2

#: bound on retained latency samples per topic (the quantile source for
#: the cluster rollup; the histogram familiy keeps the full distribution)
MAX_SAMPLES = 4096

#: a node whose deadline-hit ratio sits this far under the cluster-wide
#: ratio is an outlier in the cluster rollup
OUTLIER_MARGIN = 0.05

CTX_VERSION = 1
_CTX_TAIL = struct.Struct(">QIId")     # trace_id, slot, seq, sent_at


@dataclass(frozen=True)
class WireTraceContext:
    """Compact origin context carried in gossip/Req-Resp frame envelopes."""

    origin: str          # publishing node id
    trace_id: int        # origin-local Trace id (the causal key)
    slot: int            # slot at publish time
    seq: int             # origin's logical publish offset (per process)
    sent_at: float       # origin SlotClock._time() reading at publish

    def causal_id(self) -> str:
        return f"{self.origin}:{self.trace_id}"


def encode_ctx(ctx: WireTraceContext) -> bytes:
    origin = ctx.origin.encode()[:255]
    return (
        struct.pack(">BB", CTX_VERSION, len(origin))
        + origin
        + _CTX_TAIL.pack(
            ctx.trace_id & 0xFFFFFFFFFFFFFFFF,
            max(0, int(ctx.slot)) & 0xFFFFFFFF,
            max(0, int(ctx.seq)) & 0xFFFFFFFF,
            float(ctx.sent_at),
        )
    )


def decode_ctx(buf: bytes | None) -> WireTraceContext | None:
    """Tolerant decode: None on garbage/unknown versions — a malformed
    context must never fail the message it rode in on (observability can
    degrade; delivery cannot)."""
    if not buf:
        return None
    try:
        ver, ln = buf[0], buf[1]
        if ver != CTX_VERSION:
            return None
        origin = buf[2 : 2 + ln].decode()
        trace_id, slot, seq, sent_at = _CTX_TAIL.unpack_from(buf, 2 + ln)
    except (IndexError, struct.error, UnicodeDecodeError):
        return None
    return WireTraceContext(origin, trace_id, slot, seq, sent_at)


def flow_id(ctx: WireTraceContext) -> int:
    """Stable Perfetto flow id for one causal chain: a 48-bit digest of
    (origin, trace_id) — JSON-safe, identical on every node that saw the
    message."""
    h = hashlib.sha256(ctx.causal_id().encode()).digest()
    return int.from_bytes(h[:6], "big")


def short_topic(topic: str) -> str:
    """Label-cardinality-safe topic name: '/eth2/<fd>/<name>/ssz_snappy'
    -> '<name>' with the subnet index collapsed (beacon_attestation_5 ->
    beacon_attestation), so SLIs aggregate per topic FAMILY and survive
    fork-digest changes."""
    parts = topic.split("/")
    name = parts[3] if len(parts) >= 5 else topic
    stem, _, tail = name.rpartition("_")
    if stem and tail.isdigit():
        return stem
    return name


# ------------------------------------------------ thread-local wire context

# ONE owner: the thread-local lives in trace.py so `Tracer.begin` can
# auto-adopt it without a propagation import on the begin hot path;
# re-exported here because this module is the wire-context API surface
from .trace import current_wire_ctx, set_current_wire_ctx  # noqa: E402,F401


# ------------------------------------------------------------------ tracker

# ONE quantile owner for the whole observability package: the SLO
# engine's nearest-rank helper — a second copy here could silently
# diverge from the window quantiles operators compare these against
from .slo import _quantile as quantile  # noqa: E402


class PropagationTracker:
    """Per-node propagation SLI accountant + stall trigger."""

    def __init__(self, node_id: str, clock=None, recorder=None,
                 stall_slots: int = DEFAULT_STALL_SLOTS):
        self.node_id = node_id
        self.clock = clock                 # SlotClock; None = wall time
        self._recorder = recorder          # None = the global RECORDER
        self.slo_provider = None           # optional () -> slo snapshot
        self.stall_slots = int(stall_slots)
        self._lock = threading.Lock()
        self._samples: dict[str, list[float]] = {}   # topic -> latencies
        self._overflow: dict[str, int] = {}
        self.publishes: dict[str, int] = {}
        self.deliveries: dict[str, int] = {}
        self.ctx_missing = 0
        self._tth: list[float] = []        # remote block time-to-head
        self._tth_overflow = 0
        self._delivered_since_close = False
        self.stall_streak = 0
        self.stalls_fired = 0
        # True while a fired stall episode is still disarmed on the
        # recorder; whatever ends the episode (a delivery OR the streak
        # resetting at close, e.g. every peer disconnected) must clear —
        # a key left disarmed would silence every later stall for the
        # life of the process
        self._stall_active = False
        # close watermark (the SlotAccountant discipline): the bn slot
        # timer can tick twice inside one slot (a wakeup ~1ms early), and
        # a double close must not count one quiet slot as two
        self._closed_through: int | None = None

    # ----------------------------------------------------------- plumbing

    def _rec(self):
        if self._recorder is None:
            from . import flight_recorder

            self._recorder = flight_recorder.RECORDER
        return self._recorder

    def now(self) -> float:
        """The clock surface `sent_at` is compared against: the slot
        clock's raw time (logical under ManualSlotClock — the harness's
        determinism), wall time without one."""
        if self.clock is not None:
            try:
                return float(self.clock._time())
            except Exception:
                pass
        return time.time()

    def current_slot(self) -> int:
        if self.clock is not None:
            try:
                return int(self.clock.now() or 0)
            except Exception:
                return 0
        return 0

    # -------------------------------------------------------------- feeds

    def note_publish(self, topic: str) -> None:
        st = short_topic(topic)
        with self._lock:
            self.publishes[st] = self.publishes.get(st, 0) + 1
        NET_CTX.labels("sent").inc()

    def note_delivery(self, topic: str, ctx: WireTraceContext | None) -> None:
        """One gossip FIRST delivery arrived (duplicates never re-feed).
        With a context, the origin-to-here latency lands in the histogram
        and the bounded sample list; without one it is counted missing.
        Either way the delivery re-arms the stall trigger."""
        st = short_topic(topic)
        fire_clear = False
        # one clock read per delivery: the histogram and the retained
        # sample must agree on the SAME latency value
        lat = None if ctx is None else round(
            max(0.0, self.now() - ctx.sent_at), 6
        )
        with self._lock:
            self.deliveries[st] = self.deliveries.get(st, 0) + 1
            if self._stall_active:
                fire_clear = True
                self._stall_active = False
            self.stall_streak = 0
            self._delivered_since_close = True
            if lat is None:
                self.ctx_missing += 1
            else:
                bucket = self._samples.setdefault(st, [])
                if len(bucket) < MAX_SAMPLES:
                    bucket.append(lat)
                else:
                    self._overflow[st] = self._overflow.get(st, 0) + 1
        if lat is None:
            NET_CTX.labels("missing").inc()
        else:
            NET_CTX.labels("delivered").inc()
            NET_PROPAGATION.labels(st).observe(lat)
        if fire_clear:
            self._rec().clear(
                "propagation_stall", key=f"propagation_stall:{self.node_id}"
            )

    def note_time_to_head(self, ctx: WireTraceContext) -> None:
        """A propagated block just became this node's fork-choice head."""
        dt = round(max(0.0, self.now() - ctx.sent_at), 6)
        with self._lock:
            if len(self._tth) < MAX_SAMPLES:
                self._tth.append(dt)
            else:
                self._tth_overflow += 1
        NET_TIME_TO_HEAD.labels("remote").observe(dt)

    # ------------------------------------------------------ slot boundary

    def close_slot(self, slot: int, peers: int) -> bool:
        """Per-slot stall bookkeeping (the harness slot loop / bn slot
        timer drives it): a slot with connected peers and zero gossip
        deliveries extends the stall streak; `stall_slots` consecutive
        ones fire ONE propagation_stall incident (flight-recorder
        hysteresis keys on this node; the next delivery re-arms).
        Watermarked per slot (the SlotAccountant discipline): a repeat
        close of an already-closed slot is a no-op. Returns True when the
        trigger fired this close."""
        clear = False
        with self._lock:
            if self._closed_through is not None and slot <= self._closed_through:
                return False
            self._closed_through = slot
            delivered = self._delivered_since_close
            self._delivered_since_close = False
            if peers > 0 and not delivered:
                self.stall_streak += 1
            else:
                # the episode ended without a delivery (peers gone, or a
                # delivery raced the close): re-arm here too, or the
                # trigger key would stay disarmed forever
                if self.stall_streak and self._stall_active:
                    clear = True
                    self._stall_active = False
                self.stall_streak = 0
            streak = self.stall_streak
            fire = streak == self.stall_slots
            if fire:
                self.stalls_fired += 1
        if clear:
            self._rec().clear(
                "propagation_stall", key=f"propagation_stall:{self.node_id}"
            )
        if fire:
            self._rec().trigger(
                "propagation_stall",
                key=f"propagation_stall:{self.node_id}",
                node=self.node_id, slot=slot, streak=streak, peers=peers,
                slo=self.slo_provider,
            )
            # publish the active episode AFTER the trigger disarmed the
            # key, then re-check: a delivery racing this close (streak
            # already reset) means the episode is over — re-arm NOW, or
            # the delivery-side clear (which checks _stall_active) could
            # have run before our trigger and the key would stay disarmed
            # for every later stall
            raced = False
            with self._lock:
                if self.stall_streak >= self.stall_slots:
                    self._stall_active = True
                else:
                    raced = True
            if raced:
                self._rec().clear(
                    "propagation_stall",
                    key=f"propagation_stall:{self.node_id}",
                )
        return fire

    # ----------------------------------------------------------- snapshot

    def topic_quantiles(self) -> dict:
        """Deterministic per-topic first-delivery distribution (rounded
        logical/wall seconds; sample ORDER cannot matter — quantiles read
        a sorted copy)."""
        with self._lock:
            out = {}
            for st in sorted(set(self._samples) | set(self.deliveries)):
                vals = sorted(self._samples.get(st, ()))
                out[st] = {
                    "deliveries": self.deliveries.get(st, 0),
                    "publishes": self.publishes.get(st, 0),
                    "n": len(vals) + self._overflow.get(st, 0),
                    "p50": round(quantile(vals, 0.50), 6),
                    "p95": round(quantile(vals, 0.95), 6),
                    "max": round(vals[-1], 6) if vals else 0.0,
                }
            return out

    def samples(self) -> dict[str, list[float]]:
        with self._lock:
            return {t: list(v) for t, v in self._samples.items()}

    def time_to_head_samples(self) -> list[float]:
        with self._lock:
            return list(self._tth)

    def snapshot(self) -> dict:
        snap = {
            "node": self.node_id,
            "topics": self.topic_quantiles(),
            "ctx_missing": self.ctx_missing,
            "stall_streak": self.stall_streak,
            "stalls_fired": self.stalls_fired,
        }
        tth = sorted(self.time_to_head_samples())
        snap["time_to_head"] = {
            "n": len(tth) + self._tth_overflow,
            "p50": round(quantile(tth, 0.50), 6),
            "p95": round(quantile(tth, 0.95), 6),
        }
        return snap


# ------------------------------------------------------------ cluster rollup


def build_cluster_report(nodes, http_api=None) -> dict:
    """The deterministic cluster block for multinode/fleet scenario
    reports. `nodes` is an iterable of (index, SlotAccountant,
    PropagationTracker) triples in index order. Everything here derives
    from integer counters and logical-clock samples, so a rerun of the
    same seed reproduces it bit-for-bit.

    `http_api` (optional) is the fleet HTTP leg's per-route series block —
    scheduled request counts per `http_api_request_seconds` route, which
    are a pure function of the scenario seed. It lands under the
    `"http_api"` key verbatim; wall-clock latency quantiles stay OUT of
    this block (they live in the report's observations)."""
    hits = misses = 0
    per_node_ratio: dict[str, float | None] = {}
    merged: dict[str, list[float]] = {}
    merged_n: dict[str, int] = {}
    deliveries: dict[str, int] = {}
    publishes: dict[str, int] = {}
    tth: list[float] = []
    stalls: dict[str, int] = {}
    for idx, acct, tracker in nodes:
        h, m = acct.deadline_totals()
        hits += h
        misses += m
        total = h + m
        per_node_ratio[str(idx)] = (
            None if total == 0 else round(h / total, 4)
        )
        for st, vals in sorted(tracker.samples().items()):
            merged.setdefault(st, []).extend(vals)
        for st, q in tracker.topic_quantiles().items():
            merged_n[st] = merged_n.get(st, 0) + q["n"]
            deliveries[st] = deliveries.get(st, 0) + q["deliveries"]
            publishes[st] = publishes.get(st, 0) + q["publishes"]
        tth.extend(tracker.time_to_head_samples())
        if tracker.stalls_fired:
            stalls[str(idx)] = tracker.stalls_fired
    total = hits + misses
    ratio = None if total == 0 else round(hits / total, 4)
    outliers = sorted(
        (idx for idx, r in per_node_ratio.items()
         if r is not None and ratio is not None
         and r < ratio - OUTLIER_MARGIN),
        key=int,
    )
    propagation = {}
    # union with the delivery-counted topics: a topic whose deliveries all
    # arrived context-less still belongs in the rollup (with empty
    # quantiles) — the degraded-observability case must stay visible
    for st in sorted(set(merged) | set(deliveries)):
        vals = sorted(merged.get(st, ()))
        propagation[st] = {
            "n": merged_n.get(st, len(vals)),
            "deliveries": deliveries.get(st, 0),
            "publishes": publishes.get(st, 0),
            "p50": round(quantile(vals, 0.50), 6),
            "p95": round(quantile(vals, 0.95), 6),
            "max": round(vals[-1], 6) if vals else 0.0,
        }
    tth_sorted = sorted(tth)
    report = {
        "deadline_hits": hits,
        "deadline_misses": misses,
        "deadline_hit_ratio": ratio,
        "per_node_hit_ratio": per_node_ratio,
        "outlier_nodes": outliers,
        "propagation": propagation,
        "time_to_head": {
            "n": len(tth_sorted),
            "p50": round(quantile(tth_sorted, 0.50), 6),
            "p95": round(quantile(tth_sorted, 0.95), 6),
        },
        "propagation_stalls": stalls,
    }
    if http_api is not None:
        report["http_api"] = http_api
    return report
