"""Observability: pipeline span tracing + stage-timing snapshots.

The verification dataflow (gossip -> BeaconProcessor queues -> coalesced
batch -> host marshal -> device dispatch -> device wait -> continuation) is
the system's hot path; this package makes it legible from the outside:

  - `trace`: a lightweight span tracer. Every executed work unit carries a
    Trace through the pipeline stages; completed traces land in a bounded
    ring and feed per-stage Prometheus histograms, and the ring exports as
    Chrome trace-event (Perfetto) JSON (`bn --trace-out trace.json`).
  - `pipeline`: the stage-timing snapshot behind the
    `/lighthouse_tpu/pipeline` ops endpoint.
  - `device`: per-stage device-time attribution for the jaxbls dispatch
    (named annotation scopes always; event-timed per-stage resolves +
    `device:<stage>` trace lanes under `bn --device-trace`).
  - `perf`: compiled-program analytics (`xla_program_*` gauges from XLA
    cost/memory analysis), roofline derivation, and the BENCH_r*/
    MULTICHIP_r* trend + regression gate (`bn perf report`,
    scripts/perf_trend.py).
  - `slo`: the slot-level service-level accountant — one SlotReport per
    slot-clock boundary (admitted/processed/shed per kind, deadline-hit
    ratio for TIMELY work, route share, wait/latency quantiles), rolling
    5-slot and 32-slot windows with burn-rate, `slo_*` families, the
    `/lighthouse_tpu/slo` ops endpoint and the health degraded signal.
  - `flight_recorder`: the always-on black box — a bounded ring of
    structured events (breaker transitions, shed bursts, deadline misses,
    supervisor restarts, route flips, WARN+ log records) with incident
    triggers that dump diagnosis snapshots to `datadir/incidents/` and
    render as instant markers in the Perfetto export.
  - `propagation`: cross-node causality — the wire trace context every
    gossip publish / Req-Resp request carries, per-node propagation SLIs
    (`net_propagation_seconds{topic}`, time-to-head), the
    propagation-stall incident trigger, and the deterministic cluster
    rollup the multinode/fleet reports embed.
  - `debug_bundle`: `bn debug-bundle` — one tarball of everything above
    plus `bn doctor` output and bench metadata, for offline diagnosis.

Always-on by design: recording a trace is appending a few floats to a
deque, so there is no enabled/disabled bifurcation to test — `--trace-out`
only controls whether the ring is written to disk at shutdown.
"""

from .trace import (  # noqa: F401
    PIPELINE_STAGES,
    TRACER,
    Trace,
    Tracer,
    chrome_trace_events,
    current_trace,
    set_current_trace,
)
from .pipeline import register_processor, snapshot  # noqa: F401
from . import device, perf  # noqa: F401  (registers the device/xla families)
from . import flight_recorder, slo  # noqa: F401  (registers slo_*/flight_recorder_* families + the log sink)
from . import propagation  # noqa: F401  (registers the net_* families)
from .flight_recorder import RECORDER  # noqa: F401
from .slo import ACCOUNTANT  # noqa: F401
