"""Per-stage device-time attribution for the jaxbls verify pipeline.

The dispatch path runs four jit stages (prepare, hash-to-G2, pairs,
pairing — `crypto/jaxbls/backend.py`) asynchronously: the host enqueues
all four and blocks once, on the final result. That is the right shape
for throughput, but it makes the device a single opaque span — PR 2's
tracer shows one `device` stage and `jaxbls_device_wait_seconds` shows a
coarse compile/execute split, and nothing says WHICH stage burns the
7x headroom against estimated blst (ROADMAP "kernel speed").

This module is the one owner of per-stage device timing:

  - `run_stage(attr, stage, fn, *args)` wraps every stage dispatch. In
    the default (attribution OFF) mode it only opens a
    `jax.profiler.TraceAnnotation` scope — nanoseconds when no profiler
    session is active, and the stage shows up named in an `xprof`/
    Perfetto device capture when one is. Dispatch stays fully async.
  - With attribution ON (`bn --device-trace`, bench, the calibrator,
    `scripts/profile_components.py`, env
    `LIGHTHOUSE_TPU_DEVICE_ATTRIBUTION=1`), each stage dispatch is
    followed by an event-timed resolve (`jax.block_until_ready`), which
    SERIALIZES the pipeline — attribution is a diagnostic mode, not a
    serving mode. Each timed resolve lands in
    `jaxbls_stage_device_seconds{stage,n_sets,n_pks}`; the FIRST timed
    resolve of a (stage, bucket) in a process is classified as the
    stage's residual compile and lands in
    `jaxbls_stage_compile_seconds{stage,n_sets,n_pks}` instead (the same
    first-dispatch convention as the autotune profiler), giving the
    compile/execute split per padding bucket. The resolve also adds a
    `device:<stage>` sub-span to the current pipeline Trace, so the
    Chrome/Perfetto export renders host lanes AND a device lane per
    stage in one timeline (observability/trace.py routes `device:*`
    spans onto dedicated tracks).
  - When program analytics are also enabled (observability/perf.py),
    the first attributed dispatch per (stage, bucket) captures the
    compiled program's cost/memory analysis into the `xla_program_*`
    gauges, the autotune profile snapshot, and the bench artifacts.

Everything here is import-light: jax is imported lazily, so `bn perf
report` and the metrics lint run with no device attached.
"""

from __future__ import annotations

import os
import threading
from time import perf_counter

from ..utils.metrics import REGISTRY
from . import perf as _perf

#: canonical jit-stage order of the multi-set verify kernel
#: (`_verify_kernel` in crypto/jaxbls/backend.py)
STAGES = ("prepare", "h2c", "pairs", "pairing")

#: Trace span-name prefix that routes a span onto a device lane in the
#: Chrome trace-event export (observability/trace.py)
DEVICE_SPAN_PREFIX = "device:"

# stage resolves span sub-ms (CPU toy buckets) to ~minutes (a cold
# residual compile folded into the first timed resolve)
_STAGE_BUCKETS = (
    0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0, 60.0, 300.0,
)

STAGE_DEVICE_SECONDS = REGISTRY.histogram_vec(
    "jaxbls_stage_device_seconds",
    "attributed per-stage device wall time (dispatch -> event-timed "
    "resolve), by jit stage and padding bucket; steady-state resolves "
    "only — the first resolve per (stage, bucket) lands in "
    "jaxbls_stage_compile_seconds",
    ("stage", "n_sets", "n_pks"),
    buckets=_STAGE_BUCKETS,
)
STAGE_COMPILE_SECONDS = REGISTRY.gauge_vec(
    "jaxbls_stage_compile_seconds",
    "first attributed resolve per (stage, padding bucket): the stage's "
    "residual XLA compile + one execution (autotune first-dispatch "
    "convention)",
    ("stage", "n_sets", "n_pks"),
)

_lock = threading.Lock()
_seen: set = set()          # (stage, bucket) pairs that resolved timed once
_enabled_override: bool | None = None
_trace_annotation = None    # cached jax.profiler.TraceAnnotation (or False)


def set_enabled(on: bool | None) -> None:
    """Force attribution on/off for this process (None = back to env)."""
    global _enabled_override
    _enabled_override = None if on is None else bool(on)


def enabled() -> bool:
    if _enabled_override is not None:
        return _enabled_override
    env = os.environ.get("LIGHTHOUSE_TPU_DEVICE_ATTRIBUTION", "").lower()
    return env in ("1", "on", "yes", "true")


class attributed:
    """`with device.attributed():` — attribution on for a scope (bench,
    scripts, tests); restores the previous override on exit."""

    def __enter__(self):
        global _enabled_override
        self._prev = _enabled_override
        _enabled_override = True
        return self

    def __exit__(self, *exc):
        global _enabled_override
        _enabled_override = self._prev
        return False


class DispatchAttribution:
    """Per-dispatch carrier: the padding bucket plus the pipeline Trace
    (if any) that device sub-spans should land in."""

    __slots__ = ("bucket", "trace")

    def __init__(self, bucket: tuple, trace=None):
        self.bucket = (int(bucket[0]), int(bucket[1]))
        self.trace = trace


def begin(bucket: tuple, trace=None) -> DispatchAttribution | None:
    """Attribution handle for one dispatch, or None when disabled (the
    hot-path default: stages stay async, only named annotation scopes)."""
    if not enabled():
        return None
    if trace is None:
        from . import trace as _trace

        trace = _trace.current_trace()
    return DispatchAttribution(bucket, trace)


def _annotation():
    """jax.profiler.TraceAnnotation, imported once; False if unavailable
    (annotation then degrades to a plain call)."""
    global _trace_annotation
    if _trace_annotation is None:
        try:
            from jax.profiler import TraceAnnotation

            _trace_annotation = TraceAnnotation
        except Exception:
            _trace_annotation = False
    return _trace_annotation


def run_stage(attr: DispatchAttribution | None, stage: str, fn, *args):
    """Dispatch one jit stage under a named annotation scope; with an
    attribution handle, also event-time the resolve and record it."""
    ta = _annotation()
    if attr is None:
        if ta is False:
            return fn(*args)
        with ta(f"jaxbls:{stage}"):
            return fn(*args)
    t0 = perf_counter()
    if ta is False:
        out = fn(*args)
    else:
        with ta(f"jaxbls:{stage}"):
            out = fn(*args)
    try:
        import jax

        jax.block_until_ready(out)
    except ImportError:  # pragma: no cover - jax is baked into the image
        pass
    t1 = perf_counter()
    _record(attr, stage, t0, t1)
    if _perf.analytics_enabled():
        _perf.maybe_capture_program(stage, fn, args, attr.bucket)
    return out


def _record(attr: DispatchAttribution, stage: str, t0: float, t1: float) -> None:
    key = (stage, attr.bucket)
    with _lock:
        first = key not in _seen
        _seen.add(key)
    n, m = attr.bucket
    dt = t1 - t0
    if first:
        # residual compile (whatever XLA work this stage still owed at
        # this bucket) — keep it out of the steady-state distribution
        STAGE_COMPILE_SECONDS.labels(stage, n, m).set(dt)
    else:
        STAGE_DEVICE_SECONDS.labels(stage, n, m).observe(dt)
    if attr.trace is not None:
        attr.trace.add_span(
            f"{DEVICE_SPAN_PREFIX}{stage}", t0, t1,
            phase="compile" if first else "execute", bucket=f"{n}x{m}",
        )


def reset_seen() -> None:
    """Forget compile/execute classification state (tests)."""
    with _lock:
        _seen.clear()


# --------------------------------------------------------------- snapshots


def snapshot_stages(device_kind: str | None = None) -> dict:
    """Per-bucket, per-stage timing summary from the attributed series,
    with roofline numbers where program analytics exist for the bucket.

    Shape: {"<n>x<m>": {stage: {count, mean_ms, total_s, compile_s?,
    roofline?}}} — the bench artifact and profile_components surface."""
    out: dict = {}
    for (stage, n, m), child in STAGE_DEVICE_SECONDS.children():
        if child.n == 0:
            continue
        mean_s = child.total / child.n
        entry = {
            "count": child.n,
            "mean_ms": round(mean_s * 1e3, 3),
            "total_s": round(child.total, 4),
        }
        stats = _perf.program_stats(stage, (int(n), int(m)))
        if stats is not None:
            rl = _perf.roofline(stats, mean_s, device_kind)
            if rl is not None:
                entry["roofline"] = rl
        out.setdefault(f"{n}x{m}", {})[stage] = entry
    for (stage, n, m), child in STAGE_COMPILE_SECONDS.children():
        if child.value:
            out.setdefault(f"{n}x{m}", {}).setdefault(stage, {})[
                "compile_s"
            ] = round(child.value, 6)
    return out


# ------------------------------------------------- standalone stage profiler


def profile_stages(
    n_sets: int, n_pks: int, reps: int = 3, seed: int = 7,
    analytics: bool = True,
) -> dict:
    """Time the four real jitted stages standalone at one padding bucket:
    warm (first rep = residual compile), then `reps` timed resolves each,
    chaining real intermediates (prepare/h2c outputs feed pairs, pairs
    feeds pairing). THE stage-timing owner — scripts/profile_components.py
    is a thin CLI over this, and every observation also lands in the
    jaxbls_stage_* metric families and (with analytics) the xla_program_*
    gauges + autotune profile snapshot.

    Initializes the jax backend; only call where that is acceptable."""
    import numpy as np

    from ..crypto.jaxbls import backend as be
    from ..crypto.jaxbls import limbs as lb
    from ..parallel import get_mesh, put_pk_grid, put_sets

    # profile the programs the SERVING path runs: on a meshed process the
    # batch lane compiles the mesh-variant stages over mesh-padded
    # buckets with sharded placement — timing fresh unsharded variants at
    # those shapes would attribute cost to programs nothing executes
    mesh = get_mesh()
    prepare, h2c_stage, pairs_stage, pairing_stage = be._get_stages(mesh=mesh)
    n, m = be.padding_bucket(n_sets, n_pks, mesh=mesh)
    rng = np.random.default_rng(seed)

    def rl(shape):
        # random < 2^16 per limb, top limb zero: valid field-element range
        a = rng.integers(0, 1 << 16, size=shape + (lb.NL,), dtype=np.uint32)
        a[..., -1] = 0
        return a

    # host masters; per-batch inputs are RE-PLACED every rep because with
    # donation on (accelerator default) the stages CONSUME them — reusing
    # a donated array on rep 2 would raise 'Array has been deleted'. The
    # pubkey grids are never donated, so they place once (like the
    # serving path's device-resident pubkey cache).
    h_pk_x, h_pk_y = rl((n, m)), rl((n, m))
    h_sig_x, h_sig_y = rl((n, 2)), rl((n, 2))
    h_z = np.ones((n, be.Z_DIGITS), np.uint32)
    h_mask = np.ones((n,), np.uint32)
    h_us = rl((n, 2, 2))
    pk_x, pk_y = put_pk_grid(h_pk_x), put_pk_grid(h_pk_y)
    pk_mask = put_pk_grid(np.ones((n, m), np.uint32))

    prev_analytics = _perf.set_analytics(analytics)
    try:
        with attributed():
            for _ in range(reps + 1):  # +1: first rep eats residual compile
                sig_x, sig_y = put_sets(h_sig_x), put_sets(h_sig_y)
                z_digits, set_mask = put_sets(h_z), put_sets(h_mask)
                us = put_sets(h_us)
                attr = begin((n, m))
                z_pk, sig_acc, _bad = run_stage(
                    attr, "prepare", prepare,
                    pk_x, pk_y, pk_mask, sig_x, sig_y, z_digits, set_mask,
                )
                h_jac = run_stage(attr, "h2c", h2c_stage, us)
                pairs_out = run_stage(
                    attr, "pairs", pairs_stage, z_pk, h_jac, sig_acc, set_mask
                )
                run_stage(attr, "pairing", pairing_stage, *pairs_out)
    finally:
        _perf.set_analytics(prev_analytics)

    kind = None
    try:
        import jax

        devices = jax.devices()
        kind = devices[0].device_kind if devices else None
    except Exception:
        pass
    snap = snapshot_stages(device_kind=kind)
    return {
        "bucket": [n, m],
        "device_kind": kind,
        "reps": reps,
        "stages": snap.get(f"{n}x{m}", {}),
        "programs": _perf.program_snapshot().get(f"{n}x{m}", {}),
    }
