"""Slot-level SLO engine — the per-slot service-level accountant.

`pipeline_stage_seconds` answers "how long did stages take"; nothing so
far answers the operator question the reference client lives by: *did this
slot meet its deadline, and if not, why?* This module closes one
`SlotReport` per slot-clock boundary: per-WorkKind admitted / processed /
shed / expired counts, the deadline-hit ratio for TIMELY work, device-vs-
fallback route share, and queue-wait / verify-latency quantiles against
the slot budget. Closed reports roll into a 5-slot window (the fast
alerting signal) and a 32-slot epoch window (the capacity-planning
signal), each with SRE-style burn-rate computation:

    burn_rate = (1 - hit_ratio) / (1 - target)

so burn 1.0 means "spending error budget exactly at the sustainable rate"
and burn 10 means "the budget for this window is gone in a tenth of it".

Feeding it is push-based and hot-path cheap (a lock + integer adds): the
`BeaconProcessor` records admits/sheds/processed/queue-waits, the hybrid
router and loadgen record routes and late batches, the validator monitor
records per-epoch duty hits/misses. Slots close ONLY via `close_slot()`
(the bn slot timer; the loadgen runner after each drained slot) — closing
is watermark-guarded so a report is emitted exactly once per slot no
matter how many threads race, and a clock jump emits empty reports for
the skipped slots (bounded) so the windows never silently compress time.

Closing a slot also runs the incident triggers: burn-rate over threshold
and deadline-miss streaks hand off to the flight recorder
(observability/flight_recorder.py), which applies hysteresis and dumps.

The global `ACCOUNTANT` is the node's accountant (`/lighthouse_tpu/slo`,
the health endpoint, `bn debug-bundle`). Loadgen runs a private instance
per scenario so reports stay a pure function of (scenario, seed).
"""

from __future__ import annotations

import threading
from collections import deque

from ..utils.metrics import REGISTRY
from . import flight_recorder

#: work kinds with a slot deadline (mirrors qos.admission's TIMELY class;
#: kept as names here because qos imports the processor which imports
#: observability — a qos import from this module would cycle)
TIMELY_KINDS = frozenset(
    {
        "gossip_attestation",
        "gossip_aggregate",
        "gossip_sync_contribution",
        "gossip_sync_signature",
        # validator-client duties are slot-deadlined by definition: the
        # fleet harness feeds performed/missed duty verdicts per slot
        # (validator/services.py DutyAccountant)
        "vc_duty",
    }
)

#: rolling window shapes: 5 slots = fast page-the-operator signal,
#: 32 slots = one epoch, the capacity-planning horizon
SHORT_WINDOW = 5
EPOCH_WINDOW = 32

#: cap on empty reports emitted for one clock jump — a node resumed after
#: an hour must not spin emitting thousands of empties; the gap is
#: recorded on the first report after it instead
MAX_GAP_REPORTS = 64

#: per-slot sample bound for the wait/latency quantile lists
MAX_SAMPLES = 2048

SLOT_REPORTS = REGISTRY.counter_vec(
    "slo_slot_reports_total",
    "slot reports closed, by result (ok / degraded / empty)",
    ("result",),
)
DEADLINE_TOTAL = REGISTRY.counter_vec(
    "slo_deadline_total",
    "TIMELY work items against their slot deadline, by outcome "
    "(hit = processed in time; miss = shed, expired, or verified late)",
    ("result",),
)
HIT_RATIO = REGISTRY.gauge_vec(
    "slo_deadline_hit_ratio",
    "rolling deadline-hit ratio of TIMELY work, by window",
    ("window",),
)
BURN_RATE = REGISTRY.gauge_vec(
    "slo_burn_rate",
    "error-budget burn rate ((1 - hit_ratio) / (1 - target)), by window",
    ("window",),
)
ROUTE_TOTAL = REGISTRY.counter_vec(
    "slo_route_total",
    "verification work by the path that served it (device / host fallback)",
    ("path",),
)
DEGRADED = REGISTRY.gauge_vec(
    "slo_degraded",
    "1 while the named degradation signal is active, else 0",
    ("reason",),
)


def _quantile(sorted_samples: list, q: float) -> float:
    if not sorted_samples:
        return 0.0
    idx = min(len(sorted_samples) - 1, int(q * len(sorted_samples)))
    return sorted_samples[idx]


class _SlotCounters:
    """Mutable accumulator for one open slot (accountant-lock guarded)."""

    __slots__ = ("admitted", "processed", "shed", "late", "routes",
                 "queue_wait", "verify_lat", "wait_overflow",
                 "verify_overflow", "validator_hits", "validator_misses",
                 "workloads")

    def __init__(self):
        self.admitted: dict[str, int] = {}
        self.processed: dict[str, int] = {}
        self.shed: dict[tuple[str, str], int] = {}   # (kind, reason) -> n
        self.late = 0
        self.routes: dict[str, int] = {}
        self.queue_wait: list[float] = []
        self.verify_lat: list[float] = []
        self.wait_overflow = 0
        self.verify_overflow = 0
        self.validator_hits = 0
        self.validator_misses = 0
        # per-tenant deadline verdicts: workload -> [hits, misses] (the
        # device ledger's workload names — bls / tree_hash / epoch / ...)
        self.workloads: dict[str, list] = {}

    def merge(self, other: "_SlotCounters") -> None:
        """Fold another slot's counters into this one (clock-rebase path)."""
        for k, n in other.admitted.items():
            self.admitted[k] = self.admitted.get(k, 0) + n
        for k, n in other.processed.items():
            self.processed[k] = self.processed.get(k, 0) + n
        for k, n in other.shed.items():
            self.shed[k] = self.shed.get(k, 0) + n
        self.late += other.late
        for p, n in other.routes.items():
            self.routes[p] = self.routes.get(p, 0) + n
        room = MAX_SAMPLES - len(self.queue_wait)
        self.queue_wait.extend(other.queue_wait[:room])
        self.wait_overflow += other.wait_overflow + max(
            0, len(other.queue_wait) - room
        )
        room = MAX_SAMPLES - len(self.verify_lat)
        self.verify_lat.extend(other.verify_lat[:room])
        self.verify_overflow += other.verify_overflow + max(
            0, len(other.verify_lat) - room
        )
        self.validator_hits += other.validator_hits
        self.validator_misses += other.validator_misses
        for w, (h, m) in other.workloads.items():
            ent = self.workloads.setdefault(w, [0, 0])
            ent[0] += h
            ent[1] += m


class SlotReport:
    """One closed slot's accounting; immutable once built."""

    __slots__ = ("slot", "empty", "admitted", "processed", "shed", "late",
                 "routes", "hits", "misses", "queue_wait", "verify_lat",
                 "validator_hits", "validator_misses", "workloads",
                 "gap_before")

    def __init__(self, slot: int, c: _SlotCounters | None,
                 gap_before: int = 0):
        self.slot = slot
        self.gap_before = gap_before
        if c is None:
            c = _SlotCounters()
        self.empty = not (c.admitted or c.processed or c.shed or c.late
                          or c.validator_hits or c.validator_misses
                          or c.workloads)
        self.admitted = dict(c.admitted)
        self.processed = dict(c.processed)
        self.shed = {f"{k}:{r}": n for (k, r), n in c.shed.items()}
        self.late = c.late
        self.routes = dict(c.routes)
        self.validator_hits = c.validator_hits
        self.validator_misses = c.validator_misses
        self.workloads = {w: (hm[0], hm[1]) for w, hm in c.workloads.items()}
        # deadline accounting over TIMELY kinds: everything processed met
        # its deadline (expired work is shed at pop, never executed) except
        # the batches the verifier marked late; every TIMELY loss — full
        # queue, admission refusal, pop-time expiry — is a miss. Late is
        # NOT clamped to this slot's processed count: a straggling device
        # resolve can land its late marker one slot after its items were
        # counted processed, and a clamp would silently erase exactly the
        # stalled-device misses the SLI exists to catch (the hits
        # subtraction floors at zero instead).
        timely_processed = sum(
            n for k, n in self.processed.items() if k in TIMELY_KINDS
        )
        timely_lost = sum(
            n for (k, _r), n in c.shed.items() if k in TIMELY_KINDS
        )
        self.hits = max(0, timely_processed - self.late)
        self.misses = timely_lost + self.late
        qs = sorted(c.queue_wait)
        vs = sorted(c.verify_lat)
        self.queue_wait = {
            "p50": round(_quantile(qs, 0.50), 6),
            "p99": round(_quantile(qs, 0.99), 6),
            "max": round(qs[-1], 6) if qs else 0.0,
            "n": len(qs) + c.wait_overflow,
        }
        self.verify_lat = {
            "p50": round(_quantile(vs, 0.50), 6),
            "p99": round(_quantile(vs, 0.99), 6),
            "max": round(vs[-1], 6) if vs else 0.0,
            "n": len(vs) + c.verify_overflow,
        }

    def hit_ratio(self) -> float | None:
        total = self.hits + self.misses
        return None if total == 0 else self.hits / total

    def as_dict(self) -> dict:
        ratio = self.hit_ratio()
        out = {
            "slot": self.slot,
            "empty": self.empty,
            "admitted": self.admitted,
            "processed": self.processed,
            "shed": self.shed,
            "deadline": {
                "hits": self.hits,
                "misses": self.misses,
                "late": self.late,
                "hit_ratio": None if ratio is None else round(ratio, 4),
            },
            "routes": self.routes,
            "queue_wait_seconds": self.queue_wait,
            "verify_latency_seconds": self.verify_lat,
        }
        if self.validator_hits or self.validator_misses:
            out["validator_monitor"] = {
                "hits": self.validator_hits,
                "misses": self.validator_misses,
            }
        if self.workloads:
            out["workloads"] = {
                w: {
                    "hits": h,
                    "misses": m,
                    "hit_ratio": None if h + m == 0
                    else round(h / (h + m), 4),
                }
                for w, (h, m) in sorted(self.workloads.items())
            }
        if self.gap_before:
            out["gap_before"] = self.gap_before
        return out


class SlotAccountant:
    """Push-fed per-slot accountant with rolling SLI windows."""

    def __init__(self, *, target: float = 0.99, burn_threshold: float = 10.0,
                 miss_streak: int = 2, streak_ratio: float = 0.9,
                 shed_burst_threshold: int = 50,
                 contention_threshold: float = 0.25,
                 recorder: flight_recorder.FlightRecorder | None = None,
                 export_metrics: bool = True):
        self.target = float(target)
        self.burn_threshold = float(burn_threshold)
        self.miss_streak = int(miss_streak)
        self.streak_ratio = float(streak_ratio)
        self.shed_burst_threshold = int(shed_burst_threshold)
        #: cross-tenant contention seconds accrued since the last
        #: evaluated slot that arm the device_contention trigger
        self.contention_threshold = float(contention_threshold)
        self.recorder = recorder if recorder is not None else (
            flight_recorder.RECORDER
        )
        # a private loadgen accountant must not fight the node accountant
        # over the shared slo_* gauge children
        self._export = export_metrics
        self._lock = threading.Lock()
        self._clock = None
        self._closed_through: int | None = None
        self._pending: dict[int, _SlotCounters] = {}
        self.windows = {
            "slot_5": deque(maxlen=SHORT_WINDOW),
            "epoch_32": deque(maxlen=EPOCH_WINDOW),
        }
        self.recent: deque = deque(maxlen=64)      # closed reports, newest last
        self.closed_count = 0
        self._streak = 0                           # consecutive degraded slots
        self._burning = False
        self._contending = False                   # device_contention latch
        self._contention_baseline: dict = {}       # last-read ledger matrix
        # serializes _post_close across the concurrent close_slot callers
        # this class supports: trigger/clear state transitions must not
        # interleave (a stale clear re-arming a trigger mid-episode would
        # break the one-dump-per-episode hysteresis guarantee)
        self._post_lock = threading.Lock()
        self._post_through = -1                    # newest slot evaluated
        # close listeners (weak refs, the autotune plan-listener pattern):
        # the capacity scheduler's control loop ticks on every closed
        # report — called OUTSIDE the accountant lock, after _post_close,
        # so a listener may read window summaries or take its own locks.
        # A garbage-collected owner silently unsubscribes; tests that
        # construct many processors against the global accountant must
        # not pin dead schedulers through it.
        self._close_listeners: list = []

    def add_close_listener(self, fn) -> None:
        """Register `fn(report)` to run for every newly closed SlotReport."""
        import weakref

        try:
            ref = weakref.WeakMethod(fn)
        except TypeError:
            ref = weakref.ref(fn)
        with self._lock:
            self._close_listeners.append(ref)

    def remove_close_listener(self, fn) -> None:
        """Unsubscribe `fn` (registered via add_close_listener). A
        consumer re-binding to another accountant (the scheduler's
        bind_slo) must drop its old subscription explicitly — the
        weakref only dies with the OWNER, and a live owner subscribed to
        two accountants would tick on both."""
        with self._lock:
            self._close_listeners = [
                ref for ref in self._close_listeners
                if ref() is not None and ref() != fn
            ]

    def _notify_close(self, rep: "SlotReport") -> None:
        with self._lock:
            refs = list(self._close_listeners)
        for ref in refs:
            fn = ref()
            if fn is None:
                with self._lock:
                    try:
                        self._close_listeners.remove(ref)
                    except ValueError:
                        pass
                continue
            try:
                fn(rep)
            except Exception as e:  # a listener must never break a close
                flight_recorder.RECORDER.record(
                    "slo_close_listener_error", severity="warn",
                    slot=rep.slot, error=f"{type(e).__name__}: {e}",
                )

    # ----------------------------------------------------------- plumbing

    def clock_bound(self) -> bool:
        with self._lock:
            return self._clock is not None

    def bind_clock(self, clock) -> None:
        """Attach the slot clock records attribute against. Also hands the
        clock to the flight recorder so events carry slot stamps."""
        with self._lock:
            self._clock = clock
        self.recorder.configure(clock=clock)

    def reset(self) -> None:
        with self._lock:
            self._clock = None
            self._closed_through = None
            self._pending.clear()
            for w in self.windows.values():
                w.clear()
            self.recent.clear()
            self.closed_count = 0
            self._streak = 0
            self._burning = False
            self._contending = False
            self._contention_baseline = {}
            self._post_through = -1

    def _slot_locked(self) -> int:
        """Slot to attribute the current event to (lock held)."""
        slot = 0
        if self._clock is not None:
            try:
                slot = self._clock.now() or 0
            except Exception:
                slot = 0
        if self._closed_through is not None and slot <= self._closed_through:
            # straggler landing after its slot closed (an in-flight device
            # resolve): attribute forward, never mutate a closed report
            slot = self._closed_through + 1
        return slot

    def _counters_locked(self) -> _SlotCounters:
        slot = self._slot_locked()
        c = self._pending.get(slot)
        if c is None:
            c = self._pending[slot] = _SlotCounters()
            # bound the pending map: with no close_slot driver (bare
            # processors in tests) only the default slot accumulates, but a
            # bound here makes the no-driver case safe by construction
            if len(self._pending) > 2 * EPOCH_WINDOW:
                self._pending.pop(min(self._pending))
        return c

    # -------------------------------------------------------- event feeds

    def record_admitted(self, kind: str, n: int = 1) -> None:
        with self._lock:
            c = self._counters_locked()
            c.admitted[kind] = c.admitted.get(kind, 0) + n

    def record_processed(self, kind: str, n: int = 1) -> None:
        with self._lock:
            c = self._counters_locked()
            c.processed[kind] = c.processed.get(kind, 0) + n

    def record_shed(self, kind: str, reason: str, n: int = 1) -> None:
        with self._lock:
            c = self._counters_locked()
            key = (kind, reason)
            c.shed[key] = c.shed.get(key, 0) + n

    def record_late(self, n: int = 1, kind: str | None = None) -> None:
        """`n` items were verified but PAST their usefulness budget (a
        stalled device batch): they count as processed for conservation
        but as deadline misses for the SLI. `kind` guards attribution —
        a late NON-deadlined batch (block signature sets on the sync
        verify path) must not debit the TIMELY hit ratio; None means the
        caller knows the work is deadlined (loadgen's att/agg batches)."""
        if kind is not None and kind not in TIMELY_KINDS:
            return
        with self._lock:
            self._counters_locked().late += n

    def record_queue_wait(self, kind: str, seconds: float) -> None:
        with self._lock:
            c = self._counters_locked()
            if len(c.queue_wait) < MAX_SAMPLES:
                c.queue_wait.append(seconds)
            else:
                c.wait_overflow += 1     # "n" stays the TRUE event count

    def record_verify_latency(self, seconds: float) -> None:
        with self._lock:
            c = self._counters_locked()
            if len(c.verify_lat) < MAX_SAMPLES:
                c.verify_lat.append(seconds)
            else:
                c.verify_overflow += 1   # "n" stays the TRUE event count

    def record_route(self, path: str, n: int = 1) -> None:
        with self._lock:
            c = self._counters_locked()
            c.routes[path] = c.routes.get(path, 0) + n
        if self._export:
            ROUTE_TOTAL.labels(path).inc(n)

    def record_validator_epoch(self, hits: int, misses: int) -> None:
        """validator_monitor.finalize_epoch feeds its per-validator duty
        verdicts here so they appear in the epoch window."""
        with self._lock:
            c = self._counters_locked()
            c.validator_hits += hits
            c.validator_misses += misses

    def record_workload_deadline(self, workload: str, hits: int = 0,
                                 misses: int = 0) -> None:
        """Per-tenant deadline verdicts under the device ledger's
        workload names (bls / tree_hash / epoch / meshsim): every
        SlotReport and window summary then carries a per-workload
        deadline-hit ratio and burn rate beside the aggregate — the
        tenant-aware view the "one device, many tenants" arbiter needs."""
        with self._lock:
            ent = self._counters_locked().workloads.setdefault(
                str(workload), [0, 0]
            )
            ent[0] += int(hits)
            ent[1] += int(misses)

    # ------------------------------------------------------ slot boundary

    def close_slot(self, upto: int | None) -> list[SlotReport]:
        """Close every not-yet-closed slot <= `upto`; returns the newly
        closed reports (oldest first). Idempotent per slot: the watermark
        guarantees exactly one report per slot under concurrent callers.
        A clock jump emits empty reports for the skipped slots, bounded at
        MAX_GAP_REPORTS — a larger gap is recorded on the first report
        after it instead of flooding the windows."""
        if upto is None or upto < 0:
            return []
        reports: list[SlotReport] = []
        rebased_from = None
        with self._lock:
            clock_now = None
            if self._clock is not None:
                try:
                    clock_now = self._clock.now()
                except Exception:
                    clock_now = None
            if (
                self._closed_through is not None
                and self._closed_through - upto > EPOCH_WINDOW
                # only when the bound clock AGREES time regressed: a stale
                # caller replaying old slot numbers while the clock reads
                # high must stay an idempotent no-op
                and clock_now is not None
                and upto >= clock_now - 1
            ):
                # forward clock anomaly recovery: a spurious future clock
                # reading (NTP step, post-suspend RTC drift) ran the
                # watermark ahead; without a backward rebase every later
                # close would no-op and the SLI would freeze until wall
                # time caught up — rebase, folding any stranded pending
                # counters into `upto`.
                rebased_from = self._closed_through
                stranded = _SlotCounters()
                for s in [s for s in self._pending if s > upto]:
                    stranded.merge(self._pending.pop(s))
                existing = self._pending.get(upto)
                if existing is not None:
                    stranded.merge(existing)
                self._pending[upto] = stranded
                self._closed_through = upto - 1
            if self._closed_through is None:
                start = min(self._pending.keys(), default=upto)
            else:
                start = self._closed_through + 1
            if upto < start:
                return []
            gap = 0
            if upto - start + 1 > MAX_GAP_REPORTS:
                gap = (upto - start + 1) - MAX_GAP_REPORTS
                start = upto - MAX_GAP_REPORTS + 1
                # drop pending counters swallowed by the gap
                for s in [s for s in self._pending if s < start]:
                    self._pending.pop(s)
            for slot in range(start, upto + 1):
                rep = SlotReport(slot, self._pending.pop(slot, None),
                                 gap_before=gap if slot == start else 0)
                self._closed_through = slot
                for w in self.windows.values():
                    w.append(rep)
                self.recent.append(rep)
                self.closed_count += 1
                reports.append(rep)
        if rebased_from is not None:
            self.recorder.record(
                "slo_clock_rebase", severity="warn",
                from_slot=rebased_from, to_slot=upto,
            )
            # the trigger watermark must follow or every post-rebase slot
            # would read as stale and trigger state would freeze too
            with self._post_lock:
                self._post_through = min(self._post_through, upto - 1)
        for rep in reports:
            self._post_close(rep)
            self._notify_close(rep)
        return reports

    # ----------------------------------------------------------- analysis

    def _window_summary_locked(self, name: str) -> dict:
        reps = list(self.windows[name])
        hits = sum(r.hits for r in reps)
        misses = sum(r.misses for r in reps)
        total = hits + misses
        ratio = 1.0 if total == 0 else hits / total
        budget = max(1e-9, 1.0 - self.target)
        routes: dict[str, int] = {}
        for r in reps:
            for p, n in r.routes.items():
                routes[p] = routes.get(p, 0) + n
        route_total = sum(routes.values())
        vhits = sum(r.validator_hits for r in reps)
        vmiss = sum(r.validator_misses for r in reps)
        out = {
            "slots": len(reps),
            "hits": hits,
            "misses": misses,
            "deadline_hit_ratio": round(ratio, 4),
            "burn_rate": round((1.0 - ratio) / budget, 2),
            "route_share": {
                p: round(n / route_total, 4) for p, n in sorted(routes.items())
            } if route_total else {},
        }
        if vhits or vmiss:
            out["validator_monitor"] = {"hits": vhits, "misses": vmiss}
        per_workload: dict[str, list] = {}
        for r in reps:
            for w, (h, m) in r.workloads.items():
                ent = per_workload.setdefault(w, [0, 0])
                ent[0] += h
                ent[1] += m
        if per_workload:
            out["workloads"] = {}
            for w, (h, m) in sorted(per_workload.items()):
                wr = 1.0 if h + m == 0 else h / (h + m)
                out["workloads"][w] = {
                    "hits": h,
                    "misses": m,
                    "deadline_hit_ratio": round(wr, 4),
                    "burn_rate": round((1.0 - wr) / budget, 2),
                }
        return out

    def window_summary(self, name: str) -> dict:
        with self._lock:
            return self._window_summary_locked(name)

    def deadline_totals(self) -> tuple[int, int]:
        """(hits, misses) summed over every retained closed report — the
        cluster rollup's read (observability/propagation.py
        build_cluster_report). Bounded by the `recent` ring (64 slots),
        which covers every shipped scenario length; integer counts only,
        so the rollup stays bit-deterministic."""
        with self._lock:
            reps = list(self.recent)
        return (sum(r.hits for r in reps), sum(r.misses for r in reps))

    def burn_rate(self, window: str = "slot_5") -> float:
        return self.window_summary(window)["burn_rate"]

    def _post_close(self, rep: SlotReport) -> None:
        """Outside the accountant lock (but serialized by _post_lock):
        export gauges, emit flight-recorder events, and run the incident
        triggers for one closed report. Trigger state only advances for
        slots NEWER than any already evaluated — a racing closer's stale
        batch must not clear (re-arm) a trigger a newer slot just fired."""
        with self._post_lock:
            self._post_close_serialized(rep)

    def _post_close_serialized(self, rep: SlotReport) -> None:
        ratio = rep.hit_ratio()
        degraded = ratio is not None and ratio < self.streak_ratio
        if self._export:
            SLOT_REPORTS.labels(
                "empty" if rep.empty else ("degraded" if degraded else "ok")
            ).inc()
        stale = rep.slot <= self._post_through
        if not stale:
            self._post_through = rep.slot
        with self._lock:
            short = self._window_summary_locked("slot_5")
            epoch = self._window_summary_locked("epoch_32")
            if not stale:
                if degraded:
                    self._streak += 1
                elif not rep.empty:
                    self._streak = 0
            streak = self._streak
        if self._export:
            # deadline counters are exported at CLOSE, not at record time:
            # a processed item that a verifier later marks late would
            # otherwise count once as hit and once as miss
            DEADLINE_TOTAL.labels("hit").inc(rep.hits)
            DEADLINE_TOTAL.labels("miss").inc(rep.misses)
            HIT_RATIO.labels("slot_5").set(short["deadline_hit_ratio"])
            HIT_RATIO.labels("epoch_32").set(epoch["deadline_hit_ratio"])
            BURN_RATE.labels("slot_5").set(short["burn_rate"])
            BURN_RATE.labels("epoch_32").set(epoch["burn_rate"])
        rec = self.recorder
        if rep.misses:
            rec.record("deadline_miss", severity="warn", slot=rep.slot,
                       misses=rep.misses, late=rep.late,
                       hit_ratio=None if ratio is None else round(ratio, 4))
        shed_total = sum(
            n for k, n in rep.shed.items() if not k.endswith(":expired")
        )
        if shed_total >= self.shed_burst_threshold:
            rec.record("shed_burst", severity="warn", slot=rep.slot,
                       shed=shed_total, detail=dict(rep.shed))
        if stale:
            return   # per-report events above still emit; trigger state
                     # is owned by the newest evaluated slot
        # trigger 1: burn rate over threshold (cleared when it falls back)
        burning = short["burn_rate"] >= self.burn_threshold
        if self._export:
            DEGRADED.labels("slo_burn_rate").set(1.0 if burning else 0.0)
        # `slo=self.snapshot` (the METHOD): the recorder evaluates it only
        # when the trigger actually fires — a trigger held down through a
        # long degradation must not build a snapshot per slot to discard
        if burning and not self._burning:
            rec.trigger("slo_burn_rate", slot=rep.slot,
                        burn_rate=short["burn_rate"],
                        window="slot_5", slo=self.snapshot)
        elif not burning and self._burning:
            rec.clear("slo_burn_rate")
        self._burning = burning
        # trigger 2: deadline-miss streak (cleared by one clean slot)
        if streak >= self.miss_streak:
            rec.trigger("deadline_miss_streak", slot=rep.slot,
                        streak=streak, slo=self.snapshot)
        elif streak == 0:
            rec.clear("deadline_miss_streak")
        # trigger 3: cross-tenant device contention — the device ledger's
        # (victim, occupant) matrix accrued over threshold since the last
        # evaluated slot. Same hysteresis contract as the burn trigger: a
        # latch arms on the first over-threshold slot and a sustained
        # episode dumps once; the latch re-arms only after a slot whose
        # contention delta is back under threshold.
        self._run_contention_trigger(rep, rec)

    def _run_contention_trigger(self, rep: SlotReport, rec) -> None:
        try:
            from .device_ledger import LEDGER

            matrix = LEDGER.contention_matrix()
        except Exception:
            return   # the books must never break a slot close
        delta = {
            key: secs - self._contention_baseline.get(key, 0.0)
            for key, secs in matrix.items()
            if secs - self._contention_baseline.get(key, 0.0) > 0.0
        }
        self._contention_baseline = matrix
        total = sum(delta.values())
        contending = total >= self.contention_threshold
        if self._export:
            DEGRADED.labels("device_contention").set(
                1.0 if contending else 0.0
            )
        if contending and not self._contending:
            # the dump names who paid (victim), who held the device
            # (occupant), and the occupying batch's padding bucket
            (victim, occupant), secs = max(
                delta.items(), key=lambda kv: (kv[1], kv[0])
            )
            try:
                from .device_ledger import LEDGER

                bucket = LEDGER.last_bucket(occupant)
            except Exception:
                bucket = None
            rec.trigger("device_contention", slot=rep.slot,
                        victim=victim, occupant=occupant,
                        occupant_bucket=bucket,
                        contention_seconds=round(secs, 6),
                        contention_total_seconds=round(total, 6),
                        slo=self.snapshot)
        elif not contending and self._contending:
            rec.clear("device_contention")
        self._contending = contending

    def health(self) -> dict:
        """The degraded signal the /eth/v1/node/health endpoint consumes:
        short-window burn over threshold, or the device breaker open."""
        reasons = []
        if self.burn_rate("slot_5") >= self.burn_threshold:
            reasons.append("slo_burn_rate")
        for name in self.recorder.open_breakers(prefix="bls_device"):
            reasons.append(f"breaker_open:{name}")
        return {"degraded": bool(reasons), "reasons": reasons}

    # ----------------------------------------------------------- snapshot

    def snapshot(self, recent: int = 8) -> dict:
        with self._lock:
            reps = list(self.recent)[-recent:]
            out = {
                "target": self.target,
                "burn_threshold": self.burn_threshold,
                # the denominator the wait/latency quantiles are read
                # against: work must clear the pipeline well inside this
                "slot_budget_seconds": getattr(
                    self._clock, "seconds_per_slot", None
                ),
                "closed_through": self._closed_through,
                "slots_closed": self.closed_count,
                "open_slots": sorted(self._pending.keys()),
                "windows": {
                    name: self._window_summary_locked(name)
                    for name in self.windows
                },
                "recent_reports": [r.as_dict() for r in reps],
            }
        last = next((r for r in reversed(reps) if not r.empty), None)
        if last is not None:
            out["last_active_report"] = last.as_dict()
        return out


#: the node's accountant — /lighthouse_tpu/slo, health, debug-bundle
ACCOUNTANT = SlotAccountant()


def health() -> dict:
    return ACCOUNTANT.health()
