"""Process-wide device-occupancy ledger: one owner of "who holds the
device, who is waiting, and which tenant's SLO paid for it".

Every batched workload that reaches the device registers here under a
workload name (`bls`, `tree_hash`, `epoch`, `meshsim`, later `kzg`) —
the `PipelinedDispatcher` does it from its constructor, the epoch-vector
path does it around its direct dispatch. Each submission opens a ledger
*interval* at admit (workload, lane, bucket, est-cost from the
autotune/capacity cost model), marks it busy when the device dispatch
begins, and closes it at device resolve. The ledger turns those events
into:

  - `device_ledger_busy_seconds_total{workload,lane}` — per-tenant
    device time, the attribution PR 6's per-stage series cannot give
  - `device_ledger_admit_wait_seconds{workload}` — per-tenant admit
    latency (time between admit and device dispatch)
  - `device_ledger_utilization{chip}` / `device_ledger_overlap{chip}` —
    busy fraction since reset and current interval overlap per chip
  - `pipeline_inflight{workload}` — the per-tenant view of the
    previously anonymous depth-bounded dispatch windows
  - **cross-tenant contention time** — the headline signal: wall time
    where workload A has admitted work pending while the device is
    occupied by workload B, counted
    `device_ledger_contention_seconds_total{victim,occupant}`

Accounting is incremental and event-driven: at every interval
transition the elapsed time since the previous event lands in exactly
one of {busy, contended, idle} per chip, so per-chip conservation

    busy + contended (contention-wait) + idle == wall

holds *exactly* by construction — the `mixed_duty` loadgen scenario
exits nonzero if it does not. The clock is injectable
(`configure(clock=...)`) so deterministic harnesses drive the ledger on
a logical clock; the default is `time.perf_counter`, the same clock the
tracer stamps spans with, which is what lets `trace.py` merge the
ledger's timeline into the Perfetto export as its own process group.

Host-only by construction: imports nothing that initializes a device.
"""

from __future__ import annotations

import threading
from collections import deque
from time import perf_counter

from ..utils.metrics import REGISTRY

# ------------------------------------------------------------------ metrics
# all device_ledger_* series are labeled families (scripts/lint_metrics.py
# enforces it): an unlabeled aggregate cannot answer "which tenant held
# the device and which tenant paid for the wait"

_BUSY = REGISTRY.counter_vec(
    "device_ledger_busy_seconds_total",
    "device-occupancy seconds attributed per tenant, by workload and lane",
    ("workload", "lane"),
)
_ADMIT_WAIT = REGISTRY.histogram_vec(
    "device_ledger_admit_wait_seconds",
    "time between a submission's admit and its device dispatch, by "
    "workload — the per-tenant view of the dispatch windows' admit wait",
    ("workload",),
    buckets=(0.0001, 0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0),
)
_CONTENTION = REGISTRY.counter_vec(
    "device_ledger_contention_seconds_total",
    "cross-tenant contention: wall seconds the victim workload had "
    "admitted work pending while the device was occupied by the "
    "occupant workload",
    ("victim", "occupant"),
)
_UTILIZATION = REGISTRY.gauge_vec(
    "device_ledger_utilization",
    "fraction of wall time the chip was occupied (busy + contended) "
    "since the ledger was last reset, by chip",
    ("chip",),
)
_OVERLAP = REGISTRY.gauge_vec(
    "device_ledger_overlap",
    "number of ledger intervals currently occupying the chip, by chip "
    "— >1 means batches from more than one submission share the slot",
    ("chip",),
)
_PIPELINE_INFLIGHT = REGISTRY.gauge_vec(
    "pipeline_inflight",
    "ledger intervals currently in the busy state (device dispatch "
    "begun, not yet resolved), by workload — the per-tenant view of "
    "the previously anonymous jaxbls_pipeline_inflight{lane}",
    ("workload",),
)

#: bounded timeline ring — enough for a full loadgen run's device
#: history without unbounded growth on a long-lived node
TIMELINE_CAP = 2048


class LedgerInterval:
    """One submission's life on the device: admit -> dispatch -> resolve.

    States: "waiting" (admitted, not yet dispatched), "busy" (device
    dispatch begun), closed (removed from the ledger). All transitions
    go through the owning ledger so the incremental per-chip accounting
    sees every edge. Safe to close after a ledger reset — the close
    becomes a no-op instead of corrupting the new epoch's books."""

    __slots__ = ("workload", "lane", "bucket", "est_cost", "chips",
                 "t_open", "t_start", "state", "seq", "_ledger")

    def __init__(self, ledger, seq, workload, lane, bucket, est_cost, chips):
        self._ledger = ledger
        self.seq = seq
        self.workload = workload
        self.lane = lane
        self.bucket = bucket
        self.est_cost = est_cost
        self.chips = chips            # None = every chip (sharded batch)
        self.t_open = None            # stamped by the ledger under lock
        self.t_start = None
        self.state = "waiting"

    def start(self):
        """Device dispatch begins: waiting -> busy."""
        self._ledger._start(self)
        return self

    def close(self, outcome="ok"):
        """Device resolve: the interval leaves the ledger."""
        self._ledger._close(self, outcome)

    def occupies(self, chip):
        return self.chips is None or chip in self.chips


class DeviceLedger:
    """Process-wide owner of device occupancy across every workload.

    Thread-safe; one instance (`LEDGER`) per process, reset per
    deterministic run the way `RECORDER` is."""

    def __init__(self, n_chips=1, clock=perf_counter):
        self._lock = threading.RLock()
        self._default_clock = clock
        self._registry = {}           # workload -> {"dispatcher": ..., "seq": n}
        self._reset_locked(n_chips=n_chips, clock=clock)

    # -- configuration ---------------------------------------------------

    def _reset_locked(self, n_chips, clock):
        self._clock = clock
        self._n_chips = max(1, int(n_chips))
        now = self._clock()
        self._t0 = now
        self._last = now
        self._seq = 0
        self._open = {}               # seq -> LedgerInterval
        self._busy = [0.0] * self._n_chips
        self._contended = [0.0] * self._n_chips
        self._idle = [0.0] * self._n_chips
        self._matrix = {}             # (victim, occupant) -> seconds
        self._last_bucket = {}        # workload -> bucket of last busy iv
        self._timeline = deque(maxlen=TIMELINE_CAP)
        self._inflight = {}           # workload -> busy interval count

    def reset(self):
        """Forget every interval and all accounting; restore the default
        wall clock and single-chip shape. Intervals opened before the
        reset close as no-ops (their seq is gone from the books)."""
        with self._lock:
            self._reset_locked(n_chips=1, clock=self._default_clock)

    def configure(self, n_chips=None, clock=None):
        """Rebind the chip universe and/or the clock (deterministic
        harnesses install a logical clock). Implies a fresh accounting
        epoch — mixing clocks inside one epoch would break conservation."""
        with self._lock:
            self._reset_locked(
                n_chips=self._n_chips if n_chips is None else n_chips,
                clock=self._clock if clock is None else clock,
            )

    @property
    def n_chips(self):
        return self._n_chips

    # -- workload registry -----------------------------------------------

    def register(self, workload, dispatcher=None):
        """Register a tenant. Dispatchers call this from their
        constructor; direct-dispatch paths (epoch vectors) call it with
        dispatcher=None. Re-registration replaces the dispatcher ref
        (latest wins — loadgen harnesses rebuild their nodes)."""
        workload = str(workload)
        with self._lock:
            ent = self._registry.setdefault(
                workload, {"dispatcher": None, "registrations": 0}
            )
            ent["registrations"] += 1
            if dispatcher is not None:
                ent["dispatcher"] = dispatcher
        _PIPELINE_INFLIGHT.labels(workload).set(
            self._inflight.get(workload, 0)
        )
        return workload

    def workloads(self):
        with self._lock:
            return sorted(self._registry)

    # -- interval lifecycle ----------------------------------------------

    def open(self, workload, lane="batch", bucket=None, est_cost=None,
             chips=None):
        """Admit one submission: the interval starts life waiting."""
        with self._lock:
            now = self._advance_locked()
            if workload not in self._registry:
                self._registry[workload] = {
                    "dispatcher": None, "registrations": 0,
                }
            self._seq += 1
            iv = LedgerInterval(
                self, self._seq, str(workload), str(lane), bucket,
                est_cost, None if chips is None else tuple(chips),
            )
            iv.t_open = now
            self._open[iv.seq] = iv
            return iv

    def _start(self, iv):
        with self._lock:
            if iv.seq not in self._open or iv.state != "waiting":
                return                # closed, or a pre-reset straggler
            now = self._advance_locked()
            iv.t_start = now
            iv.state = "busy"
            self._last_bucket[iv.workload] = iv.bucket
            self._inflight[iv.workload] = self._inflight.get(iv.workload, 0) + 1
            wait = max(0.0, now - iv.t_open)
        _ADMIT_WAIT.labels(iv.workload).observe(wait)
        _PIPELINE_INFLIGHT.labels(iv.workload).set(self._inflight[iv.workload])

    def _close(self, iv, outcome):
        with self._lock:
            if iv.seq not in self._open:
                return                # already closed or reset away
            # attribute the elapsed time while the interval is still on
            # the books, THEN remove it — the reverse order would lose
            # the final busy/contention segment of every interval
            now = self._advance_locked()
            del self._open[iv.seq]
            busy_secs = 0.0
            if iv.state == "busy":
                busy_secs = max(0.0, now - iv.t_start)
                n = self._inflight.get(iv.workload, 0)
                self._inflight[iv.workload] = max(0, n - 1)
                self._timeline.append((
                    iv.workload, "wait", iv.t_open, iv.t_start,
                    iv.lane, iv.bucket, iv.est_cost, None,
                ))
                self._timeline.append((
                    iv.workload, "busy", iv.t_start, now,
                    iv.lane, iv.bucket, iv.est_cost, str(outcome),
                ))
            else:
                # abandoned before dispatch: the wait is still history
                self._timeline.append((
                    iv.workload, "wait", iv.t_open, now,
                    iv.lane, iv.bucket, iv.est_cost, str(outcome),
                ))
            iv.state = "closed"
            inflight = self._inflight.get(iv.workload, 0)
        if busy_secs:
            _BUSY.labels(iv.workload, iv.lane).inc(busy_secs)
        _PIPELINE_INFLIGHT.labels(iv.workload).set(inflight)

    # -- incremental accounting ------------------------------------------

    def _advance_locked(self):
        """Attribute the time since the last event: per chip into exactly
        one of busy/contended/idle, and contended time additionally into
        the (victim, occupant) matrix. Returns the current clock reading
        (never behind the last event — a clock regression is clamped so
        conservation survives it)."""
        now = self._clock()
        if now < self._last:
            return self._last
        dt = now - self._last
        self._last = now
        busy_ivs = [iv for iv in self._open.values() if iv.state == "busy"]
        waiting = [iv for iv in self._open.values() if iv.state == "waiting"]
        if dt > 0.0:
            # the device-level occupant: the earliest-started busy
            # interval (FIFO — the batch actually holding the queue head)
            occupant = None
            if busy_ivs:
                occupant = min(
                    busy_ivs, key=lambda iv: (iv.t_start, iv.seq)
                ).workload
            victims = set()
            for iv in waiting:
                if occupant is not None and iv.workload != occupant:
                    victims.add(iv.workload)
            for c in range(self._n_chips):
                chip_busy = [iv for iv in busy_ivs if iv.occupies(c)]
                if not chip_busy:
                    self._idle[c] += dt
                    continue
                chip_occ = min(
                    chip_busy, key=lambda iv: (iv.t_start, iv.seq)
                ).workload
                chip_victims = [
                    iv for iv in waiting
                    if iv.occupies(c) and iv.workload != chip_occ
                ]
                if chip_victims:
                    self._contended[c] += dt
                else:
                    self._busy[c] += dt
            for v in sorted(victims):
                key = (v, occupant)
                self._matrix[key] = self._matrix.get(key, 0.0) + dt
                _CONTENTION.labels(v, occupant).inc(dt)
        wall = max(now - self._t0, 1e-12)
        for c in range(self._n_chips):
            _UTILIZATION.labels(str(c)).set(
                (self._busy[c] + self._contended[c]) / wall
            )
            _OVERLAP.labels(str(c)).set(
                sum(1 for iv in busy_ivs if iv.occupies(c))
            )
        return now

    # -- read side --------------------------------------------------------

    def tick(self):
        """Bring the books up to the current clock (slot boundaries,
        report time) without an interval event."""
        with self._lock:
            self._advance_locked()

    def conservation(self):
        """Per-chip busy + contended + idle vs wall; exact by
        construction, asserted by the mixed_duty scenario."""
        with self._lock:
            now = self._advance_locked()
            wall = now - self._t0
            per_chip = []
            ok = True
            for c in range(self._n_chips):
                total = self._busy[c] + self._contended[c] + self._idle[c]
                chip_ok = abs(total - wall) <= 1e-6 + 1e-9 * abs(wall)
                ok = ok and chip_ok
                per_chip.append({
                    "chip": c,
                    "busy": self._busy[c],
                    "contention_wait": self._contended[c],
                    "idle": self._idle[c],
                    "wall": wall,
                    "ok": chip_ok,
                })
            return {"ok": ok, "wall": wall, "per_chip": per_chip}

    def contention_total(self):
        with self._lock:
            self._advance_locked()
            return sum(self._matrix.values())

    def contention_matrix(self):
        """{(victim, occupant): seconds} — copy, safe to diff against."""
        with self._lock:
            self._advance_locked()
            return dict(self._matrix)

    def last_bucket(self, workload):
        """Padding bucket of the workload's most recent busy interval —
        what a device_contention incident names as the occupying batch."""
        with self._lock:
            return self._last_bucket.get(workload)

    def busy_seconds(self):
        """{workload: seconds} summed over closed busy intervals."""
        out = {}
        with self._lock:
            for w, kind, t0, t1, *_ in self._timeline:
                if kind == "busy":
                    out[w] = out.get(w, 0.0) + (t1 - t0)
        return out

    def snapshot(self):
        """JSON-safe dump for the debug bundle / ops endpoints."""
        cons = self.conservation()
        with self._lock:
            return {
                "n_chips": self._n_chips,
                "registry": {
                    w: {
                        "registrations": ent["registrations"],
                        "has_dispatcher": ent["dispatcher"] is not None,
                    }
                    for w, ent in sorted(self._registry.items())
                },
                "open_intervals": [
                    {
                        "workload": iv.workload, "lane": iv.lane,
                        "state": iv.state, "bucket": iv.bucket,
                        "est_cost": iv.est_cost,
                    }
                    for _, iv in sorted(self._open.items())
                ],
                "inflight": {
                    w: n for w, n in sorted(self._inflight.items()) if n
                },
                "contention": {
                    f"{v}|{o}": secs
                    for (v, o), secs in sorted(self._matrix.items())
                },
                "last_bucket": dict(self._last_bucket),
                "conservation": cons,
                "timeline_len": len(self._timeline),
            }

    # -- trace export ------------------------------------------------------

    def perfetto_device_timeline(self):
        """Closed-interval spans for the Chrome-trace export, in
        deterministic order: (track, name, t0, t1, args). Busy spans land
        on the workload's occupancy track, waits on its `:wait` marker
        track — trace.py renders each track as its own thread inside one
        `device_ledger` process group."""
        with self._lock:
            rows = list(self._timeline)
        spans = []
        for workload, kind, t0, t1, lane, bucket, est_cost, outcome in rows:
            if t1 <= t0:
                continue              # zero-width: nothing to render
            track = workload if kind == "busy" else f"{workload}:wait"
            name = f"{workload}:{lane}" if kind == "busy" else "waiting"
            args = {"lane": lane}
            if bucket is not None:
                args["bucket"] = bucket
            if est_cost is not None:
                args["est_cost"] = est_cost
            if outcome is not None:
                args["outcome"] = outcome
            spans.append((track, name, t0, t1, args))
        spans.sort(key=lambda s: (s[2], s[3], s[0], s[1]))
        return spans


#: the process-wide ledger every dispatcher registers with
LEDGER = DeviceLedger()


def _wire_tracer():
    # the global tracer pulls the ledger's timeline into every
    # --trace-out export, same pattern as the flight recorder's instants
    from .trace import TRACER

    TRACER.device_timeline_source = LEDGER.perfetto_device_timeline


_wire_tracer()
