"""`bn debug-bundle` — one tarball for offline diagnosis.

An operator filing "the node misbehaved" should not have to know which of
a dozen surfaces holds the evidence. The bundle collects, best-effort,
everything a diagnosis session starts from:

  - `manifest.json`      what was collected (+ per-member status), the
                         config fingerprint, bundle schema version
  - `metrics.prom`       full Prometheus exposition of this process
  - `pipeline.json`      the /lighthouse_tpu/pipeline snapshot
  - `slo.json`           the slot-level SLO accountant snapshot
  - `flight_recorder.json`  the black-box event ring + trigger state
  - `logs.json`          recent structured log records
  - `incidents/*.json`   every incident dump found in <datadir>/incidents
  - `doctor.json`        `bn doctor` fsck of the datadir (when given)
  - `autotune_profile.json`  the installed autotune profile (when any)
  - `bench.json`         BENCH_MATRIX.json + the perf trend summary
                         (when the install's repo root carries them)
  - `cluster_report.json`  the newest loadgen report's cluster rollup
                         (cluster deadline-hit ratio, per-node outliers,
                         per-topic propagation p50/p95), when one exists
  - `device_ledger.json` the process-wide device ledger snapshot (per-
                         workload occupancy, open intervals, contention
                         matrix, per-chip conservation)
  - `mixed_duty_report.json`  the newest loadgen report's mixed-duty
                         block (per-workload SLO verdicts, ledger
                         conservation, contention incidents), when one
                         exists

Every member is independent: a half-initialized process (or a datadir-less
invocation) still produces a useful bundle, and the manifest says exactly
what is missing and why. Stdlib-only; nothing here touches a device.
"""

from __future__ import annotations

import io
import json
import os
import tarfile
import time

BUNDLE_SCHEMA = "lighthouse_tpu/debug-bundle/v1"


def _collect(fn):
    """Run one collector; returns (payload, error-or-None)."""
    try:
        return fn(), None
    except Exception as e:  # noqa: BLE001 — collectors are best-effort
        return None, f"{type(e).__name__}: {e}"


def _collect_metrics() -> str:
    from ..utils.metrics import REGISTRY

    return REGISTRY.expose_text()


def _collect_pipeline() -> dict:
    from . import snapshot

    return snapshot()


def _collect_slo() -> dict:
    from .slo import ACCOUNTANT

    return ACCOUNTANT.snapshot(recent=32)


def _collect_flight_recorder() -> dict:
    from .flight_recorder import RECORDER

    return RECORDER.snapshot()


def _collect_logs() -> list:
    from ..utils.logging import RECENT

    return [
        {"ts": ts, "level": level, "component": component, "msg": msg,
         **{k: str(v) for k, v in fields.items()}}
        for ts, level, component, msg, fields in list(RECENT)[-256:]
    ]


def _collect_doctor(datadir: str) -> dict:
    from ..store.doctor import fsck_datadir

    return fsck_datadir(datadir, repair=False)


def _collect_autotune() -> dict:
    from ..autotune import profile as at_profile
    from ..autotune import runtime as at_runtime

    prof = at_runtime.active_profile()
    if prof is None:
        # not installed in this process: fall back to this device's
        # canonical on-disk profile if one exists
        key = at_runtime.detect_device_key(wait_secs=2.0)
        if key is None:
            raise FileNotFoundError("no installed or detectable profile")
        prof = at_profile.load(at_profile.default_path(key))
    return prof.to_json()


def _collect_cluster(root: str) -> dict:
    """Latest cluster rollup (the `cluster` block a multinode/fleet
    loadtest report carries: cluster deadline-hit ratio, per-node
    outliers, per-topic propagation p50/p95): read from the newest
    loadgen report at the install root."""
    candidates = [
        os.path.join(root, name)
        for name in ("loadgen_report.json", "LOADGEN_SMOKE.json")
        if os.path.exists(os.path.join(root, name))
    ]
    for path in sorted(candidates, key=os.path.getmtime, reverse=True):
        with open(path) as f:
            rep = json.load(f)
        cluster = (rep.get("deterministic") or {}).get("cluster")
        if cluster is not None:
            return {
                "source": os.path.basename(path),
                "scenario": rep.get("scenario"),
                "seed": rep.get("seed"),
                "cluster": cluster,
            }
    raise FileNotFoundError(
        "no loadgen report with a cluster block at install root"
    )


def _collect_device_ledger() -> dict:
    from .device_ledger import LEDGER

    return LEDGER.snapshot()


def _collect_mixed_duty(root: str) -> dict:
    """Latest mixed-duty rollup (per-workload SLO verdicts, device-ledger
    conservation + contention, incident verdicts): read from the newest
    loadgen report at the install root that carries one."""
    candidates = [
        os.path.join(root, name)
        for name in ("loadgen_report.json", "LOADGEN_SMOKE.json")
        if os.path.exists(os.path.join(root, name))
    ]
    for path in sorted(candidates, key=os.path.getmtime, reverse=True):
        with open(path) as f:
            rep = json.load(f)
        if not rep.get("mixed_duty"):
            continue
        det = rep.get("deterministic") or {}
        return {
            "source": os.path.basename(path),
            "scenario": rep.get("scenario"),
            "seed": rep.get("seed"),
            "gate": rep.get("gate"),
            "workloads": det.get("workloads"),
            "device_ledger": det.get("device_ledger"),
            "contention_incidents": det.get("contention_incidents"),
        }
    raise FileNotFoundError(
        "no mixed-duty loadgen report at install root"
    )


def _collect_bench(root: str) -> dict:
    out: dict = {}
    matrix = os.path.join(root, "BENCH_MATRIX.json")
    if os.path.exists(matrix):
        with open(matrix) as f:
            out["bench_matrix"] = json.load(f)
    from . import perf

    trend = perf.trend_summary()
    if trend is not None:
        out["perf_trend"] = trend
    if not out:
        raise FileNotFoundError("no bench artifacts at install root")
    return out


def build_bundle(out_path: str, datadir: str | None = None,
                 root: str | None = None) -> dict:
    """Write the tarball; returns the manifest (also stored inside it)."""
    from .flight_recorder import config_fingerprint

    if root is None:
        # the install's repo root (where BENCH_r*.json live)
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
    members: list[tuple[str, bytes]] = []
    status: dict[str, str] = {}

    def add_json(name: str, fn) -> None:
        payload, err = _collect(fn)
        if err is not None:
            status[name] = f"skipped: {err}"
            return
        members.append(
            (name, json.dumps(payload, indent=1, default=str).encode())
        )
        status[name] = "ok"

    payload, err = _collect(_collect_metrics)
    if err is None:
        members.append(("metrics.prom", payload.encode()))
        status["metrics.prom"] = "ok"
    else:
        status["metrics.prom"] = f"skipped: {err}"
    add_json("pipeline.json", _collect_pipeline)
    add_json("slo.json", _collect_slo)
    add_json("flight_recorder.json", _collect_flight_recorder)
    add_json("logs.json", _collect_logs)
    add_json("autotune_profile.json", _collect_autotune)
    add_json("bench.json", lambda: _collect_bench(root))
    add_json("cluster_report.json", lambda: _collect_cluster(root))
    add_json("device_ledger.json", _collect_device_ledger)
    add_json("mixed_duty_report.json", lambda: _collect_mixed_duty(root))

    incidents: list[str] = []
    if datadir:
        add_json("doctor.json", lambda: _collect_doctor(datadir))
        inc_dir = os.path.join(datadir, "incidents")
        if os.path.isdir(inc_dir):
            for name in sorted(os.listdir(inc_dir)):
                if not name.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(inc_dir, name), "rb") as f:
                        members.append((f"incidents/{name}", f.read()))
                    incidents.append(name)
                except OSError as e:
                    status[f"incidents/{name}"] = f"skipped: {e}"
            status["incidents"] = f"ok: {len(incidents)} dump(s)"
        else:
            status["incidents"] = "skipped: no incidents directory"
    else:
        status["doctor.json"] = "skipped: no --datadir"
        status["incidents"] = "skipped: no --datadir"

    manifest = {
        "schema": BUNDLE_SCHEMA,
        "created": time.time(),
        "datadir": datadir,
        "members": sorted(n for n, _ in members) + ["manifest.json"],
        "status": status,
        "incidents": incidents,
        "config_fingerprint": config_fingerprint(),
    }
    members.append(
        ("manifest.json", json.dumps(manifest, indent=1, default=str).encode())
    )

    with tarfile.open(out_path, "w:gz") as tar:
        for name, data in members:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = int(manifest["created"])
            tar.addfile(info, io.BytesIO(data))
    return manifest


def run_from_args(args) -> int:
    """CLI entry for `bn debug-bundle`."""
    manifest = build_bundle(
        out_path=args.out, datadir=args.datadir, root=args.root
    )
    print(json.dumps(
        {
            "bundle": args.out,
            "members": manifest["members"],
            "incidents": manifest["incidents"],
            "status": manifest["status"],
        },
        indent=1,
    ))
    return 0
