"""Flight recorder — the node's always-on black box + incident dumper.

The QoS/breaker/supervisor machinery emits transitions that previously
vanished into scrolling logs: when a breaker opened under load there was
no durable record to diagnose from. This module keeps a bounded ring of
STRUCTURED events (breaker transitions, shed bursts, deadline misses,
supervisor restarts, route flips, every WARN+ log record via the
utils/logging observer sink), each stamped with wall time, a monotonic
timestamp (so it aligns with pipeline spans in the Perfetto export), the
current slot when a clock is bound, and the current trace id when one is
in flight.

Incident triggers — breaker open, SLO burn-rate over threshold, a
deadline-miss streak (observability/slo.py drives the latter two) — dump a
snapshot to `<incident_dir>/incident-NNNN-<reason>.json`: the recent event
ring, recent trace summaries, the SLO windows, the full metrics
exposition, and a config fingerprint. Triggers have HYSTERESIS: a reason
that fired stays disarmed until it is explicitly cleared (breaker closed,
burn rate back under threshold), so a breaker that stays open for an hour
produces one dump, not a dump storm. Dumps are additionally capped per
process as a hard backstop.

Everything here is hot-path cheap: recording an event is a lock + deque
append; the expensive snapshot work only runs when an armed trigger fires.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
from collections import deque
from time import perf_counter

from ..utils import logging as ltlog
from ..utils.metrics import REGISTRY
from .trace import TRACER, current_trace

#: incident dump schema identifier; validate_incident() checks against it
INCIDENT_SCHEMA = "lighthouse_tpu/incident/v1"

#: hard backstop on dumps per process — hysteresis is the real guard, this
#: bounds the blast radius of a trigger bug
MAX_INCIDENTS = 64

EVENTS_TOTAL = REGISTRY.counter_vec(
    "flight_recorder_events_total",
    "structured events recorded by the flight recorder, by event kind",
    ("kind",),
)
INCIDENTS_TOTAL = REGISTRY.counter_vec(
    "flight_recorder_incidents_total",
    "incident snapshots triggered, by trigger reason (counted even when "
    "no incident directory is configured to receive the dump)",
    ("reason",),
)


def config_fingerprint() -> dict:
    """Stable description of the running configuration: the LIGHTHOUSE_TPU_*
    environment, interpreter + argv, the active BLS and hash backends,
    the mesh topology string, and the installed autotune profile key —
    plus a sha256 over the canonical JSON so two dumps can be compared at
    a glance. Best-effort by design (an incident dump must never fail on
    a half-initialized process)."""
    env = {
        k: v for k, v in sorted(os.environ.items())
        if k.startswith("LIGHTHOUSE_TPU_")
    }
    out = {
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "env": env,
    }
    try:
        from ..crypto.bls import api as bls_api

        backend = bls_api._active_backend
        out["bls_backend"] = type(backend).__name__ if backend else None
    except Exception:
        out["bls_backend"] = None
    try:
        from ..autotune import runtime as at_runtime

        prof = at_runtime.active_profile()
        out["autotune_profile"] = None if prof is None else prof.key_string()
    except Exception:
        out["autotune_profile"] = None
    try:
        from ..jaxhash.router import hash_backend

        out["hash_backend"] = hash_backend()
    except Exception:
        out["hash_backend"] = None
    try:
        # topology only if the mesh layer is already loaded — the
        # fingerprint must never be the thing that initializes a device
        mesh_mod = sys.modules.get("lighthouse_tpu.parallel.mesh")
        out["mesh_topology"] = (
            None if mesh_mod is None else mesh_mod.mesh_shape_key()
        )
    except Exception:
        out["mesh_topology"] = None
    out["sha256"] = hashlib.sha256(
        json.dumps(out, sort_keys=True).encode()
    ).hexdigest()
    return out


def validate_incident(doc: dict) -> list[str]:
    """Schema check for one incident dump; returns violations (empty =
    valid). Wired into tier-1 (tests/test_slo.py) so the dump format — the
    thing an operator greps at 3am — cannot silently drift."""
    errors: list[str] = []
    if not isinstance(doc, dict):
        return ["incident dump must be a JSON object"]
    if doc.get("schema") != INCIDENT_SCHEMA:
        errors.append(f"schema must be {INCIDENT_SCHEMA!r}")
    for key, typ in (
        ("reason", str), ("seq", int), ("ts", (int, float)),
        ("context", dict), ("events", list), ("recent_traces", list),
        ("slo", dict), ("metrics", str), ("config_fingerprint", dict),
    ):
        if key not in doc:
            errors.append(f"missing key {key!r}")
        elif not isinstance(doc[key], typ):
            errors.append(f"{key!r} must be {typ}")
    for i, ev in enumerate(doc.get("events", [])):
        if not isinstance(ev, dict) or "kind" not in ev or "ts" not in ev:
            errors.append(f"events[{i}] needs 'kind' and 'ts'")
            break
    fp = doc.get("config_fingerprint")
    if isinstance(fp, dict) and "sha256" not in fp:
        errors.append("config_fingerprint needs 'sha256'")
    return errors


_UNSET = object()


class FlightRecorder:
    """Bounded structured-event ring + armed incident triggers."""

    def __init__(self, ring_size: int = 512):
        self._lock = threading.Lock()
        self.ring: deque = deque(maxlen=ring_size)
        self.incident_dir: str | None = None
        self.clock = None                 # optional SlotClock for slot stamps
        self.slo_provider = None          # () -> slo snapshot dict for dumps
        self.events_recorded = 0
        # trigger hysteresis: reason -> armed. A missing key means armed.
        self._armed: dict[str, bool] = {}
        self._incident_seq = 0
        self.incidents_written: list[str] = []     # paths, bounded
        # last observed state per breaker name (health endpoint + events)
        self.breaker_states: dict[str, str] = {}
        # last observed route per scope (flip detection)
        self._last_route: dict[str, str] = {}

    # ------------------------------------------------------------ lifecycle

    def configure(self, incident_dir=_UNSET, clock=_UNSET,
                  slo_provider=_UNSET) -> None:
        """Point the recorder at a dump directory, a slot clock, and/or an
        SLO snapshot provider (the accountant whose windows belong in this
        run's dumps). Only explicitly passed fields change — a later
        `configure(clock=...)` must not detach the dump sink — and an
        explicit None DETACHES that field (a finished loadgen run must not
        leave its dead manual clock or private accountant wired in)."""
        with self._lock:
            if incident_dir is not _UNSET:
                self.incident_dir = incident_dir
            if clock is not _UNSET:
                self.clock = clock
            if slo_provider is not _UNSET:
                self.slo_provider = slo_provider

    def reset(self) -> None:
        """Drop all state (deterministic loadgen runs, tests). Counters on
        the global registry are cumulative by design and are not reset."""
        with self._lock:
            self.ring.clear()
            self.events_recorded = 0
            self._armed.clear()
            self._incident_seq = 0
            self.incidents_written.clear()
            self.breaker_states.clear()
            self._last_route.clear()
            self.incident_dir = None
            self.clock = None
            self.slo_provider = None

    # --------------------------------------------------------------- events

    def record(self, kind: str, severity: str = "info", **fields) -> dict:
        """Append one structured event; returns it. Cheap: no IO."""
        tr = current_trace()
        clock = self.clock
        slot = None
        if clock is not None:
            try:
                slot = clock.now()
            except Exception:
                slot = None
        ev = {
            "ts": time.time(),
            "t_mono": perf_counter(),
            "kind": kind,
            "severity": severity,
            "slot": slot,
            "trace_id": tr.trace_id if tr is not None else None,
            **fields,
        }
        with self._lock:
            self.ring.append(ev)
            self.events_recorded += 1
        EVENTS_TOTAL.labels(kind).inc()
        return ev

    def events(self, last: int = 128) -> list[dict]:
        with self._lock:
            return list(self.ring)[-last:]

    def perfetto_instants(self) -> list[tuple]:
        """(t_mono, name, args) markers for the Chrome-trace export — one
        instant per recorded event, on the dedicated flight-recorder lane."""
        out = []
        for ev in self.events(last=256):
            args = {
                k: v for k, v in ev.items()
                if k not in ("t_mono", "kind") and v is not None
            }
            out.append((ev["t_mono"], f"fr:{ev['kind']}", args))
        return out

    # ------------------------------------------------------------- triggers

    def trigger(self, reason: str, key: str | None = None, **context):
        """Fire an incident if `reason` (or the finer-grained `key`) is
        armed: record the event, count it, and — when an incident_dir is
        configured — dump the snapshot. Returns the dump path, or None
        (disarmed / no sink / cap reached). The trigger disarms itself;
        `clear()` re-arms when the triggering condition ends."""
        arm_key = key or reason
        with self._lock:
            if not self._armed.get(arm_key, True):
                return None
            self._armed[arm_key] = False
            self._incident_seq += 1
            seq = self._incident_seq
            out_dir = self.incident_dir
            capped = len(self.incidents_written) >= MAX_INCIDENTS
        INCIDENTS_TOTAL.labels(reason).inc()
        self.record("incident", severity="error", reason=reason, seq=seq,
                    **{k: str(v) for k, v in context.items() if k != "slo"})
        if out_dir is None or capped:
            return None
        doc = self.build_incident(reason, seq, context)
        path = os.path.join(out_dir, f"incident-{seq:04d}-{reason}.json")
        try:
            # crash-safe write (same discipline as the store layer): the
            # process may die mid-episode, and a torn dump would break the
            # one artifact meant to explain that death
            os.makedirs(out_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            return None     # a full disk must not take the node down too
        with self._lock:
            self.incidents_written.append(path)
        return path

    def clear(self, reason: str, key: str | None = None) -> None:
        """Re-arm a trigger: the condition that fired it has ended."""
        with self._lock:
            self._armed[key or reason] = True

    def build_incident(self, reason: str, seq: int, context: dict) -> dict:
        """The snapshot an operator diagnoses from: recent events + traces
        + SLO windows + metrics exposition + config fingerprint."""
        from . import slo as _slo             # lazy: slo imports this module

        # the triggering accountant may hand its own windows in via
        # context["slo"] (loadgen runs a private accountant) — as a dict,
        # or as a CALLABLE evaluated only here, i.e. only when the trigger
        # actually fired (a held-down trigger must not build a snapshot
        # per slot just to discard it). It lands in the dedicated "slo"
        # key, not duplicated inside "context". A configured slo_provider
        # covers triggers that carry no snapshot (breaker transitions).
        context = dict(context)
        slo_snap = context.pop("slo", None)
        if slo_snap is None and self.slo_provider is not None:
            slo_snap = self.slo_provider
        if callable(slo_snap):
            try:
                slo_snap = slo_snap()
            except Exception:
                slo_snap = None
        recent_traces = []
        for tr in TRACER.snapshot_ring()[-16:]:
            recent_traces.append(
                {
                    "trace_id": tr.trace_id,
                    "kind": tr.kind,
                    "items": tr.n_items,
                    "duration_seconds": round(tr.duration(), 6),
                    "spans": [
                        {"stage": name, "seconds": round(t1 - t0, 6)}
                        for name, t0, t1, _ in tr.spans
                    ],
                }
            )
        return {
            "schema": INCIDENT_SCHEMA,
            "reason": reason,
            "seq": seq,
            "ts": time.time(),
            "context": {k: _jsonable(v) for k, v in context.items()},
            "events": self.events(last=128),
            "recent_traces": recent_traces,
            "slo": slo_snap if slo_snap is not None
            else _slo.ACCOUNTANT.snapshot(),
            "metrics": REGISTRY.expose_text(),
            "config_fingerprint": config_fingerprint(),
        }

    # ---------------------------------------------------------------- hooks

    def note_breaker(self, name: str, to: str, failures: int = 0) -> None:
        """Circuit-breaker transition (qos/breaker.py calls this AFTER
        releasing its lock). `to == "open"` fires the breaker incident;
        only a transition back to `closed` re-arms it — an
        open→half_open→open flap while degraded never re-dumps."""
        with self._lock:
            self.breaker_states[name] = to
        self.record("breaker_transition",
                    severity="warn" if to != "closed" else "info",
                    breaker=name, to=to, failures=failures)
        if to == "open":
            self.trigger("breaker_open", key=f"breaker_open:{name}",
                         breaker=name, failures=failures)
        elif to == "closed":
            self.clear("breaker_open", key=f"breaker_open:{name}")

    def open_breakers(self, prefix: str = "") -> list[str]:
        """Breakers currently OPEN (optionally filtered by name prefix) —
        the health endpoint's degraded-signal read."""
        with self._lock:
            return [
                n for n, st in self.breaker_states.items()
                if st == "open" and n.startswith(prefix)
            ]

    def note_route(self, scope: str, path: str, reason: str = "") -> None:
        """Routing decision for `scope` (e.g. "bls_device"): records an
        event only when the path FLIPS from the last observed one, so the
        ring holds transitions, not every verify."""
        with self._lock:
            last = self._last_route.get(scope)
            if last == path:
                return
            self._last_route[scope] = path
        if last is not None:          # the first observation is not a flip
            self.record("route_flip", severity="warn",
                        scope=scope, path=path, reason=reason, was=last)

    def note_supervisor_restart(self, service: str, attempt: int,
                                error: str) -> None:
        self.record("supervisor_restart", severity="warn",
                    service=service, attempt=attempt, error=error)

    #: event keys log fields must not shadow (a `log.warn(..., kind=...)`
    #: field would otherwise collide with record()'s own kwargs)
    _RESERVED_EVENT_KEYS = frozenset(
        {"ts", "t_mono", "kind", "severity", "slot", "trace_id",
         "component", "msg"}
    )

    def _on_log_record(self, ts, level, component, msg, fields) -> None:
        """utils/logging observer: every WARN+ record becomes an event."""
        safe = {
            (k if k not in self._RESERVED_EVENT_KEYS else f"field_{k}"): str(v)
            for k, v in fields.items()
        }
        self.record("log", severity=level.lower(), component=component,
                    msg=msg, **safe)

    # ------------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "events_recorded": self.events_recorded,
                "ring": list(self.ring),
                "incident_dir": self.incident_dir,
                "incidents_written": list(self.incidents_written),
                "breaker_states": dict(self.breaker_states),
                "disarmed": sorted(
                    k for k, armed in self._armed.items() if not armed
                ),
            }


def _jsonable(v):
    if isinstance(v, (str, int, float, bool, type(None), list, dict)):
        return v
    return str(v)


RECORDER = FlightRecorder()

# the WARN+ log sink is wired at import: the recorder exists for the life
# of the process, so there is nothing to unhook
ltlog.add_observer(RECORDER._on_log_record)

# the node's trace export (bn --trace-out) gets the black box's events as
# instant markers; test-local Tracer instances stay unaffected
TRACER.instants_source = RECORDER.perfetto_instants
