"""Device tree-hash engine — the second TPU workload.

BLS verification was the only device workload; once it is fast, the
survey's next hot paths — SSZ merkleization/state roots and the per-epoch
balance/reward vectors (SURVEY §2.4: the reference's `cached_tree_hash` +
hand-tuned SHA-NI assembly) — dominate by Amdahl. This package generalizes
the crypto-backend plugin boundary beyond BLS:

  engine.py         the jnp SHA-256 ladder (one schedule shared with the
                    numpy host formulation in ssz/sha256_batch.py), the
                    level-by-level `tree_hash_root` kernel with padding
                    buckets, buffer donation and mesh-aware shardings over
                    the leaf axis, dispatched through a PipelinedDispatcher
  epoch_vectors.py  vectorized epoch processing (flag/inactivity deltas,
                    effective-balance hysteresis) as device arrays, shared
                    host-numpy/device-jnp formulation, bit-exact vs the
                    pure-Python spec path
  router.py         the hybrid route policy: hashlib ladder below a size
                    threshold, device above, breaker-guarded, with
                    `tree_hash_route_total{path,reason}` mirroring
                    `bls_hybrid_route_total`

Selection: `bn --hash-backend {host,device,hybrid}` >
LIGHTHOUSE_TPU_HASH_BACKEND > "host". The host default means a node
without the flag is byte-identical to the pre-jaxhash behavior; every
device result is bit-exact against hashlib by construction (pinned in
tests/test_jaxhash.py + test_sha256_batch.py).
"""

from .router import (  # noqa: F401
    ROUTER,
    hash_backend,
    set_hash_backend,
    start_warmup,
)

__all__ = ["ROUTER", "hash_backend", "set_hash_backend", "start_warmup"]
