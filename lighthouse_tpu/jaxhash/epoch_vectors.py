"""Vectorized epoch processing: balance/reward/penalty vectors as arrays.

The altair+ epoch transition's per-validator loops (flag deltas,
inactivity penalties, effective-balance hysteresis — state_transition/
epoch.py) are embarrassingly data-parallel: every validator's delta is a
pure function of its own row plus a handful of epoch scalars. This module
expresses them ONCE over an abstract array namespace `xp` — the same
shared-schedule trick as ssz/sha256_batch.compress — so the host lane
(numpy uint64) and the device lane (jnp uint64 under a scoped
`jax.experimental.enable_x64`; jaxbls' uint32 limb kernels are untouched
by the scope) trace identical integer arithmetic, and both are pinned
bit-exact against the pure-Python spec path in tests/test_jaxhash.py.

Overflow honesty: all spec math is floor division over uint64. The worst
realistic numerators (base_reward * weight * flag_increments ~ 2^62 at
2M-validator scale; eff * inactivity_score) fit, and `altair_deltas`
CHECKS the actual bounds with Python bigints before vectorizing — a state
that would wrap falls back to the pure-Python path instead of silently
wrapping.

Routing: `altair_deltas` / `effective_balance_updates` return None unless
the jaxhash backend is device-backed (router.hash_backend() in
device/hybrid) AND the registry is at least `min_validators` — the
callers in state_transition/epoch.py then run the unchanged pure-Python
loops, so a default (host) node is byte-identical to pre-jaxhash.
"""

from __future__ import annotations

import os

import numpy as np

from ..state_transition import accessors as acc
from ..types import helpers as h
from ..types.spec import ForkName
from ..utils.logging import get_logger

DEFAULT_MIN_VALIDATORS = 1024

_log = get_logger("jaxhash.epoch")
_kernel_cache: dict = {}


def min_validators() -> int:
    raw = os.environ.get("LIGHTHOUSE_TPU_EPOCH_VEC_MIN", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return DEFAULT_MIN_VALIDATORS


def _enabled(n: int) -> bool:
    """Route the epoch vectors? Shares the tree-hash router's breaker:
    in hybrid mode a wedged device refuses O(1) here too (the router.py
    contract) instead of paying a failed jit attempt per epoch forever;
    backend "device" keeps attempting, like the hash route. A half-open
    allow claims the probe — _device_altair_deltas reports the outcome."""
    from .router import ROUTER, hash_backend

    backend = hash_backend()
    if backend not in ("device", "hybrid") or n < min_validators():
        return False
    if backend == "hybrid" and not ROUTER.allow_device():
        return False
    return True


# ------------------------------------------------- shared vector formulation


def flag_deltas_vec(xp, eff, participating, eligible, base_per_incr, incr,
                    weight, flag_incr, total_incr, leaking, is_head):
    """(rewards, penalties) uint64 vectors for ONE participation flag —
    the vector form of epoch.get_flag_index_deltas' per-validator body.
    Scalars are Python ints (they promote to the array dtype in both
    namespaces); masks are bool arrays."""
    base = (eff // incr) * base_per_incr
    zero = xp.zeros_like(eff)
    if leaking:
        rewards = zero
    else:
        rewards = xp.where(
            participating & eligible,
            base * weight * flag_incr // (total_incr * acc.WEIGHT_DENOMINATOR),
            zero,
        )
    if is_head:
        penalties = zero
    else:
        penalties = xp.where(
            eligible & ~participating, base * weight // acc.WEIGHT_DENOMINATOR,
            zero,
        )
    return rewards, penalties


def inactivity_deltas_vec(xp, eff, scores, participating_target, eligible,
                          denom):
    """Inactivity-leak penalty vector (epoch.get_inactivity_penalty_deltas;
    rewards are identically zero there)."""
    return xp.where(
        eligible & ~participating_target, eff * scores // denom,
        xp.zeros_like(eff),
    )


def effective_balance_vec(xp, balances, eff, incr, downward, upward, max_eff):
    """(changed mask, new effective balance) for the hysteresis update
    (epoch.process_effective_balance_updates, pre-electra rule)."""
    changed = (balances + downward < eff) | (eff + upward < balances)
    new = xp.minimum(balances - balances % incr, xp.full_like(balances, max_eff))
    return changed, new


# --------------------------------------------------------- state -> arrays


def _seq_array(seq, dtype, n: int) -> np.ndarray:
    """Marshal a state field to an array: chunk-wise for CowList-backed
    fields (no per-element Python iteration at the top), fromiter for
    plain lists."""
    to_numpy = getattr(seq, "to_numpy", None)
    if to_numpy is not None:
        return to_numpy(dtype)
    return np.fromiter(seq, dtype, n)


def _registry_arrays(state):
    vals = state.validators
    n = len(vals)
    eff = np.fromiter((v.effective_balance for v in vals), np.uint64, n)
    slashed = np.fromiter((bool(v.slashed) for v in vals), bool, n)
    activation = np.fromiter((v.activation_epoch for v in vals), np.uint64, n)
    exit_ep = np.fromiter((v.exit_epoch for v in vals), np.uint64, n)
    return eff, slashed, activation, exit_ep


def _active_mask(activation, exit_ep, epoch: int):
    e = np.uint64(epoch)
    return (activation <= e) & (e < exit_ep)


# ------------------------------------------------------------- device lane


def _device_epoch_kernel(n_bucket: int):
    """One jitted kernel per padded registry bucket computing all three
    flag delta pairs + the inactivity penalty vector. Built and called
    under a scoped enable_x64 (uint64 spec arithmetic); epoch scalars ride
    as traced 0-d arrays so they never fork the compile cache."""
    key = f"epoch_{n_bucket}"
    if key in _kernel_cache:
        return _kernel_cache[key]
    import jax
    import jax.numpy as jnp

    from ..utils.jaxcfg import setup_compilation_cache

    setup_compilation_cache()

    def kernel(eff, part, eligible, target_part, scores,
               base_per_incr, incr, flag_incrs, total_incr, denom, leaking):
        rewards = []
        penalties = []
        zero = jnp.zeros_like(eff)
        base = (eff // incr) * base_per_incr
        for f, weight in enumerate(acc.PARTICIPATION_FLAG_WEIGHTS):
            participating = part[f]
            rewards.append(jnp.where(
                participating & eligible & ~leaking,
                base * weight * flag_incrs[f]
                // (total_incr * acc.WEIGHT_DENOMINATOR),
                zero,
            ))
            if f == acc.TIMELY_HEAD_FLAG_INDEX:
                penalties.append(zero)
            else:
                penalties.append(jnp.where(
                    eligible & ~participating,
                    base * weight // acc.WEIGHT_DENOMINATOR, zero,
                ))
        inact = jnp.where(
            eligible & ~target_part, eff * scores // denom, zero,
        )
        return jnp.stack(rewards), jnp.stack(penalties), inact

    _kernel_cache[key] = jax.jit(kernel)
    return _kernel_cache[key]


def _pad(arr, n_bucket):
    if arr.shape[0] == n_bucket:
        return arr
    out = np.zeros((n_bucket,) + arr.shape[1:], arr.dtype)
    out[: arr.shape[0]] = arr
    return out


# ----------------------------------------------------------- public entries


def altair_deltas(state, spec, fork, eligible):
    """The four (rewards, penalties) delta sets of
    process_rewards_and_penalties_altair as plain int lists, computed
    vectorized — or None when the jaxhash backend keeps the pure-Python
    path (host backend, small registry, or a value range that would
    overflow uint64). Bit-exact with the scalar loops by construction."""
    n = len(state.validators)
    if not _enabled(n) or acc.get_current_epoch(state, spec) == 0:
        return None
    prev = acc.get_previous_epoch(state, spec)
    cur = acc.get_current_epoch(state, spec)
    eff, slashed, activation, exit_ep = _registry_arrays(state)
    part_prev = _seq_array(state.previous_epoch_participation, np.uint8, n)
    scores = _seq_array(state.inactivity_scores, np.uint64, n)
    active_cur = _active_mask(activation, exit_ep, cur)
    active_prev = _active_mask(activation, exit_ep, prev)
    eligible_mask = np.zeros(n, bool)
    eligible_mask[list(eligible)] = True

    incr = spec.effective_balance_increment
    total_active = max(incr, int(eff[active_cur].sum()))
    base_per_incr = (
        incr * spec.base_reward_factor // acc._integer_squareroot(total_active)
    )
    leaking = acc.is_in_inactivity_leak(state, spec)
    part_masks = [
        active_prev & ~slashed & ((part_prev >> f) & 1).astype(bool)
        for f in range(len(acc.PARTICIPATION_FLAG_WEIGHTS))
    ]
    flag_balances = [max(incr, int(eff[m].sum())) for m in part_masks]
    if fork == ForkName.altair:
        quotient = spec.inactivity_penalty_quotient_altair
    else:
        quotient = spec.inactivity_penalty_quotient_bellatrix
    denom = spec.inactivity_score_bias * quotient

    # overflow honesty: check the ACTUAL bounds with bigints; a state that
    # would wrap uint64 keeps the pure-Python bigint path
    max_base = (int(eff.max(initial=0)) // incr) * base_per_incr
    max_weight = max(acc.PARTICIPATION_FLAG_WEIGHTS)
    max_flag_incr = max(fb // incr for fb in flag_balances)
    if (
        max_base * max_weight * max(1, max_flag_incr) >= 2**64
        or int(eff.max(initial=0)) * int(scores.max(initial=0)) >= 2**64
        or denom >= 2**64
    ):
        return None

    total_incr = total_active // incr
    flag_incrs = [fb // incr for fb in flag_balances]
    target_part = part_masks[acc.TIMELY_TARGET_FLAG_INDEX]

    out = _device_altair_deltas(
        n, eff, part_masks, eligible_mask, target_part, scores,
        base_per_incr, incr, flag_incrs, total_incr, denom, leaking,
    )
    if out is None:
        # host-numpy lane: the same shared formulation, no device
        rew3, pen3 = [], []
        for f, weight in enumerate(acc.PARTICIPATION_FLAG_WEIGHTS):
            r, p = flag_deltas_vec(
                np, eff, part_masks[f], eligible_mask, base_per_incr, incr,
                weight, flag_incrs[f], total_incr, leaking,
                f == acc.TIMELY_HEAD_FLAG_INDEX,
            )
            rew3.append(r)
            pen3.append(p)
        inact = inactivity_deltas_vec(
            np, eff, scores, target_part, eligible_mask, denom
        )
    else:
        rew3, pen3, inact = out
    deltas = [
        (rew3[f].tolist(), pen3[f].tolist())
        for f in range(len(acc.PARTICIPATION_FLAG_WEIGHTS))
    ]
    deltas.append(([0] * n, inact.tolist()))
    return deltas


def _device_altair_deltas(n, eff, part_masks, eligible_mask, target_part,
                          scores, base_per_incr, incr, flag_incrs,
                          total_incr, denom, leaking):
    """Device leg: padded bucketed jit under scoped x64. Returns the
    (rewards(3), penalties(3), inactivity) arrays trimmed to n, or None
    on any device failure (the caller's host-numpy lane serves). Only a
    DEVICE-served computation observes jaxhash_device_seconds — the
    host-numpy fallback must not masquerade as device latency."""
    import time

    from ..observability.device_ledger import LEDGER
    from ..ssz.core import next_pow2
    from .engine import _DEVICE_SECONDS

    t0 = time.perf_counter()
    # the epoch workload has no dispatcher — it books its device time in
    # the process-wide ledger directly, as the `epoch` tenant
    interval = LEDGER.open(
        "epoch", lane="batch", bucket=None, est_cost=None
    )
    try:
        from jax.experimental import enable_x64

        nb = next_pow2(n)
        interval.bucket = nb
        interval.start()
        with enable_x64():
            kernel = _device_epoch_kernel(nb)
            part = np.stack([_pad(m, nb) for m in part_masks])
            rew, pen, inact = kernel(
                _pad(eff, nb), part, _pad(eligible_mask, nb),
                _pad(target_part, nb), _pad(scores, nb),
                np.uint64(base_per_incr), np.uint64(incr),
                np.asarray(flag_incrs, np.uint64), np.uint64(total_incr),
                np.uint64(denom), np.bool_(leaking),
            )
            rew = np.asarray(rew)[:, :n]
            pen = np.asarray(pen)[:, :n]
            inact = np.asarray(inact)[:n]
        _DEVICE_SECONDS.labels("epoch_deltas").observe(
            time.perf_counter() - t0
        )
        interval.close("ok")
        _router_record(True)
        return list(rew), list(pen), inact
    except Exception as e:  # device down/misconfigured: host lane serves
        interval.close("error")
        _log.warn("device epoch deltas failed; host vector lane serves",
                  error=f"{type(e).__name__}: {e}")
        _router_record(False)
        return None


def _router_record(ok: bool) -> None:
    """Report a device epoch attempt to the shared breaker — never raises
    (the delta math must not die on a diagnostics path)."""
    try:
        from .router import ROUTER

        ROUTER.record_device(ok)
    except Exception:
        pass


def effective_balance_updates(state, spec):
    """[(index, new_effective_balance)] for validators the hysteresis
    rule changes (epoch.process_effective_balance_updates, pre-electra) —
    or None when the pure-Python loop should run. The caller applies the
    copy_with writes so the memoized-root invalidation semantics are
    identical to the scalar path."""
    n = len(state.validators)
    if not _enabled(n):
        return None
    eff = np.fromiter(
        (v.effective_balance for v in state.validators), np.uint64, n
    )
    balances = _seq_array(state.balances, np.uint64, n)
    hysteresis_incr = spec.effective_balance_increment // spec.hysteresis_quotient
    downward = hysteresis_incr * spec.hysteresis_downward_multiplier
    upward = hysteresis_incr * spec.hysteresis_upward_multiplier
    changed, new = effective_balance_vec(
        np, balances, eff, spec.effective_balance_increment, downward,
        upward, spec.max_effective_balance,
    )
    return [(int(i), int(new[i])) for i in np.flatnonzero(changed)]
