"""The device tree-hash kernel: level-by-level SHA-256 ladders on jnp.

One compiled program per padding bucket (the jaxbls convention —
crypto/jaxbls/backend.py): the leaf count rounds up to a power of two
(`hash_bucket`, mesh-shape-keyed like `padding_bucket`), the whole ladder
of levels compiles as ONE staged jit whose input buffer is DONATED on
accelerators (each level's input is dead once its parents exist — XLA
reuses the HBM), and every dispatch rides the shared
`PipelinedDispatcher` so concurrent tree hashes double-buffer behind the
device exactly like BLS batches do.

Mesh layout (parallel/mesh.py): the leaf axis is sharded over the 1-D
`sets` axis — sibling pairs stay shard-local while the level width
exceeds the mesh, so the ladder stops at `width == mesh size` (each chip
has reduced its local subtree to one node) and the top log2(D) levels +
the virtual zero-hash depth finish on the host (~a handful of hashlib
calls). Small trees are pinned single-chip (`LIGHTHOUSE_TPU_HASH_MESH_MIN`
leaves, default 8192): below that, mesh padding and resharding would cost
more than the hash work.

The compression schedule itself is ssz/sha256_batch.compress — the ONE
definition shared with the numpy host lane, traced here over jnp uint32
lanes. Bit-exactness vs hashlib is pinned for both lanes in
tests/test_sha256_batch.py; ladder/level parity vs the host tree builder
in tests/test_jaxhash.py.
"""

from __future__ import annotations

import numpy as np

from ..ssz.core import next_pow2
from ..ssz.sha256_batch import (
    PAIR_PAD_WORDS,
    SHA256_H0,
    SHA256_K,
    bytes_from_words,
    pad_blocks,
    round_step,
    schedule_word,
    sha256_pairs,
    words_from_bytes,
)
from ..utils.metrics import REGISTRY

# ------------------------------------------------------------------ metrics
# jaxhash_* series are labeled families (scripts/lint_metrics.py enforces
# it): the dispatch family answers "which lane is hashing", the timing
# family "which op cost what", bytes "what got uploaded"

JAXHASH_DISPATCH = REGISTRY.counter_vec(
    "jaxhash_dispatch_total",
    "device tree-hash dispatches by placement lane: `sharded` over the "
    "mesh, or `single_device` (small trees are pinned single-chip; a "
    "mesh-less node is always single_device)",
    ("lane",),
)
_DEVICE_SECONDS = REGISTRY.histogram_vec(
    "jaxhash_device_seconds",
    "wall time of one device hash dispatch (submit through resolve), by "
    "op (tree_levels = the merkle ladder, epoch_deltas = the vectorized "
    "epoch stage); first dispatch at a bucket includes XLA compilation",
    ("op",),
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0),
)
_MARSHALLED = REGISTRY.counter_vec(
    "jaxhash_marshalled_bytes_total",
    "bytes packed for device upload by the tree-hash engine, by array "
    "family",
    ("array",),
)

#: smallest compile bucket (leaf axis) — below the router threshold the
#: host serves anyway, this only bounds the bucket count
MIN_LEAVES = 64

#: trees whose padded bucket is smaller than this stay single-chip even on
#: a meshed node: the mesh tax (padding to a mesh multiple + resharding)
#: exceeds the hash work of a small ladder. Env-overridable for harnesses.
DEFAULT_MESH_MIN_LEAVES = 8192

_kernel_cache: dict = {}
_dispatcher = None


def _get_dispatcher():
    """The engine's PipelinedDispatcher (lazy: pipeline resolves depth and
    donation from env/plan at construction)."""
    global _dispatcher
    if _dispatcher is None:
        from ..crypto.jaxbls.pipeline import PipelinedDispatcher

        _dispatcher = PipelinedDispatcher(workload="tree_hash")
    return _dispatcher


def mesh_min_leaves() -> int:
    import os

    raw = os.environ.get("LIGHTHOUSE_TPU_HASH_MESH_MIN", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass  # malformed env falls through to the default
    return DEFAULT_MESH_MIN_LEAVES


def _mesh_for(n_bucket: int):
    """The mesh a bucket of this width serves on: None below the
    single-chip pin threshold (and on mesh-less nodes)."""
    if n_bucket < mesh_min_leaves():
        return None
    from ..parallel import get_mesh

    return get_mesh()


def hash_bucket(n_leaves: int, mesh=None) -> int:
    """THE leaf-axis compile-bucket rounding rule: pow2 (the ladder is a
    halving tree), floored at MIN_LEAVES, rounded to a mesh multiple when
    a mesh serves the bucket (a pow2 >= the pow2 mesh size already is
    one, so this is a no-op for every realistic topology)."""
    n = max(MIN_LEAVES, next_pow2(max(1, n_leaves)))
    if mesh is None:
        return n
    from ..parallel import pad_sets

    return pad_sets(n, mesh=mesh)


def compress_rolled(state, w16, k):
    """The ROLLED device driver over the shared schedule_word/round_step
    bodies (ssz/sha256_batch.py): lax.fori_loop builds the 64-word
    message schedule, lax.scan runs the 64 rounds — per-level trace size
    drops ~20x vs the straight-line driver (a 10-level ladder's CPU
    compile fell from ~60 s to seconds), output bit-identical."""
    import jax
    import jax.numpy as jnp

    w = jnp.zeros((64,) + w16.shape[1:], jnp.uint32)
    w = jax.lax.dynamic_update_slice_in_dim(w, w16, 0, axis=0)

    def fill(t, w):
        return w.at[t].set(
            schedule_word(w[t - 16], w[t - 15], w[t - 7], w[t - 2])
        )

    w = jax.lax.fori_loop(16, 64, fill, w)

    def one_round(v, kw):
        kt, wt = kw
        return round_step(v, kt, wt), None

    v, _ = jax.lax.scan(one_round, tuple(state[i] for i in range(8)), (k, w))
    return jnp.stack(v) + state


def _make_ladder(n_bucket: int, stop: int, donate: bool, mesh):
    """Jitted level ladder for one bucket: (n_bucket, 8) uint32 digest
    words -> tuple of level word arrays (n/2, 8) ... (stop, 8). Levels
    are unrolled in the trace (their shapes halve — static per level),
    the compression inside each is rolled; the whole ladder is one
    program per bucket and intermediates never leave the device."""
    import jax
    import jax.numpy as jnp

    from ..utils.jaxcfg import setup_compilation_cache

    setup_compilation_cache()
    k = jnp.asarray(np.array(SHA256_K, np.uint32))
    h0 = jnp.asarray(np.array(SHA256_H0, np.uint32))
    pad = jnp.asarray(np.array(PAIR_PAD_WORDS, np.uint32))
    n_levels = (n_bucket // stop).bit_length() - 1

    def hash_pairs(cur):
        m2 = cur.shape[0] // 2
        w16 = jnp.concatenate([cur[0::2], cur[1::2]], axis=1).T  # (16, m2)
        state = jnp.broadcast_to(h0[:, None], (8, m2))
        state = compress_rolled(state, w16, k)
        state = compress_rolled(
            state, jnp.broadcast_to(pad[:, None], (16, m2)), k
        )
        return state.T

    def ladder(words):
        out = []
        cur = words
        for _ in range(n_levels):
            cur = hash_pairs(cur)
            out.append(cur)
        return tuple(out)

    kwargs = {}
    if donate:
        # the leaves buffer is dead once level 0 exists; levels reuse HBM
        kwargs["donate_argnums"] = (0,)
    if mesh is not None:
        from ..parallel import sets_sharding

        kwargs["in_shardings"] = (sets_sharding(mesh, 2),)
    return jax.jit(ladder, **kwargs)


def _get_ladder(n_bucket: int, mesh):
    """(jitted ladder, stop width) cached per (bucket, donation, mesh
    signature) — the jaxbls stage-cache convention: both decisions are
    baked into the jit, and harnesses flip them within one process."""
    from ..crypto.jaxbls.pipeline import donation_enabled

    donate = donation_enabled()[0]
    if mesh is None:
        stop, key = 1, f"ladder_{n_bucket}_d{int(donate)}"
    else:
        from ..parallel import mesh_shape_key
        from ..parallel.mesh import SET_AXIS

        stop = int(mesh.shape[SET_AXIS])
        if stop >= n_bucket:  # degenerate: nothing left to shard
            return _get_ladder(n_bucket, None)
        key = f"ladder_{n_bucket}_d{int(donate)}_{mesh_shape_key(mesh)}"
    if key not in _kernel_cache:
        _kernel_cache[key] = (_make_ladder(n_bucket, stop, donate, mesh), stop)
    return _kernel_cache[key]


class _LevelsHandle:
    """In-flight ladder dispatch: resolves to host word arrays. With
    `last_only` just the final device level transfers — the root-only
    path (ssz merkleize) must not pay ~2x the leaf bytes of device->host
    copies for levels it immediately discards. `first` skips the
    transfers below that level index (None placeholders keep positions);
    the final device level always materializes — the host tail hashes
    upward from it."""

    __slots__ = ("_levels", "_last_only", "_first")

    def __init__(self, levels, last_only=False, first=0):
        self._levels = levels
        self._last_only = last_only
        self._first = first

    def result(self):
        levels = self._levels
        if self._last_only:
            out = [np.asarray(levels[-1])]
        else:
            last = len(levels) - 1
            out = [
                np.asarray(lvl) if i >= self._first or i == last else None
                for i, lvl in enumerate(levels)
            ]
        self._levels = None  # drop device refs once materialized
        return out


def device_build_levels(leaves: np.ndarray, depth: int,
                        root_only: bool = False, min_level: int = 0):
    """(levels, root) for `leaves` ((n, 32) uint8, n >= 1) padded to
    2**depth — bit-identical to ssz/tree_cache._build: level d is the
    (ceil(n/2^(d+1)), 32) parent array, the list is `depth` long (virtual
    zero-hash levels included), the root is the top node. With
    `root_only=True` levels is None and only the top device level
    transfers to host (the merkleize root path). With `min_level` the
    device levels below that index skip the device->host transfer and
    come back as None (best-effort: host-tail levels above the mesh stop
    are computed regardless, they're a handful of tiny arrays) — the CoW
    spine build at 1M leaves drops ~32 MB of copies this way.

    The device computes the padded pow2 ladder (zero-chunk padding IS the
    SSZ zero-hash folding, so trimmed prefixes match the host builder
    exactly); the mesh-stop tail and the virtual depth finish on host.
    Raises on device failure — the router owns the fallback."""
    import time

    from ..parallel import put_sets, put_single
    from ..ssz.core import ZERO_HASHES

    n_real = int(leaves.shape[0])
    nb = hash_bucket(n_real)
    mesh = _mesh_for(nb)
    if mesh is not None:
        nb = hash_bucket(n_real, mesh=mesh)
    real_depth = nb.bit_length() - 1
    if depth < real_depth:
        raise ValueError(
            f"virtual depth {depth} below padded bucket depth {real_depth}"
        )
    t0 = time.perf_counter()
    ladder, stop = _get_ladder(nb, mesh)
    words = np.zeros((nb, 8), np.uint32)
    words[:n_real] = words_from_bytes(np.ascontiguousarray(leaves))
    _MARSHALLED.labels("leaves").inc(words.nbytes)
    JAXHASH_DISPATCH.labels(
        "sharded" if mesh is not None else "single_device"
    ).inc()
    put = put_single if mesh is None else (lambda a: put_sets(a, mesh=mesh))
    placed = put(words)

    dev_levels = _get_dispatcher().submit(
        lambda: _LevelsHandle(ladder(placed), last_only=root_only,
                              first=min_level)
    ).result()

    import hashlib

    if root_only:
        full = bytes_from_words(dev_levels[0])  # the stop-width level
        while full.shape[0] > 1:
            full = sha256_pairs(full[0::2], full[1::2])
        node = full[0].tobytes()
        for d in range(real_depth, depth):
            node = hashlib.sha256(node + ZERO_HASHES[d]).digest()
        _DEVICE_SECONDS.labels("tree_levels").observe(
            time.perf_counter() - t0
        )
        return None, node

    levels = []
    cur_w = n_real
    full = None
    for lvl_words in dev_levels:  # widths nb/2 ... stop
        cur_w = (cur_w + 1) // 2
        if lvl_words is None:  # skipped transfer (below min_level)
            levels.append(None)
            continue
        full = bytes_from_words(lvl_words)
        levels.append(full[:cur_w].copy())
    # host tail: the remaining real levels below the mesh stop width ...
    while full.shape[0] > 1:
        full = sha256_pairs(full[0::2], full[1::2])
        cur_w = (cur_w + 1) // 2
        levels.append(full[:cur_w].copy())
    # ... and the virtual zero-hash depth (1-element levels, like _build)
    node = levels[-1][0].tobytes()
    for d in range(real_depth, depth):
        node = hashlib.sha256(node + ZERO_HASHES[d]).digest()
        levels.append(np.frombuffer(node, np.uint8).reshape(1, 32).copy())
    _DEVICE_SECONDS.labels("tree_levels").observe(time.perf_counter() - t0)
    root = levels[-1][0].tobytes() if depth else leaves[0].tobytes()
    return levels, root


def warm_tree_bucket(n_leaves: int) -> float:
    """Precompile the ladder for one leaf-count bucket (dummy zero leaves
    through the full dispatch path); returns the wall seconds. The
    autotune plan's tree-hash buckets warm through here at bring-up
    (router.start_warmup) so the first real state root at a planned shape
    skips the cold compile."""
    import time

    t0 = time.time()
    nb = hash_bucket(max(1, n_leaves))
    leaves = np.zeros((min(n_leaves, nb), 32), np.uint8)
    # root_only: the compiled program is identical, and warmup must not
    # pay ~2x the leaf bytes of device->host level transfers it discards
    device_build_levels(leaves, nb.bit_length() - 1, root_only=True)
    return time.time() - t0


# ---------------------------------------------------- device sha256 (tests)


def sha256_msgs_device(msgs: np.ndarray) -> np.ndarray:
    """Device-lane analog of ssz/sha256_batch.sha256_msgs: the SAME
    shared schedule traced over jnp, for the host/device hashlib-parity
    test matrix (multi-block messages included). Not a serving path —
    the serving kernels are the bucketed ladders above."""
    import jax
    import jax.numpy as jnp

    n, length = msgs.shape
    suffix = np.frombuffer(pad_blocks(length), np.uint8)
    padded = np.concatenate(
        [msgs, np.broadcast_to(suffix, (n, suffix.shape[0]))], axis=1
    )
    words = words_from_bytes(padded)  # (n, 16*blocks)
    key = f"msgs_{words.shape[1] // 16}blk"
    if key not in _kernel_cache:
        k = jnp.asarray(np.array(SHA256_K, np.uint32))
        h0 = jnp.asarray(np.array(SHA256_H0, np.uint32))
        blocks = words.shape[1] // 16

        def digest(w):
            state = jnp.broadcast_to(h0[:, None], (8, w.shape[0]))
            for blk in range(blocks):
                state = compress_rolled(
                    state, w[:, 16 * blk : 16 * blk + 16].T, k
                )
            return state.T

        _kernel_cache[key] = jax.jit(digest)
    out_words = np.asarray(_kernel_cache[key](words))
    return bytes_from_words(out_words)
