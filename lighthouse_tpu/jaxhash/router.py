"""Hybrid tree-hash routing: hashlib below the threshold, device above.

The policy mirror of crypto/bls/hybrid.py for the second workload. Every
large merkleization (ssz/core.merkleize, ssz/tree_cache._build) asks the
router first; the decision is counted ONCE in
`tree_hash_route_total{path,reason}` by the path that finally served it —
the exact contract of `bls_hybrid_route_total`, so one dashboard reads
both workloads the same way.

Routing policy:
  - backend "host" (the default)      -> host, always (reason backend_host;
    a node without --hash-backend is byte-identical to pre-jaxhash)
  - below `min_leaves`                -> host (reason small): the hashlib
    SHA-NI ladder beats any device round trip on small trees
  - breaker OPEN (backend "hybrid")   -> host, O(1) refusal (reason
    circuit_open). The breaker trips on consecutive device failures;
    recovery is half-open probe-driven (lighthouse_tpu/qos/breaker.py),
    state exported as `tree_hash_circuit_state`. Backend "device" skips
    the open-circuit refusal (an operator pinning the device path wants
    every attempt) but still records outcomes.
  - device dispatch raises            -> host answers (reason
    device_error), failure recorded.
  - otherwise                         -> device (reason ok).
"""

from __future__ import annotations

import os
import threading

from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY

HASH_BACKENDS = ("host", "device", "hybrid")
DEFAULT_MIN_LEAVES = 1024

_ROUTE = REGISTRY.counter_vec(
    "tree_hash_route_total",
    "large-tree merkleizations by the path that served them and the "
    "routing reason (the tree-hash analog of bls_hybrid_route_total)",
    ("path", "reason"),
)
_CIRCUIT_STATE = REGISTRY.gauge(
    "tree_hash_circuit_state",
    "tree-hash device-path circuit breaker state (0=closed, 1=open, "
    "2=half_open); DEPRECATED alias of circuit_state{workload=\"tree_hash\"}",
)

_state = {"backend": None}


def hash_backend() -> str:
    """The active hash backend: explicit set_hash_backend >
    LIGHTHOUSE_TPU_HASH_BACKEND > "host"."""
    if _state["backend"] is not None:
        return _state["backend"]
    env = os.environ.get("LIGHTHOUSE_TPU_HASH_BACKEND", "").strip().lower()
    return env if env in HASH_BACKENDS else "host"


def set_hash_backend(name: str | None) -> None:
    """Pin the hash backend for this process (bn --hash-backend; None
    reverts to env/default resolution)."""
    if name is not None and name not in HASH_BACKENDS:
        raise ValueError(
            f"unknown hash backend {name!r} (have: {', '.join(HASH_BACKENDS)})"
        )
    _state["backend"] = name


class TreeHashRouter:
    """One process-wide instance (ROUTER below) owns the breaker and the
    threshold; tests construct private ones."""

    def __init__(self, min_leaves: int | None = None):
        if min_leaves is None:
            raw = os.environ.get("LIGHTHOUSE_TPU_HASH_MIN_LEAVES", "").strip()
            try:
                min_leaves = int(raw) if raw else DEFAULT_MIN_LEAVES
            except ValueError:
                min_leaves = DEFAULT_MIN_LEAVES
        self.min_leaves = max(2, int(min_leaves))
        self._log = get_logger("jaxhash.router")
        from ..qos.breaker import CircuitBreaker

        self._breaker = CircuitBreaker(
            "tree_hash_device", failure_threshold=3,
            state_gauge=_CIRCUIT_STATE, workload="tree_hash",
        )

    # ------------------------------------------------------------- routing

    def allow_device(self) -> bool:
        """Breaker admission for OTHER device consumers sharing this
        device (the epoch-vector stage): open = refuse O(1); a half-open
        True claims the probe slot, so the caller MUST report the attempt
        via record_device."""
        return self._breaker.allow()

    def record_device(self, ok: bool) -> None:
        (self._breaker.record_success if ok
         else self._breaker.record_failure)()

    def _route(self, n_leaves: int) -> tuple[str, str]:
        backend = hash_backend()
        if backend == "host":
            return "host", "backend_host"
        if n_leaves < self.min_leaves:
            return "host", "small"
        if backend == "hybrid" and not self._breaker.allow():
            return "host", "circuit_open"
        return "device", "ok"

    def maybe_build_levels(self, leaves, depth: int, n_leaves: int | None = None,
                           root_only: bool = False, min_level: int = 0):
        """(levels, root) exactly as ssz/tree_cache._build would return,
        via the device — or None when the host path should serve (the
        caller runs its unchanged hashlib ladder). Never raises. `leaves`
        may be a zero-arg callable producing the (n, 32) uint8 array (with
        `n_leaves` given), so a host-routed call never pays the marshal;
        `root_only` skips the per-level device->host transfers (levels
        comes back None); `min_level` lets a caller that retains only the
        upper levels (the CoW spine) skip the device->host transfers of
        everything below it — those entries come back None."""
        n = int(n_leaves if n_leaves is not None else leaves.shape[0])
        path, reason = self._route(n)
        if path == "host":
            _ROUTE.labels("host", reason).inc()
            return None
        if callable(leaves):
            leaves = leaves()
        from . import engine

        try:
            # min_level only when asked: the 2-kwarg call shape is the
            # stable seam tests/monkeypatched engines rely on
            kw = {"min_level": min_level} if min_level else {}
            result = engine.device_build_levels(leaves, depth,
                                                root_only=root_only, **kw)
        except Exception as e:
            self._breaker.record_failure()
            self._log.warn(
                "device tree hash failed; host ladder serves",
                n_leaves=n, error=f"{type(e).__name__}: {e}",
            )
            _ROUTE.labels("host", "device_error").inc()
            return None
        self._breaker.record_success()
        _ROUTE.labels("device", "ok").inc()
        return result

    def prefer_full_build(self, n_leaves: int, n_dirty_leaves: int) -> bool:
        """The CoW incremental-vs-rebuild decision: per-chunk host rehash
        wins while the dirty fraction is small; past it a full ladder is
        cheaper — and when the full ladder would be served by the DEVICE
        the crossover drops (the rebuild amortizes over the mesh while
        the dirty-path rehash is always host-serial)."""
        path, _ = self._route(n_leaves)
        if path == "device":
            return n_dirty_leaves * 4 >= n_leaves
        return n_dirty_leaves > max(64, n_leaves // 8)

    def maybe_tree_root(self, leaves, depth: int, n_leaves: int | None = None):
        """Root-only entry for ssz/core.merkleize: bytes, or None for the
        host ladder. Only the top device level transfers to host."""
        routed = self.maybe_build_levels(leaves, depth, n_leaves=n_leaves,
                                         root_only=True)
        return None if routed is None else routed[1]


ROUTER = TreeHashRouter()


def route_totals() -> dict:
    """{"path/reason": count} snapshot of tree_hash_route_total — the
    loadgen state_root scenario reports the per-run delta."""
    return {
        "/".join(str(v) for v in key): child.value
        for key, child in _ROUTE.children()
    }


# ------------------------------------------------------------------ warmup


def start_warmup(buckets=None) -> threading.Thread:
    """Precompile the plan's tree-hash buckets in a daemon thread (node
    bring-up when --hash-backend is device/hybrid): the autotune r9
    profile carries `tree_hash_buckets`; without one the default warms
    the validator-registry scale the state root hits first. Any failure
    degrades to cold-compile-on-first-root, never a crashed node."""
    log = get_logger("jaxhash.warmup")
    if buckets is None:
        plan = None
        try:
            from ..autotune import runtime

            plan = runtime.active_plan()
        except Exception:
            pass
        buckets = tuple(getattr(plan, "tree_hash_warmup", ()) or ()) or (16384,)

    def run():
        from . import engine

        for n_leaves in buckets:
            try:
                secs = engine.warm_tree_bucket(int(n_leaves))
                log.info("tree-hash bucket warmed", n_leaves=int(n_leaves),
                         secs=round(secs, 1))
            except Exception as e:
                log.warn("tree-hash bucket warm failed",
                         n_leaves=int(n_leaves),
                         error=f"{type(e).__name__}: {e}")

    t = threading.Thread(target=run, daemon=True, name="jaxhash-warmup")
    t.start()
    return t
