"""Chain-facing execution-layer bridge.

This is the circuit the reference runs between consensus and execution:
  - block import calls `engine_newPayload` and maps the verdict onto the
    fork-choice execution status (optimistic / valid / invalid)
    (/root/reference/beacon_node/beacon_chain/src/execution_payload.rs:113,
     /root/reference/beacon_node/execution_layer/src/lib.rs:807)
  - head updates send `engine_forkchoiceUpdated`
    (canonical_head.rs fcU-on-head-change)
  - block production requests payload attributes via fcU and collects the
    built payload (+ deneb blobs bundle) with `engine_getPayload`
    (execution_layer/src/lib.rs get_payload flow)

The engine handle is duck-typed: `EngineApiClient` (JSON-RPC + JWT over
HTTP) and `MockExecutionLayer` (in-process double) both fit. All JSON
conversions live here so the engine side stays a plain transport.
"""

from __future__ import annotations

from ..execution.engine_api import PayloadStatus


# ------------------------------------------------------ JSON conversions
# Engine-API wire format: camelCase keys, 0x-hex QUANTITY for integers,
# 0x-hex DATA for byte strings (engine_api/json_structures.rs analog).


def _hexb(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _hexq(n: int) -> str:
    return hex(int(n))


def _unb(s: str) -> bytes:
    return bytes.fromhex(s[2:]) if s else b""


def _unq(s) -> int:
    if isinstance(s, int):
        return s
    return int(s, 16)


def withdrawal_to_json(w) -> dict:
    return {
        "index": _hexq(w.index),
        "validatorIndex": _hexq(w.validator_index),
        "address": _hexb(w.address),
        "amount": _hexq(w.amount),
    }


def withdrawal_from_json(types, d: dict):
    return types.Withdrawal.make(
        index=_unq(d["index"]),
        validator_index=_unq(d["validatorIndex"]),
        address=_unb(d["address"]),
        amount=_unq(d["amount"]),
    )


def payload_to_json(payload) -> dict:
    """SSZ ExecutionPayload container -> engine-API JSON (fork-agnostic:
    fields absent from the container are simply not emitted)."""
    out = {
        "parentHash": _hexb(payload.parent_hash),
        "feeRecipient": _hexb(payload.fee_recipient),
        "stateRoot": _hexb(payload.state_root),
        "receiptsRoot": _hexb(payload.receipts_root),
        "logsBloom": _hexb(payload.logs_bloom),
        "prevRandao": _hexb(payload.prev_randao),
        "blockNumber": _hexq(payload.block_number),
        "gasLimit": _hexq(payload.gas_limit),
        "gasUsed": _hexq(payload.gas_used),
        "timestamp": _hexq(payload.timestamp),
        "extraData": _hexb(payload.extra_data),
        "baseFeePerGas": _hexq(payload.base_fee_per_gas),
        "blockHash": _hexb(payload.block_hash),
        "transactions": [_hexb(t) for t in payload.transactions],
    }
    if hasattr(payload, "withdrawals"):
        out["withdrawals"] = [withdrawal_to_json(w) for w in payload.withdrawals]
    if hasattr(payload, "blob_gas_used"):
        out["blobGasUsed"] = _hexq(payload.blob_gas_used)
        out["excessBlobGas"] = _hexq(payload.excess_blob_gas)
    return out


def payload_from_json(types, d: dict):
    """Engine-API JSON -> SSZ ExecutionPayload for the active fork's types.
    Missing optional fields default (tolerates minimal test doubles)."""
    kw = dict(
        parent_hash=_unb(d["parentHash"]),
        fee_recipient=_unb(d.get("feeRecipient", "0x" + "00" * 20)),
        state_root=_unb(d.get("stateRoot", "0x" + "00" * 32)),
        receipts_root=_unb(d.get("receiptsRoot", "0x" + "00" * 32)),
        logs_bloom=_unb(d.get("logsBloom", "0x" + "00" * 256)),
        prev_randao=_unb(d.get("prevRandao", "0x" + "00" * 32)),
        block_number=_unq(d.get("blockNumber", 0)),
        gas_limit=_unq(d.get("gasLimit", 0)),
        gas_used=_unq(d.get("gasUsed", 0)),
        timestamp=_unq(d.get("timestamp", 0)),
        extra_data=_unb(d.get("extraData", "0x")),
        base_fee_per_gas=_unq(d.get("baseFeePerGas", 0)),
        block_hash=_unb(d["blockHash"]),
        transactions=[_unb(t) for t in d.get("transactions", [])],
    )
    field_names = {f.name for f in types.ExecutionPayload.fields}
    if "withdrawals" in field_names:
        kw["withdrawals"] = [
            withdrawal_from_json(types, w) for w in d.get("withdrawals", [])
        ]
    if "blob_gas_used" in field_names:
        kw["blob_gas_used"] = _unq(d.get("blobGasUsed", 0))
        kw["excess_blob_gas"] = _unq(d.get("excessBlobGas", 0))
    return types.ExecutionPayload.make(**kw)


# ------------------------------------------------------------- the bridge


class ExecutionLayer:
    """Holds the engine handle + chain-side policy (execution_layer/src/lib.rs
    trimmed to the consensus-facing surface)."""

    def __init__(self, engine, spec, default_fee_recipient: bytes = b"\x00" * 20,
                 verify_block_hashes: bool = False):
        self.engine = engine
        self.spec = spec
        self.default_fee_recipient = default_fee_recipient
        # cross-check payload.block_hash == keccak(rlp(header)) on import
        # (block_hash.rs); OFF for test doubles whose hashes are synthetic
        self.verify_block_hashes = verify_block_hashes
        # metrics-ish counters
        self.new_payloads = 0
        self.forkchoice_updates = 0
        self.payloads_built = 0

    # ---- import side (execution_payload.rs notify_new_payload)

    def notify_new_payload(self, payload, parent_beacon_block_root=None,
                           kzg_commitments=()) -> str:
        """Submit an imported block's payload; returns the engine verdict
        (VALID / INVALID / SYNCING / ACCEPTED). When enabled, the payload's
        claimed block_hash is first re-derived locally — a wrong hash is
        INVALID without consulting the engine (block_hash.rs).
        `kzg_commitments` (the block body's) become the V3 call's expected
        blob versioned hashes (sha256(commitment) with a 0x01 version
        byte)."""
        if self.verify_block_hashes:
            from ..execution.block_hash import verify_payload_block_hash

            if not verify_payload_block_hash(payload, parent_beacon_block_root):
                return PayloadStatus.invalid.value
        self.new_payloads += 1
        import hashlib

        versioned = [
            b"\x01" + hashlib.sha256(bytes(c)).digest()[1:]
            for c in kzg_commitments
        ]
        res = self.engine.new_payload(
            payload_to_json(payload),
            versioned_hashes=versioned,
            parent_beacon_block_root=parent_beacon_block_root,
        )
        return res.get("status", PayloadStatus.syncing.value)

    # ---- head side (canonical_head.rs fcU)

    def notify_forkchoice_updated(
        self, head_hash: bytes, safe_hash: bytes, finalized_hash: bytes, attrs=None
    ) -> dict:
        self.forkchoice_updates += 1
        return self.engine.forkchoice_updated(head_hash, safe_hash, finalized_hash, attrs)

    # ---- production side (get_payload flow)

    def produce_payload(
        self,
        types,
        head_payload_hash: bytes,
        safe_hash: bytes,
        finalized_hash: bytes,
        timestamp: int,
        prev_randao: bytes,
        fee_recipient: bytes | None = None,
        withdrawals=None,
        parent_beacon_block_root: bytes | None = None,
    ):
        """fcU-with-attributes + getPayload. Returns (ExecutionPayload,
        blobs_bundle | None) where blobs_bundle = (blobs, commitments,
        proofs) as raw bytes."""
        attrs = {
            "timestamp": _hexq(timestamp),
            "prevRandao": _hexb(prev_randao),
            "suggestedFeeRecipient": _hexb(fee_recipient or self.default_fee_recipient),
        }
        if withdrawals is not None:
            attrs["withdrawals"] = [withdrawal_to_json(w) for w in withdrawals]
        if parent_beacon_block_root is not None:
            # PayloadAttributesV3 (deneb+): required or the fcU is rejected
            attrs["parentBeaconBlockRoot"] = _hexb(parent_beacon_block_root)
        res = self.notify_forkchoice_updated(
            head_payload_hash, safe_hash, finalized_hash, attrs
        )
        status = res.get("payloadStatus", {}).get("status")
        payload_id = res.get("payloadId")
        if payload_id is None:
            raise RuntimeError(f"engine did not start a payload build: {status}")
        out = self.engine.get_payload(payload_id)
        self.payloads_built += 1
        payload = payload_from_json(types, out["executionPayload"])
        bundle = None
        raw = out.get("blobsBundle")
        if raw is not None:
            bundle = (
                [b if isinstance(b, bytes) else _unb(b) for b in raw["blobs"]],
                [c if isinstance(c, bytes) else _unb(c) for c in raw["commitments"]],
                [p if isinstance(p, bytes) else _unb(p) for p in raw["proofs"]],
            )
        return payload, bundle
