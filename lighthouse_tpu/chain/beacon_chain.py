"""BeaconChain — chain orchestration: verification pipelines, import, head.

Parity surface (trimmed to the load-bearing paths of
/root/reference/beacon_node/beacon_chain/src/):
  - gossip block verification (block_verification.rs GossipVerifiedBlock
    :639 -> SignatureVerifiedBlock :648): slot/parent/dedup checks, cheap
    proposer-signature check, then full batch verification on import
  - process_block / import_block (beacon_chain.rs:3035,:3362): state
    transition with VERIFY_BULK (one TPU batch per block), store writes,
    fork-choice on_block, head recompute (canonical_head.rs:473)
  - attestation verification, single and batched
    (attestation_verification.rs + batch.rs): committee resolution via the
    shuffling cache, observed-dedup, batched BLS verify, fork-choice votes
  - caches: ValidatorPubkeyCache (device feed), ShufflingCache,
    BeaconProposerCache, observed_* gossip dedup sets
  - chain-segment processing with ONE signature batch for the whole
    segment (block_verification.rs:568 signature_verify_chain_segment)
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import bls
from ..fork_choice.fork_choice import ForkChoice
from ..state_transition import accessors as acc
from ..state_transition import signature_sets as sigs
from ..state_transition.block import (
    BlockProcessingError,
    SignatureBatch,
    SignatureStrategy,
    per_block_processing,
)
from ..state_transition.slot import process_slots, types_for_slot
from ..store.hot_cold import HotColdDB
from ..types.state_util import clone_state
from ..types import helpers as h
from ..types.spec import ChainSpec, DOMAIN_BEACON_ATTESTER
from ..utils.slot_clock import SlotClock
from .pubkey_cache import ValidatorPubkeyCache

# Validator-monitor attribution failures survived in place (the block is
# already imported; monitoring must never fail it): previously bare
# `except Exception: continue` — now each skipped attestation is a
# counted, logged event (the node_gossip_errors_total treatment).
from ..utils.metrics import REGISTRY as _REGISTRY

_MONITOR_ERRORS = _REGISTRY.counter_vec(
    "beacon_chain_monitor_errors_total",
    "validator-monitor block-import attribution failures survived "
    "(the attestation is skipped, the import stands), by stage",
    ("stage",),
)


class BlockError(Exception):
    """Block rejected (block_verification.rs BlockError analog)."""


class AttestationError(Exception):
    """Attestation rejected (attestation_verification.rs Error analog)."""


@dataclass
class ChainConfig:
    reorg_threshold_percent: int = 20
    import_max_skip_slots: int | None = None
    # background-migrator cadence: advance the hot/cold split once
    # finalization has moved this many epochs past it (migrate.rs /
    # --epochs-per-migration); 0 disables live migration
    epochs_per_migration: int = 1
    # slasher retention horizon in epochs (--slasher-history-length)
    slasher_history_epochs: int = 4096


class ShufflingCache:
    """(epoch, decision_root) -> CommitteeCache (shuffling_cache.rs)."""

    def __init__(self, capacity: int = 16):
        self.capacity = capacity
        self._map: dict[tuple[int, bytes], object] = {}

    def get_or_build(self, state, spec, epoch: int, decision_root: bytes):
        key = (epoch, decision_root)
        got = self._map.get(key)
        if got is None:
            got = acc.build_committee_cache(state, spec, epoch)
            if len(self._map) >= self.capacity:
                self._map.pop(next(iter(self._map)))
            self._map[key] = got
        return got


class BeaconChain:
    def __init__(
        self,
        spec: ChainSpec,
        genesis_state,
        store: HotColdDB | None = None,
        slot_clock: SlotClock | None = None,
        config: ChainConfig | None = None,
        kzg_setup=None,
        anchor_block=None,
        execution_layer=None,
    ):
        """genesis_state doubles as the ANCHOR state: pass a finalized
        checkpoint state (+ its anchor_block) to start from a weak-
        subjectivity checkpoint instead of genesis
        (client/src/builder.rs:366-528 weak_subjectivity_state analog)."""
        from ..utils.slot_clock import ManualSlotClock

        self.spec = spec
        self.config = config or ChainConfig()
        self.store = store or HotColdDB(spec)
        self.slot_clock = slot_clock or ManualSlotClock(
            genesis_state.genesis_time, spec.seconds_per_slot
        )
        self.genesis_validators_root = bytes(genesis_state.genesis_validators_root)

        types = types_for_slot(spec, genesis_state.slot)
        state_root = types.BeaconState.hash_tree_root(genesis_state)
        if anchor_block is not None:
            # checkpoint start: the supplied block must commit to the state
            if bytes(anchor_block.message.state_root) != state_root:
                raise BlockError("anchor block/state mismatch")
            self.genesis_block_root = types.BeaconBlock.hash_tree_root(
                anchor_block.message
            )
            self.store.put_block(self.genesis_block_root, anchor_block, types)
        else:
            # The anchor block root must match what descendants reference:
            # hash of the state's latest_block_header with its state_root
            # filled (the header's body_root may predate fork upgrades, so
            # we must not rebuild the body ourselves).
            header = genesis_state.latest_block_header
            if bytes(header.state_root) == b"\x00" * 32:
                header = header.copy_with(state_root=state_root)
            self.genesis_block_root = types.BeaconBlockHeader.hash_tree_root(header)
            genesis_block = types.BeaconBlock.make(
                slot=genesis_state.slot,
                proposer_index=header.proposer_index,
                parent_root=header.parent_root,
                state_root=header.state_root,
                body=types.BeaconBlockBody.default(),
            )
            signed_genesis = types.SignedBeaconBlock.make(
                message=genesis_block, signature=b"\x00" * 96
            )
            self.store.put_block(self.genesis_block_root, signed_genesis, types)
        self.anchor_slot = int(genesis_state.slot)
        self.oldest_block_slot = self.anchor_slot  # backfill progress marker
        self._oldest_block_root = self.genesis_block_root
        self.store.put_state(state_root, genesis_state, types)

        self.fork_choice = ForkChoice(
            spec, self.genesis_block_root, genesis_state.slot, genesis_state
        )
        # head states kept in memory: bounded LRU with build promises
        from .caches import (
            AttesterCache,
            BlockTimesCache,
            EarlyAttesterCache,
            ObservedSlashable,
            StateLRU,
        )

        self.state_cache = StateLRU(capacity=32)
        self._advanced: dict = {}   # state-advance timer output (head -> next-slot state)
        self.state_cache[state_root] = genesis_state
        self.block_times = BlockTimesCache()
        self.attester_cache = AttesterCache()
        self.early_attester_cache = EarlyAttesterCache()
        self.observed_slashable = ObservedSlashable()
        self.slasher = None           # optional slasher feed (set by the node)
        self.block_slots: dict[bytes, int] = {self.genesis_block_root: genesis_state.slot}
        self.state_root_by_block: dict[bytes, bytes] = {
            self.genesis_block_root: state_root
        }
        self.head_root = self.genesis_block_root

        self.pubkey_cache = ValidatorPubkeyCache(self.store)
        self.pubkey_cache.import_new_pubkeys(genesis_state)
        self.shuffling_cache = ShufflingCache()
        self.proposer_cache: dict[tuple[int, bytes], list[int]] = {}

        from .validator_monitor import ValidatorMonitor

        # per-validator performance tracking (validator_monitor.rs): driven
        # from the import path + epoch rollover below; inert until a
        # validator is registered (CLI --monitor-validators / API)
        self.monitor = ValidatorMonitor(spec)
        self._monitor_epoch: int | None = None
        self._monitor_sync_indices: tuple[int, list[int]] | None = None

        # observed-* gossip dedup (observed_attesters.rs etc.)
        self.observed_block_producers: set[tuple[int, int]] = set()
        self.observed_attesters: set[tuple[int, int]] = set()          # (epoch, validator)
        self.observed_aggregators: set[tuple[int, int]] = set()
        self.observed_blocks: set[bytes] = set()
        self.observed_blob_sidecars: set[tuple[bytes, int]] = set()    # (root, index)

        from .data_availability import DataAvailabilityChecker
        from .naive_aggregation import NaiveAttestationPool, NaiveSyncContributionPool

        self.data_availability = DataAvailabilityChecker(
            spec, kzg_setup, store=self.store
        )
        self.naive_attestation_pool = NaiveAttestationPool(spec)
        self.naive_sync_pool = NaiveSyncContributionPool(spec)
        # validator_index -> fee recipient, fed by prepare_beacon_proposer
        self.proposer_preparations: dict[int, bytes] = {}
        # eth1 deposit/block cache feeding production (eth1_chain.rs); set
        # by the node when an eth1 endpoint is configured
        self.eth1_cache = None

        # ---- execution layer circuit (execution_payload.rs analog)
        self.execution_layer = execution_layer
        # block root -> execution block hash of its chain (inherited through
        # pre-merge/empty payloads) — feeds forkchoiceUpdated + getPayload
        genesis_payload_hash = b"\x00" * 32
        hdr = getattr(genesis_state, "latest_execution_payload_header", None)
        if hdr is not None:
            genesis_payload_hash = bytes(hdr.block_hash)
        self.payload_hash_by_block: dict[bytes, bytes] = {
            self.genesis_block_root: genesis_payload_hash
        }
        self._el_last_head_sent: bytes | None = None
        # blobs bundles from locally-built payloads, keyed by their
        # commitment list: served back when the signed block is published
        self._produced_bundles: dict[tuple, tuple] = {}

    # ------------------------------------------------- checkpoint / resume

    @classmethod
    def from_checkpoint(cls, spec, anchor_state, anchor_block, **kw):
        """Start from a trusted finalized state/block pair (checkpoint sync;
        required-by-default startup mode in the reference since v4.6.0)."""
        return cls(spec, anchor_state, anchor_block=anchor_block, **kw)

    def import_historical_blocks(self, blocks) -> int:
        """Backfill: import a contiguous ascending run of blocks ENDING at
        the current oldest block's parent, with hash-linkage checks and ONE
        batched proposer-signature verification for the whole run
        (historical_blocks.rs:189 ParallelSignatureSets analog — a flagship
        TPU batch workload). Returns blocks accepted."""
        if not blocks:
            return 0
        spec = self.spec
        oldest = self.store.get_block(
            self._oldest_block_root, types_for_slot(spec, self.oldest_block_slot)
        )
        expected_root = bytes(oldest.message.parent_root)
        get_pubkey = self.pubkey_cache.pubkey_getter()
        batch = SignatureBatch()
        roots = []
        for sb in reversed(blocks):          # newest -> oldest linkage walk
            types = types_for_slot(spec, sb.message.slot)
            root = types.BeaconBlock.hash_tree_root(sb.message)
            if root != expected_root:
                raise BlockError("backfill chain discontinuity")
            roots.append((root, sb, types))
            expected_root = bytes(sb.message.parent_root)
            if sb.message.slot > 0:
                batch.add(
                    sigs.historical_block_proposal_set(
                        spec, types, sb, self.genesis_validators_root, get_pubkey
                    )
                )
        if not batch.verify():
            raise BlockError("backfill signature batch invalid")
        for root, sb, types in roots:
            self.store.put_block(root, sb, types)
            self.block_slots[root] = int(sb.message.slot)
        # roots[-1] is blocks[0] (the oldest) — the linkage walk went newest
        # to oldest, so its root is already computed
        self.oldest_block_slot = int(blocks[0].message.slot)
        self._oldest_block_root = roots[-1][0]
        return len(blocks)

    PERSIST_HEAD_KEY = b"persisted-head"

    def persist(self) -> None:
        """Persist the minimal resume set: head root + anchor info + op-pool-
        independent indices. States/blocks are already durably in the store;
        resume() rebuilds fork choice by replaying stored blocks from the
        finalized anchor (builder.rs resume path)."""
        import pickle

        fin_epoch, fin_root = self.fork_choice.store.finalized_checkpoint
        payload = {
            "head_root": self.head_root,
            "finalized_root": fin_root,
            "finalized_epoch": fin_epoch,
            "anchor_root": self.genesis_block_root,
            "oldest_block_slot": self.oldest_block_slot,
            "oldest_block_root": self._oldest_block_root,
            "block_slots": self.block_slots,
            "state_root_by_block": self.state_root_by_block,
        }
        self.store.put_chain_item(self.PERSIST_HEAD_KEY, pickle.dumps(payload))
        # durability barrier: a persist that only reached the page cache is
        # not a persist (store flush applies the engine's fsync policy)
        self.store.flush()

    @classmethod
    def resume(cls, spec, store, **kw):
        """Rebuild a chain from a persisted store: load the finalized anchor
        state, replay stored descendant blocks into fork choice, restore the
        head (beacon_chain/src/builder.rs resume analog)."""
        import pickle

        raw = store.get_chain_item(cls.PERSIST_HEAD_KEY)
        if raw is None:
            raise BlockError("no persisted chain in store")
        try:
            meta = pickle.loads(raw)
        except Exception as e:  # noqa: BLE001 — torn/corrupt persist record
            raise BlockError(f"persisted chain record unreadable: {e}") from e
        # anchor: highest stored block at/below finalization whose state we
        # still have — walk back from head via parents
        block_slots = meta["block_slots"]
        state_by_block = meta["state_root_by_block"]

        # find the finalized anchor block+state
        fin_root = meta["finalized_root"]
        if fin_root == b"\x00" * 32 or fin_root not in block_slots:
            fin_root = meta["anchor_root"]
        fin_slot = block_slots.get(fin_root)
        fin_state_root = state_by_block.get(fin_root)
        if fin_slot is None or fin_state_root is None:
            raise BlockError("persisted anchor unknown to the chain indices")
        types = types_for_slot(spec, fin_slot)
        anchor_block = store.get_block(fin_root, types)
        anchor_state = store.get_state(fin_state_root, types)
        if anchor_state is None or anchor_block is None:
            raise BlockError("persisted anchor incomplete")

        chain = cls(spec, anchor_state, store=store, anchor_block=anchor_block, **kw)
        chain.oldest_block_slot = meta["oldest_block_slot"]
        chain._oldest_block_root = meta["oldest_block_root"]
        chain.block_slots.update(block_slots)

        # replay the post-anchor chain into fork choice (ascending slots)
        replay = [
            (slot, root)
            for root, slot in block_slots.items()
            if slot > fin_slot and root in state_by_block
        ]
        for slot, root in sorted(replay):
            t = types_for_slot(spec, slot)
            sb = store.get_block(root, t)
            st = store.get_state(state_by_block[root], t)
            if sb is None or st is None:
                continue
            chain.slot_clock.set_slot(max(chain.current_slot, slot))
            chain.fork_choice.on_tick(chain.current_slot)
            chain.fork_choice.on_block(sb, root, st)
            chain.state_cache[state_by_block[root]] = st
            chain.state_root_by_block[root] = state_by_block[root]
            chain.pubkey_cache.import_new_pubkeys(st)
        chain._persisted_head = meta["head_root"]
        chain.recompute_head()
        return chain

    @classmethod
    def from_store(cls, spec, store, **kw):
        """Restart path over an existing datadir: `resume()` with corrupt-
        head recovery made explicit. A persisted head whose block or state
        the store no longer has (crash between fork-choice update and state
        write) is simply absent from the replay, so fork choice lands on
        the best surviving block — the fork_revert.rs outcome without a
        separate revert pass. Raises BlockError when the persist record
        itself is missing/unreadable or the finalized anchor is gone; the
        caller (cli.cmd_bn) then falls back to its configured start anchor."""
        from ..utils.logging import get_logger

        log = get_logger("chain")
        chain = cls.resume(spec, store, **kw)
        persisted = getattr(chain, "_persisted_head", None)
        if persisted is not None and chain.head_root != persisted:
            log.warn(
                "persisted head unavailable after crash; recovered to the "
                "best surviving block",
                persisted=persisted.hex()[:8],
                recovered=chain.head_root.hex()[:8],
            )
        else:
            log.info(
                "chain resumed from persisted head",
                head=chain.head_root.hex()[:8],
                slot=chain.block_slots.get(chain.head_root),
            )
        return chain

    def revert_to_fork_boundary(self, bad_root: bytes):
        """Corrupt-head recovery (fork_revert.rs): rebuild fork choice from
        the finalized anchor, replaying every stored block EXCEPT the bad
        block and its descendants. Returns the new head root."""
        fin_epoch, fin_root = self.fork_choice.store.finalized_checkpoint
        if fin_root == b"\x00" * 32 or fin_root not in self.block_slots:
            fin_root = self.genesis_block_root
        fin_slot = self.block_slots[fin_root]
        types = types_for_slot(self.spec, fin_slot)
        fin_state_root = self.state_root_by_block.get(fin_root)
        fin_state = (
            self.state_cache.get(fin_state_root)
            or self.store.get_state(fin_state_root, types)
            if fin_state_root
            else None
        )
        if fin_state is None:
            raise BlockError("finalized state unavailable for fork revert")
        if fin_state_root:
            self.state_cache[fin_state_root] = fin_state

        self.fork_choice = ForkChoice(self.spec, fin_root, fin_slot, fin_state)
        # replay stored descendants, skipping the bad branch
        banned = {bad_root}
        replay = sorted(
            (slot, root)
            for root, slot in self.block_slots.items()
            if slot > fin_slot
        )
        for slot, root in replay:
            t = types_for_slot(self.spec, slot)
            sb = self.store.get_block(root, t)
            if sb is None:
                continue
            if bytes(sb.message.parent_root) in banned or root in banned:
                banned.add(root)
                continue
            sroot = self.state_root_by_block.get(root)
            st = self.state_cache.get(sroot) if sroot else None
            if st is None and sroot:
                st = self.store.get_state(sroot, t)
            if st is None:
                banned.add(root)        # no state -> can't vouch for branch
                continue
            self.fork_choice.on_tick(max(self.current_slot, slot))
            self.fork_choice.on_block(sb, root, st)
        for root in banned:
            self.block_slots.pop(root, None)
            self.state_root_by_block.pop(root, None)
            self.store.delete_block(root)
        self.fork_choice.on_tick(self.current_slot)
        return self.recompute_head()

    # ---------------------------------------------------------------- time

    @property
    def current_slot(self) -> int:
        s = self.slot_clock.now()
        return s if s is not None else 0

    def per_slot_task(self) -> None:
        self.fork_choice.on_tick(self.current_slot)
        self.naive_attestation_pool.prune(self.current_slot)
        self.naive_sync_pool.prune(self.current_slot)
        if self.monitor.active:
            self._monitor_epoch_rollover()
        fin_epoch = self.fork_choice.store.finalized_checkpoint[0]
        self.observed_slashable.prune(fin_epoch, self.spec.preset.SLOTS_PER_EPOCH)
        if self.monitor.active and fin_epoch > 0:
            self.monitor.prune(fin_epoch)
        if (
            self.slasher is not None
            and hasattr(self.slasher, "prune")
            and fin_epoch > getattr(self, "_slasher_pruned_at", 0)
        ):
            self._slasher_pruned_at = fin_epoch
            self.slasher.prune(
                fin_epoch,
                self.spec.preset.SLOTS_PER_EPOCH,
                history_epochs=self.config.slasher_history_epochs,
            )
        # pending DA joins at/below finalization can never import
        self.data_availability.prune_finalized(
            fin_epoch * self.spec.preset.SLOTS_PER_EPOCH
        )
        self._maybe_migrate_finalized(fin_epoch)

    def _maybe_migrate_finalized(self, fin_epoch: int) -> None:
        """Background-migrator analog (beacon_chain/src/migrate.rs): once
        finalization has advanced `epochs_per_migration` past the store's
        hot/cold split, walk the newly finalized canonical segment (by
        parent links from the finalized block) and move it across the
        split — states drop from the hot DB, roots land in the freezer's
        chunked vectors, restore points keep full copies."""
        if self.store is None or self.config.epochs_per_migration <= 0:
            return
        spe = self.spec.preset.SLOTS_PER_EPOCH
        fin_slot = fin_epoch * spe
        split = self.store.split_slot
        if fin_slot - split < self.config.epochs_per_migration * spe:
            return
        from ..state_transition.slot import types_for_slot

        fin_root = self.fork_choice.store.finalized_checkpoint[1]
        # the split advances only to the finalized BLOCK's slot (not the
        # epoch boundary): the finalized block's own state must stay hot
        # (fork revert loads exactly it), and with a skipped boundary slot
        # that block sits below the boundary — advancing the split past an
        # unmigrated block would strand it outside every future walk and
        # punch a hole in the freezer's chunked root vectors
        fin_block_slot = self.block_slots.get(fin_root)
        if fin_block_slot is None or fin_block_slot <= split:
            return
        seg: list[tuple[int, bytes, bytes]] = []
        root = fin_root
        while root is not None:
            slot = self.block_slots.get(root)
            if slot is None or slot < split:
                break
            blk = self.store.get_block(root, types_for_slot(self.spec, slot))
            if blk is None:
                break
            if slot < fin_block_slot:
                seg.append((int(slot), root, bytes(blk.message.state_root)))
            if slot == 0:
                break
            root = bytes(blk.message.parent_root)
        if not seg:
            # empty segment still advances the split so the check above
            # does not re-walk every slot
            self.store.migrate_to_freezer(
                fin_block_slot, [], types_for_slot(self.spec, 0)
            )
            return
        seg.reverse()
        self.store.migrate_to_freezer(
            fin_block_slot, seg, types_for_slot(self.spec, seg[0][0])
        )

    # ---------------------------------------------------------------- head

    def advance_head_state(self) -> bool:
        """state_advance_timer.rs analog: during the slot TAIL, pre-compute
        the head state advanced to the next slot so block production and
        first-thing-next-slot attestation serving skip the epoch-transition
        latency. The advanced state is cached under a synthetic key that
        _state_for_block consults first."""
        next_slot = self.current_slot + 1
        head = self.head_root
        cached = self._advanced.get(head)
        if cached is not None and cached.slot >= next_slot:
            return False
        state = clone_state(self.head_state(), self.spec)
        if state.slot >= next_slot:
            return False
        process_slots(state, self.spec, next_slot)
        self._advanced = {head: state}      # only ever one entry (the head)
        return True

    def head_state(self):
        sroot = self.state_root_by_block[self.head_root]
        st = self.state_cache.get(sroot)
        if st is None:
            # evicted from the LRU (deep reorg/revert): reload from store
            types = types_for_slot(self.spec, self.block_slots[self.head_root])
            st = self.store.get_state(sroot, types)
            if st is None:
                raise BlockError("head state unavailable")
            self.state_cache[sroot] = st
        return st

    def head_block(self):
        types = types_for_slot(self.spec, self.block_slots[self.head_root])
        return self.store.get_block(self.head_root, types)

    def recompute_head(self) -> bytes:
        self.fork_choice.on_tick(self.current_slot)
        head = self.fork_choice.get_head()
        self.head_root = head
        self._notify_el_of_head(head)
        return head

    def verify_slashing_for_pool(self, slashing, kind: str) -> None:
        """Validate an externally-submitted slashing BEFORE it can reach the
        op pool: run the real state-transition processing (slashability
        checks + signature sets) against a clone of the head state. A
        garbage or spent slashing packed into a produced block would make
        the node's own blocks invalid (observed_operations.rs + the gossip
        verification the HTTP publish path must mirror). Raises
        BlockProcessingError/AttestationError on anything unincludable."""
        from ..state_transition import block as blk

        spec = self.spec
        state = clone_state(self.head_state(), spec)
        types = types_for_slot(spec, state.slot)
        fork = spec.fork_name_at_slot(state.slot)
        get_pubkey = self.pubkey_cache.pubkey_getter()
        batch = SignatureBatch()
        if kind == "attester":
            blk.process_attester_slashing(
                state, spec, types, slashing, fork, batch.add, get_pubkey
            )
        elif kind == "proposer":
            blk.process_proposer_slashing(
                state, spec, types, slashing, fork, batch.add, get_pubkey
            )
        else:
            raise ValueError(kind)
        if not batch.verify():
            raise BlockProcessingError("slashing signature invalid")

    def process_invalid_execution_payload(self, block_root: bytes) -> bytes:
        """An EL verdict (late newPayload / fcU error) invalidated an
        already-imported optimistic block: poison it and its descendants in
        fork choice and move the head off the invalid subtree
        (proto_array execution-status invalidation)."""
        self.fork_choice.proto.on_invalid_execution_payload(block_root)
        return self.recompute_head()

    def _notify_el_of_head(self, head: bytes) -> None:
        """Send engine_forkchoiceUpdated on head change (canonical_head.rs
        update_execution_engine_forkchoice analog). Skipped pre-merge (no
        execution chain to steer) and deduplicated per head root. An
        INVALID verdict on an optimistically-imported head poisons its
        subtree and moves the head off it."""
        if self.execution_layer is None or head == self._el_last_head_sent:
            return
        head_hash = self.payload_hash_by_block.get(head, b"\x00" * 32)
        if head_hash == b"\x00" * 32:
            return
        jc_root = self.fork_choice.store.justified_checkpoint[1]
        fc_root = self.fork_choice.store.finalized_checkpoint[1]
        safe_hash = self.payload_hash_by_block.get(jc_root, b"\x00" * 32)
        fin_hash = self.payload_hash_by_block.get(fc_root, b"\x00" * 32)
        try:
            res = self.execution_layer.notify_forkchoice_updated(
                head_hash, safe_hash, fin_hash
            )
        except Exception:
            # engine flakiness must not break head updates (retried on the
            # next head recompute); the health machine tracks failures
            return
        self._el_last_head_sent = head
        status = (res or {}).get("payloadStatus", {}).get("status")
        from ..execution.engine_api import PayloadStatus

        if status == PayloadStatus.invalid.value:
            # invalidation moves the head off this subtree; the recursive
            # recompute_head -> _notify_el_of_head chain terminates because
            # every step invalidates at least one block
            self.process_invalid_execution_payload(head)

    # ------------------------------------------------------------ gossip block

    def verify_block_for_gossip(self, signed_block, block_root=None):
        """Cheap structural + proposer-signature verification
        (GossipVerifiedBlock::new analog)."""
        spec = self.spec
        block = signed_block.message
        types = types_for_slot(spec, block.slot)
        if block_root is None:
            block_root = types.BeaconBlock.hash_tree_root(block)

        if block.slot > self.current_slot:
            raise BlockError(f"future block: {block.slot} > {self.current_slot}")
        if block_root in self.observed_blocks or self.store.block_exists(block_root):
            raise BlockError("block already known")
        parent_root = bytes(block.parent_root)
        if not self.store.block_exists(parent_root):
            raise BlockError("parent unknown")
        fin_epoch = self.fork_choice.store.finalized_checkpoint[0]
        fin_slot = h.compute_start_slot_at_epoch(fin_epoch, spec)
        if block.slot <= fin_slot:
            raise BlockError("block older than finalization")
        # proposer signature over a cheaply-advanced parent state — MUST
        # come before any equivocation bookkeeping, or unverifiable spam
        # could poison the observed caches against the honest proposer
        state = self._state_for_block(parent_root, block.slot)
        batch = SignatureBatch()
        try:
            batch.add(
                sigs.block_proposal_set(
                    state, spec, types, signed_block,
                    self.pubkey_cache.pubkey_getter(), block_root=block_root,
                )
            )
        except sigs.SignatureSetError as e:
            raise BlockError(f"undecodable signature: {e}") from e
        if not batch.verify():
            raise BlockError("invalid proposer signature")

        key = (block.slot, block.proposer_index)
        prior = self.observed_slashable.peek_proposal(
            int(block.proposer_index), int(block.slot), block_root
        )
        if prior is not None or key in self.observed_block_producers:
            # a VERIFIED conflicting proposal: feed the slasher both signed
            # headers (the prior one reconstructed from the store) and reject
            self._report_proposer_equivocation(signed_block, block_root, prior, types)
            raise BlockError("proposer equivocation for slot")

        self.observed_slashable.record_proposal(
            int(block.proposer_index), int(block.slot), block_root
        )
        self.observed_block_producers.add(key)
        self.observed_blocks.add(block_root)
        self.block_times.observed(block_root)
        if self.slasher is not None:
            self.slasher.accept_proposal(
                self._proposal_record(signed_block, block_root, types)
            )
        return block_root

    def _proposal_record(self, signed_block, block_root: bytes, types):
        from ..slasher.slasher import ProposalRecord

        block = signed_block.message
        hdr = types.BeaconBlockHeader.make(
            slot=block.slot,
            proposer_index=block.proposer_index,
            parent_root=block.parent_root,
            state_root=block.state_root,
            body_root=types.BeaconBlockBody.hash_tree_root(block.body),
        )
        return ProposalRecord(
            proposer_index=int(block.proposer_index),
            slot=int(block.slot),
            block_root=block_root,
            signed_header=types.SignedBeaconBlockHeader.make(
                message=hdr, signature=signed_block.signature
            ),
        )

    def _report_proposer_equivocation(self, signed_block, block_root, prior_root, types):
        if self.slasher is None:
            return
        self.slasher.accept_proposal(
            self._proposal_record(signed_block, block_root, types)
        )
        if prior_root is not None:
            prior_block = self.store.get_block(prior_root, types)
            if prior_block is not None:
                self.slasher.accept_proposal(
                    self._proposal_record(prior_block, prior_root, types)
                )

    def _state_for_block(self, parent_root: bytes, slot: int):
        """Parent post-state advanced to `slot` (cheap_state_advance).

        Consults the state-advance timer's pre-computed next-slot state
        first — the common case (a block building on the head at the next
        slot) then skips the advance entirely."""
        adv = self._advanced.get(parent_root)
        if adv is not None and adv.slot == slot:
            return clone_state(adv, self.spec)
        state_root = self.state_root_by_block.get(parent_root)
        if state_root is None or state_root not in self.state_cache:
            raise BlockError("parent state unavailable")
        state = clone_state(self.state_cache[state_root], self.spec)
        if state.slot < slot:
            process_slots(state, self.spec, slot)
        return state

    # ------------------------------------------------------------ import

    def process_block(
        self,
        signed_block,
        block_root=None,
        proposal_already_verified: bool = False,
        blobs=None,
        blobs_verified: bool = False,
    ) -> bytes:
        """Full verification + import (process_block/import_block analog).

        Deneb+ blocks carrying commitments are gated on data availability:
        sidecars either arrive via `blobs` (RPC/publish paths) or must have
        been collected by the DA checker from gossip; otherwise the block is
        held and AvailabilityPendingError raised
        (data_availability_checker.rs:40)."""
        from .data_availability import AvailabilityPendingError
        from ..types.spec import ForkName

        spec = self.spec
        block = signed_block.message
        types = types_for_slot(spec, block.slot)
        if block_root is None:
            block_root = types.BeaconBlock.hash_tree_root(block)
        parent_root = bytes(block.parent_root)
        if not self.store.block_exists(parent_root):
            raise BlockError("parent unknown")

        fork = spec.fork_name_at_slot(block.slot)
        commitments = (
            list(block.body.blob_kzg_commitments) if fork >= ForkName.deneb else []
        )
        sidecars = []
        if commitments:
            if blobs is not None:
                sidecars = list(blobs)
                if len(sidecars) != len(commitments) or any(
                    bytes(sc.kzg_commitment) != bytes(c)
                    for sc, c in zip(sidecars, commitments)
                ):
                    raise BlockError("sidecars do not match block commitments")
            else:
                got = self.data_availability.put_block(block_root, signed_block, types)
                if got is None:
                    raise AvailabilityPendingError(
                        block_root, self.data_availability.missing_indices(block_root)
                    )
                _, sidecars = got
                blobs_verified = True  # gossip-verified on arrival
            if not blobs_verified and not self.data_availability.verify_kzg_proofs(
                sidecars
            ):
                raise BlockError("blob KZG batch invalid")

        state = self._state_for_block(parent_root, block.slot)
        get_pubkey = self.pubkey_cache.pubkey_getter()

        batch = SignatureBatch()
        if not proposal_already_verified:
            batch.add(
                sigs.block_proposal_set(
                    state, spec, types, signed_block, get_pubkey, block_root=block_root
                )
            )

        # run per-block processing, accumulating the remaining signature sets
        # into the same batch, then verify EVERYTHING in one device call
        def handle(s):
            batch.add(s)

        from ..state_transition import block as blk

        try:
            blk.process_block_header(state, spec, types, block)
            fork = spec.fork_name_at_slot(block.slot)
            from ..types.spec import ForkName

            if fork >= ForkName.bellatrix:
                blk.process_withdrawals_and_payload(state, spec, types, block, fork)
            blk.process_randao(
                state, spec, types, block, SignatureStrategy.VERIFY_BULK, handle, get_pubkey
            )
            blk.process_eth1_data(state, spec, types, block.body)
            blk.process_operations(state, spec, types, block, fork, handle, get_pubkey)
            if fork >= ForkName.altair:
                blk.process_sync_aggregate(state, spec, types, block, handle, get_pubkey)
        except sigs.SignatureSetError as e:
            raise BlockError(f"undecodable signature: {e}") from e
        except BlockProcessingError as e:
            raise BlockError(str(e)) from e

        if not batch.verify():
            raise BlockError("block signature batch invalid")

        state_root = types.BeaconState.hash_tree_root(state)
        if bytes(block.state_root) != state_root:
            raise BlockError("state root mismatch")

        # Execution validity: hand the payload to the EL BEFORE import
        # (execution_payload.rs:113 notify_new_payload). INVALID rejects the
        # block and poisons its would-be subtree; SYNCING/ACCEPTED imports
        # optimistically (fork choice keeps the node optimistic until a
        # later fcU/newPayload confirms).
        el_status = None
        payload_hash = self.payload_hash_by_block.get(parent_root, b"\x00" * 32)
        if fork >= ForkName.bellatrix and hasattr(block.body, "execution_payload"):
            payload = block.body.execution_payload
            if bytes(payload.block_hash) != b"\x00" * 32:
                payload_hash = bytes(payload.block_hash)
                if self.execution_layer is not None:
                    from ..execution.engine_api import PayloadStatus

                    try:
                        el_status = self.execution_layer.notify_new_payload(
                            payload,
                            parent_beacon_block_root=parent_root,
                            kzg_commitments=getattr(
                                block.body, "blob_kzg_commitments", ()
                            ),
                        )
                    except Exception:
                        # engine unreachable: import optimistically, exactly
                        # like a SYNCING verdict (engines.rs offline state)
                        el_status = PayloadStatus.syncing.value
                    if el_status == PayloadStatus.invalid.value:
                        raise BlockError("execution payload invalid")

        # import: store + caches + fork choice
        self.store.put_block(block_root, signed_block, types)
        if sidecars:
            import struct

            parts = [types.BlobSidecar.serialize(sc) for sc in sidecars]
            self.store.put_blobs(
                block_root,
                struct.pack("<I", len(parts))
                + b"".join(struct.pack("<I", len(p)) + p for p in parts),
            )
        self.store.put_state(state_root, state, types)
        self.state_cache[state_root] = state
        self.block_slots[block_root] = block.slot
        self.state_root_by_block[block_root] = state_root
        self.pubkey_cache.import_new_pubkeys(state)

        self.payload_hash_by_block[block_root] = payload_hash

        # Timely = arrived within the attestation deadline (1/3 slot) of its
        # OWN slot — not merely "imported during its slot". A block landing
        # after attesters voted for its parent must count as late, or the
        # proposer re-org (get_proposer_head) can never fire for the
        # canonical late-block case. Manual clocks sit at the slot start, so
        # logical-time tests keep their on-time semantics.
        timely = (
            self.current_slot == block.slot
            and self.slot_clock.seconds_into_slot() < self.spec.seconds_per_slot / 3
        )
        self.fork_choice.on_tick(self.current_slot)
        self.fork_choice.on_block(signed_block, block_root, state, is_timely=timely)
        if el_status is not None:
            from ..execution.engine_api import PayloadStatus

            if el_status == PayloadStatus.valid.value:
                # VALID verdict also confirms all optimistic ancestors
                self.fork_choice.proto.on_valid_execution_payload(block_root)
        self.block_times.imported(block_root)
        prev_head = self.head_root
        self.recompute_head()
        # Early-attester data: serve attestations for the block imported this
        # slot — but only when fork choice actually selected it as head
        # (beacon_chain.rs only caches on `new_head_root == block_root`); a
        # losing fork block must not hijack attestation data.
        if self.head_root == block_root:
            from .caches import AttesterData

            epoch = h.compute_epoch_at_slot(block.slot, spec)
            self.early_attester_cache.add(
                int(block.slot),
                AttesterData(
                    beacon_block_root=block_root,
                    parent_root=parent_root,
                    source_epoch=int(state.current_justified_checkpoint.epoch),
                    source_root=bytes(state.current_justified_checkpoint.root),
                    target_epoch=epoch,
                    target_root=self._target_root_for(state, epoch, block_root),
                ),
            )
        from ..utils.metrics import BLOCK_OBSERVED_TO_HEAD, BLOCK_OBSERVED_TO_IMPORT

        d = self.block_times.import_delay(block_root)
        if d is not None:
            BLOCK_OBSERVED_TO_IMPORT.observe(d)
        if self.head_root != prev_head:
            self.block_times.became_head(self.head_root)
            d = self.block_times.head_delay(self.head_root)
            if d is not None:
                BLOCK_OBSERVED_TO_HEAD.observe(d)
        if self.monitor.active:
            self._monitor_block_import(block, state, fork)
        return block_root

    # ------------------------------------------------- validator monitor

    def _monitor_block_import(self, block, post_state, fork) -> None:
        """Feed the ValidatorMonitor from an imported block: proposal,
        per-attestation attesting indices (recomputed from the post state —
        only runs when validators are registered), sync-committee
        participation, and slashings (validator_monitor.rs
        register_attestation_in_block and friends)."""
        from ..types.spec import ForkName
        from ..utils.logging import get_logger

        mlog = get_logger("validator_monitor")
        spec = self.spec
        att_sets = []
        for att in block.body.attestations:
            epoch = int(att.data.target.epoch)
            try:
                # reuse the chain-wide shuffling cache, keyed exactly like
                # the gossip attestation path (_committee_for)
                cc = self.shuffling_cache.get_or_build(
                    post_state, spec, epoch, bytes(att.data.target.root)
                )
            except Exception as e:  # noqa: BLE001 — monitoring must never
                _MONITOR_ERRORS.labels("shuffling").inc()  # fail an import
                mlog.warn("monitor shuffling lookup failed; attestation "
                          "skipped", slot=int(att.data.slot), epoch=epoch,
                          error=f"{type(e).__name__}: {e}")
                continue
            try:
                if fork >= ForkName.electra:
                    indices = acc.get_attesting_indices_electra(
                        post_state, spec, att, cc
                    )
                else:
                    committee = cc.committee(att.data.slot, att.data.index)
                    indices = [
                        i for i, bit in zip(committee, att.aggregation_bits) if bit
                    ]
            except Exception as e:  # noqa: BLE001
                _MONITOR_ERRORS.labels("attesting_indices").inc()
                mlog.warn("monitor attesting-index recovery failed; "
                          "attestation skipped", slot=int(att.data.slot),
                          index=int(att.data.index),
                          error=f"{type(e).__name__}: {e}")
                continue
            att_sets.append((att, indices))
        self.monitor.on_block_imported(block, att_sets)

        if fork >= ForkName.altair and hasattr(block.body, "sync_aggregate"):
            self.monitor.on_sync_aggregate(
                int(block.slot),
                self._sync_committee_member_indices(post_state),
                list(block.body.sync_aggregate.sync_committee_bits),
            )

        epoch = int(block.slot) // spec.preset.SLOTS_PER_EPOCH
        for sl in block.body.proposer_slashings:
            self.monitor.on_slashing(
                int(sl.signed_header_1.message.proposer_index), epoch
            )
        for sl in block.body.attester_slashings:
            a = set(sl.attestation_1.attesting_indices)
            for vi in sorted(a & set(sl.attestation_2.attesting_indices)):
                self.monitor.on_slashing(int(vi), epoch)

    def _sync_committee_member_indices(self, state) -> list[int]:
        """Validator indices of the CURRENT sync committee, cached per sync
        period (pubkey -> index via the pubkey cache)."""
        spec = self.spec
        epoch = int(state.slot) // spec.preset.SLOTS_PER_EPOCH
        period = epoch // spec.preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        if self._monitor_sync_indices and self._monitor_sync_indices[0] == period:
            return self._monitor_sync_indices[1]
        indices = []
        for pk in state.current_sync_committee.pubkeys:
            got = self.pubkey_cache.get_index(bytes(pk))
            indices.append(-1 if got is None else got)
        self._monitor_sync_indices = (period, indices)
        return indices

    def _monitor_epoch_rollover(self) -> None:
        """On entering a new epoch E: record E's expected proposers (for
        missed-block detection) and close epoch E-2's books. Closing lags
        ONE FULL EPOCH (like validator_monitor.rs): attestations from the
        tail of E-1 are includable throughout E, so E-1's participation
        flags are only complete once E ends — a state in epoch E-1 (whose
        previous_epoch_participation is E-2, now final) is what we read."""
        spe = self.spec.preset.SLOTS_PER_EPOCH
        cur_epoch = self.current_slot // spe
        if cur_epoch == self._monitor_epoch:
            return
        prev_epoch_seen = self._monitor_epoch
        self._monitor_epoch = cur_epoch
        try:
            head = self.head_state()
            start = cur_epoch * spe
            st = head
            if st.slot < start:
                st = clone_state(head, self.spec)
                process_slots(st, self.spec, start)
            duties = [
                (slot, acc.get_beacon_proposer_index(st, self.spec, slot))
                for slot in range(start, start + spe)
            ]
            self.monitor.on_proposer_duties(cur_epoch, duties)

            if cur_epoch >= 2:
                # close every epoch whose books became final since the last
                # tick (the clock may jump several epochs after a stall);
                # only the newest target can read real participation flags —
                # a state inside epoch E-1 has previous participation == E-2
                # bounded backfill: none on the first tick (a checkpoint
                # start at epoch 300k must not reconcile 300k empty epochs)
                # and at most 32 epochs after a stall
                if prev_epoch_seen is None:
                    oldest = cur_epoch - 2
                else:
                    oldest = max(prev_epoch_seen - 1, cur_epoch - 2 - 32, 0)
                for tgt in range(oldest, cur_epoch - 2):
                    self.monitor.finalize_epoch(tgt, None)
                prev_start = (cur_epoch - 1) * spe
                st_close = head
                if st_close.slot < prev_start:
                    st_close = clone_state(head, self.spec)
                    process_slots(st_close, self.spec, prev_start)
                in_prev_epoch = prev_start <= st_close.slot < start
                self.monitor.finalize_epoch(
                    cur_epoch - 2, st_close if in_prev_epoch else None
                )
        except Exception as e:
            from ..utils.logging import get_logger

            get_logger("validator_monitor").warn(
                "epoch rollover bookkeeping failed", error=str(e)
            )

    def process_gossip_blob(self, sidecar):
        """Gossip blob-sidecar entry: verify, feed the DA checker, and import
        the joined block if it just became available. Returns the imported
        block root or None (network_beacon_processor process_gossip_blob
        analog)."""
        from .data_availability import verify_blob_sidecar_for_gossip

        block_root = verify_blob_sidecar_for_gossip(self, sidecar)
        got = self.data_availability.put_blob(block_root, sidecar)
        if got is not None:
            block, sidecars = got
            return self.process_block(block, blobs=sidecars, blobs_verified=True)
        return None

    def get_blobs(self, block_root: bytes):
        """Stored sidecars for an imported block (by-root RPC / API serve)."""
        raw = self.store.get_blobs(block_root)
        if raw is None:
            return []
        import struct

        slot = self.block_slots.get(block_root)
        types = types_for_slot(self.spec, slot if slot is not None else 0)
        n = struct.unpack_from("<I", raw, 0)[0]
        off = 4
        out = []
        for _ in range(n):
            ln = struct.unpack_from("<I", raw, off)[0]
            off += 4
            out.append(types.BlobSidecar.deserialize(raw[off : off + ln]))
            off += ln
        return out

    def process_chain_segment(self, blocks, blobs_by_root=None) -> list[bytes]:
        """Import a batch of contiguous blocks with ONE signature batch for
        the whole segment (signature_verify_chain_segment analog).

        blobs_by_root: {block_root: [sidecar]} fetched over RPC alongside
        the range (block_sidecar_coupling) — verified as a KZG batch inside
        process_block."""
        if not blocks:
            return []
        spec = self.spec
        get_pubkey = self.pubkey_cache.pubkey_getter()
        # 1. one pass building proposal sets against cheaply-advanced states
        batch = SignatureBatch()
        state = self._state_for_block(bytes(blocks[0].message.parent_root), blocks[0].message.slot)
        trial = clone_state(state, spec)
        for sb in blocks:
            types = types_for_slot(spec, sb.message.slot)
            if trial.slot < sb.message.slot:
                process_slots(trial, spec, sb.message.slot)
            batch.add(sigs.block_proposal_set(trial, spec, types, sb, get_pubkey))
            batch.add(sigs.randao_set(trial, spec, types, sb.message, get_pubkey))
        if not batch.verify():
            raise BlockError("chain segment signature batch invalid")
        # 2. sequential import without re-verifying proposal signatures
        roots = []
        for sb in blocks:
            blobs = None
            if blobs_by_root is not None:
                types = types_for_slot(spec, sb.message.slot)
                root = types.BeaconBlock.hash_tree_root(sb.message)
                blobs = blobs_by_root.get(root)
            roots.append(
                self.process_block(
                    sb, proposal_already_verified=True, blobs=blobs
                )
            )
        return roots

    def _target_root_for(self, state, epoch: int, head_root: bytes) -> bytes:
        start = h.compute_start_slot_at_epoch(epoch, self.spec)
        if state.slot <= start:
            return head_root
        return bytes(
            state.block_roots[start % self.spec.preset.SLOTS_PER_HISTORICAL_ROOT]
        )

    # ------------------------------------------------------------ attestations

    @staticmethod
    def _attestation_committee_index(att) -> int:
        """The committee an attestation covers. Electra (EIP-7549) moved
        the index out of AttestationData (data.index MUST be 0) into the
        committee_bits field; gossip attestations/aggregates set exactly
        one bit."""
        cb = getattr(att, "committee_bits", None)
        if cb is None:
            return int(att.data.index)
        set_bits = [i for i, b in enumerate(cb) if b]
        if len(set_bits) != 1:
            raise AttestationError("expected exactly one committee bit")
        if int(att.data.index) != 0:
            raise AttestationError("electra attestation data.index must be 0")
        return set_bits[0]

    def _committee_for(self, data, committee_index: int | None = None):
        spec = self.spec
        epoch = data.target.epoch
        cache = self.shuffling_cache.get_or_build(
            self._attestation_state(data), spec, epoch, bytes(data.target.root)
        )
        idx = int(data.index) if committee_index is None else committee_index
        if idx >= cache.committees_per_slot:
            raise AttestationError("bad committee index")
        return cache.committee(data.slot, idx)

    def _attestation_state(self, data):
        """A state usable to compute the committee for `data`."""
        target_root = bytes(data.target.root)
        state_root = self.state_root_by_block.get(target_root)
        if state_root and state_root in self.state_cache:
            return self.state_cache[state_root]
        return self.head_state()

    def prepare_unaggregated_attestations(self, attestations) -> list:
        """Host-side phase of batch gossip verification: committee lookup,
        dedup, signature-set construction. Returns [(att, attesting, set)]
        ready for one device submission."""
        spec = self.spec
        get_pubkey = self.pubkey_cache.pubkey_getter()
        prepared = []
        # batch-LOCAL dedup: observed_attesters is only updated at
        # completion, so without this a validator equivocating twice within
        # one coalescing window would get both attestations verified and
        # forwarded (the sequential path dropped the second)
        seen_in_batch: set = set()
        for att in attestations:
            data = att.data
            epoch = data.target.epoch
            if data.target.epoch not in (
                h.compute_epoch_at_slot(data.slot, spec),
            ):
                continue
            try:
                committee = self._committee_for(
                    data, self._attestation_committee_index(att)
                )
            except AttestationError:
                continue
            if len(att.aggregation_bits) != len(committee):
                continue
            attesting = [i for i, b in zip(committee, att.aggregation_bits) if b]
            if len(attesting) != 1:
                continue  # unaggregated = exactly one bit
            if (epoch, attesting[0]) in self.observed_attesters:
                continue
            if (epoch, attesting[0]) in seen_in_batch:
                continue
            seen_in_batch.add((epoch, attesting[0]))
            state = self._attestation_state(data)
            types = types_for_slot(spec, data.slot)
            indexed = types.IndexedAttestation.make(
                attesting_indices=attesting, data=data, signature=att.signature
            )
            try:
                s = sigs.indexed_attestation_set(state, spec, types, indexed, get_pubkey)
            except sigs.SignatureSetError:
                continue
            prepared.append((att, attesting, s))
        return prepared

    def complete_attestation_batch(self, prepared, ok: bool) -> list:
        """Device-result phase: on batch failure fall back to per-set
        verification (attestation_verification/batch.rs:213-221), record
        observed attesters, return verified (att, attesting_indices)."""
        results = []
        for att, attesting, s in prepared:
            valid = ok or bls.verify_signature_sets([s])
            if valid:
                self.observed_attesters.add((att.data.target.epoch, attesting[0]))
                types = types_for_slot(self.spec, att.data.slot)
                self.naive_attestation_pool.insert(att, types)
                if self.slasher is not None:
                    from ..slasher.slasher import AttestationRecord

                    indexed = types.IndexedAttestation.make(
                        attesting_indices=attesting, data=att.data,
                        signature=att.signature,
                    )
                    self.slasher.accept_attestation(
                        AttestationRecord(
                            validator_index=attesting[0],
                            source=int(att.data.source.epoch),
                            target=int(att.data.target.epoch),
                            data_root=types.AttestationData.hash_tree_root(att.data),
                            indexed=indexed,
                        )
                    )
                results.append((att, attesting))
        return results

    def verify_unaggregated_attestations(self, attestations) -> list:
        """Batch gossip verification (batch_verify_unaggregated_attestations,
        attestation_verification/batch.rs:140): prepare + ONE device batch +
        complete. The split phases let the beacon processor overlap host
        marshalling with in-flight device batches
        (submit_attestation_batch)."""
        prepared = self.prepare_unaggregated_attestations(attestations)
        if not prepared:
            return []
        ok = bls.verify_signature_sets([s for _, _, s in prepared])
        return self.complete_attestation_batch(prepared, ok)

    def submit_attestation_batch(self, attestations, on_done=None,
                                 on_prepared=None):
        """Pipelined form: prepare on host, submit async to the device, and
        return (handle, continuation). The continuation — run when the
        processor resolves the handle — completes verification and applies
        fork-choice votes. Returns None if nothing verifiable.

        on_prepared([att, ...]) fires after the host phase with the
        attestations that made it into the device batch — callers tracking
        per-message outcomes (the gossip deferred-validation path) learn
        which inputs were dropped at prepare (duplicates/unverifiable)."""
        prepared = self.prepare_unaggregated_attestations(attestations)
        if on_prepared is not None:
            on_prepared([att for att, _indices, _s in prepared])
        if not prepared:
            if on_done is not None:
                on_done([])
            return None
        handle = bls.verify_signature_sets_async([s for _, _, s in prepared])

        def continuation(ok: bool):
            results = self.complete_attestation_batch(prepared, ok)
            for att, indices in results:
                self.apply_attestation_to_fork_choice(att, indices)
            if on_done is not None:
                on_done(results)
            return results

        return handle, continuation

    def verify_aggregated_attestations(self, signed_aggregates) -> list:
        """Batch gossip verification of SignedAggregateAndProof messages:
        3 signature sets each (selection proof, aggregator signature,
        indexed attestation) verified in ONE batch
        (attestation_verification/batch.rs:31-135)."""
        spec = self.spec
        get_pubkey = self.pubkey_cache.pubkey_getter()
        prepared = []
        sets = []
        for signed in signed_aggregates:
            msg = signed.message
            att = msg.aggregate
            data = att.data
            epoch = data.target.epoch
            key = (epoch, msg.aggregator_index)
            if key in self.observed_aggregators:
                continue
            try:
                committee = self._committee_for(
                    data, self._attestation_committee_index(att)
                )
            except AttestationError:
                continue
            if len(att.aggregation_bits) != len(committee):
                continue
            attesting = [i for i, b in zip(committee, att.aggregation_bits) if b]
            if not attesting:
                continue
            state = self._attestation_state(data)
            types = types_for_slot(spec, data.slot)
            indexed = types.IndexedAttestation.make(
                attesting_indices=sorted(attesting), data=data, signature=att.signature
            )
            try:
                trio = [
                    sigs.selection_proof_set(
                        state, spec, types, data.slot, msg.aggregator_index,
                        msg.selection_proof, get_pubkey,
                    ),
                    sigs.aggregate_and_proof_set(state, spec, types, signed, get_pubkey),
                    sigs.indexed_attestation_set(state, spec, types, indexed, get_pubkey),
                ]
            except sigs.SignatureSetError:
                continue
            prepared.append((signed, attesting, trio))
            sets.extend(trio)
        if not sets:
            return []
        ok = bls.verify_signature_sets(sets)
        results = []
        for signed, attesting, trio in prepared:
            valid = ok or bls.verify_signature_sets(trio)
            if valid:
                self.observed_aggregators.add(
                    (signed.message.aggregate.data.target.epoch, signed.message.aggregator_index)
                )
                results.append((signed.message.aggregate, attesting))
        return results

    def verify_sync_committee_message(self, msg) -> bool:
        """Gossip verification of a single SyncCommitteeMessage
        (sync_committee_verification.rs)."""
        spec = self.spec
        state = self.head_state()
        if not hasattr(state, "current_sync_committee"):
            raise AttestationError("pre-altair state")
        pk_bytes = bytes(state.validators[msg.validator_index].pubkey)
        committee_pks = {bytes(pk) for pk in state.current_sync_committee.pubkeys}
        if pk_bytes not in committee_pks:
            raise AttestationError("not in sync committee")
        get_pubkey = self.pubkey_cache.pubkey_getter()
        s = sigs.sync_committee_message_set(state, spec, msg, get_pubkey)
        return bls.verify_signature_sets([s])

    def verify_signed_contribution(self, signed) -> bool:
        """Gossip verification of a SignedContributionAndProof: selection
        proof + aggregator signature + aggregate sync signature, one batch
        (sync_committee_verification.rs contribution path)."""
        spec = self.spec
        state = self.head_state()
        msg = signed.message
        contrib = msg.contribution
        get_pubkey = self.pubkey_cache.pubkey_getter()
        types = types_for_slot(spec, contrib.slot)
        sub_size = spec.preset.SYNC_COMMITTEE_SIZE // spec.sync_committee_subnet_count
        # participant pubkeys for the contribution signature
        start = int(contrib.subcommittee_index) * sub_size
        pks = [
            bytes(state.current_sync_committee.pubkeys[start + i])
            for i, b in enumerate(contrib.aggregation_bits)
            if b
        ]
        if not pks:
            return False
        try:
            trio = [
                sigs.sync_selection_proof_set(
                    state, spec, types, contrib.slot, contrib.subcommittee_index,
                    msg.aggregator_index, msg.selection_proof, get_pubkey,
                ),
                sigs.contribution_and_proof_set(state, spec, types, signed, get_pubkey),
            ]
            # aggregate sync signature over the block root
            from ..types.spec import DOMAIN_SYNC_COMMITTEE

            epoch = h.compute_epoch_at_slot(contrib.slot, spec)
            domain = h.get_domain(state, spec, DOMAIN_SYNC_COMMITTEE, epoch)
            root = h.compute_signing_root_from_root(
                bytes(contrib.beacon_block_root), domain
            )
            by_bytes = sigs.get_pubkey_by_bytes
            trio.append(
                bls.SignatureSet(
                    bls.Signature.deserialize(bytes(contrib.signature)),
                    [by_bytes(get_pubkey, pk) for pk in pks],
                    root,
                )
            )
        except sigs.SignatureSetError:
            return False
        return bls.verify_signature_sets(trio)

    def sync_subcommittee_positions(self, validator_index: int) -> list[tuple[int, int]]:
        """(subcommittee_index, index_in_subcommittee) pairs for a validator
        in the CURRENT sync committee (duplicates possible by spec)."""
        state = self.head_state()
        spec = self.spec
        pk = bytes(state.validators[validator_index].pubkey)
        sub_size = spec.preset.SYNC_COMMITTEE_SIZE // spec.sync_committee_subnet_count
        out = []
        for i, cpk in enumerate(state.current_sync_committee.pubkeys):
            if bytes(cpk) == pk:
                out.append((i // sub_size, i % sub_size))
        return out

    def process_sync_committee_messages(self, msgs) -> int:
        """Verify a batch of sync-committee messages in ONE device batch and
        feed the naive contribution pool. Returns messages accepted."""
        spec = self.spec
        state = self.head_state()
        get_pubkey = self.pubkey_cache.pubkey_getter()
        prepared = []
        for msg in msgs:
            try:
                positions = self.sync_subcommittee_positions(int(msg.validator_index))
            except (IndexError, AttributeError):
                continue
            if not positions:
                continue
            try:
                s = sigs.sync_committee_message_set(state, spec, msg, get_pubkey)
            except sigs.SignatureSetError:
                continue
            prepared.append((msg, positions, s))
        if not prepared:
            return 0
        ok = bls.verify_signature_sets([s for _, _, s in prepared])
        accepted = 0
        for msg, positions, s in prepared:
            if ok or bls.verify_signature_sets([s]):
                for sub_idx, pos in positions:
                    self.naive_sync_pool.insert(
                        int(msg.slot), bytes(msg.beacon_block_root), sub_idx, pos,
                        bytes(msg.signature),
                    )
                accepted += 1
        return accepted

    # ------------------------------------------------------------ production

    def produce_block(
        self,
        slot: int,
        randao_reveal: bytes,
        op_pool=None,
        graffiti: bytes | None = None,
        blobs_bundle=None,
    ):
        """Produce an unsigned block on the head state
        (produce_block_on_state, beacon_chain.rs:4720 analog).

        blobs_bundle: optional (blobs, commitments, proofs) from the EL's
        getPayload (deneb+); commitments go into the body, and the caller
        builds sidecars from the signed block via
        data_availability.build_sidecars."""
        from ..state_transition.block import SignatureStrategy
        from ..types.spec import ForkName

        if graffiti is None:
            # node default (--graffiti / graffiti_calculator.rs role);
            # callers (API) still override per request
            graffiti = getattr(self, "graffiti", b"\x00" * 32)
        spec = self.spec
        types = types_for_slot(spec, slot)
        fork = spec.fork_name_at_slot(slot)
        # proposer re-org: build on the head's PARENT when the head is a
        # weak late block that fork choice deems safe to orphan
        # (get_proposer_head, fork_choice.rs:516)
        parent_root = self.fork_choice.get_proposer_head(self.head_root, slot)
        state = self._state_for_block(parent_root, slot)
        proposer = acc.get_beacon_proposer_index(state, spec)

        attestations = []
        if op_pool is not None:
            attestations = op_pool.get_attestations_for_block(state, types)

        # eth1 voting + deposit inclusion (eth1_chain.rs): the vote may flip
        # state.eth1_data inside process_eth1_data, and deposits are checked
        # against the POST-vote data — compute the effective value the same
        # way the verifier will.
        eth1_data = state.eth1_data
        deposits = []
        if self.eth1_cache is not None:
            from ..state_transition.block import eth1_data_after_vote

            eth1_data = self.eth1_cache.eth1_vote(state, spec, types)
            deposits = self.eth1_cache.deposits_for_block_inclusion(
                state, spec, types,
                eth1_data=eth1_data_after_vote(state, spec, eth1_data),
                fork=fork,
            )

        body_kwargs = dict(
            randao_reveal=randao_reveal,
            eth1_data=eth1_data,
            graffiti=graffiti,
            proposer_slashings=[],
            attester_slashings=[],
            attestations=attestations,
            deposits=deposits,
            voluntary_exits=[],
        )
        if op_pool is not None:
            ps, asl, exits, changes = op_pool.get_slashings_and_exits(state, types)
            body_kwargs.update(
                proposer_slashings=ps, attester_slashings=asl, voluntary_exits=exits
            )
            if fork >= ForkName.capella:
                body_kwargs["bls_to_execution_changes"] = changes
        if fork >= ForkName.altair:
            # pack the sync aggregate built from last slot's subnet
            # contributions signing our parent
            agg = self.naive_sync_pool.get_sync_aggregate(
                max(slot, 1) - 1, parent_root, types
            )
            body_kwargs["sync_aggregate"] = agg or types.SyncAggregate.make(
                sync_committee_bits=[False] * spec.preset.SYNC_COMMITTEE_SIZE,
                sync_committee_signature=bls.INFINITY_SIGNATURE_BYTES,
            )
        if fork >= ForkName.bellatrix:
            payload = types.ExecutionPayload.default()
            if self.execution_layer is not None:
                payload, el_bundle = self._request_el_payload(
                    state, spec, types, fork, proposer, parent_root
                )
                if el_bundle is not None and blobs_bundle is None:
                    blobs_bundle = el_bundle
            body_kwargs["execution_payload"] = payload
        if fork >= ForkName.capella and "bls_to_execution_changes" not in body_kwargs:
            body_kwargs["bls_to_execution_changes"] = []
        if fork >= ForkName.deneb:
            body_kwargs["blob_kzg_commitments"] = (
                list(blobs_bundle[1]) if blobs_bundle is not None else []
            )
            if blobs_bundle is not None:
                # stash so publish can rebuild sidecars after signing;
                # slot-stamped so unpublished bundles (VC refusal, failover
                # to another BN) don't leak for the process lifetime
                self._produced_bundles[
                    tuple(bytes(c) for c in blobs_bundle[1])
                ] = (int(slot), blobs_bundle)
                horizon = int(slot) - 2 * spec.preset.SLOTS_PER_EPOCH
                for k in [
                    k for k, (s, _) in self._produced_bundles.items() if s < horizon
                ]:
                    del self._produced_bundles[k]

        block = types.BeaconBlock.make(
            slot=slot,
            proposer_index=proposer,
            parent_root=parent_root,
            state_root=b"\x00" * 32,
            body=types.BeaconBlockBody.make(**body_kwargs),
        )
        trial = types.SignedBeaconBlock.make(message=block, signature=b"\x00" * 96)
        post = self._state_for_block(parent_root, slot)
        per_block_processing(
            post, trial, spec, types,
            strategy=SignatureStrategy.NO_VERIFICATION, verify_block_root=True,
        )
        return block.copy_with(state_root=types.BeaconState.hash_tree_root(post))

    def _request_el_payload(self, state, spec, types, fork, proposer: int,
                            parent_root: bytes | None = None):
        """fcU-with-attributes + getPayload against the EL for a block being
        produced on `state` (already advanced to the proposal slot)
        (execution_layer/src/lib.rs get_payload flow). Returns
        (ExecutionPayload, blobs_bundle | None)."""
        from ..state_transition.block import (
            compute_timestamp_at_slot,
            get_expected_withdrawals,
        )
        from ..types.spec import ForkName

        if parent_root is None:
            parent_root = self.head_root
        head_hash = self.payload_hash_by_block.get(parent_root, b"\x00" * 32)
        jc_root = self.fork_choice.store.justified_checkpoint[1]
        fc_root = self.fork_choice.store.finalized_checkpoint[1]
        withdrawals = None
        if fork >= ForkName.capella:
            withdrawals, _ = get_expected_withdrawals(state, spec, types)
        payload, bundle = self.execution_layer.produce_payload(
            types,
            head_payload_hash=head_hash,
            safe_hash=self.payload_hash_by_block.get(jc_root, b"\x00" * 32),
            finalized_hash=self.payload_hash_by_block.get(fc_root, b"\x00" * 32),
            timestamp=compute_timestamp_at_slot(state, spec, state.slot),
            prev_randao=acc.h.get_randao_mix(
                state, spec, acc.get_current_epoch(state, spec)
            ),
            fee_recipient=self.proposer_preparations.get(proposer),
            withdrawals=withdrawals,
            parent_beacon_block_root=parent_root if fork >= ForkName.deneb else None,
        )
        return payload, bundle

    def sidecars_for_produced_block(self, signed_block):
        """Build blob sidecars for a locally-produced block that was just
        signed, from the blobs bundle the EL returned at production time
        (publish_blocks.rs builds sidecars from cached payload contents).
        Returns [] when the block carries no commitments or no bundle is
        stashed (e.g. produced without an EL)."""
        from .data_availability import build_sidecars

        body = signed_block.message.body
        commitments = tuple(
            bytes(c) for c in getattr(body, "blob_kzg_commitments", ())
        )
        if not commitments:
            return []
        # NON-destructive lookup: a failed import must be retryable with
        # the same bundle (slot-horizon pruning in produce_block bounds the
        # stash instead)
        entry = self._produced_bundles.get(commitments)
        if entry is None:
            return []
        _, (blobs, _, proofs) = entry
        types = types_for_slot(self.spec, signed_block.message.slot)
        return build_sidecars(types, self.spec, signed_block, blobs, proofs)

    def apply_attestation_to_fork_choice(self, att, attesting_indices):
        self.fork_choice.on_attestation(
            att.data.slot,
            attesting_indices,
            bytes(att.data.beacon_block_root),
            att.data.target.epoch,
        )
