"""BeaconChain — chain orchestration: verification pipelines, import, head.

Parity surface (trimmed to the load-bearing paths of
/root/reference/beacon_node/beacon_chain/src/):
  - gossip block verification (block_verification.rs GossipVerifiedBlock
    :639 -> SignatureVerifiedBlock :648): slot/parent/dedup checks, cheap
    proposer-signature check, then full batch verification on import
  - process_block / import_block (beacon_chain.rs:3035,:3362): state
    transition with VERIFY_BULK (one TPU batch per block), store writes,
    fork-choice on_block, head recompute (canonical_head.rs:473)
  - attestation verification, single and batched
    (attestation_verification.rs + batch.rs): committee resolution via the
    shuffling cache, observed-dedup, batched BLS verify, fork-choice votes
  - caches: ValidatorPubkeyCache (device feed), ShufflingCache,
    BeaconProposerCache, observed_* gossip dedup sets
  - chain-segment processing with ONE signature batch for the whole
    segment (block_verification.rs:568 signature_verify_chain_segment)
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..crypto import bls
from ..fork_choice.fork_choice import ForkChoice
from ..state_transition import accessors as acc
from ..state_transition import signature_sets as sigs
from ..state_transition.block import (
    BlockProcessingError,
    SignatureBatch,
    SignatureStrategy,
    per_block_processing,
)
from ..state_transition.slot import process_slots, types_for_slot
from ..store.hot_cold import HotColdDB
from ..testing.harness import clone_state
from ..types import helpers as h
from ..types.spec import ChainSpec, DOMAIN_BEACON_ATTESTER
from ..utils.slot_clock import SlotClock
from .pubkey_cache import ValidatorPubkeyCache


class BlockError(Exception):
    """Block rejected (block_verification.rs BlockError analog)."""


class AttestationError(Exception):
    """Attestation rejected (attestation_verification.rs Error analog)."""


@dataclass
class ChainConfig:
    reorg_threshold_percent: int = 20
    import_max_skip_slots: int | None = None


class ShufflingCache:
    """(epoch, decision_root) -> CommitteeCache (shuffling_cache.rs)."""

    def __init__(self, capacity: int = 16):
        self.capacity = capacity
        self._map: dict[tuple[int, bytes], object] = {}

    def get_or_build(self, state, spec, epoch: int, decision_root: bytes):
        key = (epoch, decision_root)
        got = self._map.get(key)
        if got is None:
            got = acc.build_committee_cache(state, spec, epoch)
            if len(self._map) >= self.capacity:
                self._map.pop(next(iter(self._map)))
            self._map[key] = got
        return got


class BeaconChain:
    def __init__(
        self,
        spec: ChainSpec,
        genesis_state,
        store: HotColdDB | None = None,
        slot_clock: SlotClock | None = None,
        config: ChainConfig | None = None,
    ):
        from ..utils.slot_clock import ManualSlotClock

        self.spec = spec
        self.config = config or ChainConfig()
        self.store = store or HotColdDB(spec)
        self.slot_clock = slot_clock or ManualSlotClock(
            genesis_state.genesis_time, spec.seconds_per_slot
        )

        types = types_for_slot(spec, genesis_state.slot)
        state_root = types.BeaconState.hash_tree_root(genesis_state)
        # The anchor block root must match what descendants reference:
        # hash of the state's latest_block_header with its state_root filled
        # (the header's body_root may predate fork upgrades, so we must not
        # rebuild the body ourselves).
        header = genesis_state.latest_block_header
        if bytes(header.state_root) == b"\x00" * 32:
            header = header.copy_with(state_root=state_root)
        self.genesis_block_root = types.BeaconBlockHeader.hash_tree_root(header)
        genesis_block = types.BeaconBlock.make(
            slot=genesis_state.slot,
            proposer_index=header.proposer_index,
            parent_root=header.parent_root,
            state_root=header.state_root,
            body=types.BeaconBlockBody.default(),
        )
        signed_genesis = types.SignedBeaconBlock.make(
            message=genesis_block, signature=b"\x00" * 96
        )
        self.store.put_block(self.genesis_block_root, signed_genesis, types)
        self.store.put_state(state_root, genesis_state, types)

        self.fork_choice = ForkChoice(
            spec, self.genesis_block_root, genesis_state.slot, genesis_state
        )
        # head state kept in memory (state_cache analog: root -> state)
        self.state_cache: dict[bytes, object] = {state_root: genesis_state}
        self.block_slots: dict[bytes, int] = {self.genesis_block_root: genesis_state.slot}
        self.state_root_by_block: dict[bytes, bytes] = {
            self.genesis_block_root: state_root
        }
        self.head_root = self.genesis_block_root

        self.pubkey_cache = ValidatorPubkeyCache(self.store)
        self.pubkey_cache.import_new_pubkeys(genesis_state)
        self.shuffling_cache = ShufflingCache()
        self.proposer_cache: dict[tuple[int, bytes], list[int]] = {}

        # observed-* gossip dedup (observed_attesters.rs etc.)
        self.observed_block_producers: set[tuple[int, int]] = set()
        self.observed_attesters: set[tuple[int, int]] = set()          # (epoch, validator)
        self.observed_aggregators: set[tuple[int, int]] = set()
        self.observed_blocks: set[bytes] = set()

    # ---------------------------------------------------------------- time

    @property
    def current_slot(self) -> int:
        s = self.slot_clock.now()
        return s if s is not None else 0

    def per_slot_task(self) -> None:
        self.fork_choice.on_tick(self.current_slot)

    # ---------------------------------------------------------------- head

    def head_state(self):
        return self.state_cache[self.state_root_by_block[self.head_root]]

    def head_block(self):
        types = types_for_slot(self.spec, self.block_slots[self.head_root])
        return self.store.get_block(self.head_root, types)

    def recompute_head(self) -> bytes:
        self.fork_choice.on_tick(self.current_slot)
        head = self.fork_choice.get_head()
        self.head_root = head
        return head

    # ------------------------------------------------------------ gossip block

    def verify_block_for_gossip(self, signed_block, block_root=None):
        """Cheap structural + proposer-signature verification
        (GossipVerifiedBlock::new analog)."""
        spec = self.spec
        block = signed_block.message
        types = types_for_slot(spec, block.slot)
        if block_root is None:
            block_root = types.BeaconBlock.hash_tree_root(block)

        if block.slot > self.current_slot:
            raise BlockError(f"future block: {block.slot} > {self.current_slot}")
        if block_root in self.observed_blocks or self.store.block_exists(block_root):
            raise BlockError("block already known")
        parent_root = bytes(block.parent_root)
        if not self.store.block_exists(parent_root):
            raise BlockError("parent unknown")
        fin_epoch = self.fork_choice.store.finalized_checkpoint[0]
        fin_slot = h.compute_start_slot_at_epoch(fin_epoch, spec)
        if block.slot <= fin_slot:
            raise BlockError("block older than finalization")
        key = (block.slot, block.proposer_index)
        if key in self.observed_block_producers:
            raise BlockError("proposer equivocation for slot")

        # proposer signature over a cheaply-advanced parent state
        state = self._state_for_block(parent_root, block.slot)
        batch = SignatureBatch()
        try:
            batch.add(
                sigs.block_proposal_set(
                    state, spec, types, signed_block,
                    self.pubkey_cache.pubkey_getter(), block_root=block_root,
                )
            )
        except sigs.SignatureSetError as e:
            raise BlockError(f"undecodable signature: {e}") from e
        if not batch.verify():
            raise BlockError("invalid proposer signature")

        self.observed_block_producers.add(key)
        self.observed_blocks.add(block_root)
        return block_root

    def _state_for_block(self, parent_root: bytes, slot: int):
        """Parent post-state advanced to `slot` (cheap_state_advance)."""
        state_root = self.state_root_by_block.get(parent_root)
        if state_root is None or state_root not in self.state_cache:
            raise BlockError("parent state unavailable")
        state = clone_state(self.state_cache[state_root], self.spec)
        if state.slot < slot:
            process_slots(state, self.spec, slot)
        return state

    # ------------------------------------------------------------ import

    def process_block(
        self,
        signed_block,
        block_root=None,
        proposal_already_verified: bool = False,
    ) -> bytes:
        """Full verification + import (process_block/import_block analog)."""
        spec = self.spec
        block = signed_block.message
        types = types_for_slot(spec, block.slot)
        if block_root is None:
            block_root = types.BeaconBlock.hash_tree_root(block)
        parent_root = bytes(block.parent_root)
        if not self.store.block_exists(parent_root):
            raise BlockError("parent unknown")

        state = self._state_for_block(parent_root, block.slot)
        get_pubkey = self.pubkey_cache.pubkey_getter()

        batch = SignatureBatch()
        if not proposal_already_verified:
            batch.add(
                sigs.block_proposal_set(
                    state, spec, types, signed_block, get_pubkey, block_root=block_root
                )
            )

        # run per-block processing, accumulating the remaining signature sets
        # into the same batch, then verify EVERYTHING in one device call
        def handle(s):
            batch.add(s)

        from ..state_transition import block as blk

        try:
            blk.process_block_header(state, spec, types, block)
            fork = spec.fork_name_at_slot(block.slot)
            from ..types.spec import ForkName

            if fork >= ForkName.bellatrix:
                blk.process_withdrawals_and_payload(state, spec, types, block, fork)
            blk.process_randao(
                state, spec, types, block, SignatureStrategy.VERIFY_BULK, handle, get_pubkey
            )
            blk.process_eth1_data(state, spec, types, block.body)
            blk.process_operations(state, spec, types, block, fork, handle, get_pubkey)
            if fork >= ForkName.altair:
                blk.process_sync_aggregate(state, spec, types, block, handle, get_pubkey)
        except sigs.SignatureSetError as e:
            raise BlockError(f"undecodable signature: {e}") from e
        except BlockProcessingError as e:
            raise BlockError(str(e)) from e

        if not batch.verify():
            raise BlockError("block signature batch invalid")

        state_root = types.BeaconState.hash_tree_root(state)
        if bytes(block.state_root) != state_root:
            raise BlockError("state root mismatch")

        # import: store + caches + fork choice
        self.store.put_block(block_root, signed_block, types)
        self.store.put_state(state_root, state, types)
        self.state_cache[state_root] = state
        self.block_slots[block_root] = block.slot
        self.state_root_by_block[block_root] = state_root
        self.pubkey_cache.import_new_pubkeys(state)

        timely = self.current_slot == block.slot
        self.fork_choice.on_block(signed_block, block_root, state, is_timely=timely)
        self.recompute_head()
        self._prune_state_cache()
        return block_root

    def process_chain_segment(self, blocks) -> list[bytes]:
        """Import a batch of contiguous blocks with ONE signature batch for
        the whole segment (signature_verify_chain_segment analog)."""
        if not blocks:
            return []
        spec = self.spec
        get_pubkey = self.pubkey_cache.pubkey_getter()
        # 1. one pass building proposal sets against cheaply-advanced states
        batch = SignatureBatch()
        state = self._state_for_block(bytes(blocks[0].message.parent_root), blocks[0].message.slot)
        trial = clone_state(state, spec)
        for sb in blocks:
            types = types_for_slot(spec, sb.message.slot)
            if trial.slot < sb.message.slot:
                process_slots(trial, spec, sb.message.slot)
            batch.add(sigs.block_proposal_set(trial, spec, types, sb, get_pubkey))
            batch.add(sigs.randao_set(trial, spec, types, sb.message, get_pubkey))
        if not batch.verify():
            raise BlockError("chain segment signature batch invalid")
        # 2. sequential import without re-verifying proposal signatures
        roots = []
        for sb in blocks:
            roots.append(self.process_block(sb, proposal_already_verified=True))
        return roots

    def _prune_state_cache(self, keep: int = 8):
        if len(self.state_cache) <= keep:
            return
        # keep the most recent states by slot
        by_slot = sorted(
            self.state_cache.items(), key=lambda kv: kv[1].slot, reverse=True
        )
        self.state_cache = dict(by_slot[:keep])

    # ------------------------------------------------------------ attestations

    def _committee_for(self, data):
        spec = self.spec
        epoch = data.target.epoch
        head_state = self.head_state()
        cache = self.shuffling_cache.get_or_build(
            self._attestation_state(data), spec, epoch, bytes(data.target.root)
        )
        if data.index >= cache.committees_per_slot:
            raise AttestationError("bad committee index")
        return cache.committee(data.slot, data.index)

    def _attestation_state(self, data):
        """A state usable to compute the committee for `data`."""
        target_root = bytes(data.target.root)
        state_root = self.state_root_by_block.get(target_root)
        if state_root and state_root in self.state_cache:
            return self.state_cache[state_root]
        return self.head_state()

    def verify_unaggregated_attestations(self, attestations) -> list:
        """Batch gossip verification (batch_verify_unaggregated_attestations,
        attestation_verification/batch.rs:140). Returns list of
        (attestation, attesting_indices) that verified; raises only on
        per-batch failures of structure, not on individual invalid sigs —
        on batch failure falls back to per-set verification, exactly like
        the reference (:213-221)."""
        spec = self.spec
        get_pubkey = self.pubkey_cache.pubkey_getter()
        prepared = []
        sets = []
        for att in attestations:
            data = att.data
            epoch = data.target.epoch
            if data.target.epoch not in (
                h.compute_epoch_at_slot(data.slot, spec),
            ):
                continue
            committee = self._committee_for(data)
            if len(att.aggregation_bits) != len(committee):
                continue
            attesting = [i for i, b in zip(committee, att.aggregation_bits) if b]
            if len(attesting) != 1:
                continue  # unaggregated = exactly one bit
            if (epoch, attesting[0]) in self.observed_attesters:
                continue
            state = self._attestation_state(data)
            types = types_for_slot(spec, data.slot)
            indexed = types.IndexedAttestation.make(
                attesting_indices=attesting, data=data, signature=att.signature
            )
            try:
                s = sigs.indexed_attestation_set(state, spec, types, indexed, get_pubkey)
            except sigs.SignatureSetError:
                continue
            prepared.append((att, attesting, s))
            sets.append(s)

        if not sets:
            return []
        ok = bls.verify_signature_sets(sets)
        results = []
        for att, attesting, s in prepared:
            valid = ok or bls.verify_signature_sets([s])
            if valid:
                self.observed_attesters.add((att.data.target.epoch, attesting[0]))
                results.append((att, attesting))
        return results

    def verify_aggregated_attestations(self, signed_aggregates) -> list:
        """Batch gossip verification of SignedAggregateAndProof messages:
        3 signature sets each (selection proof, aggregator signature,
        indexed attestation) verified in ONE batch
        (attestation_verification/batch.rs:31-135)."""
        spec = self.spec
        get_pubkey = self.pubkey_cache.pubkey_getter()
        prepared = []
        sets = []
        for signed in signed_aggregates:
            msg = signed.message
            att = msg.aggregate
            data = att.data
            epoch = data.target.epoch
            key = (epoch, msg.aggregator_index)
            if key in self.observed_aggregators:
                continue
            try:
                committee = self._committee_for(data)
            except AttestationError:
                continue
            if len(att.aggregation_bits) != len(committee):
                continue
            attesting = [i for i, b in zip(committee, att.aggregation_bits) if b]
            if not attesting:
                continue
            state = self._attestation_state(data)
            types = types_for_slot(spec, data.slot)
            indexed = types.IndexedAttestation.make(
                attesting_indices=sorted(attesting), data=data, signature=att.signature
            )
            try:
                trio = [
                    sigs.selection_proof_set(
                        state, spec, types, data.slot, msg.aggregator_index,
                        msg.selection_proof, get_pubkey,
                    ),
                    sigs.aggregate_and_proof_set(state, spec, types, signed, get_pubkey),
                    sigs.indexed_attestation_set(state, spec, types, indexed, get_pubkey),
                ]
            except sigs.SignatureSetError:
                continue
            prepared.append((signed, attesting, trio))
            sets.extend(trio)
        if not sets:
            return []
        ok = bls.verify_signature_sets(sets)
        results = []
        for signed, attesting, trio in prepared:
            valid = ok or bls.verify_signature_sets(trio)
            if valid:
                self.observed_aggregators.add(
                    (signed.message.aggregate.data.target.epoch, signed.message.aggregator_index)
                )
                results.append((signed.message.aggregate, attesting))
        return results

    def verify_sync_committee_message(self, msg) -> bool:
        """Gossip verification of a single SyncCommitteeMessage
        (sync_committee_verification.rs)."""
        spec = self.spec
        state = self.head_state()
        if not hasattr(state, "current_sync_committee"):
            raise AttestationError("pre-altair state")
        pk_bytes = bytes(state.validators[msg.validator_index].pubkey)
        committee_pks = {bytes(pk) for pk in state.current_sync_committee.pubkeys}
        if pk_bytes not in committee_pks:
            raise AttestationError("not in sync committee")
        get_pubkey = self.pubkey_cache.pubkey_getter()
        s = sigs.sync_committee_message_set(state, spec, msg, get_pubkey)
        return bls.verify_signature_sets([s])

    # ------------------------------------------------------------ production

    def produce_block(self, slot: int, randao_reveal: bytes, op_pool=None, graffiti: bytes = b"\x00" * 32):
        """Produce an unsigned block on the head state
        (produce_block_on_state, beacon_chain.rs:4720 analog)."""
        from ..state_transition.block import SignatureStrategy
        from ..types.spec import ForkName

        spec = self.spec
        types = types_for_slot(spec, slot)
        fork = spec.fork_name_at_slot(slot)
        state = self._state_for_block(self.head_root, slot)
        proposer = acc.get_beacon_proposer_index(state, spec)

        attestations = []
        if op_pool is not None:
            attestations = op_pool.get_attestations_for_block(state, types)

        body_kwargs = dict(
            randao_reveal=randao_reveal,
            eth1_data=state.eth1_data,
            graffiti=graffiti,
            proposer_slashings=[],
            attester_slashings=[],
            attestations=attestations,
            deposits=[],
            voluntary_exits=[],
        )
        if op_pool is not None:
            ps, asl, exits, changes = op_pool.get_slashings_and_exits(state, types)
            body_kwargs.update(
                proposer_slashings=ps, attester_slashings=asl, voluntary_exits=exits
            )
            if fork >= ForkName.capella:
                body_kwargs["bls_to_execution_changes"] = changes
        if fork >= ForkName.altair:
            body_kwargs["sync_aggregate"] = types.SyncAggregate.make(
                sync_committee_bits=[False] * spec.preset.SYNC_COMMITTEE_SIZE,
                sync_committee_signature=bls.INFINITY_SIGNATURE_BYTES,
            )
        if fork >= ForkName.bellatrix:
            body_kwargs["execution_payload"] = types.ExecutionPayload.default()
        if fork >= ForkName.capella and "bls_to_execution_changes" not in body_kwargs:
            body_kwargs["bls_to_execution_changes"] = []
        if fork >= ForkName.deneb:
            body_kwargs["blob_kzg_commitments"] = []

        block = types.BeaconBlock.make(
            slot=slot,
            proposer_index=proposer,
            parent_root=self.head_root,
            state_root=b"\x00" * 32,
            body=types.BeaconBlockBody.make(**body_kwargs),
        )
        trial = types.SignedBeaconBlock.make(message=block, signature=b"\x00" * 96)
        post = self._state_for_block(self.head_root, slot)
        per_block_processing(
            post, trial, spec, types,
            strategy=SignatureStrategy.NO_VERIFICATION, verify_block_root=True,
        )
        return block.copy_with(state_root=types.BeaconState.hash_tree_root(post))

    def apply_attestation_to_fork_choice(self, att, attesting_indices):
        self.fork_choice.on_attestation(
            att.data.slot,
            attesting_indices,
            bytes(att.data.beacon_block_root),
            att.data.target.epoch,
        )
