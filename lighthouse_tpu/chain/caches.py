"""Beacon-chain auxiliary caches (the §2.2 set the reference treats as
first-class components).

- ObservedSlashable — per-(proposer, slot) and per-(attester, target) record
  of WHAT was signed, so a second, different message is recognized as an
  equivocation and turned into slasher feed + gossip evidence
  (/root/reference/beacon_node/beacon_chain/src/observed_slashable.rs,
  observed_operations.rs). The plain observed_* dedup sets only answer
  "seen before?" — this answers "seen a CONFLICTING one?".
- BlockTimesCache — gossip-arrival/import/head timestamps per root, the
  observability + re-org-decision feed (block_times_cache.rs).
- EarlyAttesterCache — serve attestation data for the block imported this
  slot, populated only when fork choice selected it as head
  (early_attester_cache.rs).
- AttesterCache — the minimal (justified, target) data needed to serve
  attestation_data without holding a full state (attester_cache.rs).
- StateLRU — bounded promise-style state cache with insertion-order
  eviction (store/state_cache.rs analog for the in-chain map).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field


class ObservedSlashable:
    """Record signed roots; return the CONFLICTING prior root on equivocation."""

    def __init__(self, capacity: int = 8192):
        self._proposals: OrderedDict[tuple[int, int], bytes] = OrderedDict()
        self._attestations: OrderedDict[tuple[int, int], bytes] = OrderedDict()
        self.capacity = capacity

    def _put(self, store: OrderedDict, key, root: bytes):
        store[key] = root
        while len(store) > self.capacity:
            store.popitem(last=False)

    def peek_proposal(self, proposer: int, slot: int, block_root: bytes) -> bytes | None:
        """Prior DIFFERENT root for (proposer, slot), WITHOUT recording —
        equivocation must only be judged against VERIFIED proposals, and a
        proposal must only be recorded after its signature checks out
        (otherwise garbage-signed spam poisons the cache and gets the
        honest block rejected)."""
        prev = self._proposals.get((proposer, slot))
        return prev if prev is not None and prev != block_root else None

    def record_proposal(self, proposer: int, slot: int, block_root: bytes) -> None:
        key = (proposer, slot)
        if key not in self._proposals:
            self._put(self._proposals, key, block_root)

    def observe_proposal(self, proposer: int, slot: int, block_root: bytes) -> bytes | None:
        """peek + record in one step (callers that verify first)."""
        prior = self.peek_proposal(proposer, slot, block_root)
        if prior is None:
            self.record_proposal(proposer, slot, block_root)
        return prior

    def observe_attestation(self, validator: int, target_epoch: int, data_root: bytes) -> bytes | None:
        key = (validator, target_epoch)
        prev = self._attestations.get(key)
        if prev is None:
            self._put(self._attestations, key, data_root)
            return None
        return prev if prev != data_root else None

    def prune(self, finalized_epoch: int, slots_per_epoch: int) -> None:
        cut = finalized_epoch * slots_per_epoch
        for k in [k for k in self._proposals if k[1] < cut]:
            del self._proposals[k]
        for k in [k for k in self._attestations if k[1] < finalized_epoch]:
            del self._attestations[k]


@dataclass
class BlockTimes:
    seen_at: float | None = None          # gossip arrival
    imported_at: float | None = None
    became_head_at: float | None = None


class BlockTimesCache:
    """Arrival/import/head latency per block root (block_times_cache.rs)."""

    def __init__(self, capacity: int = 128, now_fn=time.monotonic):
        self._map: OrderedDict[bytes, BlockTimes] = OrderedDict()
        self.capacity = capacity
        self._now = now_fn

    def _entry(self, root: bytes) -> BlockTimes:
        e = self._map.get(root)
        if e is None:
            e = BlockTimes()
            self._map[root] = e
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)
        return e

    def observed(self, root: bytes) -> None:
        e = self._entry(root)
        if e.seen_at is None:
            e.seen_at = self._now()

    def imported(self, root: bytes) -> None:
        self._entry(root).imported_at = self._now()

    def became_head(self, root: bytes) -> None:
        self._entry(root).became_head_at = self._now()

    def import_delay(self, root: bytes) -> float | None:
        e = self._map.get(root)
        if e is None or e.seen_at is None or e.imported_at is None:
            return None
        return e.imported_at - e.seen_at

    def head_delay(self, root: bytes) -> float | None:
        e = self._map.get(root)
        if e is None or e.seen_at is None or e.became_head_at is None:
            return None
        return e.became_head_at - e.seen_at


@dataclass
class AttesterData:
    """Everything needed to serve attestation_data for one (slot, index)."""

    beacon_block_root: bytes
    parent_root: bytes
    source_epoch: int
    source_root: bytes
    target_epoch: int
    target_root: bytes


class EarlyAttesterCache:
    """Serve attestations for the block imported THIS slot
    (early_attester_cache.rs). Populated only when fork choice selected the
    imported block as head (beacon_chain.rs `new_head_root == block_root`),
    and served only while that block is still the head — an imported fork
    block that LOST fork choice must not hijack attestation data."""

    def __init__(self):
        self._item: tuple[int, AttesterData] | None = None   # (slot, data)

    def add(self, slot: int, data: AttesterData) -> None:
        self._item = (slot, data)

    def try_attest(self, slot: int, head_root: bytes) -> AttesterData | None:
        if self._item is None or self._item[0] != slot:
            return None
        data = self._item[1]
        if data.beacon_block_root == head_root:
            return data
        return None


class AttesterCache:
    """(epoch, decision_root) -> (source checkpoint, target root) — attest
    without holding the full state (attester_cache.rs)."""

    def __init__(self, capacity: int = 16):
        self._map: OrderedDict[tuple[int, bytes], tuple] = OrderedDict()
        self.capacity = capacity

    def put(self, epoch: int, decision_root: bytes, source, target_root: bytes) -> None:
        self._map[(epoch, decision_root)] = (source, target_root)
        while len(self._map) > self.capacity:
            self._map.popitem(last=False)

    def get(self, epoch: int, decision_root: bytes):
        return self._map.get((epoch, decision_root))


class StateLRU:
    """Bounded state map with LRU eviction + per-root build promises so
    concurrent requests for the same state compute it once
    (shuffling_cache.rs promise idiom applied to states)."""

    def __init__(self, capacity: int = 8):
        self.capacity = capacity
        self._map: OrderedDict[bytes, object] = OrderedDict()
        self._building: dict[bytes, threading.Event] = {}
        self._lock = threading.Lock()

    def __contains__(self, root: bytes) -> bool:
        with self._lock:
            return root in self._map

    def get(self, root: bytes):
        with self._lock:
            st = self._map.get(root)
            if st is not None:
                self._map.move_to_end(root)
            return st

    def __getitem__(self, root: bytes):
        st = self.get(root)
        if st is None:
            raise KeyError(root.hex()[:16])
        return st

    def __setitem__(self, root: bytes, state) -> None:
        with self._lock:
            self._map[root] = state
            self._map.move_to_end(root)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def get_or_build(self, root: bytes, build):
        """Return the cached state or build it ONCE across threads."""
        while True:
            with self._lock:
                st = self._map.get(root)
                if st is not None:
                    self._map.move_to_end(root)
                    return st
                ev = self._building.get(root)
                if ev is None:
                    ev = threading.Event()
                    self._building[root] = ev
                    break
            ev.wait()
        try:
            st = build()
            self[root] = st
            return st
        finally:
            with self._lock:
                ev2 = self._building.pop(root, None)
            if ev2 is not None:
                ev2.set()

    def values(self):
        with self._lock:
            return list(self._map.values())

    def items(self):
        with self._lock:
            return list(self._map.items())

    def __len__(self):
        with self._lock:
            return len(self._map)
