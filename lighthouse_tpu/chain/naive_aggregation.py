"""Naive aggregation pools: self-built aggregates from gossip singles.

Parity surface: /root/reference/beacon_node/beacon_chain/src/
naive_aggregation_pool.rs — the BN aggregates every verified single-bit
attestation (and sync-committee message) it sees on its subnets, so that
when a local validator turns out to be an aggregator it can serve
`aggregate_attestation` / `sync_committee_contribution` without having seen
someone else's aggregate. Aggregation here is signature point addition over
the active BLS backend's G2 math; slots are pruned once stale."""

from __future__ import annotations

from collections import defaultdict

from ..crypto import bls
from ..crypto.bls381 import curve as cv

SLOT_RETENTION = 3


def _sig_point(sig_bytes: bytes):
    return bls.Signature.deserialize(bytes(sig_bytes))


def _agg_bytes(points) -> bytes:
    acc = None
    fake = bls.get_backend().name == "fake"
    if fake:
        # fake backend: signatures aren't points; carry the first one through
        return points[0].serialize() if points else bls.INFINITY_SIGNATURE_BYTES
    for s in points:
        acc = cv.g2_add(acc, s.point)
    return bls.Signature(acc).serialize()


class NaiveAttestationPool:
    """(data_root, committee) -> aggregated bits + signature per slot.

    Electra (EIP-7549) note: all committees of a slot share ONE
    AttestationData (index=0), so the data root alone cannot bucket — the
    committee index (from committee_bits) is part of the key, and served
    aggregates carry the committee's bit set."""

    def __init__(self, spec):
        self.spec = spec
        # slot -> (data_root, committee|None) -> (data, cb, bits, [sigs])
        self._by_slot: dict[int, dict] = defaultdict(dict)

    @staticmethod
    def _committee_of(att):
        cb = getattr(att, "committee_bits", None)
        if cb is None:
            return None, None
        set_bits = [i for i, b in enumerate(cb) if b]
        if len(set_bits) != 1:
            raise ValueError("expected exactly one committee bit")
        return set_bits[0], tuple(cb)

    def insert(self, att, types) -> bool:
        """Insert a verified single attestation; returns True if it added
        new bits."""
        slot = int(att.data.slot)
        cidx, cb = self._committee_of(att)
        key = (types.AttestationData.hash_tree_root(att.data), cidx)
        bucket = self._by_slot[slot].get(key)
        bits = list(att.aggregation_bits)
        sig = _sig_point(att.signature)
        if bucket is None:
            self._by_slot[slot][key] = (att.data, cb, bits, [sig])
            return True
        _data, _cb, cur, sigs = bucket
        new = [b and not c for b, c in zip(bits, cur)]
        if not any(new):
            return False
        merged = [b or c for b, c in zip(bits, cur)]
        self._by_slot[slot][key] = (_data, _cb, merged, sigs + [sig])
        return True

    def get_aggregate(self, slot: int, data_root: bytes, types,
                      committee_index: int | None = None):
        """Best aggregate for (slot, data root[, committee]). Pre-electra
        callers omit committee_index; electra aggregation duties supply it
        (the v2 aggregate_attestation API carries it)."""
        slot_map = self._by_slot.get(slot, {})
        bucket = slot_map.get((data_root, committee_index))
        if bucket is None and committee_index is None:
            # electra entries under an unspecified committee: serve the
            # first matching data root
            for (root, _cidx), b in slot_map.items():
                if root == data_root:
                    bucket = b
                    break
        if bucket is None:
            return None
        data, cb, bits, sigs = bucket
        kwargs = dict(
            aggregation_bits=bits, data=data, signature=_agg_bytes(sigs)
        )
        if cb is not None:
            kwargs["committee_bits"] = list(cb)
        return types.Attestation.make(**kwargs)

    def prune(self, current_slot: int) -> None:
        for s in list(self._by_slot):
            if s + SLOT_RETENTION < current_slot:
                del self._by_slot[s]


class NaiveSyncContributionPool:
    """(slot, root, subcommittee) -> aggregated sync contribution."""

    def __init__(self, spec):
        self.spec = spec
        self._by_slot: dict[int, dict] = defaultdict(dict)

    def insert(self, slot: int, beacon_block_root: bytes, subcommittee_index: int,
               index_in_subcommittee: int, signature_bytes: bytes) -> bool:
        key = (bytes(beacon_block_root), subcommittee_index)
        size = (
            self.spec.preset.SYNC_COMMITTEE_SIZE
            // self.spec.sync_committee_subnet_count
        )
        bucket = self._by_slot[slot].get(key)
        sig = _sig_point(signature_bytes)
        if bucket is None:
            bits = [False] * size
            bits[index_in_subcommittee] = True
            self._by_slot[slot][key] = (bits, [sig])
            return True
        bits, sigs = bucket
        if bits[index_in_subcommittee]:
            return False
        bits[index_in_subcommittee] = True
        sigs.append(sig)
        return True

    def get_contribution(self, slot: int, beacon_block_root: bytes,
                         subcommittee_index: int, types):
        bucket = self._by_slot.get(slot, {}).get(
            (bytes(beacon_block_root), subcommittee_index)
        )
        if bucket is None:
            return None
        bits, sigs = bucket
        return types.SyncCommitteeContribution.make(
            slot=slot,
            beacon_block_root=beacon_block_root,
            subcommittee_index=subcommittee_index,
            aggregation_bits=bits,
            signature=_agg_bytes(sigs),
        )

    def get_sync_aggregate(self, slot: int, beacon_block_root: bytes, types):
        """Merge every subcommittee's contribution for (slot, root) into a
        block-ready SyncAggregate (operation_pool get_sync_aggregate analog,
        /root/reference/beacon_node/operation_pool/src/lib.rs:158). Returns
        None when no contribution matches."""
        size = self.spec.preset.SYNC_COMMITTEE_SIZE
        n_sub = self.spec.sync_committee_subnet_count
        sub_size = size // n_sub
        bits = [False] * size
        points = []
        found = False
        for sub in range(n_sub):
            bucket = self._by_slot.get(slot, {}).get((bytes(beacon_block_root), sub))
            if bucket is None:
                continue
            found = True
            sub_bits, sub_sigs = bucket
            for i, bit in enumerate(sub_bits):
                if bit:
                    bits[sub * sub_size + i] = True
            points.extend(sub_sigs)
        if not found:
            return None
        return types.SyncAggregate.make(
            sync_committee_bits=bits, sync_committee_signature=_agg_bytes(points)
        )

    def prune(self, current_slot: int) -> None:
        for s in list(self._by_slot):
            if s + SLOT_RETENTION < current_slot:
                del self._by_slot[s]
