"""CapacityScheduler — closed-loop, deadline-aware continuous batching.

Until PR 14 batch formation was a fixed-cap `while q and len(items) < cap`
loop inside `BeaconProcessor._pop_locked` and every serving knob was
static: autotune planned once at startup, admission shed at fixed
watermarks, and the hybrid router's urgent threshold never moved. Yet the
feedback signals for a real control loop all exist — queue-wait and
verify-latency quantiles per slot (observability/slo.py), breaker state
(qos/breaker.py), deadline-hit ratios and burn rates over the 5/32-slot
windows, and the plan-listener actuator (autotune/runtime.py) that lets
knobs retune mid-run. This module closes the loop:

  decision   Every pop of a batchable queue asks `decide()`: dispatch a
             batch NOW, or hold and let it coalesce wider. Dispatch when
             the queue has a full batch (`cap_full`), when the slot budget
             says waiting would finish the batch too late (`deadline` —
             estimated verify time vs the seconds left in the slot), or
             when the device window is idle (`idle` — serving immediately
             is free). Hold (`coalesce`) only while the device is busy and
             there is budget slack: exactly vLLM-style continuous
             batching, "dispatch when the slot budget says so, not when a
             fixed window fills". A harness-installed budget gate
             (`budget` — loadgen/capacity.py's device-time ledger) can
             hold work across slot boundaries deterministically.

  model      The scheduler learns the device's batch cost online: every
             resolved batch feeds `observe_verify(kind, n, secs)` and a
             least-squares fit over PADDED batch sizes (the jaxbls
             padding-bucket discipline: a batch of n sets pays for
             pow2ceil(n) lanes) yields `secs(n) = a + b * pow2ceil(n)`.
             Padding-aware cost is what makes cap choice non-trivial: a
             1100-set batch pays 2048 lanes, two 512+128 batches pay 640.

  retune     Each closed SLO slot report (SlotAccountant close listener)
             re-derives the knobs: batch caps pick the cheapest cap on a
             pow2 ladder for the EWMA'd demand under the fitted cost
             model; admission watermarks tighten while the 5-slot burn
             rate is over 1x (bulk yields earlier so timely work keeps
             the pipeline) and relax back when it recovers; the urgent
             threshold becomes the largest batch the model serves within
             the urgent latency budget. Explicit pins always win
             (`BeaconProcessorConfig(max_attestation_batch=N)` /
             `bn --max-attestation-batch` set the `_explicit` flags, the
             PR 10 "explicitness is self-describing" rule), and a breaker
             that is not closed freezes cap retuning — host-fallback
             latencies must not steer device batch sizing.

  actuation  Per-instance knobs (caps, watermarks) apply directly. The
             process-global knobs (urgent threshold, and the caps as seen
             by other plan consumers) are published through the EXISTING
             autotune plan-listener contract: `publish_plan=True` (the
             live bn node path) installs a `scheduler:`-sourced Plan via
             `runtime.install_runtime_plan`, so `HybridBackend._apply_plan`,
             the jaxbls dispatcher and `BeaconProcessor._on_plan_installed`
             all pick the change up live — and env/CLI pins keep winning
             inside each consumer's own precedence resolution. A plan
             installed by someone ELSE (a real `autotune calibrate`
             profile) re-bases this controller instead of being fought.

Observability: current caps in `scheduler_batch_cap{kind}`, every
decision in `scheduler_decisions_total{kind,reason}`, every knob move in
`scheduler_retunes_total{knob,direction}` plus a `scheduler_retune`
flight-recorder event, live watermarks in
`scheduler_admission_watermark{klass}`. `stats()` returns the
deterministic mirror loadgen reports embed.
"""

from __future__ import annotations

import threading
from collections import deque

from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY

log = get_logger("capacity_scheduler")

# ------------------------------------------------------------------ metrics
# labeled families (scripts/lint_metrics.py enforces it): an unlabeled
# scheduler_* aggregate could not answer "which kind's cap moved, which
# decision held the queue, which knob retuned"

_BATCH_CAP = REGISTRY.gauge_vec(
    "scheduler_batch_cap",
    "live batch cap chosen by the capacity scheduler, by work kind",
    ("kind",),
)
_DECISIONS = REGISTRY.counter_vec(
    "scheduler_decisions_total",
    "batch-formation decisions, by work kind and reason (cap_full / "
    "deadline / idle / drain / coalesce / budget)",
    ("kind", "reason"),
)
_RETUNES = REGISTRY.counter_vec(
    "scheduler_retunes_total",
    "control-loop knob moves, by knob (att_cap / agg_cap / bulk_watermark "
    "/ backfill_watermark / urgent_max_sets) and direction (up / down)",
    ("knob", "direction"),
)
_WATERMARK = REGISTRY.gauge_vec(
    "scheduler_admission_watermark",
    "live admission watermark fraction, by priority class",
    ("klass",),
)

# the pow2 ladder cap retuning chooses from (jaxbls MIN_SETS floor to the
# planner's MAX_BATCH_CAP ceiling — the same clamp autotune plans under)
CAP_LADDER = (64, 128, 256, 512, 1024, 2048, 4096)
MIN_CAP, MAX_CAP = CAP_LADDER[0], CAP_LADDER[-1]
# observation window for the cost fit; old shapes age out as traffic moves
MODEL_WINDOW = 64
# the fit needs this many observations over >= 2 distinct padded sizes
MODEL_MIN_SAMPLES = 4
# demand EWMA smoothing (per closed slot)
DEMAND_ALPHA = 0.5
# dispatch when the estimated batch time exceeds this fraction of the
# seconds remaining in the current slot — waiting longer would finish the
# batch too late to matter for this slot's deadline-hit ratio
DEADLINE_SLACK = 0.8
# a cap's own batch duration must fit inside this fraction of the slot or
# a mid-slot dispatch finishes past the boundary — the latency half of the
# continuous-batching tradeoff (throughput wants wide batches, the slot
# deadline wants short ones); caps whose single-batch cost exceeds it are
# excluded from the ladder choice while any cap qualifies
CAP_LATENCY_FRACTION = 0.5
# a cap move needs at least this relative predicted-cost improvement over
# the incumbent: demand jitter around a cost-tie boundary (where two caps
# serve within a few percent of each other) must not flap the knob
CAP_IMPROVEMENT_MIN = 0.05
# watermark control: tighten while short-window burn >= 1x (error budget
# spending faster than sustainable), relax when it falls back under
WATERMARK_TIGHTEN_BURN = 1.0
WATERMARK_RELAX_BURN = 0.5
WATERMARK_STEP = 0.1
WATERMARK_FLOOR = 0.25
# urgent threshold: largest batch the fitted model serves within this
# budget rides the urgent lane (clamped to the hybrid router's sane range)
URGENT_BUDGET_MS = 25.0
URGENT_CLAMP = (1, 64)


def pow2ceil(n: int) -> int:
    """Padded lane count of an n-set batch (the jaxbls padding-bucket
    discipline: device programs compile per pow2 bucket)."""
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


class Decision:
    """One batch-formation verdict."""

    __slots__ = ("dispatch", "cap", "reason")

    def __init__(self, dispatch: bool, cap: int, reason: str):
        self.dispatch = dispatch
        self.cap = cap
        self.reason = reason


class CapacityScheduler:
    """Owns batch formation + the closed-loop knob retuning for one
    BeaconProcessor. Construction is cheap and import-light; the autotune
    and flight-recorder hookups degrade silently-but-loudly (structured
    warns) when those subsystems are broken."""

    def __init__(self, config, admission=None, *, publish_plan: bool = False,
                 retune_enabled: bool = True):
        self.config = config
        self.admission = admission
        self.publish_plan = publish_plan
        self.retune_enabled = retune_enabled
        self._lock = threading.Lock()
        # live caps start from the config's resolution (installed plan or
        # defaults); explicit ctor/CLI caps are PINNED — the controller
        # never moves them (explicitness is self-describing)
        self.caps = {
            "gossip_attestation": int(config.max_attestation_batch),
            "gossip_aggregate": int(config.max_aggregate_batch),
        }
        self.pinned = {
            "gossip_attestation": bool(
                getattr(config, "max_attestation_batch_explicit", False)
            ),
            "gossip_aggregate": bool(
                getattr(config, "max_aggregate_batch_explicit", False)
            ),
        }
        # watermark bases come from the admission controller's configured
        # values; the live values move between [floor, base]
        self._wm_base = (
            (admission.bulk_watermark, admission.backfill_watermark)
            if admission is not None else (0.75, 0.5)
        )
        self.urgent_max_sets = None      # None until the model justifies one
        # cost model: (padded_n, secs) ring + the current (a, b) fit
        self._obs: deque = deque(maxlen=MODEL_WINDOW)
        self._fit: tuple | None = None   # (a, b) or None while cold
        # per-kind demand EWMA (admitted per slot), fed at slot close
        self._demand: dict[str, float] = {}
        # per-kind queue high-water observed by decide() since the last
        # retune tick: the BACKLOG signal. Cap choice targets
        # max(arrival EWMA, high-water) — a draining queue must be served
        # at backlog-sized batches, not at the (already falling) arrival
        # rate, or the controller shrinks caps exactly when the queue
        # most needs wide ones
        self._depth_hw: dict[str, int] = {}
        # deterministic mirrors of the Prometheus families (loadgen
        # reports embed these; seeds, not scrapes, must explain them)
        self.decisions: dict[tuple, int] = {}
        self.retunes: list[dict] = []
        self._retunes_bound = 256
        self.slots_seen = 0
        self.last_retune_slot: int | None = None
        # optional harness hook (loadgen/capacity.py): a callable
        # (kind_name, n) -> bool consulted FIRST; False holds the batch
        # even under force — the deterministic device-time ledger
        self._budget_gate = None
        self._slo_ref = None
        self._m_caps = {
            k: _BATCH_CAP.labels(k) for k in self.caps
        }
        for k, v in self.caps.items():
            self._m_caps[k].set(v)
        _WATERMARK.labels("bulk").set(self._wm_base[0])
        _WATERMARK.labels("backfill").set(self._wm_base[1])

    # ------------------------------------------------------------- wiring

    def bind_slo(self, accountant) -> None:
        """Subscribe to the accountant's slot closes (the control-loop
        tick). Re-binding (loadgen swaps the processor's accountant after
        construction) UNSUBSCRIBES from the old one first: the scheduler
        outlives the swap, so its weakref on the old accountant stays
        live — without the explicit removal a node-hosted processor
        rebound to a private accountant would tick on BOTH, feeding the
        demand EWMA another workload's admitted counts. Re-binding the
        SAME accountant is a no-op (a duplicate subscription would tick
        the loop twice per slot)."""
        if accountant is self._slo_ref:
            return
        old = self._slo_ref
        if old is not None:
            try:
                old.remove_close_listener(self.on_slot_close)
            except Exception:
                pass  # old accountant gone/ancient: nothing to drop
        self._slo_ref = accountant
        try:
            accountant.add_close_listener(self.on_slot_close)
        except Exception as e:  # pragma: no cover - accountant too old
            log.warn("slo close-listener hookup failed; retunes disabled",
                     error=f"{type(e).__name__}: {e}")

    def set_budget_gate(self, gate) -> None:
        self._budget_gate = gate

    def on_plan_installed(self, plan) -> None:
        """Autotune plan listener: a profile installed by someone else
        re-bases the unpinned caps; our own scheduler-sourced installs
        are ignored (no feedback loop)."""
        if plan is not None and str(getattr(plan, "source", "")).startswith(
            "scheduler:"
        ):
            return
        with self._lock:
            for kind, attr in (
                ("gossip_attestation", "max_attestation_batch"),
                ("gossip_aggregate", "max_aggregate_batch"),
            ):
                if self.pinned[kind]:
                    continue
                base = getattr(plan, attr, None) if plan is not None else None
                if base is None:
                    base = getattr(self.config, attr)
                self.caps[kind] = int(base)
                self._m_caps[kind].set(self.caps[kind])

    # ------------------------------------------------------------ decision

    def _count(self, kind: str, reason: str) -> None:
        # the mirror dict is read under the lock by stats() (the pipeline
        # ops endpoint): a first-ever key inserted lock-free would grow
        # the dict mid-iteration there
        with self._lock:
            self.decisions[(kind, reason)] = self.decisions.get(
                (kind, reason), 0
            ) + 1
        _DECISIONS.labels(kind, reason).inc()

    def _slot_slack(self) -> float | None:
        """Seconds left in the current slot, or None without a clock —
        read through the admission controller's slot clock, so loadgen's
        ManualSlotClock makes the deadline decision fully deterministic."""
        adm = self.admission
        clock = getattr(adm, "slot_clock", None) if adm is not None else None
        if clock is None:
            return None
        try:
            if clock.now() is None:
                return None
            return float(clock.duration_to_next_slot())
        except Exception:
            return None

    def est_secs(self, n: int) -> float | None:
        """Fitted batch verify time for n sets (padded), or None cold."""
        fit = self._fit
        if fit is None:
            return None
        a, b = fit
        return a + b * pow2ceil(n)

    def decide(self, kind, depth: int, *, inflight: int = 0,
               max_inflight: int = 1, force: bool = False) -> Decision:
        """The per-pop dispatch verdict for one batchable queue. Called
        under the processor lock: O(1), no blocking, no re-entry."""
        name = getattr(kind, "name", str(kind))
        with self._lock:
            cap = self.caps.get(name, MAX_CAP)
            gate = self._budget_gate
            if depth > self._depth_hw.get(name, 0):
                self._depth_hw[name] = depth
        n = min(depth, cap)
        if gate is not None and not gate(name, n):
            # the harness ledger says this batch does not fit the slot's
            # device budget: hold even under force — the epilogue clears
            # the gate when the run truly drains
            self._count(name, "budget")
            return Decision(False, cap, "budget")
        if depth >= cap:
            self._count(name, "cap_full")
            return Decision(True, cap, "cap_full")
        if force:
            self._count(name, "drain")
            return Decision(True, cap, "drain")
        slack = self._slot_slack()
        if slack is not None:
            est = self.est_secs(n)
            if est is not None and est >= slack * DEADLINE_SLACK:
                # waiting any longer finishes this batch past the slot
                # budget: go now with what we have
                self._count(name, "deadline")
                return Decision(True, cap, "deadline")
        if inflight < max_inflight:
            # a free device window slot: dispatching now is free, holding
            # would only add latency
            self._count(name, "idle")
            return Decision(True, cap, "idle")
        # device busy and budget slack remains: let the batch widen
        self._count(name, "coalesce")
        return Decision(False, cap, "coalesce")

    # --------------------------------------------------------------- model

    def observe_verify(self, kind, n_sets: int, secs: float) -> None:
        """One resolved batch's measured verify time feeds the cost fit."""
        if n_sets <= 0 or secs < 0:
            return
        with self._lock:
            self._obs.append((pow2ceil(n_sets), float(secs)))
            self._refit_locked()

    def _refit_locked(self) -> None:
        obs = self._obs
        if len(obs) < MODEL_MIN_SAMPLES:
            return
        xs = [o[0] for o in obs]
        if len(set(xs)) < 2:
            return                       # one padded size fits no line
        ys = [o[1] for o in obs]
        n = float(len(obs))
        mx = sum(xs) / n
        my = sum(ys) / n
        sxx = sum((x - mx) ** 2 for x in xs)
        if sxx <= 0:
            return
        b = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / sxx
        a = my - b * mx
        if b <= 0:
            return                       # nonsensical fit: keep the old one
        self._fit = (max(0.0, a), b)

    def _model_locked(self) -> dict:
        fit = self._fit
        return {
            "samples": len(self._obs),
            "base_secs": None if fit is None else round(fit[0], 6),
            "per_lane_secs": None if fit is None else round(fit[1], 9),
        }

    def model(self) -> dict:
        with self._lock:
            return self._model_locked()

    # -------------------------------------------------------------- retune

    def _best_cap_locked(self, demand: float,
                         latency_budget: float | None) -> int | None:
        """Cheapest ladder cap for one slot's demand under the fitted
        padded-cost model: minimize sum of per-batch base + padded-lane
        time over the batches a cap of C forms for D sets — subject to
        the LATENCY constraint that one full batch completes within
        `latency_budget` seconds (a cap whose own duration overruns the
        slot marks everything it carries late no matter how efficient
        its lanes are). Ties break DOWN (the ladder is walked ascending
        and only a strictly cheaper cap wins): when demand fits one batch
        under several caps the costs tie exactly, and the smallest tying
        cap is the stable choice — a jittering demand curve must not flap
        the cap between equivalent values."""
        fit = self._fit
        if fit is None or demand <= 0:
            return None
        a, b = fit
        best, best_cost = None, None
        d = max(1, int(round(demand)))
        for cap in CAP_LADDER:
            if (
                latency_budget is not None
                and best is not None
                and a + b * pow2ceil(cap) > latency_budget
            ):
                break    # over the latency budget; a qualifying cap exists
            cost = self._cap_cost_locked(cap, d)
            if best_cost is None or cost < best_cost - 1e-12:
                best, best_cost = cap, cost
        return best

    def _cap_cost_locked(self, cap: int, d: int) -> float:
        """Predicted device time to serve d sets at cap (padded lanes +
        per-batch base), under the current fit (caller checked it)."""
        a, b = self._fit
        full, rem = divmod(d, cap)
        batches = full + (1 if rem else 0)
        lanes = full * pow2ceil(cap) + (pow2ceil(rem) if rem else 0)
        return batches * a + lanes * b

    def _latency_budget(self) -> float | None:
        """CAP_LATENCY_FRACTION of the slot length, or None clockless."""
        adm = self.admission
        clock = getattr(adm, "slot_clock", None) if adm is not None else None
        sps = getattr(clock, "seconds_per_slot", None)
        if not sps:
            return None
        return float(sps) * CAP_LATENCY_FRACTION

    def _record_retune_locked(self, slot, knob, old, new, reason) -> None:
        direction = "up" if new > old else "down"
        _RETUNES.labels(knob, direction).inc()
        event = {"slot": slot, "knob": knob, "from": old, "to": new,
                 "reason": reason}
        self.retunes.append(event)
        if len(self.retunes) > self._retunes_bound:
            del self.retunes[: len(self.retunes) - self._retunes_bound]
        self.last_retune_slot = slot
        try:
            from ..observability.flight_recorder import RECORDER

            RECORDER.record("scheduler_retune", **event)
        except Exception:
            pass  # diagnostics must never break the control loop
        log.info("scheduler retune", **{k: str(v) for k, v in event.items()})

    def _breaker_closed(self) -> bool:
        """True unless the BLS device breaker is open: cap retuning must
        not learn from host-fallback latencies, and a wedged device is
        the breaker's problem, not a batch-sizing one. Scoped to the
        `bls_device` breaker — the path these caps feed; an open
        tree-hash or harness breaker says nothing about BLS batch
        sizing (the health endpoint scopes the same way, slo.health)."""
        try:
            from ..observability.flight_recorder import RECORDER

            return not RECORDER.open_breakers(prefix="bls_device")
        except Exception:
            return True

    def on_slot_close(self, report) -> None:
        """The control-loop tick: one closed SlotReport re-derives every
        unpinned knob. Deterministic — everything it reads (report
        counters, demand EWMA, the cost fit) is a pure function of the
        fed observations."""
        acct = self._slo_ref
        self.slots_seen += 1
        if not self.retune_enabled:
            return
        slot = getattr(report, "slot", 0)
        admitted = getattr(report, "admitted", {}) or {}
        retunes = []
        with self._lock:
            for kind in self.caps:
                d = float(admitted.get(kind, 0))
                if d <= 0:
                    # a traffic-free slot is no demand EVIDENCE, just an
                    # idle tick: decaying the estimate toward zero would
                    # shrink caps exactly when a quiet node should keep
                    # its learned sizing for the next burst
                    continue
                prev = self._demand.get(kind)
                self._demand[kind] = (
                    d if prev is None
                    else DEMAND_ALPHA * d + (1 - DEMAND_ALPHA) * prev
                )
        # ---- batch caps: model-predictive choice over the pow2 ladder
        if self._breaker_closed():
            budget = self._latency_budget()
            with self._lock:
                for kind, knob in (
                    ("gossip_attestation", "att_cap"),
                    ("gossip_aggregate", "agg_cap"),
                ):
                    hw = self._depth_hw.pop(kind, 0)
                    if self.pinned[kind]:
                        continue
                    if float(admitted.get(kind, 0)) <= 0 and hw <= 0:
                        continue     # no evidence this slot: hold the cap
                    target = max(self._demand.get(kind, 0.0), float(hw))
                    best = self._best_cap_locked(target, budget)
                    if best is None or best == self.caps[kind]:
                        continue
                    # hysteresis: only move for a real predicted win — a
                    # few-percent tie must not flap the knob with jitter
                    d_int = max(1, int(round(target)))
                    cur_cost = self._cap_cost_locked(self.caps[kind], d_int)
                    new_cost = self._cap_cost_locked(best, d_int)
                    lat_ok = budget is None or (
                        self._fit[0]
                        + self._fit[1] * pow2ceil(self.caps[kind])
                    ) <= budget
                    if lat_ok and new_cost > cur_cost * (
                        1.0 - CAP_IMPROVEMENT_MIN
                    ):
                        continue
                    retunes.append(
                        (slot, knob, self.caps[kind], best, "demand_model")
                    )
                    self.caps[kind] = best
                    self._m_caps[kind].set(best)
        # ---- admission watermarks: burn-driven tighten/relax
        adm = self.admission
        if adm is not None and acct is not None:
            try:
                burn = acct.window_summary("slot_5")["burn_rate"]
            except Exception:
                burn = 0.0
            bulk_base, backfill_base = self._wm_base
            bulk, backfill = adm.bulk_watermark, adm.backfill_watermark
            if burn >= WATERMARK_TIGHTEN_BURN:
                new_bulk = max(WATERMARK_FLOOR, bulk - WATERMARK_STEP)
                new_backfill = max(
                    WATERMARK_FLOOR, backfill - WATERMARK_STEP
                )
            elif burn < WATERMARK_RELAX_BURN:
                new_bulk = min(bulk_base, bulk + WATERMARK_STEP / 2)
                new_backfill = min(
                    backfill_base, backfill + WATERMARK_STEP / 2
                )
            else:
                new_bulk, new_backfill = bulk, backfill
            if abs(new_bulk - bulk) > 1e-9:
                retunes.append(
                    (slot, "bulk_watermark", round(bulk, 3),
                     round(new_bulk, 3), f"burn_{burn}")
                )
                adm.bulk_watermark = new_bulk
                _WATERMARK.labels("bulk").set(new_bulk)
            if abs(new_backfill - backfill) > 1e-9:
                retunes.append(
                    (slot, "backfill_watermark", round(backfill, 3),
                     round(new_backfill, 3), f"burn_{burn}")
                )
                adm.backfill_watermark = new_backfill
                _WATERMARK.labels("backfill").set(new_backfill)
        # ---- urgent threshold: largest batch inside the urgent budget
        with self._lock:
            fit = self._fit
            if fit is not None:
                a, b = fit
                budget = URGENT_BUDGET_MS / 1e3
                lo, hi = URGENT_CLAMP
                n = lo
                while n < hi and a + b * pow2ceil(n * 2) <= budget:
                    n *= 2
                if a + b * pow2ceil(lo) > budget:
                    n = lo
                if self.urgent_max_sets != n:
                    retunes.append(
                        (slot, "urgent_max_sets",
                         self.urgent_max_sets or 0, n, "latency_model")
                    )
                    self.urgent_max_sets = n
        with self._lock:
            for r in retunes:
                self._record_retune_locked(*r)
        if retunes and self.publish_plan:
            self._publish_plan()

    def _publish_plan(self) -> None:
        """Actuate the global knobs through the autotune plan-listener
        contract: consumers (hybrid router, jaxbls dispatcher, the
        processor's own max_inflight listener) re-resolve with their env/
        CLI layers still winning. Never raises into the control loop."""
        try:
            from dataclasses import replace

            from ..autotune import runtime
            from ..autotune.planner import DEFAULT_PLAN

            base = runtime.active_plan() or DEFAULT_PLAN
            with self._lock:
                plan = replace(
                    base,
                    max_attestation_batch=self.caps["gossip_attestation"],
                    max_aggregate_batch=self.caps["gossip_aggregate"],
                    urgent_max_sets=(
                        self.urgent_max_sets
                        if self.urgent_max_sets is not None
                        else base.urgent_max_sets
                    ),
                    source=f"scheduler:{len(self.retunes)}",
                )
            runtime.install_runtime_plan(plan)
        except Exception as e:
            log.warn("scheduler plan publish failed",
                     error=f"{type(e).__name__}: {e}")

    # ------------------------------------------------------------ snapshot

    def stats(self) -> dict:
        """Deterministic control-loop state for reports and the pipeline
        ops endpoint."""
        with self._lock:
            return {
                "caps": dict(self.caps),
                "pinned": {k: v for k, v in self.pinned.items() if v},
                "urgent_max_sets": self.urgent_max_sets,
                "watermarks": (
                    {
                        "bulk": round(self.admission.bulk_watermark, 3),
                        "backfill": round(
                            self.admission.backfill_watermark, 3
                        ),
                    }
                    if self.admission is not None else None
                ),
                "demand_ewma": {
                    k: round(v, 2) for k, v in self._demand.items()
                },
                "model": self._model_locked(),
                "decisions": {
                    f"{k}:{r}": n
                    for (k, r), n in sorted(self.decisions.items())
                },
                "retunes": list(self.retunes),
                "retune_count": len(self.retunes),
                "last_retune_slot": self.last_retune_slot,
                "slots_seen": self.slots_seen,
            }
