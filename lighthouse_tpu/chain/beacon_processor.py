"""BeaconProcessor — priority work scheduler with gossip batch coalescing.

Parity surface: /root/reference/beacon_node/beacon_processor/src/lib.rs —
the Work queue taxonomy (:549-658), bounded FIFO/LIFO queues per kind
(:301-372), explicit priority order (:955-1090), and the dynamic coalescing
of queued gossip attestations/aggregates into batch work items
(:970-1087). That coalescing is the upstream feeder for the TPU backend:
the reference caps batches at 64 because CPU batch verification saturates;
here the default batch caps are sized for chip occupancy instead
(DEFAULT_MAX_*_BATCH), and the scheduler drains widest-first.

Threading model: unlike the reference's tokio worker pool, this scheduler
is a synchronous priority queue pumped by a small thread pool — Python's
GIL makes many workers pointless, but the heavy work (device batches,
native store IO, sha256) all releases the GIL or runs on device, so a few
workers suffice. Determinism-first: `run_until_idle` drains synchronously
for tests (manual time), `start`/`stop` run the pump in threads.

Observability: every queue is a labeled Prometheus series (the reference's
beacon_processor_*_queue_total idiom) and every executed work unit carries
a Trace through the pipeline stages — enqueue (submit -> pop), coalesce
(batch formation), marshal (runner execution, which for device batches is
host marshal + async dispatch), device (handle wait), continuation (chain
mutation). See lighthouse_tpu/observability. The per-batch overhead is a
few dict lookups + histogram observes; nothing here blocks on a scrape.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from dataclasses import dataclass, field
from enum import IntEnum
from time import perf_counter
from typing import Callable

from ..observability import slo as obs_slo
from ..observability import trace as obs
from ..qos.admission import count_shed
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY

log = get_logger("beacon_processor")


class WorkKind(IntEnum):
    """Priority order, highest first (lib.rs:955-1090 ordering)."""

    chain_reprocess = 0
    gossip_block = 1
    api_request_p0 = 2
    gossip_aggregate = 3
    gossip_attestation = 4
    gossip_sync_contribution = 5
    gossip_sync_signature = 6
    rpc_block = 7
    chain_segment = 8
    api_request_p1 = 9
    gossip_voluntary_exit = 10
    gossip_proposer_slashing = 11
    gossip_attester_slashing = 12
    gossip_bls_change = 13
    backfill_segment = 14


DEFAULT_MAX_ATTESTATION_BATCH = 1024   # reference default 64; sized for TPU
DEFAULT_MAX_AGGREGATE_BATCH = 512

# ------------------------------------------------------------------ metrics
# labeled per-kind families (beacon_processor/src/metrics.rs analog: the
# reference exports one gauge per queue; here one family with a kind label)

_QUEUE_DEPTH = REGISTRY.gauge_vec(
    "beacon_processor_queue_depth",
    "work items currently queued, by work kind",
    ("kind",),
)
_DROPPED = REGISTRY.counter_vec(
    "beacon_processor_dropped_total",
    "work items dropped because their queue was full, by work kind",
    ("kind",),
)
_PROCESSED = REGISTRY.counter_vec(
    "beacon_processor_processed_total",
    "work items executed, by work kind",
    ("kind",),
)
_QUEUE_WAIT = REGISTRY.histogram_vec(
    "beacon_processor_queue_wait_seconds",
    "submit-to-pop latency of the oldest item in each executed work unit",
    ("kind",),
    buckets=(0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0, 30.0),
)
_EXEC_LOCK_WAIT = REGISTRY.histogram(
    "beacon_processor_exec_lock_wait_seconds",
    "time spent waiting for the chain-mutation exec lock",
    buckets=(0.00001, 0.0001, 0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
)
_INFLIGHT = REGISTRY.gauge(
    "beacon_processor_inflight_batches",
    "device verification batches currently in flight",
)
_BATCHES_FORMED = REGISTRY.counter(
    "beacon_processor_batches_formed_total",
    "coalesced multi-item batches formed by the scheduler",
)
_ERRORS = REGISTRY.counter_vec(
    "beacon_processor_errors_total",
    "work unit failures swallowed by the pump, by pipeline stage",
    ("stage",),
)


def _planned(attr: str, default: int) -> int:
    """Batch cap from the installed autotune plan, else the hard-coded
    default — with no profile installed the config is byte-identical to
    the pre-autotune constants (lighthouse_tpu/autotune/planner.py)."""
    try:
        from ..autotune import runtime

        plan = runtime.active_plan()
        if plan is not None:
            return int(getattr(plan, attr))
    except Exception:
        pass
    return default


def _pipeline_depth() -> int:
    """Default in-flight window: the jaxbls pipeline depth resolution
    (env LIGHTHOUSE_TPU_PIPELINE_DEPTH > autotune plan > 4). jax-free —
    crypto/jaxbls/pipeline.py imports nothing device-side at module
    level — and never raises into config construction."""
    try:
        from ..crypto.jaxbls.pipeline import resolve_depth

        return int(resolve_depth()[0])
    except Exception:
        return 4
DEFAULT_QUEUE_LENGTHS = {
    WorkKind.gossip_attestation: 16384,
    WorkKind.gossip_aggregate: 4096,
    WorkKind.gossip_block: 1024,
    WorkKind.rpc_block: 1024,
    WorkKind.chain_segment: 64,
    WorkKind.backfill_segment: 64,
}
DEFAULT_QUEUE_LEN = 1024


@dataclass
class WorkItem:
    kind: WorkKind
    run: Callable[[], None] | None = None
    # batchable items carry a payload + a batch runner instead
    payload: object = None
    run_batch: Callable[[list], None] | None = None
    # stamped by submit(): feeds the queue-wait histogram + enqueue span
    t_enq: float = 0.0
    # QoS (lighthouse_tpu/qos): last slot at which this work still matters;
    # checked at pop time against the admission controller's slot clock
    deadline_slot: int | None = None
    # called with the shed reason ("queue_full" / "expired" / "admission")
    # when the item is lost — the gossip layer resolves its deferred
    # validation slot here so shed work never strands a PENDING entry
    on_shed: Callable[[str], None] | None = None


@dataclass
class BeaconProcessorConfig:
    # default caps consult the installed autotune plan (device-measured
    # throughput knee) and fall back to the guessed constants; an explicit
    # value (CLI --max-*-batch) always wins over both — AND pins the cap
    # against the capacity scheduler's runtime retuning (None auto-resolves
    # and stays retunable, a number self-describes as explicit, the same
    # contract max_inflight established in r8)
    max_attestation_batch: int | None = None
    max_aggregate_batch: int | None = None
    max_attestation_batch_explicit: bool = False
    max_aggregate_batch_explicit: bool = False
    # cores-wide like the reference's pool (beacon_processor/src/lib.rs:732
    # sizes by num_cpus); capped — beyond a few workers the Python-side
    # share of each task stops scaling under the GIL
    num_workers: int = field(
        default_factory=lambda: max(2, min(8, os.cpu_count() or 2))
    )
    # max device batches in flight before the pump blocks on the oldest —
    # the double-buffering depth (SURVEY §7 step 2: host marshals batch N+1
    # while the device verifies batch N). None (the default) auto-resolves
    # through the jaxbls dispatcher's depth resolution (env > autotune
    # plan > default 4) so the processor window and the backend window
    # agree, AND keeps re-resolving on runtime profile installs via the
    # processor's plan listener. Passing a NUMBER pins it: explicitness
    # is self-describing (__post_init__ flips max_inflight_explicit), so
    # a caller constructing BeaconProcessorConfig(max_inflight=2) is
    # never clobbered by a later plan install.
    max_inflight: int | None = None
    max_inflight_explicit: bool = False
    # the capacity scheduler (chain/scheduler.py) publishes its retuned
    # knobs process-wide through the autotune plan-listener contract only
    # when this is set (the live bn node path; in-process harnesses with
    # several processors keep actuation per-instance)
    scheduler_publish_plan: bool = False

    def __post_init__(self):
        if self.max_inflight is None:
            self.max_inflight = _pipeline_depth()
        else:
            self.max_inflight_explicit = True
        if self.max_attestation_batch is None:
            self.max_attestation_batch = _planned(
                "max_attestation_batch", DEFAULT_MAX_ATTESTATION_BATCH
            )
        else:
            self.max_attestation_batch_explicit = True
        if self.max_aggregate_batch is None:
            self.max_aggregate_batch = _planned(
                "max_aggregate_batch", DEFAULT_MAX_AGGREGATE_BATCH
            )
        else:
            self.max_aggregate_batch_explicit = True


class BeaconProcessor:
    BATCHABLE = (WorkKind.gossip_attestation, WorkKind.gossip_aggregate)

    def __init__(self, config: BeaconProcessorConfig | None = None,
                 admission=None):
        self.config = config or BeaconProcessorConfig()
        # QoS admission controller (lighthouse_tpu/qos/admission.py) — when
        # None, submit/pop behave exactly like the pre-QoS processor except
        # for the oldest-first shed on full batchable queues
        self.admission = admission
        self.queues: dict[WorkKind, deque] = {k: deque() for k in WorkKind}
        self.max_lengths = {
            k: DEFAULT_QUEUE_LENGTHS.get(k, DEFAULT_QUEUE_LEN) for k in WorkKind
        }
        self.dropped: dict[WorkKind, int] = {k: 0 for k in WorkKind}
        self.expired: dict[WorkKind, int] = {k: 0 for k in WorkKind}
        self.shed_admission: dict[WorkKind, int] = {k: 0 for k in WorkKind}
        self.processed: dict[WorkKind, int] = {k: 0 for k in WorkKind}
        self.batches_formed = 0
        self.pipelined_batches = 0
        # per-kind metric children resolved ONCE: the hot path pays a plain
        # dict lookup per event, never a family lock
        self._m_depth = {k: _QUEUE_DEPTH.labels(k.name) for k in WorkKind}
        self._m_dropped = {k: _DROPPED.labels(k.name) for k in WorkKind}
        self._m_processed = {k: _PROCESSED.labels(k.name) for k in WorkKind}
        self._m_wait = {k: _QUEUE_WAIT.labels(k.name) for k in WorkKind}
        # in-flight device submissions: (handle, continuation, trace) FIFO
        self._inflight: deque = deque()
        self._lock = threading.Lock()
        # Serializes chain-mutating execution (runners + continuations)
        # across workers: without it two workers could concurrently mutate
        # observed-* caches / naive pools / fork-choice votes that the
        # gossip path otherwise serializes. Device waits (handle.result())
        # deliberately happen OUTSIDE this lock so workers still overlap
        # host marshalling with device verification.
        self._exec_lock = threading.RLock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # capacity scheduler (chain/scheduler.py): owns batch formation —
        # _pop_locked delegates the dispatch-vs-coalesce verdict and the
        # live batch caps to it — and closes the control loop by retuning
        # caps/watermarks/urgent threshold from the SLO slot reports
        from .scheduler import CapacityScheduler

        self.scheduler = CapacityScheduler(
            self.config, admission=self.admission,
            publish_plan=self.config.scheduler_publish_plan,
        )
        # slot-level SLO accountant (observability/slo.py): every admit /
        # shed / processed / queue-wait lands in the current slot's report.
        # Defaults to the node's global accountant; loadgen swaps in a
        # private instance so scenario reports stay seed-deterministic.
        # (Property setter: the scheduler's control loop follows the swap.)
        self.slo = obs_slo.ACCOUNTANT
        from ..observability import register_processor

        register_processor(self)
        # live retune (r8): a mesh-aware autotune profile installed
        # mid-run re-resolves the in-flight window through the same plan
        # listener contract the jaxbls dispatcher and the hybrid router
        # use — unless the operator pinned --max-inflight-batches. A
        # broken autotune import must never take down the processor, but
        # it must be LOUD (the PR 9 no-silent-except rule): a node whose
        # plan listener silently failed to register would serve stale
        # knobs forever with nothing to show for it.
        try:
            from ..autotune import runtime as _at_runtime

            _at_runtime.add_plan_listener(self._on_plan_installed)
            _at_runtime.add_plan_listener(self.scheduler.on_plan_installed)
        except Exception as e:
            _ERRORS.labels("plan_listener").inc()
            log.warn(
                "autotune plan-listener registration failed; runtime "
                "retunes disabled for this processor",
                error=f"{type(e).__name__}: {e}",
            )

    @property
    def slo(self):
        return self._slo

    @slo.setter
    def slo(self, accountant) -> None:
        """Swapping the accountant (loadgen's private per-run instance)
        re-binds the scheduler's control-loop tick to the new one."""
        self._slo = accountant
        self.scheduler.bind_slo(accountant)

    def _on_plan_installed(self, _plan) -> None:
        if self.config.max_inflight_explicit:
            return
        self.config.max_inflight = _pipeline_depth()

    # ------------------------------------------------------------- submit

    def submit(self, item: WorkItem) -> bool:
        """Enqueue; returns False if the item was refused (already past its
        slot deadline, admission class over its watermark, or a full
        non-batchable queue). A full BATCHABLE
        queue sheds its OLDEST entry instead and admits the incoming item —
        the reference's LIFO-queue semantics for gossip attestations
        (beacon_processor/src/lib.rs:301-372): under flood, fresher work has
        strictly more propagation value than work already going stale. The
        `dropped` counter stays accurate either way: one item is lost per
        over-full submit, it is just not always the incoming one."""
        item.t_enq = perf_counter()
        kind = item.kind
        shed = None           # (item, reason) resolved outside the lock
        accepted = False
        with self._lock:
            q = self.queues[kind]
            cap = self.max_lengths[kind]
            if self.admission is not None and self.admission.is_expired(item):
                # dead on arrival (stale replay past its window): shed the
                # INCOMING item as expired — it must never take a queue
                # slot, and above all never displace live work via the
                # oldest-first branch below
                self.expired[kind] += 1
                shed = (item, "expired")
            elif self.admission is not None and not self.admission.admit(
                kind, len(q), cap
            ):
                self.shed_admission[kind] += 1
                shed = (item, "admission")
            elif len(q) >= cap:
                self.dropped[kind] += 1
                self._m_dropped[kind].inc()
                if kind in self.BATCHABLE and q:
                    shed = (q.popleft(), "queue_full")
                    q.append(item)
                    accepted = True
                else:
                    shed = (item, "queue_full")
            else:
                q.append(item)
                accepted = True
            self._m_depth[kind].set(len(q))
        # shed bookkeeping outside self._lock: on_shed re-enters the gossip
        # layer (report_validation_result takes the gossipsub lock)
        if shed is not None:
            self._notify_shed(shed[0], shed[1])
        if accepted:
            self.slo.record_admitted(kind.name)
            self._wake.set()
        return accepted

    def _notify_shed(self, item: WorkItem, reason: str) -> None:
        count_shed(item.kind.name, reason)
        self.slo.record_shed(item.kind.name, reason)
        if item.on_shed is not None:
            try:
                item.on_shed(reason)
            except Exception as e:  # shed callbacks must never kill a caller
                _ERRORS.labels("shed_callback").inc()
                log.error("on_shed callback failed", kind=item.kind.name,
                          error=f"{type(e).__name__}: {e}")

    # ------------------------------------------------------------- drain

    def _next_work(self, force: bool = False):
        """Pop the highest-priority work; coalesce batchable kinds.
        Returns (single, batch, trace) — the trace carries the enqueue and
        coalesce spans of whatever was popped. Items whose slot deadline
        has passed are shed HERE, counted `expired` (they already paid
        their queue residency; running them now would burn a device batch
        slot on unactionable work). Batch FORMATION is the capacity
        scheduler's call (chain/scheduler.py): a batchable queue may be
        HELD to coalesce wider; `force=True` (run_until_idle, drain, the
        worker's post-wait pass) overrides coalesce-holds so held work is
        never starved — only a harness budget gate outlasts force."""
        expired: list[WorkItem] = []
        try:
            with self._lock:
                return self._pop_locked(expired, force)
        finally:
            # self.expired was bumped under the lock (workers race here);
            # only the metric + callback run outside it
            for it in expired:
                self._notify_shed(it, "expired")

    def _pop_locked(self, expired: list, force: bool = False):
        adm = self.admission
        for kind in WorkKind:
            q = self.queues[kind]
            if not q:
                continue
            t_pop = perf_counter()
            if kind in self.BATCHABLE:
                decision = self.scheduler.decide(
                    kind, len(q),
                    inflight=len(self._inflight),
                    max_inflight=self.config.max_inflight,
                    force=force,
                )
                if not decision.dispatch:
                    continue   # held to coalesce; lower priorities may run
                cap = decision.cap
                items = []
                while q and len(items) < cap:
                    it = q.popleft()
                    if adm is not None and adm.is_expired(it):
                        self.expired[kind] += 1
                        expired.append(it)
                        continue
                    items.append(it)
                self._m_depth[kind].set(len(q))
                if not items:
                    continue   # whole queue had expired; try the next kind
                trace = self._begin_trace(kind, items[0], len(items), t_pop)
                if len(items) == 1:
                    return items[0], None, trace
                self.batches_formed += 1
                _BATCHES_FORMED.inc()
                return None, items, trace
            item = None
            while q:
                it = q.popleft()
                if adm is not None and adm.is_expired(it):
                    self.expired[kind] += 1
                    expired.append(it)
                    continue
                item = it
                break
            self._m_depth[kind].set(len(q))
            if item is None:
                continue       # whole queue had expired; try the next kind
            trace = self._begin_trace(kind, item, 1, t_pop)
            return item, None, trace
        return None, None, None

    def _begin_trace(self, kind, oldest: WorkItem, n: int, t_pop: float):
        """Trace for one popped work unit: the enqueue span covers the
        OLDEST item's queue residency (== the max wait in the unit), the
        coalesce span the pop/batch-form step itself."""
        self._m_wait[kind].observe(t_pop - oldest.t_enq)
        self.slo.record_queue_wait(kind.name, t_pop - oldest.t_enq)
        # sample the per-kind queue-depth gauges into the tracer's counter
        # ring: the Chrome trace export renders them as counter rows
        # ("ph": "C") so backlog is visible next to the pipeline spans
        obs.TRACER.sample_counters(
            "queue_depth",
            {k.name: g.value for k, g in self._m_depth.items()},
        )
        trace = obs.TRACER.begin(kind.name, n)
        trace.add_span("enqueue", oldest.t_enq, t_pop)
        trace.add_span("coalesce", t_pop, perf_counter(), items=n)
        return trace

    def _execute(self, single, batch, trace=None) -> None:
        t_wait = perf_counter()
        self._exec_lock.acquire()
        _EXEC_LOCK_WAIT.observe(perf_counter() - t_wait)
        obs.set_current_trace(trace)
        t_marshal = perf_counter()
        try:
            if batch is not None:
                kind = batch[0].kind
                runner = batch[0].run_batch
                payloads = [it.payload for it in batch]
                result = runner(payloads)
            elif single is not None:
                kind = single.kind
                if single.run is not None:
                    result = single.run()
                elif single.run_batch is not None:
                    result = single.run_batch([single.payload])
                else:
                    result = None
            else:
                return
        finally:
            obs.set_current_trace(None)
            self._exec_lock.release()
        if trace is not None:
            trace.add_span("marshal", t_marshal, perf_counter())
        n = len(batch) if batch is not None else 1
        self.processed[kind] += n
        self._m_processed[kind].inc(n)
        self.slo.record_processed(kind.name, n)
        self._handle_result(result, trace, kind, n)

    def _handle_result(self, result, trace=None, kind=None, n=1) -> None:
        """A runner may return (handle, continuation): the device batch is
        in flight and the continuation runs when it resolves. The pump keeps
        pulling (and marshalling) new work while up to max_inflight device
        batches verify — the host/device overlap the reference gets from
        its worker pool (beacon_processor/src/lib.rs:732-1100)."""
        if (
            isinstance(result, tuple)
            and len(result) == 2
            and hasattr(result[0], "result")
            and callable(result[1])
        ):
            with self._lock:
                self._inflight.append((result[0], result[1], trace, kind, n))
                self.pipelined_batches += 1
                _INFLIGHT.set(len(self._inflight))
                over = len(self._inflight) > self.config.max_inflight
            if over:
                self._resolve_oldest()
        else:
            # no device leg: the work completed inside the marshal span
            obs.TRACER.finish(trace)

    def _resolve_oldest(self) -> bool:
        with self._lock:
            if not self._inflight:
                return False
            handle, cont, trace, kind, n = self._inflight.popleft()
            _INFLIGHT.set(len(self._inflight))
        # a device failure mid-batch (tunnel drop) must never kill the pump
        # worker: the batch is lost (its deferred gossip validations expire
        # as ignores) but the node keeps verifying
        t_dev = perf_counter()
        try:
            res = handle.result()      # device wait: outside the exec lock
        except Exception as e:
            _ERRORS.labels("device").inc()
            log.error(
                "device batch failed; batch dropped",
                error=f"{type(e).__name__}: {e}",
            )
            obs.TRACER.finish(trace)
            return True
        if trace is not None:
            trace.add_span("device", t_dev, perf_counter())
        dev_secs = perf_counter() - t_dev
        self.slo.record_verify_latency(dev_secs)
        if kind is not None and kind in self.BATCHABLE:
            # the scheduler's batch cost model learns from DEVICE resolves
            # only (host-path wall time must not steer device batch sizing)
            self.scheduler.observe_verify(kind.name, n, dev_secs)
        t_cont = perf_counter()
        try:
            with self._exec_lock:
                cont(res)              # chain mutation: serialized
        except Exception as e:
            _ERRORS.labels("continuation").inc()
            log.error(
                "batch continuation failed",
                error=f"{type(e).__name__}: {e}",
            )
        if trace is not None:
            trace.add_span("continuation", t_cont, perf_counter())
        obs.TRACER.finish(trace)
        return True

    def drain_inflight(self) -> int:
        n = 0
        while self._resolve_oldest():
            n += 1
        return n

    def run_until_idle(self) -> int:
        """Synchronously drain everything (test/deterministic mode).
        Forced passes override the scheduler's coalesce-holds — only a
        harness budget gate (loadgen/capacity.py) outlasts force, and a
        gate-held queue counts as idle here (run_available is the pump
        that respects it)."""
        n = 0
        while True:
            single, batch, trace = self._next_work(force=True)
            if single is None and batch is None:
                n += self.drain_inflight()
                if self.queues_empty() or self._only_gated():
                    return n
                continue
            self._execute(single, batch, trace)
            n += 1

    def _only_gated(self) -> bool:
        """True when everything still queued is held by a scheduler
        budget gate: a forced pump must return instead of spinning."""
        if self.scheduler._budget_gate is None:
            return False
        with self._lock:
            if self._inflight:
                return False
            return all(
                (not q) or k in self.BATCHABLE
                for k, q in self.queues.items()
            ) and any(q for q in self.queues.values())

    def run_available(self) -> int:
        """Pump only what the scheduler releases (no force): held batches
        stay queued to coalesce — the capacity harness's per-slot drive,
        where a device-time budget gate carries backlog across slots."""
        n = 0
        while True:
            single, batch, trace = self._next_work()
            if single is None and batch is None:
                self.drain_inflight()
                single, batch, trace = self._next_work()
                if single is None and batch is None:
                    return n
            self._execute(single, batch, trace)
            n += 1

    def drain(self, timeout: float = 5.0) -> bool:
        """Graceful-shutdown drain: finish queued + in-flight work within
        `timeout` seconds. With the worker pool running it waits for the
        pump to empty the queues; without (synchronous/test mode) it pumps
        inline. Returns True when everything drained — False means the
        deadline hit with work still queued (the caller sheds it by
        stopping; queued gossip items resolve via on_shed at GC, and the
        deadline bounds how long SIGTERM can hang)."""
        import time as _time

        deadline = perf_counter() + max(0.0, timeout)
        if self._threads:
            self._wake.set()
            while perf_counter() < deadline:
                if self.queues_empty():
                    return True
                _time.sleep(0.005)
            return self.queues_empty()
        while perf_counter() < deadline:
            single, batch, trace = self._next_work(force=True)
            if single is None and batch is None:
                self.drain_inflight()
                if self.queues_empty():
                    return True
                continue
            self._execute(single, batch, trace)
        return self.queues_empty()

    def queues_empty(self) -> bool:
        with self._lock:
            return all(not q for q in self.queues.values()) and not self._inflight

    def stats(self) -> dict:
        """Live scheduler state for /lighthouse_tpu/pipeline snapshots."""
        with self._lock:
            queued = {
                k.name: len(q) for k, q in self.queues.items() if q
            }
            inflight = len(self._inflight)
        return {
            "queued": queued,
            "inflight_batches": inflight,
            "max_inflight": self.config.max_inflight,
            "batches_formed": self.batches_formed,
            "pipelined_batches": self.pipelined_batches,
            "processed": {
                k.name: v for k, v in self.processed.items() if v
            },
            "dropped": {k.name: v for k, v in self.dropped.items() if v},
            "expired": {k.name: v for k, v in self.expired.items() if v},
            "shed_admission": {
                k.name: v for k, v in self.shed_admission.items() if v
            },
            "workers": len(self._threads),
            "scheduler": self.scheduler.stats(),
        }

    def qos_totals(self) -> dict:
        """Aggregate loss counts for remote monitoring (utils/monitoring.py
        puts these in its POST body). "shed" matches the Prometheus
        `qos_shed_total` family's total — EVERY lost item across all
        reasons (queue_full + admission + expired) — so a dashboard can
        cross-check the two; "expired" is the deadline subset of it."""
        expired = sum(self.expired.values())
        return {
            "shed": sum(self.dropped.values())
            + sum(self.shed_admission.values()) + expired,
            "expired": expired,
        }

    # ------------------------------------------------------------- threads

    def start(self) -> None:
        self._stop.clear()
        for i in range(self.config.num_workers):
            t = threading.Thread(target=self._worker, name=f"beacon-proc-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def _worker(self) -> None:
        force_next = False
        while not self._stop.is_set():
            single, batch, trace = self._next_work(force=force_next)
            force_next = False
            if single is None and batch is None:
                if self._resolve_oldest():
                    continue
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                # the wait bounds how long a coalesce-hold can starve a
                # small batch on the live path: the next pass dispatches
                # whatever the scheduler was still widening
                force_next = True
                continue
            try:
                self._execute(single, batch, trace)
            except Exception as e:  # worker never dies on bad work
                _ERRORS.labels("execute").inc()
                log.error(
                    "work unit failed; pump continues",
                    kind=(single or batch[0]).kind.name,
                    error=f"{type(e).__name__}: {e}",
                )
                # the failed unit's enqueue/coalesce spans still belong in
                # the ring — failing work is exactly what an operator pulls
                # a trace for
                obs.TRACER.finish(trace)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
