"""BeaconProcessor — priority work scheduler with gossip batch coalescing.

Parity surface: /root/reference/beacon_node/beacon_processor/src/lib.rs —
the Work queue taxonomy (:549-658), bounded FIFO/LIFO queues per kind
(:301-372), explicit priority order (:955-1090), and the dynamic coalescing
of queued gossip attestations/aggregates into batch work items
(:970-1087). That coalescing is the upstream feeder for the TPU backend:
the reference caps batches at 64 because CPU batch verification saturates;
here the default batch caps are sized for chip occupancy instead
(DEFAULT_MAX_*_BATCH), and the scheduler drains widest-first.

Threading model: unlike the reference's tokio worker pool, this scheduler
is a synchronous priority queue pumped by a small thread pool — Python's
GIL makes many workers pointless, but the heavy work (device batches,
native store IO, sha256) all releases the GIL or runs on device, so a few
workers suffice. Determinism-first: `run_until_idle` drains synchronously
for tests (manual time), `start`/`stop` run the pump in threads.
"""

from __future__ import annotations

import os
import queue
import threading
from collections import deque
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Callable


class WorkKind(IntEnum):
    """Priority order, highest first (lib.rs:955-1090 ordering)."""

    chain_reprocess = 0
    gossip_block = 1
    api_request_p0 = 2
    gossip_aggregate = 3
    gossip_attestation = 4
    gossip_sync_contribution = 5
    gossip_sync_signature = 6
    rpc_block = 7
    chain_segment = 8
    api_request_p1 = 9
    gossip_voluntary_exit = 10
    gossip_proposer_slashing = 11
    gossip_attester_slashing = 12
    gossip_bls_change = 13
    backfill_segment = 14


DEFAULT_MAX_ATTESTATION_BATCH = 1024   # reference default 64; sized for TPU
DEFAULT_MAX_AGGREGATE_BATCH = 512


def _planned(attr: str, default: int) -> int:
    """Batch cap from the installed autotune plan, else the hard-coded
    default — with no profile installed the config is byte-identical to
    the pre-autotune constants (lighthouse_tpu/autotune/planner.py)."""
    try:
        from ..autotune import runtime

        plan = runtime.active_plan()
        if plan is not None:
            return int(getattr(plan, attr))
    except Exception:
        pass
    return default
DEFAULT_QUEUE_LENGTHS = {
    WorkKind.gossip_attestation: 16384,
    WorkKind.gossip_aggregate: 4096,
    WorkKind.gossip_block: 1024,
    WorkKind.rpc_block: 1024,
    WorkKind.chain_segment: 64,
    WorkKind.backfill_segment: 64,
}
DEFAULT_QUEUE_LEN = 1024


@dataclass
class WorkItem:
    kind: WorkKind
    run: Callable[[], None] | None = None
    # batchable items carry a payload + a batch runner instead
    payload: object = None
    run_batch: Callable[[list], None] | None = None


@dataclass
class BeaconProcessorConfig:
    # default caps consult the installed autotune plan (device-measured
    # throughput knee) and fall back to the guessed constants; an explicit
    # value (CLI --max-*-batch) always wins over both
    max_attestation_batch: int = field(
        default_factory=lambda: _planned(
            "max_attestation_batch", DEFAULT_MAX_ATTESTATION_BATCH
        )
    )
    max_aggregate_batch: int = field(
        default_factory=lambda: _planned(
            "max_aggregate_batch", DEFAULT_MAX_AGGREGATE_BATCH
        )
    )
    # cores-wide like the reference's pool (beacon_processor/src/lib.rs:732
    # sizes by num_cpus); capped — beyond a few workers the Python-side
    # share of each task stops scaling under the GIL
    num_workers: int = field(
        default_factory=lambda: max(2, min(8, os.cpu_count() or 2))
    )
    # max device batches in flight before the pump blocks on the oldest —
    # the double-buffering depth (SURVEY §7 step 2: host marshals batch N+1
    # while the device verifies batch N)
    max_inflight: int = 4


class BeaconProcessor:
    BATCHABLE = (WorkKind.gossip_attestation, WorkKind.gossip_aggregate)

    def __init__(self, config: BeaconProcessorConfig | None = None):
        self.config = config or BeaconProcessorConfig()
        self.queues: dict[WorkKind, deque] = {k: deque() for k in WorkKind}
        self.max_lengths = {
            k: DEFAULT_QUEUE_LENGTHS.get(k, DEFAULT_QUEUE_LEN) for k in WorkKind
        }
        self.dropped: dict[WorkKind, int] = {k: 0 for k in WorkKind}
        self.processed: dict[WorkKind, int] = {k: 0 for k in WorkKind}
        self.batches_formed = 0
        self.pipelined_batches = 0
        # in-flight device submissions: (handle, continuation) FIFO
        self._inflight: deque = deque()
        self._lock = threading.Lock()
        # Serializes chain-mutating execution (runners + continuations)
        # across workers: without it two workers could concurrently mutate
        # observed-* caches / naive pools / fork-choice votes that the
        # gossip path otherwise serializes. Device waits (handle.result())
        # deliberately happen OUTSIDE this lock so workers still overlap
        # host marshalling with device verification.
        self._exec_lock = threading.RLock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------- submit

    def submit(self, item: WorkItem) -> bool:
        """Enqueue; returns False if the queue for this kind is full (the
        item is dropped, like the reference's bounded queues)."""
        with self._lock:
            q = self.queues[item.kind]
            if len(q) >= self.max_lengths[item.kind]:
                self.dropped[item.kind] += 1
                return False
            q.append(item)
        self._wake.set()
        return True

    # ------------------------------------------------------------- drain

    def _next_work(self):
        """Pop the highest-priority work; coalesce batchable kinds."""
        with self._lock:
            for kind in WorkKind:
                q = self.queues[kind]
                if not q:
                    continue
                if kind in self.BATCHABLE:
                    cap = (
                        self.config.max_attestation_batch
                        if kind == WorkKind.gossip_attestation
                        else self.config.max_aggregate_batch
                    )
                    items = []
                    while q and len(items) < cap:
                        items.append(q.popleft())
                    if len(items) == 1:
                        return items[0], None
                    self.batches_formed += 1
                    return None, items
                return q.popleft(), None
        return None, None

    def _execute(self, single, batch) -> None:
        if batch is not None:
            kind = batch[0].kind
            runner = batch[0].run_batch
            payloads = [it.payload for it in batch]
            with self._exec_lock:
                result = runner(payloads)
            self._handle_result(result)
            self.processed[kind] += len(batch)
        elif single is not None:
            if single.run is not None:
                with self._exec_lock:
                    result = single.run()
                self._handle_result(result)
            elif single.run_batch is not None:
                with self._exec_lock:
                    result = single.run_batch([single.payload])
                self._handle_result(result)
            self.processed[single.kind] += 1

    def _handle_result(self, result) -> None:
        """A runner may return (handle, continuation): the device batch is
        in flight and the continuation runs when it resolves. The pump keeps
        pulling (and marshalling) new work while up to max_inflight device
        batches verify — the host/device overlap the reference gets from
        its worker pool (beacon_processor/src/lib.rs:732-1100)."""
        if (
            isinstance(result, tuple)
            and len(result) == 2
            and hasattr(result[0], "result")
            and callable(result[1])
        ):
            with self._lock:
                self._inflight.append(result)
                self.pipelined_batches += 1
                over = len(self._inflight) > self.config.max_inflight
            if over:
                self._resolve_oldest()

    def _resolve_oldest(self) -> bool:
        with self._lock:
            if not self._inflight:
                return False
            handle, cont = self._inflight.popleft()
        # a device failure mid-batch (tunnel drop) must never kill the pump
        # worker: the batch is lost (its deferred gossip validations expire
        # as ignores) but the node keeps verifying
        try:
            res = handle.result()      # device wait: outside the exec lock
        except Exception:
            import traceback

            traceback.print_exc()
            return True
        try:
            with self._exec_lock:
                cont(res)              # chain mutation: serialized
        except Exception:
            import traceback

            traceback.print_exc()
        return True

    def drain_inflight(self) -> int:
        n = 0
        while self._resolve_oldest():
            n += 1
        return n

    def run_until_idle(self) -> int:
        """Synchronously drain everything (test/deterministic mode)."""
        n = 0
        while True:
            single, batch = self._next_work()
            if single is None and batch is None:
                n += self.drain_inflight()
                if self.queues_empty():
                    return n
                continue
            self._execute(single, batch)
            n += 1

    def queues_empty(self) -> bool:
        with self._lock:
            return all(not q for q in self.queues.values()) and not self._inflight

    # ------------------------------------------------------------- threads

    def start(self) -> None:
        self._stop.clear()
        for i in range(self.config.num_workers):
            t = threading.Thread(target=self._worker, name=f"beacon-proc-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def _worker(self) -> None:
        while not self._stop.is_set():
            single, batch = self._next_work()
            if single is None and batch is None:
                if self._resolve_oldest():
                    continue
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            try:
                self._execute(single, batch)
            except Exception:  # worker never dies on bad work
                import traceback

                traceback.print_exc()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
