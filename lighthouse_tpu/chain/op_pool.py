"""OperationPool — pending operations + greedy max-cover attestation packing.

Parity surface: /root/reference/beacon_node/operation_pool/src/lib.rs:50
(pools for attestations, slashings, exits, BLS changes, sync contributions),
attestation_storage.rs (attestations stored split by data with compact
participation sets) and max_cover.rs (greedy weighted maximum-coverage
packing of aggregates into the block's MAX_ATTESTATIONS slots).
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field

from ..state_transition import accessors as acc
from ..types import helpers as h
from ..types.spec import ChainSpec


@dataclass
class PooledAttestation:
    data_key: bytes              # hash_tree_root(AttestationData)
    data: object
    aggregation_bits: tuple[bool, ...]
    signature: bytes
    attesting_indices: frozenset[int]
    # electra (EIP-7549): which committees the aggregate covers; None for
    # pre-electra attestations (committee identified by data.index instead)
    committee_bits: tuple[bool, ...] | None = None


def max_cover(items: list[tuple[frozenset, float, object]], limit: int) -> list[object]:
    """Greedy weighted max-cover (max_cover.rs MaximumCover analog):
    items are (element_set, weight_per_element..., payload). Picks up to
    `limit` payloads maximizing newly-covered elements; re-scores each round."""
    chosen = []
    covered: set = set()
    remaining = list(items)
    for _ in range(limit):
        best = None
        best_gain = 0
        for entry in remaining:
            elems, weight, _payload = entry
            gain = sum(weight for e in elems if e not in covered)
            if gain > best_gain:
                best_gain = gain
                best = entry
        if best is None:
            break
        covered |= set(best[0])
        chosen.append(best[2])
        remaining.remove(best)
    return chosen


class OperationPool:
    def __init__(self, spec: ChainSpec):
        self.spec = spec
        # data_key -> list[PooledAttestation] (attestation_storage analog)
        self.attestations: dict[bytes, list[PooledAttestation]] = defaultdict(list)
        self.attestation_data: dict[bytes, object] = {}
        self.proposer_slashings: dict[int, object] = {}
        self.attester_slashings: list[object] = []
        self.voluntary_exits: dict[int, object] = {}
        self.bls_changes: dict[int, object] = {}
        self.sync_contributions: dict[tuple[int, bytes, int], object] = {}

    # ------------------------------------------------------------- inserts

    def insert_attestation(self, att, attesting_indices, types) -> None:
        key = types.AttestationData.hash_tree_root(att.data)
        cb = getattr(att, "committee_bits", None)
        entry = PooledAttestation(
            data_key=key,
            data=att.data,
            aggregation_bits=tuple(att.aggregation_bits),
            signature=bytes(att.signature),
            attesting_indices=frozenset(attesting_indices),
            committee_bits=tuple(cb) if cb is not None else None,
        )
        bucket = self.attestations[key]
        # drop if strictly covered by an existing aggregate
        for existing in bucket:
            if entry.attesting_indices <= existing.attesting_indices:
                return
        bucket[:] = [
            e for e in bucket if not (e.attesting_indices < entry.attesting_indices)
        ]
        bucket.append(entry)
        self.attestation_data[key] = att.data

    def insert_proposer_slashing(self, slashing) -> None:
        self.proposer_slashings[slashing.signed_header_1.message.proposer_index] = slashing

    def insert_attester_slashing(self, slashing) -> None:
        # dedup by content: a retried POST / regossip must not stack copies
        if any(s == slashing for s in self.attester_slashings):
            return
        self.attester_slashings.append(slashing)

    def insert_voluntary_exit(self, signed_exit) -> None:
        self.voluntary_exits[signed_exit.message.validator_index] = signed_exit

    def insert_bls_change(self, signed_change) -> None:
        self.bls_changes[signed_change.message.validator_index] = signed_change

    # ------------------------------------------------------------- packing

    def get_attestations_for_block(self, state, types) -> list:
        """Greedy max-cover packing into MAX_ATTESTATIONS
        (lib.rs:252-343 analog). Weight = effective-balance-weighted new
        coverage of (epoch, validator) pairs not yet on chain (approximated
        by participation flags)."""
        spec = self.spec
        current_epoch = acc.get_current_epoch(state, spec)
        previous_epoch = acc.get_previous_epoch(state, spec)
        items = []
        for key, bucket in self.attestations.items():
            data = self.attestation_data[key]
            if data.target.epoch not in (previous_epoch, current_epoch):
                continue
            if not (
                data.slot + spec.min_attestation_inclusion_delay
                <= state.slot
                <= data.slot + spec.preset.SLOTS_PER_EPOCH
            ):
                continue
            # the source must still match the packing state's justified
            # checkpoint, or process_attestation rejects the block (stale
            # attestations straddling a justification advance)
            justified = (
                state.current_justified_checkpoint
                if data.target.epoch == current_epoch
                else state.previous_justified_checkpoint
            )
            if data.source != justified:
                continue
            participation = (
                state.current_epoch_participation
                if data.target.epoch == current_epoch
                else state.previous_epoch_participation
            )
            for entry in bucket:
                fresh = frozenset(
                    i
                    for i in entry.attesting_indices
                    if not acc.has_flag(participation[i], acc.TIMELY_TARGET_FLAG_INDEX)
                )
                if not fresh:
                    continue
                items.append((fresh, 1.0, entry))
        # the block's fork decides the container shape: electra blocks can
        # only carry electra-shaped (committee_bits) attestations and vice
        # versa — at the fork boundary the mismatched pool tail is dropped,
        # exactly like the reference (and the test harness) does
        electra_block = any(
            f.name == "committee_bits" for f in types.Attestation.fields
        )
        limit = (
            spec.preset.MAX_ATTESTATIONS_ELECTRA
            if electra_block
            else spec.preset.MAX_ATTESTATIONS
        )
        candidates = [
            it
            for it in items
            if (it[2].committee_bits is not None) == electra_block
        ]
        # canonical order before the greedy pass: max_cover breaks ties by
        # list position, and the pool fills in gossip ARRIVAL order — two
        # nodes holding identical contents must pack identical blocks
        # (deterministic multi-node runs depend on it)
        candidates.sort(
            key=lambda it: (
                int(it[2].data.slot),
                sorted(it[0]),
                bytes(it[2].signature),
            )
        )
        chosen = max_cover(candidates, limit)
        out = []
        for entry in chosen:
            kwargs = dict(
                aggregation_bits=list(entry.aggregation_bits),
                data=entry.data,
                signature=entry.signature,
            )
            if electra_block:
                kwargs["committee_bits"] = list(entry.committee_bits)
            out.append(types.Attestation.make(**kwargs))
        return out

    def get_slashings_and_exits(self, state, types):
        spec = self.spec
        epoch = acc.get_current_epoch(state, spec)
        proposer_slashings = [
            s
            for s in self.proposer_slashings.values()
            if h.is_slashable_validator(
                state.validators[s.signed_header_1.message.proposer_index], epoch
            )
        ][: spec.preset.MAX_PROPOSER_SLASHINGS]
        def attester_slashing_includable(s) -> bool:
            # process_attester_slashing requires >=1 still-slashable common
            # index; packing a spent slashing invalidates the whole block
            common = set(s.attestation_1.attesting_indices) & set(
                s.attestation_2.attesting_indices
            )
            return any(
                i < len(state.validators)
                and h.is_slashable_validator(state.validators[i], epoch)
                for i in common
            )

        limit = getattr(
            spec.preset, "MAX_ATTESTER_SLASHINGS_ELECTRA", None
        ) if any(
            f.name == "committee_bits" for f in types.Attestation.fields
        ) else spec.preset.MAX_ATTESTER_SLASHINGS
        if limit is None:
            limit = spec.preset.MAX_ATTESTER_SLASHINGS
        attester_slashings = [
            s for s in self.attester_slashings if attester_slashing_includable(s)
        ][:limit]
        def exit_includable(e) -> bool:
            # mirror process_voluntary_exit's non-signature checks: packing
            # an op the state transition would reject invalidates the block
            vi = int(e.message.validator_index)
            if vi >= len(state.validators):
                return False
            v = state.validators[vi]
            return (
                v.exit_epoch == 2**64 - 1
                and h.is_active_validator(v, epoch)
                and epoch >= e.message.epoch
                and epoch >= v.activation_epoch + spec.shard_committee_period
            )

        exits = [
            e for e in self.voluntary_exits.values() if exit_includable(e)
        ][: spec.preset.MAX_VOLUNTARY_EXITS]
        def change_includable(c) -> bool:
            # mirror process_bls_to_execution_change's non-signature checks
            vi = int(c.message.validator_index)
            if vi >= len(state.validators):
                return False
            wc = bytes(state.validators[vi].withdrawal_credentials)
            return (
                wc[:1] == b"\x00"
                and wc[1:] == h.sha256(bytes(c.message.from_bls_pubkey))[1:]
            )

        changes = [
            c for c in self.bls_changes.values() if change_includable(c)
        ][: spec.preset.MAX_BLS_TO_EXECUTION_CHANGES]
        return proposer_slashings, attester_slashings, exits, changes

    # --------------------------------------------------------- persistence

    OP_POOL_KEY = b"persisted-op-pool"

    def persist(self, store, types) -> None:
        """Serialize the pool into the chain store so a restart does not
        drop pending operations (operation_pool/src/persistence.rs).
        Containers go through SSZ (their classes are built dynamically per
        SpecTypes, so pickling the objects directly would not round-trip)."""
        import pickle

        payload = {
            "attestations": [
                (
                    types.AttestationData.serialize(e.data),
                    list(e.aggregation_bits),
                    e.signature,
                    sorted(e.attesting_indices),
                    list(e.committee_bits) if e.committee_bits is not None else None,
                )
                for bucket in self.attestations.values()
                for e in bucket
            ],
            "proposer_slashings": [
                types.ProposerSlashing.serialize(s)
                for s in self.proposer_slashings.values()
            ],
            "attester_slashings": [
                types.AttesterSlashing.serialize(s) for s in self.attester_slashings
            ],
            "voluntary_exits": [
                types.SignedVoluntaryExit.serialize(e)
                for e in self.voluntary_exits.values()
            ],
            "bls_changes": [
                types.SignedBLSToExecutionChange.serialize(c)
                for c in self.bls_changes.values()
            ]
            if hasattr(types, "SignedBLSToExecutionChange")
            else [],
        }
        store.put_chain_item(self.OP_POOL_KEY, pickle.dumps(payload))

    @classmethod
    def load(cls, store, spec, types) -> "OperationPool":
        """Rebuild a pool persisted by `persist`; empty pool when none."""
        import pickle
        import types as _pytypes

        pool = cls(spec)
        raw = store.get_chain_item(cls.OP_POOL_KEY)
        if raw is None:
            return pool
        payload = pickle.loads(raw)
        for entry in payload["attestations"]:
            # tolerate the pre-committee_bits 4-tuple format (a store
            # persisted by an older build must not abort startup)
            data_ssz, bits, sig, indices = entry[:4]
            cb = entry[4] if len(entry) > 4 else None
            att = _pytypes.SimpleNamespace(
                data=types.AttestationData.deserialize(data_ssz),
                aggregation_bits=bits,
                signature=sig,
            )
            if cb is not None:
                att.committee_bits = cb
            pool.insert_attestation(att, indices, types)
        for s in payload["proposer_slashings"]:
            pool.insert_proposer_slashing(types.ProposerSlashing.deserialize(s))
        for s in payload["attester_slashings"]:
            pool.insert_attester_slashing(types.AttesterSlashing.deserialize(s))
        for e in payload["voluntary_exits"]:
            pool.insert_voluntary_exit(types.SignedVoluntaryExit.deserialize(e))
        if hasattr(types, "SignedBLSToExecutionChange"):
            for c in payload.get("bls_changes", []):
                pool.insert_bls_change(
                    types.SignedBLSToExecutionChange.deserialize(c)
                )
        return pool

    # ------------------------------------------------------------- pruning

    def prune(self, state) -> None:
        """Drop operations no longer includable (persistence.rs prune path)."""
        spec = self.spec
        current_epoch = acc.get_current_epoch(state, spec)
        keep = {}
        for key, bucket in self.attestations.items():
            data = self.attestation_data[key]
            if data.target.epoch + 1 >= current_epoch:
                keep[key] = bucket
        self.attestations = defaultdict(list, keep)
        self.attestation_data = {
            k: v for k, v in self.attestation_data.items() if k in keep
        }
        self.voluntary_exits = {
            i: e
            for i, e in self.voluntary_exits.items()
            if state.validators[i].exit_epoch == 2**64 - 1
        }
        self.proposer_slashings = {
            i: s
            for i, s in self.proposer_slashings.items()
            if not state.validators[i].slashed
        }
        epoch = acc.get_current_epoch(state, self.spec)
        self.attester_slashings = [
            s
            for s in self.attester_slashings
            if any(
                i < len(state.validators)
                and h.is_slashable_validator(state.validators[i], epoch)
                for i in set(s.attestation_1.attesting_indices)
                & set(s.attestation_2.attesting_indices)
            )
        ]
