"""Blob sidecar verification + data-availability checking (deneb+).

Parity surface:
  - gossip blob-sidecar verification — index bounds, slot/finalization
    windows, parent checks, header proposer signature, KZG commitment
    inclusion proof, KZG blob proof, (block_root, index) dedup
    (/root/reference/beacon_node/beacon_chain/src/blob_verification.rs).
  - availability checking — joining blocks and their blob sidecars before
    import, holding whichever side arrives first; import is gated on all
    commitments having a verified matching sidecar
    (/root/reference/beacon_node/beacon_chain/src/data_availability_checker.rs:40).
    The pending store is a bounded in-memory LRU that SPILLS evicted
    entries to the store's da_spill column and transparently faults them back
    on access (overflow_lru_cache.rs OverflowLRUCache semantics): under
    blob spam the in-memory footprint stays capped while no verified
    component is lost.

KZG proofs of all sidecars of a block verify as ONE batch through the shared
pairing kernel (crypto/kzg.verify_blob_kzg_proof_batch — the same device
path as BLS, the north-star workload sharing noted in SURVEY.md §2.4).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field

from ..crypto import kzg as ckzg
from ..ssz.proof import branch_for, build_tree, verify_branch
from ..types.containers import KZGCommitment
from ..types import helpers as h

class BlobError(Exception):
    """Blob sidecar rejected (blob_verification.rs GossipBlobError analog)."""


class BlobIgnoreError(Exception):
    """Blob sidecar gossip IGNORE: do not propagate, do not penalize the
    sender (blob_verification.rs maps these to GossipBlobError variants
    handled as ignore, not reject).

    `retriable=True` means verification could not run YET (parent/state
    unavailable, future slot): a retransmission should be re-validated once
    the dependency arrives. `retriable=False` is terminal (duplicate,
    pre-finalization): the dedup cache must keep suppressing replays or a
    peer could farm free validation work by replaying old sidecars.
    `missing_parent` is set when the blocking dependency is specifically an
    unimported parent block — the condition a local reprocess queue can key
    a retry on. `retry_at_slot` is set when the dependency is TIME (a
    future-slot sidecar): terminal for gossip dedup, but the owner should
    queue it locally and re-validate once that slot starts."""

    def __init__(self, msg: str, retriable: bool = True,
                 missing_parent: bytes | None = None,
                 retry_at_slot: int | None = None):
        super().__init__(msg)
        self.retriable = retriable
        self.missing_parent = missing_parent
        self.retry_at_slot = retry_at_slot


class AvailabilityPendingError(Exception):
    """Block cannot import yet: blobs missing (held in the DA checker)."""

    def __init__(self, block_root: bytes, missing: list[int]):
        super().__init__(f"awaiting blobs {missing} for {block_root.hex()[:8]}")
        self.block_root = block_root
        self.missing = missing


# --------------------------------------------------- inclusion proofs


def _commitments_field_index(types) -> int:
    for i, f in enumerate(types.BeaconBlockBody.fields):
        if f.name == "blob_kzg_commitments":
            return i
    raise ValueError("body has no blob_kzg_commitments")


def _list_depth(limit: int) -> int:
    d = 0
    while (1 << d) < limit:
        d += 1
    return d


def commitment_inclusion_proof(types, spec, body, index: int) -> list[bytes]:
    """Branch proving body.blob_kzg_commitments[index] under the body root
    (bottom-up: list data tree, length mix-in, body container levels)."""
    commitments = list(body.blob_kzg_commitments)
    limit = spec.preset.MAX_BLOB_COMMITMENTS_PER_BLOCK
    roots = [KZGCommitment.hash_tree_root(c) for c in commitments]
    layers = build_tree(roots, limit)
    branch = branch_for(layers, index)
    branch.append(len(commitments).to_bytes(32, "little"))  # mix-in sibling

    chunks = [
        f.type.hash_tree_root(getattr(body, f.name)) for f in types.BeaconBlockBody.fields
    ]
    body_layers = build_tree(chunks, len(types.BeaconBlockBody.fields))
    branch += branch_for(body_layers, _commitments_field_index(types))
    return branch


def verify_commitment_inclusion(types, spec, sidecar) -> bool:
    """Verify sidecar.kzg_commitment_inclusion_proof against the header's
    body_root (blob_verification.rs verify_kzg_commitment_inclusion_proof)."""
    leaf = KZGCommitment.hash_tree_root(sidecar.kzg_commitment)
    list_depth = _list_depth(spec.preset.MAX_BLOB_COMMITMENTS_PER_BLOCK)
    # position bits bottom-up: leaf index | data-root-left (0) | field index
    pos = int(sidecar.index) | (_commitments_field_index(types) << (list_depth + 1))
    body_root = bytes(sidecar.signed_block_header.message.body_root)
    branch = [bytes(b) for b in sidecar.kzg_commitment_inclusion_proof]
    if len(branch) != list_depth + 1 + _list_depth(len(types.BeaconBlockBody.fields)):
        return False
    return verify_branch(leaf, branch, pos, body_root)


def build_sidecars(types, spec, signed_block, blobs, proofs):
    """Sidecars for a produced block: inclusion proofs over its own body
    (the production mirror of verification; beacon_chain.rs blob sidecar
    construction on publish)."""
    block = signed_block.message
    header = types.BeaconBlockHeader.make(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=block.state_root,
        body_root=types.BeaconBlockBody.hash_tree_root(block.body),
    )
    signed_header = types.SignedBeaconBlockHeader.make(
        message=header, signature=signed_block.signature
    )
    out = []
    for i, (blob, proof) in enumerate(zip(blobs, proofs)):
        out.append(
            types.BlobSidecar.make(
                index=i,
                blob=blob,
                kzg_commitment=block.body.blob_kzg_commitments[i],
                kzg_proof=proof,
                signed_block_header=signed_header,
                kzg_commitment_inclusion_proof=commitment_inclusion_proof(
                    types, spec, block.body, i
                ),
            )
        )
    return out


# --------------------------------------------------- availability checker


@dataclass
class _PendingComponents:
    block: object | None = None          # SignedBeaconBlock
    types: object | None = None
    blobs: dict = field(default_factory=dict)   # index -> sidecar (verified)


class DataAvailabilityChecker:
    """Joins blocks and blob sidecars before import.

    Bounded in-memory LRU; with a backing store, LRU evictions spill the
    serialized pending components to the da_spill column and accesses fault
    them back in (overflow_lru_cache.rs)."""

    def __init__(
        self,
        spec,
        setup: "ckzg.TrustedSetup | None" = None,
        capacity: int = 64,
        store=None,
    ):
        self.spec = spec
        self.setup = setup
        self._pending: OrderedDict[bytes, _PendingComponents] = OrderedDict()
        self.capacity = capacity
        self.store = store  # HotColdDB or None
        self.spilled = 0     # metric: total entries written to disk
        # root -> slot of the spilled entry (slot drives finalization pruning)
        self._on_disk: dict[bytes, int] = {}
        if store is not None:
            self._recover_spilled()

    def _recover_spilled(self) -> None:
        """Rebuild the disk index after a restart — otherwise spilled
        entries would be orphaned forever (unbounded disk growth under
        blob spam across restarts)."""
        from ..store.kv import Column

        for key, raw in self.store.blobs_db.iter_column(Column.da_spill):
            self._on_disk[key] = self._entry_slot_from_bytes(raw)

    @staticmethod
    def _entry_slot_from_bytes(raw: bytes) -> int:
        """Slot of a serialized entry without full deserialization: the
        block slot if present, else the first sidecar's header slot."""
        if raw[0] == 1:
            return int.from_bytes(raw[1:9], "little")
        # no block: u16 count then first sidecar slot
        return int.from_bytes(raw[3:11], "little")

    @staticmethod
    def _entry_slot(e: _PendingComponents) -> int:
        if e.block is not None:
            return int(e.block.message.slot)
        first = next(iter(e.blobs.values()))
        return int(first.signed_block_header.message.slot)

    def prune_finalized(self, finalized_slot: int) -> int:
        """Drop spilled entries at or below the finalized slot (the
        reference prunes its overflow cache at finalization —
        overflow_lru_cache.rs). Returns the number deleted."""
        if self.store is None:
            return 0
        from ..store.kv import Column

        victims = [r for r, s in self._on_disk.items() if s <= finalized_slot]
        for root in victims:
            self.store.blobs_db.delete(Column.da_spill, root)
            del self._on_disk[root]
        # in-memory entries too: a finalized-slot pending join can never
        # complete into a viable block
        mem_victims = [
            r for r, e in self._pending.items()
            if (e.block is not None or e.blobs)
            and self._entry_slot(e) <= finalized_slot
        ]
        for root in mem_victims:
            self._pending.pop(root, None)
        return len(victims) + len(mem_victims)

    # ------------------------------------------------------- spill plumbing

    def _serialize_entry(self, e: _PendingComponents) -> bytes | None:
        """has_block u8 | [slot u64 | len u32 | block] | n u16 |
        (slot u64 | len u32 | sidecar)* — slots resolve SSZ types back."""
        from ..state_transition.slot import types_for_slot

        out = bytearray()
        if e.block is not None:
            raw = e.types.SignedBeaconBlock.serialize(e.block)
            out += b"\x01" + int(e.block.message.slot).to_bytes(8, "little")
            out += len(raw).to_bytes(4, "little") + raw
        else:
            out += b"\x00"
        out += len(e.blobs).to_bytes(2, "little")
        for idx in sorted(e.blobs):
            sc = e.blobs[idx]
            slot = int(sc.signed_block_header.message.slot)
            types = types_for_slot(self.spec, slot)
            raw = types.BlobSidecar.serialize(sc)
            out += slot.to_bytes(8, "little")
            out += len(raw).to_bytes(4, "little") + raw
        return bytes(out)

    def _deserialize_entry(self, raw: bytes) -> _PendingComponents:
        from ..state_transition.slot import types_for_slot

        e = _PendingComponents()
        off = 1
        if raw[0] == 1:
            slot = int.from_bytes(raw[off : off + 8], "little")
            off += 8
            n = int.from_bytes(raw[off : off + 4], "little")
            off += 4
            e.types = types_for_slot(self.spec, slot)
            e.block = e.types.SignedBeaconBlock.deserialize(raw[off : off + n])
            off += n
        count = int.from_bytes(raw[off : off + 2], "little")
        off += 2
        for _ in range(count):
            slot = int.from_bytes(raw[off : off + 8], "little")
            off += 8
            n = int.from_bytes(raw[off : off + 4], "little")
            off += 4
            types = types_for_slot(self.spec, slot)
            sc = types.BlobSidecar.deserialize(raw[off : off + n])
            off += n
            e.blobs[int(sc.index)] = sc
        return e

    def _evict_one(self) -> None:
        root, e = self._pending.popitem(last=False)
        if self.store is None:
            return  # memory-only mode: oldest entry is dropped
        if e.block is None and not e.blobs:
            return  # nothing worth preserving
        from ..store.kv import Column

        raw = self._serialize_entry(e)
        self.store.blobs_db.put(Column.da_spill, root, raw)
        self._on_disk[root] = self._entry_slot(e)
        self.spilled += 1

    def _fault_in(self, block_root: bytes) -> _PendingComponents | None:
        """Load a spilled entry back into memory (removing the disk copy)."""
        if self.store is None or block_root not in self._on_disk:
            return None
        from ..store.kv import Column

        raw = self.store.blobs_db.get(Column.da_spill, block_root)
        if raw is None:
            self._on_disk.pop(block_root, None)
            return None
        self.store.blobs_db.delete(Column.da_spill, block_root)
        self._on_disk.pop(block_root, None)
        e = self._deserialize_entry(raw)
        self._pending[block_root] = e
        while len(self._pending) > self.capacity:
            self._evict_one()
        return e

    def _entry(self, block_root: bytes) -> _PendingComponents:
        e = self._pending.get(block_root)
        if e is None:
            e = self._fault_in(block_root)
        if e is None:
            e = _PendingComponents()
            self._pending[block_root] = e
            while len(self._pending) > self.capacity:
                self._evict_one()
        else:
            self._pending.move_to_end(block_root)
        return e

    def _lookup(self, block_root: bytes) -> _PendingComponents | None:
        """Read-only view: spilled entries are deserialized WITHOUT moving
        them back into memory (faulting in would evict + re-write another
        entry — needless disk churn for a pure query)."""
        e = self._pending.get(block_root)
        if e is not None or self.store is None or block_root not in self._on_disk:
            return e
        from ..store.kv import Column

        raw = self.store.blobs_db.get(Column.da_spill, block_root)
        if raw is None:
            self._on_disk.pop(block_root, None)
            return None
        return self._deserialize_entry(raw)

    # ------------------------------------------------------------ interface

    def put_block(self, block_root: bytes, signed_block, types):
        """Register a block awaiting blobs. Returns (block, sidecars) if now
        fully available, else None."""
        e = self._entry(block_root)
        e.block, e.types = signed_block, types
        return self._check(block_root)

    def put_blob(self, block_root: bytes, sidecar):
        """Register a gossip-verified sidecar. Returns (block, sidecars) if
        its block is now fully available, else None."""
        e = self._entry(block_root)
        e.blobs[int(sidecar.index)] = sidecar
        return self._check(block_root)

    def missing_indices(self, block_root: bytes) -> list[int]:
        e = self._lookup(block_root)
        if e is None or e.block is None:
            return []
        n = len(e.block.message.body.blob_kzg_commitments)
        return [i for i in range(n) if i not in e.blobs]

    def pending_count(self) -> int:
        """Entries tracked in memory + spilled to disk (observability)."""
        return len(self._pending) + len(self._on_disk)

    def _check(self, block_root: bytes):
        e = self._pending.get(block_root)
        if e is None or e.block is None:
            return None
        commitments = list(e.block.message.body.blob_kzg_commitments)
        sidecars = []
        for i, c in enumerate(commitments):
            sc = e.blobs.get(i)
            if sc is None or bytes(sc.kzg_commitment) != bytes(c):
                return None
            sidecars.append(sc)
        self._pending.pop(block_root)
        return e.block, sidecars

    def verify_kzg_proofs(self, sidecars) -> bool:
        """One batched pairing check for all sidecars (kzg batch verify)."""
        if not sidecars:
            return True
        if self.setup is None:
            raise BlobError("no KZG trusted setup loaded")
        return ckzg.verify_blob_kzg_proof_batch(
            [bytes(sc.blob) for sc in sidecars],
            [bytes(sc.kzg_commitment) for sc in sidecars],
            [bytes(sc.kzg_proof) for sc in sidecars],
            self.setup,
        )


# --------------------------------------------------- gossip verification


def verify_blob_sidecar_for_gossip(chain, sidecar, verify_kzg: bool = True) -> bytes:
    """Full gossip checks for one sidecar; returns the block root.

    Mirrors blob_verification.rs GossipVerifiedBlob::new order: index bound,
    slot window, (root, index) dedup, parent known + slot ordering, not
    pre-finalization, inclusion proof, proposer signature (batched through
    the BLS backend), KZG proof."""
    from ..state_transition import signature_sets as sigs
    from ..state_transition.block import SignatureBatch
    from ..state_transition.slot import types_for_slot

    spec = chain.spec
    header = sidecar.signed_block_header.message
    slot = header.slot
    fork = spec.fork_name_at_slot(slot)
    types = types_for_slot(spec, slot)
    block_root = types.BeaconBlockHeader.hash_tree_root(header)

    if int(sidecar.index) >= spec.max_blobs(fork):
        raise BlobError(f"blob index {sidecar.index} out of range")
    if slot > chain.current_slot:
        # terminal for gossip dedup (same-instant mesh duplicates must not
        # burn retry budget) — the owner queues it locally for the slot
        # start via retry_at_slot (ReprocessQueue early-block semantics)
        raise BlobIgnoreError("future slot", retriable=False, retry_at_slot=int(slot))
    key = (block_root, int(sidecar.index))
    if key in chain.observed_blob_sidecars:
        raise BlobIgnoreError("sidecar already seen", retriable=False)
    fin_epoch = chain.fork_choice.store.finalized_checkpoint[0]
    if slot <= h.compute_start_slot_at_epoch(fin_epoch, spec):
        raise BlobIgnoreError("sidecar older than finalization", retriable=False)
    parent_root = bytes(header.parent_root)
    if not chain.store.block_exists(parent_root):
        raise BlobIgnoreError("parent unknown", missing_parent=parent_root)
    parent_slot = chain.block_slots.get(parent_root)
    if parent_slot is not None and parent_slot >= slot:
        raise BlobError("not later than parent")

    if not verify_commitment_inclusion(types, spec, sidecar):
        raise BlobError("bad commitment inclusion proof")

    # proposer signature over the header (same domain as block proposals).
    # State unavailability means verification CANNOT RUN — that must surface
    # as ignore, not accept (the sig/KZG checks below never happened).
    from .beacon_chain import BlockError

    try:
        state = chain._state_for_block(parent_root, slot)
    except BlockError as e:
        raise BlobIgnoreError(f"state unavailable: {e}") from e
    if int(header.proposer_index) >= len(state.validators):
        raise BlobError("proposer index out of range")
    batch = SignatureBatch()
    try:
        batch.add(
            sigs.block_header_set(
                state, spec, types, sidecar.signed_block_header,
                chain.pubkey_cache.pubkey_getter(),
            )
        )
    except sigs.SignatureSetError as e:
        raise BlobError(f"undecodable header signature: {e}") from e
    if not batch.verify():
        raise BlobError("invalid header proposer signature")

    if verify_kzg:
        if not chain.data_availability.verify_kzg_proofs([sidecar]):
            raise BlobError("KZG proof invalid")

    chain.observed_blob_sidecars.add(key)
    return block_root
