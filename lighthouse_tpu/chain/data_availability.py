"""Blob sidecar verification + data-availability checking (deneb+).

Parity surface:
  - gossip blob-sidecar verification — index bounds, slot/finalization
    windows, parent checks, header proposer signature, KZG commitment
    inclusion proof, KZG blob proof, (block_root, index) dedup
    (/root/reference/beacon_node/beacon_chain/src/blob_verification.rs).
  - availability checking — joining blocks and their blob sidecars before
    import, holding whichever side arrives first; import is gated on all
    commitments having a verified matching sidecar
    (/root/reference/beacon_node/beacon_chain/src/data_availability_checker.rs:40,
     overflow_lru_cache.rs). Here the pending store is a bounded in-memory
    LRU (the reference spills to disk beyond capacity; a node that falls
    that far behind re-requests over RPC anyway).

KZG proofs of all sidecars of a block verify as ONE batch through the shared
pairing kernel (crypto/kzg.verify_blob_kzg_proof_batch — the same device
path as BLS, the north-star workload sharing noted in SURVEY.md §2.4).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field

from ..crypto import kzg as ckzg
from ..ssz.proof import branch_for, build_tree, verify_branch
from ..types.containers import KZGCommitment
from ..types import helpers as h

class BlobError(Exception):
    """Blob sidecar rejected (blob_verification.rs GossipBlobError analog)."""


class BlobIgnoreError(Exception):
    """Blob sidecar gossip IGNORE: do not propagate, do not penalize the
    sender (blob_verification.rs maps these to GossipBlobError variants
    handled as ignore, not reject).

    `retriable=True` means verification could not run YET (parent/state
    unavailable, future slot): a retransmission should be re-validated once
    the dependency arrives. `retriable=False` is terminal (duplicate,
    pre-finalization): the dedup cache must keep suppressing replays or a
    peer could farm free validation work by replaying old sidecars.
    `missing_parent` is set when the blocking dependency is specifically an
    unimported parent block — the condition a local reprocess queue can key
    a retry on. `retry_at_slot` is set when the dependency is TIME (a
    future-slot sidecar): terminal for gossip dedup, but the owner should
    queue it locally and re-validate once that slot starts."""

    def __init__(self, msg: str, retriable: bool = True,
                 missing_parent: bytes | None = None,
                 retry_at_slot: int | None = None):
        super().__init__(msg)
        self.retriable = retriable
        self.missing_parent = missing_parent
        self.retry_at_slot = retry_at_slot


class AvailabilityPendingError(Exception):
    """Block cannot import yet: blobs missing (held in the DA checker)."""

    def __init__(self, block_root: bytes, missing: list[int]):
        super().__init__(f"awaiting blobs {missing} for {block_root.hex()[:8]}")
        self.block_root = block_root
        self.missing = missing


# --------------------------------------------------- inclusion proofs


def _commitments_field_index(types) -> int:
    for i, f in enumerate(types.BeaconBlockBody.fields):
        if f.name == "blob_kzg_commitments":
            return i
    raise ValueError("body has no blob_kzg_commitments")


def _list_depth(limit: int) -> int:
    d = 0
    while (1 << d) < limit:
        d += 1
    return d


def commitment_inclusion_proof(types, spec, body, index: int) -> list[bytes]:
    """Branch proving body.blob_kzg_commitments[index] under the body root
    (bottom-up: list data tree, length mix-in, body container levels)."""
    commitments = list(body.blob_kzg_commitments)
    limit = spec.preset.MAX_BLOB_COMMITMENTS_PER_BLOCK
    roots = [KZGCommitment.hash_tree_root(c) for c in commitments]
    layers = build_tree(roots, limit)
    branch = branch_for(layers, index)
    branch.append(len(commitments).to_bytes(32, "little"))  # mix-in sibling

    chunks = [
        f.type.hash_tree_root(getattr(body, f.name)) for f in types.BeaconBlockBody.fields
    ]
    body_layers = build_tree(chunks, len(types.BeaconBlockBody.fields))
    branch += branch_for(body_layers, _commitments_field_index(types))
    return branch


def verify_commitment_inclusion(types, spec, sidecar) -> bool:
    """Verify sidecar.kzg_commitment_inclusion_proof against the header's
    body_root (blob_verification.rs verify_kzg_commitment_inclusion_proof)."""
    leaf = KZGCommitment.hash_tree_root(sidecar.kzg_commitment)
    list_depth = _list_depth(spec.preset.MAX_BLOB_COMMITMENTS_PER_BLOCK)
    # position bits bottom-up: leaf index | data-root-left (0) | field index
    pos = int(sidecar.index) | (_commitments_field_index(types) << (list_depth + 1))
    body_root = bytes(sidecar.signed_block_header.message.body_root)
    branch = [bytes(b) for b in sidecar.kzg_commitment_inclusion_proof]
    if len(branch) != list_depth + 1 + _list_depth(len(types.BeaconBlockBody.fields)):
        return False
    return verify_branch(leaf, branch, pos, body_root)


def build_sidecars(types, spec, signed_block, blobs, proofs):
    """Sidecars for a produced block: inclusion proofs over its own body
    (the production mirror of verification; beacon_chain.rs blob sidecar
    construction on publish)."""
    block = signed_block.message
    header = types.BeaconBlockHeader.make(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=block.state_root,
        body_root=types.BeaconBlockBody.hash_tree_root(block.body),
    )
    signed_header = types.SignedBeaconBlockHeader.make(
        message=header, signature=signed_block.signature
    )
    out = []
    for i, (blob, proof) in enumerate(zip(blobs, proofs)):
        out.append(
            types.BlobSidecar.make(
                index=i,
                blob=blob,
                kzg_commitment=block.body.blob_kzg_commitments[i],
                kzg_proof=proof,
                signed_block_header=signed_header,
                kzg_commitment_inclusion_proof=commitment_inclusion_proof(
                    types, spec, block.body, i
                ),
            )
        )
    return out


# --------------------------------------------------- availability checker


@dataclass
class _PendingComponents:
    block: object | None = None          # SignedBeaconBlock
    types: object | None = None
    blobs: dict = field(default_factory=dict)   # index -> sidecar (verified)


class DataAvailabilityChecker:
    """Joins blocks and blob sidecars before import (bounded LRU)."""

    def __init__(self, spec, setup: "ckzg.TrustedSetup | None" = None, capacity: int = 64):
        self.spec = spec
        self.setup = setup
        self._pending: OrderedDict[bytes, _PendingComponents] = OrderedDict()
        self.capacity = capacity

    def _entry(self, block_root: bytes) -> _PendingComponents:
        e = self._pending.get(block_root)
        if e is None:
            e = _PendingComponents()
            self._pending[block_root] = e
            while len(self._pending) > self.capacity:
                self._pending.popitem(last=False)
        else:
            self._pending.move_to_end(block_root)
        return e

    def put_block(self, block_root: bytes, signed_block, types):
        """Register a block awaiting blobs. Returns (block, sidecars) if now
        fully available, else None."""
        e = self._entry(block_root)
        e.block, e.types = signed_block, types
        return self._check(block_root)

    def put_blob(self, block_root: bytes, sidecar):
        """Register a gossip-verified sidecar. Returns (block, sidecars) if
        its block is now fully available, else None."""
        e = self._entry(block_root)
        e.blobs[int(sidecar.index)] = sidecar
        return self._check(block_root)

    def missing_indices(self, block_root: bytes) -> list[int]:
        e = self._pending.get(block_root)
        if e is None or e.block is None:
            return []
        n = len(e.block.message.body.blob_kzg_commitments)
        return [i for i in range(n) if i not in e.blobs]

    def _check(self, block_root: bytes):
        e = self._pending.get(block_root)
        if e is None or e.block is None:
            return None
        commitments = list(e.block.message.body.blob_kzg_commitments)
        sidecars = []
        for i, c in enumerate(commitments):
            sc = e.blobs.get(i)
            if sc is None or bytes(sc.kzg_commitment) != bytes(c):
                return None
            sidecars.append(sc)
        self._pending.pop(block_root)
        return e.block, sidecars

    def verify_kzg_proofs(self, sidecars) -> bool:
        """One batched pairing check for all sidecars (kzg batch verify)."""
        if not sidecars:
            return True
        if self.setup is None:
            raise BlobError("no KZG trusted setup loaded")
        return ckzg.verify_blob_kzg_proof_batch(
            [bytes(sc.blob) for sc in sidecars],
            [bytes(sc.kzg_commitment) for sc in sidecars],
            [bytes(sc.kzg_proof) for sc in sidecars],
            self.setup,
        )


# --------------------------------------------------- gossip verification


def verify_blob_sidecar_for_gossip(chain, sidecar, verify_kzg: bool = True) -> bytes:
    """Full gossip checks for one sidecar; returns the block root.

    Mirrors blob_verification.rs GossipVerifiedBlob::new order: index bound,
    slot window, (root, index) dedup, parent known + slot ordering, not
    pre-finalization, inclusion proof, proposer signature (batched through
    the BLS backend), KZG proof."""
    from ..state_transition import signature_sets as sigs
    from ..state_transition.block import SignatureBatch
    from ..state_transition.slot import types_for_slot

    spec = chain.spec
    header = sidecar.signed_block_header.message
    slot = header.slot
    fork = spec.fork_name_at_slot(slot)
    types = types_for_slot(spec, slot)
    block_root = types.BeaconBlockHeader.hash_tree_root(header)

    if int(sidecar.index) >= spec.max_blobs(fork):
        raise BlobError(f"blob index {sidecar.index} out of range")
    if slot > chain.current_slot:
        # terminal for gossip dedup (same-instant mesh duplicates must not
        # burn retry budget) — the owner queues it locally for the slot
        # start via retry_at_slot (ReprocessQueue early-block semantics)
        raise BlobIgnoreError("future slot", retriable=False, retry_at_slot=int(slot))
    key = (block_root, int(sidecar.index))
    if key in chain.observed_blob_sidecars:
        raise BlobIgnoreError("sidecar already seen", retriable=False)
    fin_epoch = chain.fork_choice.store.finalized_checkpoint[0]
    if slot <= h.compute_start_slot_at_epoch(fin_epoch, spec):
        raise BlobIgnoreError("sidecar older than finalization", retriable=False)
    parent_root = bytes(header.parent_root)
    if not chain.store.block_exists(parent_root):
        raise BlobIgnoreError("parent unknown", missing_parent=parent_root)
    parent_slot = chain.block_slots.get(parent_root)
    if parent_slot is not None and parent_slot >= slot:
        raise BlobError("not later than parent")

    if not verify_commitment_inclusion(types, spec, sidecar):
        raise BlobError("bad commitment inclusion proof")

    # proposer signature over the header (same domain as block proposals).
    # State unavailability means verification CANNOT RUN — that must surface
    # as ignore, not accept (the sig/KZG checks below never happened).
    from .beacon_chain import BlockError

    try:
        state = chain._state_for_block(parent_root, slot)
    except BlockError as e:
        raise BlobIgnoreError(f"state unavailable: {e}") from e
    if int(header.proposer_index) >= len(state.validators):
        raise BlobError("proposer index out of range")
    batch = SignatureBatch()
    try:
        batch.add(
            sigs.block_header_set(
                state, spec, types, sidecar.signed_block_header,
                chain.pubkey_cache.pubkey_getter(),
            )
        )
    except sigs.SignatureSetError as e:
        raise BlobError(f"undecodable header signature: {e}") from e
    if not batch.verify():
        raise BlobError("invalid header proposer signature")

    if verify_kzg:
        if not chain.data_availability.verify_kzg_proofs([sidecar]):
            raise BlobError("KZG proof invalid")

    chain.observed_blob_sidecars.add(key)
    return block_root
