"""Eth1 layer: deposit-contract cache (incremental merkle tree + proofs)
and eth1-data voting.

Parity surface: /root/reference/beacon_node/eth1/src/ (deposit log cache,
block cache feeding eth1-data votes) and beacon_chain/src/eth1_chain.rs
(vote selection). The deposit tree is the standard depth-32 incremental
merkle tree of the deposit contract, with length mixed in for the SSZ
List[DepositData] root — proofs from it feed process_deposit's
is_valid_merkle_branch (state_transition/block.py).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

DEPOSIT_TREE_DEPTH = 32


def _hash(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


class DepositTree:
    """Incremental merkle tree over deposit-data roots with proof support.

    Keeps all leaves (the cache stores every log anyway, eth1/src/
    deposit_cache.rs) so historical proofs at any deposit_count work —
    that is what blocks need: a proof against eth1_data.deposit_root which
    commits to deposit_count leaves."""

    def __init__(self):
        self.leaves: list[bytes] = []
        self._zeros = [b"\x00" * 32]
        for _ in range(DEPOSIT_TREE_DEPTH):
            self._zeros.append(_hash(self._zeros[-1], self._zeros[-1]))

    def push(self, deposit_data_root: bytes) -> None:
        self.leaves.append(deposit_data_root)

    def __len__(self):
        return len(self.leaves)

    def _root_at(self, count: int) -> bytes:
        """Root of the depth-32 tree over the first `count` leaves, with
        the deposit count mixed in (deposit contract get_deposit_root)."""
        node_layer = list(self.leaves[:count])
        for d in range(DEPOSIT_TREE_DEPTH):
            nxt = []
            for i in range(0, len(node_layer), 2):
                left = node_layer[i]
                right = node_layer[i + 1] if i + 1 < len(node_layer) else self._zeros[d]
                nxt.append(_hash(left, right))
            node_layer = nxt or [self._zeros[d + 1]]
        return _hash(node_layer[0], count.to_bytes(32, "little"))

    def root(self, count: int | None = None) -> bytes:
        return self._root_at(len(self.leaves) if count is None else count)

    def proof(self, index: int, count: int | None = None) -> list[bytes]:
        """Branch for leaf `index` in the tree of the first `count` leaves,
        plus the mixed-in length leaf (DEPOSIT_TREE_DEPTH + 1 elements,
        matching Deposit.proof)."""
        count = len(self.leaves) if count is None else count
        assert index < count
        layer = list(self.leaves[:count])
        idx = index
        branch = []
        for d in range(DEPOSIT_TREE_DEPTH):
            sib = idx ^ 1
            if sib < len(layer):
                branch.append(layer[sib])
            else:
                branch.append(self._zeros[d])
            nxt = []
            for i in range(0, len(layer), 2):
                left = layer[i]
                right = layer[i + 1] if i + 1 < len(layer) else self._zeros[d]
                nxt.append(_hash(left, right))
            layer = nxt or [self._zeros[d + 1]]
            idx //= 2
        branch.append(count.to_bytes(32, "little"))
        return branch


@dataclass
class Eth1Block:
    number: int
    hash: bytes
    timestamp: int
    deposit_root: bytes
    deposit_count: int


@dataclass
class Eth1Cache:
    """Block + deposit caches driving eth1-data votes (service.rs)."""

    tree: DepositTree = field(default_factory=DepositTree)
    blocks: list[Eth1Block] = field(default_factory=list)
    deposits: list[object] = field(default_factory=list)   # DepositData values

    def add_deposit(self, deposit_data, types) -> None:
        self.deposits.append(deposit_data)
        self.tree.push(types.DepositData.hash_tree_root(deposit_data))

    def add_block(self, block: Eth1Block) -> None:
        self.blocks.append(block)

    def deposits_for_block_inclusion(self, state, spec, types):
        """Deposits the next block must include (eth1_deposit_index ..
        eth1_data.deposit_count), with proofs against the state's
        eth1_data.deposit_root."""
        start = state.eth1_deposit_index
        count = min(
            state.eth1_data.deposit_count - start, spec.preset.MAX_DEPOSITS
        )
        out = []
        for i in range(start, start + count):
            proof = self.tree.proof(i, count=state.eth1_data.deposit_count)
            out.append(types.Deposit.make(proof=proof, data=self.deposits[i]))
        return out

    def eth1_vote(self, state, spec, types):
        """Pick an eth1-data vote (eth1_chain.rs voting: follow-distance
        block in the voting period; falls back to the current vote)."""
        period_start = _voting_period_start_time(state, spec)
        follow_secs = 2048 * 14  # ETH1_FOLLOW_DISTANCE * seconds per eth1 block
        candidates = [
            b for b in self.blocks if b.timestamp + follow_secs <= period_start
        ]
        if not candidates:
            return state.eth1_data
        best = max(candidates, key=lambda b: b.number)
        if best.deposit_count < state.eth1_data.deposit_count:
            return state.eth1_data  # never roll back deposits
        return types.Eth1Data.make(
            deposit_root=best.deposit_root,
            deposit_count=best.deposit_count,
            block_hash=best.hash,
        )


def _voting_period_start_time(state, spec) -> int:
    period_slots = spec.preset.EPOCHS_PER_ETH1_VOTING_PERIOD * spec.preset.SLOTS_PER_EPOCH
    start_slot = state.slot - state.slot % period_slots
    return state.genesis_time + start_slot * spec.seconds_per_slot
