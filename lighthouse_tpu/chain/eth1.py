"""Eth1 layer: deposit-contract cache (incremental merkle tree + proofs)
and eth1-data voting.

Parity surface: /root/reference/beacon_node/eth1/src/ (deposit log cache,
block cache feeding eth1-data votes) and beacon_chain/src/eth1_chain.rs
(vote selection). The deposit tree is the standard depth-32 incremental
merkle tree of the deposit contract, with length mixed in for the SSZ
List[DepositData] root — proofs from it feed process_deposit's
is_valid_merkle_branch (state_transition/block.py).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

DEPOSIT_TREE_DEPTH = 32


def _hash(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


class DepositTree:
    """Incremental merkle tree over deposit-data roots with proof support.

    Keeps all leaves (the cache stores every log anyway, eth1/src/
    deposit_cache.rs) so historical proofs at any deposit_count work —
    that is what blocks need: a proof against eth1_data.deposit_root which
    commits to deposit_count leaves."""

    def __init__(self):
        self.leaves: list[bytes] = []
        self._zeros = [b"\x00" * 32]
        for _ in range(DEPOSIT_TREE_DEPTH):
            self._zeros.append(_hash(self._zeros[-1], self._zeros[-1]))

    def push(self, deposit_data_root: bytes) -> None:
        self.leaves.append(deposit_data_root)

    def __len__(self):
        return len(self.leaves)

    def _root_at(self, count: int) -> bytes:
        """Root of the depth-32 tree over the first `count` leaves, with
        the deposit count mixed in (deposit contract get_deposit_root)."""
        node_layer = list(self.leaves[:count])
        for d in range(DEPOSIT_TREE_DEPTH):
            nxt = []
            for i in range(0, len(node_layer), 2):
                left = node_layer[i]
                right = node_layer[i + 1] if i + 1 < len(node_layer) else self._zeros[d]
                nxt.append(_hash(left, right))
            node_layer = nxt or [self._zeros[d + 1]]
        return _hash(node_layer[0], count.to_bytes(32, "little"))

    def root(self, count: int | None = None) -> bytes:
        return self._root_at(len(self.leaves) if count is None else count)

    def proof(self, index: int, count: int | None = None) -> list[bytes]:
        """Branch for leaf `index` in the tree of the first `count` leaves,
        plus the mixed-in length leaf (DEPOSIT_TREE_DEPTH + 1 elements,
        matching Deposit.proof)."""
        count = len(self.leaves) if count is None else count
        assert index < count
        layer = list(self.leaves[:count])
        idx = index
        branch = []
        for d in range(DEPOSIT_TREE_DEPTH):
            sib = idx ^ 1
            if sib < len(layer):
                branch.append(layer[sib])
            else:
                branch.append(self._zeros[d])
            nxt = []
            for i in range(0, len(layer), 2):
                left = layer[i]
                right = layer[i + 1] if i + 1 < len(layer) else self._zeros[d]
                nxt.append(_hash(left, right))
            layer = nxt or [self._zeros[d + 1]]
            idx //= 2
        branch.append(count.to_bytes(32, "little"))
        return branch


@dataclass
class Eth1Block:
    number: int
    hash: bytes
    timestamp: int
    deposit_root: bytes
    deposit_count: int


@dataclass
class Eth1Cache:
    """Block + deposit caches driving eth1-data votes (service.rs)."""

    tree: DepositTree = field(default_factory=DepositTree)
    blocks: list[Eth1Block] = field(default_factory=list)
    deposits: list[object] = field(default_factory=list)   # DepositData values

    def add_deposit(self, deposit_data, types) -> None:
        self.deposits.append(deposit_data)
        self.tree.push(types.DepositData.hash_tree_root(deposit_data))

    def add_block(self, block: Eth1Block) -> None:
        self.blocks.append(block)

    def deposits_for_block_inclusion(self, state, spec, types, eth1_data=None,
                                     fork=None):
        """Deposits the next block must include (eth1_deposit_index ..
        eth1_data.deposit_count), with proofs against `eth1_data` —
        pass the POST-vote eth1_data when the block's own vote will flip it
        (process_eth1_data runs before process_operations). Electra caps the
        legacy bridge at deposit_requests_start_index (EIP-6110)."""
        ed = eth1_data if eth1_data is not None else state.eth1_data
        start = state.eth1_deposit_index
        limit = ed.deposit_count
        from ..types.spec import ForkName

        if fork is not None and fork >= ForkName.electra:
            limit = min(limit, state.deposit_requests_start_index)
            if start >= limit:
                return []
        count = min(limit - start, spec.preset.MAX_DEPOSITS)
        out = []
        for i in range(start, start + count):
            proof = self.tree.proof(i, count=ed.deposit_count)
            out.append(types.Deposit.make(proof=proof, data=self.deposits[i]))
        return out

    def eth1_vote(self, state, spec, types):
        """Pick an eth1-data vote (eth1_chain.rs voting: follow-distance
        block in the voting period; falls back to the current vote)."""
        period_start = _voting_period_start_time(state, spec)
        follow_secs = 2048 * 14  # ETH1_FOLLOW_DISTANCE * seconds per eth1 block
        candidates = [
            b for b in self.blocks if b.timestamp + follow_secs <= period_start
        ]
        if not candidates:
            return state.eth1_data
        best = max(candidates, key=lambda b: b.number)
        if best.deposit_count < state.eth1_data.deposit_count:
            return state.eth1_data  # never roll back deposits
        return types.Eth1Data.make(
            deposit_root=best.deposit_root,
            deposit_count=best.deposit_count,
            block_hash=best.hash,
        )


class Eth1Service:
    """Deposit-log scraper service (eth1/src/service.rs analog): polls an
    eth1 JSON-RPC endpoint for DepositEvent logs from the deposit contract
    and new block headers, feeding the Eth1Cache + DepositTree that back
    eth1-data voting and deposit inclusion. The endpoint is duck-typed
    (`eth_getLogs`/`eth_blockNumber`/`eth_getBlockByNumber` via .call) so
    the mock EL's JSON-RPC double and a real HTTP client both slot in."""

    DEPOSIT_EVENT_TOPIC = bytes.fromhex(
        "649bbc62d0e31342afea4e5cd82d4049e7e1ee912fc0889aa790803be39038c5"
    )

    def __init__(self, rpc, spec, types, cache: "Eth1Cache | None" = None,
                 follow_distance: int = 0, batch_blocks: int = 1000):
        self.rpc = rpc
        self.spec = spec
        self.types = types
        self.cache = cache or Eth1Cache()
        self.follow_distance = follow_distance
        self.batch_blocks = batch_blocks
        self.last_processed_block = -1
        self.errors = 0

    @staticmethod
    def decode_deposit_log(data: bytes):
        """ABI-decode a DepositEvent log payload: five dynamic bytes fields
        (pubkey, withdrawal_credentials, amount[le64], signature, index)."""
        def dyn(offset_slot: int) -> bytes:
            off = int.from_bytes(data[offset_slot * 32 : offset_slot * 32 + 32], "big")
            ln = int.from_bytes(data[off : off + 32], "big")
            return data[off + 32 : off + 32 + ln]

        pubkey = dyn(0)
        wc = dyn(1)
        amount = int.from_bytes(dyn(2), "little")
        signature = dyn(3)
        index = int.from_bytes(dyn(4), "little")
        return pubkey, wc, amount, signature, index

    def poll_once(self) -> int:
        """One scrape round: fetch logs/blocks up to head-follow_distance.
        Returns deposits ingested; errors are counted, never raised (the
        reference's service loop survives flaky endpoints)."""
        try:
            head = int(self.rpc.call("eth_blockNumber", []), 16)
            target = head - self.follow_distance
            if target <= self.last_processed_block:
                return 0
            frm = self.last_processed_block + 1
            to = min(target, frm + self.batch_blocks - 1)
            logs = self.rpc.call(
                "eth_getLogs",
                [
                    {
                        "fromBlock": hex(frm),
                        "toBlock": hex(to),
                        "address": "0x" + self.spec.deposit_contract_address.hex(),
                        "topics": ["0x" + self.DEPOSIT_EVENT_TOPIC.hex()],
                    }
                ],
            )
            decoded = [
                self.decode_deposit_log(bytes.fromhex(lg["data"][2:])) for lg in logs
            ]
            # A missing/duplicated/reordered log would silently corrupt the
            # deposit tree root and every later proof: each event's own index
            # MUST be the next tree leaf (service.rs errors on non-consecutive
            # deposit logs). A retried range may legitimately re-serve an
            # already-ingested prefix (a prior round ingested, then failed
            # before advancing last_processed_block) — skip idx < base, then
            # require the remainder to be exactly consecutive from base.
            # Validate BEFORE ingesting so a bad range is retried intact.
            base = len(self.cache.tree)
            fresh = [d for d in decoded if d[4] >= base]
            if any(idx != base + i for i, (_, _, _, _, idx) in enumerate(fresh)):
                self.errors += 1
                return 0
            n = 0
            for pk, wc, amount, sig, _idx in fresh:
                dd = self.types.DepositData.make(
                    pubkey=pk, withdrawal_credentials=wc, amount=amount, signature=sig
                )
                self.cache.add_deposit(dd, self.types)
                n += 1
            blk = self.rpc.call("eth_getBlockByNumber", [hex(to), False])
            if blk is not None:
                self.cache.add_block(
                    Eth1Block(
                        number=to,
                        hash=bytes.fromhex(blk["hash"][2:]),
                        timestamp=int(blk["timestamp"], 16),
                        deposit_count=len(self.cache.tree),
                        deposit_root=self.cache.tree.root(),
                    )
                )
            self.last_processed_block = to
            return n
        except Exception:  # noqa: BLE001 — endpoint flakiness must not kill the node
            self.errors += 1
            return 0


class MockEth1Rpc:
    """JSON-RPC double serving deposit logs (mock eth1 endpoint for tests
    and the simulator: eth1/src/service tests use the same shape)."""

    def __init__(self, deposit_contract_address: bytes):
        self.address = deposit_contract_address
        self.blocks: list[dict] = [
            {"hash": "0x" + "00" * 32, "timestamp": hex(1_600_000_000), "number": "0x0"}
        ]
        self.logs: list[dict] = []

    def add_block(self, timestamp: int) -> int:
        import hashlib

        n = len(self.blocks)
        h = hashlib.sha256(f"eth1-{n}".encode()).digest()
        self.blocks.append(
            {"hash": "0x" + h.hex(), "timestamp": hex(timestamp), "number": hex(n)}
        )
        return n

    def add_deposit_log(self, block_number: int, pubkey: bytes, wc: bytes,
                        amount_gwei: int, signature: bytes, index: int) -> None:
        def dyn_tuple(fields: list[bytes]) -> bytes:
            head = b""
            tail = b""
            base = 32 * len(fields)
            for f in fields:
                head += (base + len(tail)).to_bytes(32, "big")
                tail += len(f).to_bytes(32, "big") + f + b"\x00" * ((32 - len(f) % 32) % 32)
            return head + tail

        data = dyn_tuple(
            [
                pubkey,
                wc,
                amount_gwei.to_bytes(8, "little"),
                signature,
                index.to_bytes(8, "little"),
            ]
        )
        self.logs.append(
            {
                "blockNumber": hex(block_number),
                "address": "0x" + self.address.hex(),
                "topics": ["0x" + Eth1Service.DEPOSIT_EVENT_TOPIC.hex()],
                "data": "0x" + data.hex(),
            }
        )

    def call(self, method: str, params: list):
        if method == "eth_blockNumber":
            return hex(len(self.blocks) - 1)
        if method == "eth_getBlockByNumber":
            n = int(params[0], 16)
            return self.blocks[n] if n < len(self.blocks) else None
        if method == "eth_getLogs":
            f = params[0]
            frm, to = int(f["fromBlock"], 16), int(f["toBlock"], 16)
            return [
                lg for lg in self.logs if frm <= int(lg["blockNumber"], 16) <= to
            ]
        raise ValueError(f"unknown method {method}")


def _voting_period_start_time(state, spec) -> int:
    period_slots = spec.preset.EPOCHS_PER_ETH1_VOTING_PERIOD * spec.preset.SLOTS_PER_EPOCH
    start_slot = state.slot - state.slot % period_slots
    return state.genesis_time + start_slot * spec.seconds_per_slot
