"""ValidatorMonitor — per-validator performance tracking.

Parity surface: /root/reference/beacon_node/beacon_chain/src/
validator_monitor.rs (2.1k LoC): registered validators get per-epoch
hit/miss accounting for attestations (source/target/head timeliness and
inclusion delay), block proposals INCLUDING missed proposals, and
sync-committee duty performance; epoch summaries are logged at epoch
boundaries (misses at warning level — the operator alert), exported as
Prometheus metrics, and served over the API
(/lighthouse_tpu/ui/validator-metrics — ui.rs post_validator_monitor_metrics
analog). BeaconChain drives the event methods from its import path and
epoch rollover (beacon_chain.py), so a registered validator is observed
with no further configuration anywhere else.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass

from ..state_transition import accessors as acc
from ..types.spec import ChainSpec
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY

log = get_logger("validator_monitor")

MONITORED_VALIDATORS = REGISTRY.gauge(
    "validator_monitor_validators", "Number of validators being monitored"
)
# hit/miss observations as labeled families (one family per duty, broken
# down by outcome) instead of the previous six ad-hoc singletons — a
# dashboard computes a per-duty hit ratio with one `sum by (result)`
# query, and the SLO epoch window ingests the same verdicts
# (observability/slo.py record_validator_epoch).
MONITOR_BLOCKS = REGISTRY.counter_vec(
    "validator_monitor_blocks_total",
    "Monitored validators' proposal duties, by outcome "
    "(proposed / missed)",
    ("result",),
)
MONITOR_ATTESTATIONS = REGISTRY.counter_vec(
    "validator_monitor_attestations_total",
    "Monitored validators' per-epoch attestation verdicts, by outcome "
    "(timely_target = credit earned / miss = epoch closed with no credit)",
    ("result",),
)
MONITOR_SYNC = REGISTRY.counter_vec(
    "validator_monitor_sync_total",
    "Monitored validators' sync-committee slots, by outcome "
    "(included / missed)",
    ("result",),
)


@dataclass
class EpochSummary:
    attestations: int = 0
    attestation_min_delay: int | None = None
    attestation_source_hits: int = 0
    attestation_target_hits: int = 0
    attestation_head_hits: int = 0
    blocks_proposed: int = 0
    blocks_missed: int = 0
    sync_signatures: int = 0
    sync_misses: int = 0
    slashed: bool = False

    def as_dict(self) -> dict:
        return {
            "attestations": self.attestations,
            "attestation_min_inclusion_delay": self.attestation_min_delay,
            "attestation_source_hits": self.attestation_source_hits,
            "attestation_target_hits": self.attestation_target_hits,
            "attestation_head_hits": self.attestation_head_hits,
            "blocks_proposed": self.blocks_proposed,
            "blocks_missed": self.blocks_missed,
            "sync_signatures": self.sync_signatures,
            "sync_misses": self.sync_misses,
            "slashed": self.slashed,
        }


class ValidatorMonitor:
    def __init__(self, spec: ChainSpec, auto_register: bool = False):
        self.spec = spec
        self.auto_register = auto_register
        self.watched: set[int] = set()
        # (validator_index, epoch) -> EpochSummary
        self.summaries: dict[tuple[int, int], EpochSummary] = defaultdict(EpochSummary)
        # epoch -> [(slot, proposer_index)] expected duties (miss detection)
        self._proposer_duties: dict[int, list[tuple[int, int]]] = {}
        # slots that actually got an imported block, per epoch
        self._proposed_slots: dict[int, set[int]] = defaultdict(set)
        self._finalized_epochs: set[int] = set()

    @property
    def active(self) -> bool:
        return self.auto_register or bool(self.watched)

    def register(self, validator_index: int) -> None:
        self.watched.add(int(validator_index))
        MONITORED_VALIDATORS.set(len(self.watched))

    def _tracked(self, idx: int) -> bool:
        return self.auto_register or idx in self.watched

    # ------------------------------------------------------------- events

    def on_block_imported(self, block, attesting_index_sets) -> None:
        """Called on import with the block and, per included attestation,
        its attesting indices + inclusion info."""
        epoch = block.slot // self.spec.preset.SLOTS_PER_EPOCH
        self._proposed_slots[epoch].add(int(block.slot))
        if self._tracked(block.proposer_index):
            self.summaries[(block.proposer_index, epoch)].blocks_proposed += 1
            MONITOR_BLOCKS.labels("proposed").inc()
            log.info(
                "monitored proposal included",
                validator=int(block.proposer_index),
                slot=int(block.slot),
            )
        for att, indices in attesting_index_sets:
            delay = block.slot - att.data.slot
            att_epoch = att.data.target.epoch
            for vi in indices:
                if not self._tracked(vi):
                    continue
                s = self.summaries[(vi, att_epoch)]
                s.attestations += 1
                if s.attestation_min_delay is None or delay < s.attestation_min_delay:
                    s.attestation_min_delay = delay

    def on_sync_aggregate(self, slot: int, committee_indices, bits) -> None:
        """Per imported block: the sync-committee membership (validator
        indices in committee order; negative = unknown pubkey, skipped)
        and the block's participation bits."""
        epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
        for vi, bit in zip(committee_indices, bits):
            if vi < 0 or not self._tracked(vi):
                continue
            s = self.summaries[(vi, epoch)]
            if bit:
                s.sync_signatures += 1
                MONITOR_SYNC.labels("included").inc()
            else:
                s.sync_misses += 1
                MONITOR_SYNC.labels("missed").inc()

    def on_proposer_duties(self, epoch: int, duties) -> None:
        """Record expected proposers for an epoch: [(slot, validator_idx)]."""
        self._proposer_duties[epoch] = [(int(s), int(v)) for s, v in duties]

    def on_attestation_participation(self, state, epoch: int) -> None:
        """Read participation flags after epoch processing (altair+)."""
        if not hasattr(state, "previous_epoch_participation"):
            return
        for vi, flags in enumerate(state.previous_epoch_participation):
            if not self._tracked(vi):
                continue
            s = self.summaries[(vi, epoch)]
            if acc.has_flag(flags, acc.TIMELY_SOURCE_FLAG_INDEX):
                s.attestation_source_hits += 1
            if acc.has_flag(flags, acc.TIMELY_TARGET_FLAG_INDEX):
                s.attestation_target_hits += 1
            if acc.has_flag(flags, acc.TIMELY_HEAD_FLAG_INDEX):
                s.attestation_head_hits += 1

    def on_slashing(self, validator_index: int, epoch: int) -> None:
        if self._tracked(validator_index):
            self.summaries[(validator_index, epoch)].slashed = True
            log.warn(
                "monitored validator slashed",
                validator=int(validator_index),
                epoch=int(epoch),
            )

    def finalize_epoch(self, epoch: int, state=None) -> None:
        """Close the books for an epoch: read participation flags (state is
        a post-state whose PREVIOUS epoch is `epoch`), detect missed
        proposals against the recorded duties, and emit the operator-facing
        epoch summary — misses at warning level (the missed-block /
        missed-attestation alerting the reference provides)."""
        if epoch < 0 or epoch in self._finalized_epochs:
            return
        self._finalized_epochs.add(epoch)
        if state is not None:
            self.on_attestation_participation(state, epoch)

        proposed = self._proposed_slots.get(epoch, set())
        epoch_hits = 0
        epoch_misses = 0
        for slot, vi in self._proposer_duties.pop(epoch, []):
            if not self._tracked(vi):
                continue
            if slot not in proposed:
                self.summaries[(vi, epoch)].blocks_missed += 1
                MONITOR_BLOCKS.labels("missed").inc()
                epoch_misses += 1
                log.warn(
                    "monitored validator MISSED a block",
                    validator=vi, slot=slot, epoch=epoch,
                )
            else:
                # fulfilled proposal duties are HITS in the SLO epoch
                # window — misses alone would bias the ratio downward
                epoch_hits += 1

        # explicit registrations always get a verdict (including "no data" ->
        # miss); in auto mode, every validator the epoch produced data for
        report_set = set(self.watched) | {
            vi for (vi, e) in self.summaries.keys() if e == epoch
        }
        for vi in sorted(report_set):
            s = self.summaries[(vi, epoch)]
            if s.attestation_target_hits:
                MONITOR_ATTESTATIONS.labels("timely_target").inc(
                    s.attestation_target_hits
                )
                epoch_hits += s.attestation_target_hits
                log.info(
                    "validator epoch summary", validator=vi, epoch=epoch,
                    attestations=s.attestations,
                    min_inclusion_delay=s.attestation_min_delay,
                    target_hits=s.attestation_target_hits,
                    head_hits=s.attestation_head_hits,
                    proposed=s.blocks_proposed,
                    sync_signatures=s.sync_signatures,
                )
            else:
                MONITOR_ATTESTATIONS.labels("miss").inc()
                epoch_misses += 1
                log.warn(
                    "monitored validator MISSED attestation credit",
                    validator=vi, epoch=epoch, attestations=s.attestations,
                )
            # sync-committee verdicts were counted per slot at import time
            # (on_sync_aggregate); fold them into the same epoch feed
            epoch_hits += s.sync_signatures
            epoch_misses += s.sync_misses
        if epoch_hits or epoch_misses:
            # the duty verdicts land in the SLO epoch window next to the
            # pipeline's deadline accounting (observability/slo.py)
            from ..observability import slo as obs_slo

            obs_slo.ACCOUNTANT.record_validator_epoch(epoch_hits, epoch_misses)

    # ------------------------------------------------------------- queries

    def summary(self, validator_index: int, epoch: int) -> EpochSummary:
        return self.summaries[(validator_index, epoch)]

    def epoch_report(self, epoch: int) -> dict[int, EpochSummary]:
        return {vi: s for (vi, e), s in self.summaries.items() if e == epoch}

    def metrics_for(self, indices, epoch: int) -> dict:
        """API payload: {index: summary dict} for the given epoch (the
        /lighthouse_tpu/ui/validator-metrics response body)."""
        out = {}
        for vi in indices:
            s = self.summaries.get((int(vi), epoch))
            out[str(int(vi))] = (s or EpochSummary()).as_dict()
        return out

    def prune(self, before_epoch: int) -> None:
        self.summaries = defaultdict(
            EpochSummary,
            {k: v for k, v in self.summaries.items() if k[1] >= before_epoch},
        )
        for e in [e for e in self._proposed_slots if e < before_epoch]:
            del self._proposed_slots[e]
        for e in [e for e in self._proposer_duties if e < before_epoch]:
            del self._proposer_duties[e]
        self._finalized_epochs = {e for e in self._finalized_epochs if e >= before_epoch}
