"""ValidatorMonitor — per-validator performance tracking.

Parity surface: /root/reference/beacon_node/beacon_chain/src/
validator_monitor.rs (2.1k LoC): registered validators get per-epoch
hit/miss accounting for attestations (source/target/head timeliness),
block proposals, sync-committee duty, plus inclusion-delay tracking;
summaries are logged/exposed at epoch boundaries.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field

from ..state_transition import accessors as acc
from ..types.spec import ChainSpec


@dataclass
class EpochSummary:
    attestations: int = 0
    attestation_min_delay: int | None = None
    attestation_source_hits: int = 0
    attestation_target_hits: int = 0
    attestation_head_hits: int = 0
    blocks_proposed: int = 0
    sync_signatures: int = 0
    slashed: bool = False


class ValidatorMonitor:
    def __init__(self, spec: ChainSpec, auto_register: bool = False):
        self.spec = spec
        self.auto_register = auto_register
        self.watched: set[int] = set()
        # (validator_index, epoch) -> EpochSummary
        self.summaries: dict[tuple[int, int], EpochSummary] = defaultdict(EpochSummary)

    def register(self, validator_index: int) -> None:
        self.watched.add(validator_index)

    def _tracked(self, idx: int) -> bool:
        return self.auto_register or idx in self.watched

    # ------------------------------------------------------------- events

    def on_block_imported(self, block, attesting_index_sets) -> None:
        """Called on import with the block and, per included attestation,
        its attesting indices + inclusion info."""
        epoch = block.slot // self.spec.preset.SLOTS_PER_EPOCH
        if self._tracked(block.proposer_index):
            self.summaries[(block.proposer_index, epoch)].blocks_proposed += 1
        for att, indices in attesting_index_sets:
            delay = block.slot - att.data.slot
            att_epoch = att.data.target.epoch
            for vi in indices:
                if not self._tracked(vi):
                    continue
                s = self.summaries[(vi, att_epoch)]
                s.attestations += 1
                if s.attestation_min_delay is None or delay < s.attestation_min_delay:
                    s.attestation_min_delay = delay

    def on_attestation_participation(self, state, epoch: int) -> None:
        """Read participation flags after epoch processing (altair+)."""
        if not hasattr(state, "previous_epoch_participation"):
            return
        for vi, flags in enumerate(state.previous_epoch_participation):
            if not self._tracked(vi):
                continue
            s = self.summaries[(vi, epoch)]
            if acc.has_flag(flags, acc.TIMELY_SOURCE_FLAG_INDEX):
                s.attestation_source_hits += 1
            if acc.has_flag(flags, acc.TIMELY_TARGET_FLAG_INDEX):
                s.attestation_target_hits += 1
            if acc.has_flag(flags, acc.TIMELY_HEAD_FLAG_INDEX):
                s.attestation_head_hits += 1

    def on_slashing(self, validator_index: int, epoch: int) -> None:
        if self._tracked(validator_index):
            self.summaries[(validator_index, epoch)].slashed = True

    # ------------------------------------------------------------- queries

    def summary(self, validator_index: int, epoch: int) -> EpochSummary:
        return self.summaries[(validator_index, epoch)]

    def epoch_report(self, epoch: int) -> dict[int, EpochSummary]:
        return {
            vi: s for (vi, e), s in self.summaries.items() if e == epoch
        }

    def prune(self, before_epoch: int) -> None:
        self.summaries = defaultdict(
            EpochSummary,
            {k: v for k, v in self.summaries.items() if k[1] >= before_epoch},
        )
