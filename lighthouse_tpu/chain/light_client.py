"""Light-client server: bootstrap/update production + verification.

Parity surface: /root/reference/beacon_node/beacon_chain/src/
light_client_server_cache.rs and the LightClient* containers of
consensus/types — LightClientBootstrap (header + current sync committee +
branch), LightClientUpdate (attested/finalized headers, next sync committee
branch, finality branch, sync aggregate), FinalityUpdate/OptimisticUpdate,
served over the /eth/v1/beacon/light_client endpoints.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..ssz.proof import container_field_proof, verify_branch
from ..state_transition.slot import types_for_slot
from ..types.spec import ChainSpec


@dataclass
class LightClientBootstrap:
    header: object                      # BeaconBlockHeader
    current_sync_committee: object
    current_sync_committee_branch: list


@dataclass
class LightClientUpdate:
    attested_header: object
    next_sync_committee: object
    next_sync_committee_branch: list
    finalized_header: object | None
    finality_branch: list
    sync_aggregate: object
    signature_slot: int


@dataclass
class LightClientFinalityUpdate:
    attested_header: object
    finalized_header: object
    finality_branch: list
    sync_aggregate: object
    signature_slot: int


@dataclass
class LightClientOptimisticUpdate:
    attested_header: object
    sync_aggregate: object
    signature_slot: int


class LightClientServerCache:
    def __init__(self, spec: ChainSpec):
        self.spec = spec
        self.latest_finality_update: LightClientFinalityUpdate | None = None
        self.latest_optimistic_update: LightClientOptimisticUpdate | None = None
        self.bootstraps: dict[bytes, LightClientBootstrap] = {}
        self.best_updates: dict[int, LightClientUpdate] = {}   # sync period -> update

    # ------------------------------------------------------------- produce

    def produce_bootstrap(self, state, block_header) -> LightClientBootstrap:
        types = types_for_slot(self.spec, state.slot)
        _leaf, branch, _pos, _depth = container_field_proof(
            types.BeaconState, state, ["current_sync_committee"]
        )
        return LightClientBootstrap(
            header=block_header,
            current_sync_committee=state.current_sync_committee,
            current_sync_committee_branch=branch,
        )

    def produce_update(self, attested_state, attested_header, finalized_header, sync_aggregate, signature_slot):
        types = types_for_slot(self.spec, attested_state.slot)
        _l, next_branch, _p, _d = container_field_proof(
            types.BeaconState, attested_state, ["next_sync_committee"]
        )
        _l2, fin_branch, _p2, _d2 = container_field_proof(
            types.BeaconState, attested_state, ["finalized_checkpoint", "root"]
        )
        period = (
            attested_state.slot
            // self.spec.preset.SLOTS_PER_EPOCH
            // self.spec.preset.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        )
        update = LightClientUpdate(
            attested_header=attested_header,
            next_sync_committee=attested_state.next_sync_committee,
            next_sync_committee_branch=next_branch,
            finalized_header=finalized_header,
            finality_branch=fin_branch,
            sync_aggregate=sync_aggregate,
            signature_slot=signature_slot,
        )
        prior = self.best_updates.get(period)
        if prior is None or _participants(update) > _participants(prior):
            self.best_updates[period] = update
        return update

    def on_finality(self, attested_state, attested_header, finalized_header, sync_aggregate, signature_slot):
        types = types_for_slot(self.spec, attested_state.slot)
        _l, fin_branch, _p, _d = container_field_proof(
            types.BeaconState, attested_state, ["finalized_checkpoint", "root"]
        )
        self.latest_finality_update = LightClientFinalityUpdate(
            attested_header=attested_header,
            finalized_header=finalized_header,
            finality_branch=fin_branch,
            sync_aggregate=sync_aggregate,
            signature_slot=signature_slot,
        )

    def on_head(self, attested_header, sync_aggregate, signature_slot):
        self.latest_optimistic_update = LightClientOptimisticUpdate(
            attested_header=attested_header,
            sync_aggregate=sync_aggregate,
            signature_slot=signature_slot,
        )


def _participants(update: LightClientUpdate) -> int:
    return sum(1 for b in update.sync_aggregate.sync_committee_bits if b)


# ------------------------------------------------------------- verification


def verify_bootstrap(spec: ChainSpec, bootstrap: LightClientBootstrap, types) -> bool:
    """Check the sync-committee branch against the header's state root."""
    leaf = types.SyncCommittee.hash_tree_root(bootstrap.current_sync_committee)
    # position of current_sync_committee among state fields
    idx = next(
        i for i, f in enumerate(types.BeaconState.fields)
        if f.name == "current_sync_committee"
    )
    return verify_branch(
        leaf,
        bootstrap.current_sync_committee_branch,
        idx,
        bytes(bootstrap.header.state_root),
    )


def verify_finality_branch(spec: ChainSpec, update, types, finalized_block_root: bytes) -> bool:
    """The finality branch proves state.finalized_checkpoint.root against
    the attested header's state root. Leaf position: root is field 1 of the
    Checkpoint (depth 1) under finalized_checkpoint's state field index."""
    state_idx = next(
        i for i, f in enumerate(types.BeaconState.fields)
        if f.name == "finalized_checkpoint"
    )
    pos = 1 + (state_idx << 1)
    return verify_branch(
        finalized_block_root,
        update.finality_branch,
        pos,
        bytes(update.attested_header.state_root),
    )
