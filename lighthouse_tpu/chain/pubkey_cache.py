"""ValidatorPubkeyCache — index -> decompressed pubkey, store-backed.

Parity surface: /root/reference/beacon_node/beacon_chain/src/
validator_pubkey_cache.rs:17-146. This cache is THE feed for batch
verification: signature-set constructors resolve indices through it, and the
TPU backend packs the decompressed affine coordinates straight into device
arrays (a per-pubkey Montgomery-form limb array is memoized so repeat
verifications skip the int->limb conversion)."""

from __future__ import annotations

import numpy as np

from ..crypto import bls
from ..crypto.bls381.constants import P
from ..crypto.jaxbls import limbs as lb
from ..store.kv import Column, KeyValueOp


class ValidatorPubkeyCache:
    def __init__(self, store=None):
        self.store = store
        self.pubkeys: list[bls.PublicKey] = []
        self.pubkey_bytes: list[bytes] = []
        self.index_by_bytes: dict[bytes, int] = {}
        self._mont_coords: list[tuple[np.ndarray, np.ndarray] | None] = []
        if store is not None:
            self._load()

    def _load(self):
        items = sorted(self.store.hot.iter_column(Column.pubkey_cache))
        for key, value in items:
            index = int.from_bytes(key, "little")
            assert index == len(self.pubkeys), "pubkey cache gap"
            pk = bls.PublicKey.deserialize(value)
            self._push(pk, value)

    def _push(self, pk: bls.PublicKey, pk_bytes: bytes):
        self.index_by_bytes[bytes(pk_bytes)] = len(self.pubkeys)
        self.pubkeys.append(pk)
        self.pubkey_bytes.append(bytes(pk_bytes))
        self._mont_coords.append(None)

    def import_new_pubkeys(self, state) -> None:
        """Add any validators beyond the cache length (import_new_pubkeys
        analog; called on state advance/import)."""
        if len(state.validators) <= len(self.pubkeys):
            return
        ops = []
        for i in range(len(self.pubkeys), len(state.validators)):
            pkb = bytes(state.validators[i].pubkey)
            pk = bls.PublicKey.deserialize(pkb)
            self._push(pk, pkb)
            if self.store is not None:
                ops.append(
                    KeyValueOp.put(Column.pubkey_cache, i.to_bytes(8, "little"), pkb)
                )
        if ops:
            self.store.hot.do_atomically(ops)

    def get(self, index: int) -> bls.PublicKey:
        return self.pubkeys[index]

    def get_index(self, pubkey_bytes: bytes) -> int | None:
        return self.index_by_bytes.get(bytes(pubkey_bytes))

    def mont_coords(self, index: int) -> tuple[np.ndarray, np.ndarray]:
        """Montgomery-form limb arrays (x, y) for direct device packing."""
        cached = self._mont_coords[index]
        if cached is None:
            x, y = self.pubkeys[index].point
            cached = (
                lb.pack(x * lb.R_MONT % P),
                lb.pack(y * lb.R_MONT % P),
            )
            self._mont_coords[index] = cached
        return cached

    def __len__(self):
        return len(self.pubkeys)

    def pubkey_getter(self):
        """A get_pubkey callable for signature_sets with by-bytes support."""

        def get_pubkey(index: int) -> bls.PublicKey:
            return self.pubkeys[index]

        def by_bytes(pkb: bytes) -> bls.PublicKey:
            idx = self.index_by_bytes.get(bytes(pkb))
            if idx is not None:
                return self.pubkeys[idx]
            return bls.PublicKey.deserialize(bytes(pkb))

        get_pubkey.by_bytes = by_bytes
        return get_pubkey
