"""Multi-chip parallelism: the device mesh + sharding layout of the
verification pipeline (see mesh.py)."""

from .mesh import (get_mesh, pad_pks, pad_sets, put_pk_grid, put_sets,
                   reset_mesh_cache, sets_sharding)

__all__ = ["get_mesh", "pad_pks", "pad_sets", "put_pk_grid", "put_sets",
           "reset_mesh_cache", "sets_sharding"]
