"""Multi-chip parallelism: the device mesh + sharding layout of the
verification pipeline (see mesh.py)."""

from .mesh import (get_mesh, mesh_shape_key, pad_pks, pad_sets,
                   parse_mesh_shape, pks_sharding, put_pk_grid, put_sets,
                   put_single, replicated_sharding, reset_mesh_cache,
                   sets_sharding)

__all__ = ["get_mesh", "mesh_shape_key", "pad_pks", "pad_sets",
           "parse_mesh_shape", "pks_sharding", "put_pk_grid", "put_sets",
           "put_single", "replicated_sharding", "reset_mesh_cache",
           "sets_sharding"]
