"""Device-mesh layer: production multi-chip sharding of signature batches.

The framework's scaling unit is the signature-set axis (SURVEY.md §5
"distributed communication backend"): every tensor in the verification
pipeline carries the set index as its leading axis, so data-parallel
sharding over a 1-D `sets` mesh makes the per-set stages embarrassingly
parallel while the two cross-set reductions — the signature tree-sum in
stage 1 and the shared-accumulator Fq12 pair product in stage 4 — become
XLA collectives over ICI. This module owns mesh discovery and input
placement; `crypto/jaxbls/backend.py` consults it on every dispatch, so
`verify_signature_sets` transparently uses however many chips are attached
(the analog of blst scaling across cores, except the "cores" are chips).
"""

from __future__ import annotations

import os

SET_AXIS = "sets"

_cached: list = []  # [mesh_or_None] once resolved


def get_mesh():
    """The process-wide 1-D device mesh over the `sets` axis, or None when
    only one device is attached (or LIGHTHOUSE_TPU_MESH=0). Resolved once —
    device topology does not change within a process."""
    if _cached:
        return _cached[0]
    mesh = None
    if os.environ.get("LIGHTHOUSE_TPU_MESH", "1") != "0":
        import jax

        devices = jax.devices()
        if len(devices) > 1:
            import numpy as np
            from jax.sharding import Mesh

            mesh = Mesh(np.array(devices), (SET_AXIS,))
    _cached.append(mesh)
    return mesh


def reset_mesh_cache() -> None:
    """Testing hook: force re-discovery (e.g. after forcing a virtual CPU
    device count)."""
    _cached.clear()


def sets_sharding(mesh, ndim: int):
    """NamedSharding partitioning the leading (set) axis, replicating the
    rest."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(SET_AXIS, *([None] * (ndim - 1))))


def put_sets(a, mesh=None):
    """Place an array with its leading axis sharded over the mesh; plain
    device_put when no mesh. The leading dimension must divide the mesh
    size (callers pad the set axis with masked entries — see pad_sets)."""
    import jax

    if mesh is None:
        mesh = get_mesh()
    if mesh is None:
        return jax.device_put(a)
    import numpy as np

    return jax.device_put(a, sets_sharding(mesh, np.ndim(a)))


def pad_sets(n: int, mesh=None) -> int:
    """Round a set count up so it divides evenly across the mesh."""
    if mesh is None:
        mesh = get_mesh()
    if mesh is None:
        return n
    size = mesh.devices.size
    return ((n + size - 1) // size) * size
