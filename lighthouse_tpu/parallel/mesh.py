"""Device-mesh layer: production multi-chip sharding of signature batches.

The framework's scaling unit is the signature-set axis (SURVEY.md §5
"distributed communication backend"): every tensor in the verification
pipeline carries the set index as its leading axis, so data-parallel
sharding over a 1-D `sets` mesh makes the per-set stages embarrassingly
parallel while the two cross-set reductions — the signature tree-sum in
stage 1 and the shared-accumulator Fq12 pair product in stage 4 — become
XLA collectives over ICI. This module owns mesh discovery and input
placement; `crypto/jaxbls/backend.py` consults it on every dispatch, so
`verify_signature_sets` transparently uses however many chips are attached
(the analog of blst scaling across cores, except the "cores" are chips).

Resolution seams (all consumed by the forced-host-device harness,
`XLA_FLAGS=--xla_force_host_platform_device_count=8`):

  LIGHTHOUSE_TPU_MESH=0          disable the mesh entirely (single chip)
  LIGHTHOUSE_TPU_MESH_DEVICES=k  use only the first k attached devices —
                                 the `bn loadtest --mesh-devices` sweep's
                                 way of comparing 1-vs-8-chip serving in
                                 one process (k=1 means no mesh)
  LIGHTHOUSE_TPU_PK_SHARDS=k     fold the devices into a 2-D (sets, pks)
                                 mesh; must be a power of two dividing the
                                 device count, rejected LOUDLY otherwise

`reset_mesh_cache()` re-runs discovery after any of these change — the
test seam the harness flips between sweep points.
"""

from __future__ import annotations

import os

from ..utils.metrics import REGISTRY

SET_AXIS = "sets"
PK_AXIS = "pks"

# ------------------------------------------------------------------ metrics
# mesh_* series are labeled families (scripts/lint_metrics.py enforces it):
# the axis breakdown answers "what topology is this node actually serving
# on", the dispatch family answers "which lane is sharding work"

_MESH_AXIS_SIZE = REGISTRY.gauge_vec(
    "mesh_axis_size",
    "devices along each mesh axis of the resolved device mesh (1-D sets "
    "or 2-D sets x pks); absent until a mesh resolves",
    ("axis",),
)
MESH_DISPATCH = REGISTRY.counter_vec(
    "mesh_sharded_dispatch_total",
    "jaxbls batch dispatches by placement lane: `sharded` over the mesh, "
    "`urgent` (the bypass lane, pinned to one chip), or `single_device` "
    "(ordinary batches on a mesh-less node)",
    ("lane",),
)

_cached: list = []  # [mesh_or_None] once resolved


def _record_bringup(mesh) -> None:
    """Flight-recorder + metrics + one structured log line for a resolved
    mesh: topology changes are exactly the bring-up facts an incident dump
    should carry next to breaker/route events. Every known axis gauge is
    (re)written — a re-resolution from 2-D to 1-D (or to no mesh at all)
    must not leave a stale pks/sets size on /metrics."""
    from ..utils.logging import get_logger

    shape = dict(mesh.shape) if mesh is not None else {}
    for axis in (SET_AXIS, PK_AXIS):
        _MESH_AXIS_SIZE.labels(axis).set(shape.get(axis, 0))
    if mesh is None:
        return
    get_logger("mesh").info(
        "device mesh resolved", shape=str(shape),
        devices=int(mesh.devices.size),
    )
    try:
        from ..observability.flight_recorder import RECORDER

        RECORDER.record(
            "mesh_bringup", devices=int(mesh.devices.size),
            **{f"axis_{a}": int(s) for a, s in shape.items()},
        )
    except Exception:
        pass  # diagnostics must never break mesh discovery


def _reject_pk_shards(raw: str, devices: int, why: str) -> None:
    """ONE structured warn naming the rejected LIGHTHOUSE_TPU_PK_SHARDS
    value — the docstring's "loudly". Every rejection path (unparseable
    included) funnels through here so none can fall back silently."""
    from ..utils.logging import get_logger

    get_logger("mesh").warn(
        "ignoring LIGHTHOUSE_TPU_PK_SHARDS (must be a power of two "
        "dividing the device count); falling back to the 1-D sets mesh",
        value=raw, devices=devices, reason=why,
    )
    try:
        from ..observability.flight_recorder import RECORDER

        RECORDER.record("mesh_config_rejected", severity="warn",
                        pk_shards=raw, devices=devices, reason=why)
    except Exception:
        pass


def get_mesh():
    """The process-wide device mesh, or None when only one device is
    attached (or LIGHTHOUSE_TPU_MESH=0, or LIGHTHOUSE_TPU_MESH_DEVICES=1).
    Resolved once — device topology does not change within a process;
    harnesses that flip the env seams call `reset_mesh_cache` after.

    Default shape: 1-D over the `sets` axis (signature sets are
    data-parallel). LIGHTHOUSE_TPU_PK_SHARDS=k > 1 folds the devices into a
    2-D (sets, pks) mesh: the PUBKEY axis of each set is also sharded, so a
    single huge aggregation (the 512-pubkey sync-committee case — the
    within-set Pippenger-style parallelism SURVEY §5 calls for) spreads its
    point tree across chips, with the tree reduction lowering to
    collectives over the pks axis."""
    if _cached:
        return _cached[0]
    mesh = None
    if os.environ.get("LIGHTHOUSE_TPU_MESH", "1") != "0":
        import jax

        devices = jax.devices()
        raw_cap = os.environ.get("LIGHTHOUSE_TPU_MESH_DEVICES", "").strip()
        if raw_cap:
            try:
                cap = int(raw_cap)
            except ValueError:
                cap = None
            if cap is None or cap < 1:
                # unparseable OR non-positive: every invalid value is
                # rejected loudly — silent fallback is how a typo'd knob
                # serves the wrong topology for weeks
                from ..utils.logging import get_logger

                get_logger("mesh").warn(
                    "ignoring invalid LIGHTHOUSE_TPU_MESH_DEVICES "
                    "(must be an integer >= 1); using all devices",
                    value=raw_cap,
                )
            else:
                devices = devices[:cap]
        # the kernels' tree reductions (and pad_sets' pow2-multiple rule)
        # require a power-of-two set axis: a 3- or 6-device slice would
        # send the first dispatch into an unsatisfiable padding search.
        # Serve on the largest pow2 prefix and say so.
        if len(devices) > 1 and len(devices) & (len(devices) - 1):
            usable = 1 << (len(devices).bit_length() - 1)
            from ..utils.logging import get_logger

            get_logger("mesh").warn(
                "device count is not a power of two; meshing the first "
                "pow2 devices (the tree reductions are pow2-structured)",
                devices=len(devices), usable=usable,
            )
            devices = devices[:usable]
        if len(devices) > 1:
            import numpy as np
            from jax.sharding import Mesh

            raw = os.environ.get("LIGHTHOUSE_TPU_PK_SHARDS", "1")
            try:
                pk_shards = int(raw)
            except ValueError:
                pk_shards = 1
                # the pre-r10 silent branch: an unparseable value fell
                # back to the 1-D mesh with no trace of the typo'd knob
                _reject_pk_shards(raw, len(devices), "unparseable")
            # the kernels' tree reductions are pow2-structured: only accept
            # a pow2 shard count that divides the device count. EVERY
            # other value — zero/negative included — falls back to the
            # 1-D mesh loudly; only an explicit 1 (the documented
            # "no pk sharding") is a quiet no-op.
            valid = (
                pk_shards > 1
                and pk_shards & (pk_shards - 1) == 0
                and len(devices) % pk_shards == 0
            )
            if pk_shards < 1:
                _reject_pk_shards(raw, len(devices), "non_positive")
            elif pk_shards > 1 and not valid:
                _reject_pk_shards(
                    raw, len(devices),
                    "not_pow2" if pk_shards & (pk_shards - 1) else "not_dividing",
                )
            if valid:
                grid = np.array(devices).reshape(-1, pk_shards)
                mesh = Mesh(grid, (SET_AXIS, PK_AXIS))
            else:
                mesh = Mesh(np.array(devices), (SET_AXIS,))
    _record_bringup(mesh)  # also clears stale gauges when mesh is None
    _cached.append(mesh)
    return mesh


def reset_mesh_cache() -> None:
    """Test/harness seam: force re-discovery. The forced-host-device
    harness (and the `--mesh-devices` sweep) flips LIGHTHOUSE_TPU_MESH /
    LIGHTHOUSE_TPU_MESH_DEVICES / LIGHTHOUSE_TPU_PK_SHARDS and calls this
    so the next `get_mesh()` re-reads them; the jaxbls stage cache is
    keyed by the mesh signature, so a re-resolved mesh picks up fresh
    compiled variants without clearing anything else."""
    _cached.clear()


def mesh_shape_key(mesh=_cached) -> str:
    """Canonical topology string for autotune profile keys: "single" for
    no mesh, else axis-size segments like "sets8" / "sets4-pks2". Pass an
    explicit mesh (or None) to stringify a known topology without
    resolving the live one."""
    if mesh is _cached:
        mesh = get_mesh()
    if mesh is None:
        return "single"
    return "-".join(f"{axis}{size}" for axis, size in dict(mesh.shape).items())


def parse_mesh_shape(key: str | None) -> dict:
    """Inverse of mesh_shape_key: {"sets": 8, "pks": 2}; {} for
    None/"single"/unparseable (treated as single-chip)."""
    import re

    if not key or key == "single":
        return {}
    out = {}
    for part in str(key).split("-"):
        m = re.fullmatch(r"([a-z_]+)(\d+)", part)
        if not m:
            return {}
        out[m.group(1)] = int(m.group(2))
    return out


def sets_sharding(mesh, ndim: int):
    """NamedSharding partitioning the leading (set) axis, replicating the
    rest."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(SET_AXIS, *([None] * (ndim - 1))))


def pks_sharding(mesh, ndim: int):
    """NamedSharding partitioning (set, pubkey) leading axes — for the
    (n, m, ...) pubkey coordinate arrays on a 2-D mesh."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(
        mesh, PartitionSpec(SET_AXIS, PK_AXIS, *([None] * (ndim - 2)))
    )


def replicated_sharding(mesh):
    """NamedSharding replicating an array on every mesh device (the
    cross-set accumulators and scalar verdicts)."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def put_sets(a, mesh=None):
    """Place an array with its leading axis sharded over the mesh; plain
    device_put when no mesh. The leading dimension must divide the mesh
    size (callers pad the set axis with masked entries — see pad_sets)."""
    import jax

    if mesh is None:
        mesh = get_mesh()
    if mesh is None:
        return jax.device_put(a)
    import numpy as np

    return jax.device_put(a, sets_sharding(mesh, np.ndim(a)))


def put_pk_grid(a, mesh=None):
    """Place an (n_sets, n_pks, ...) pubkey array: set axis sharded always;
    pubkey axis additionally sharded on a 2-D mesh."""
    import jax

    if mesh is None:
        mesh = get_mesh()
    if mesh is None:
        return jax.device_put(a)
    import numpy as np

    if PK_AXIS in mesh.axis_names:
        return jax.device_put(a, pks_sharding(mesh, np.ndim(a)))
    return jax.device_put(a, sets_sharding(mesh, np.ndim(a)))


def put_single(a):
    """Place an array whole on the default (first) device — the urgent
    bypass lane's placement: a ~ms single-set verify must never pay mesh
    resharding or collective latency (docs/PERF_NOTES.md "Multichip
    serving"). Deliberately UNCOMMITTED (no explicit device): the default
    device is chip 0, and uncommitted placement lowers identically to the
    host-numpy inputs the warmup paths feed, so both hit one compiled
    program."""
    import jax

    return jax.device_put(a)


def _axis_size(mesh, axis: str) -> int:
    return mesh.shape[axis] if mesh is not None and axis in mesh.axis_names else 1


def _pad_pow2_multiple(n: int, size: int) -> int:
    """Smallest power of two >= n that is also a multiple of `size` — the
    kernels' tree reductions are pow2-structured AND sharded axes must
    divide the mesh axis, so both constraints apply together. `size` must
    itself be a power of two (get_mesh guarantees it); a non-pow2 size
    has NO pow2 multiple, so raise instead of searching forever."""
    if size > 1 and size & (size - 1):
        raise ValueError(
            f"mesh axis size {size} is not a power of two — no pow2 "
            "padding exists (get_mesh should have rejected this topology)"
        )
    p = 1
    while p < max(n, 1):
        p *= 2
    while p % size:
        p *= 2
    return p


def pad_sets(n: int, mesh=None) -> int:
    """Round a set count up so it divides evenly across the mesh (and stays
    a power of two for the signature tree-sum). Pass an explicit mesh to
    pad for a topology other than the live one (the padding/bucket rule is
    mesh-shape-keyed — crypto/jaxbls/backend.padding_bucket)."""
    if mesh is None:
        mesh = get_mesh()
    if mesh is None:
        return n
    return _pad_pow2_multiple(n, _axis_size(mesh, SET_AXIS))


def pad_pks(m: int, mesh=None) -> int:
    """Round a per-set pubkey count up to a pow2 multiple of the pks axis
    (the pubkey aggregation is a pow2 halving tree)."""
    if mesh is None:
        mesh = get_mesh()
    if mesh is None:
        return m
    return _pad_pow2_multiple(m, _axis_size(mesh, PK_AXIS))
