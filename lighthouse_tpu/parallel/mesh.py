"""Device-mesh layer: production multi-chip sharding of signature batches.

The framework's scaling unit is the signature-set axis (SURVEY.md §5
"distributed communication backend"): every tensor in the verification
pipeline carries the set index as its leading axis, so data-parallel
sharding over a 1-D `sets` mesh makes the per-set stages embarrassingly
parallel while the two cross-set reductions — the signature tree-sum in
stage 1 and the shared-accumulator Fq12 pair product in stage 4 — become
XLA collectives over ICI. This module owns mesh discovery and input
placement; `crypto/jaxbls/backend.py` consults it on every dispatch, so
`verify_signature_sets` transparently uses however many chips are attached
(the analog of blst scaling across cores, except the "cores" are chips).
"""

from __future__ import annotations

import os

SET_AXIS = "sets"
PK_AXIS = "pks"

_cached: list = []  # [mesh_or_None] once resolved


def get_mesh():
    """The process-wide device mesh, or None when only one device is
    attached (or LIGHTHOUSE_TPU_MESH=0). Resolved once — device topology
    does not change within a process.

    Default shape: 1-D over the `sets` axis (signature sets are
    data-parallel). LIGHTHOUSE_TPU_PK_SHARDS=k > 1 folds the devices into a
    2-D (sets, pks) mesh: the PUBKEY axis of each set is also sharded, so a
    single huge aggregation (the 512-pubkey sync-committee case — the
    within-set Pippenger-style parallelism SURVEY §5 calls for) spreads its
    point tree across chips, with the tree reduction lowering to
    collectives over the pks axis."""
    if _cached:
        return _cached[0]
    mesh = None
    if os.environ.get("LIGHTHOUSE_TPU_MESH", "1") != "0":
        import jax

        devices = jax.devices()
        if len(devices) > 1:
            import numpy as np
            from jax.sharding import Mesh

            raw = os.environ.get("LIGHTHOUSE_TPU_PK_SHARDS", "1")
            try:
                pk_shards = int(raw)
            except ValueError:
                pk_shards = 1
            # the kernels' tree reductions are pow2-structured: only accept
            # a pow2 shard count that divides the device count (anything
            # else falls back to the 1-D mesh, loudly)
            valid = (
                pk_shards > 1
                and pk_shards & (pk_shards - 1) == 0
                and len(devices) % pk_shards == 0
            )
            if pk_shards > 1 and not valid:
                from ..utils.logging import get_logger

                get_logger("mesh").warn(
                    "ignoring LIGHTHOUSE_TPU_PK_SHARDS (must be a power of "
                    "two dividing the device count)",
                    value=raw, devices=len(devices),
                )
            if valid:
                grid = np.array(devices).reshape(-1, pk_shards)
                mesh = Mesh(grid, (SET_AXIS, PK_AXIS))
            else:
                mesh = Mesh(np.array(devices), (SET_AXIS,))
    _cached.append(mesh)
    return mesh


def reset_mesh_cache() -> None:
    """Testing hook: force re-discovery (e.g. after forcing a virtual CPU
    device count)."""
    _cached.clear()


def sets_sharding(mesh, ndim: int):
    """NamedSharding partitioning the leading (set) axis, replicating the
    rest."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(SET_AXIS, *([None] * (ndim - 1))))


def pks_sharding(mesh, ndim: int):
    """NamedSharding partitioning (set, pubkey) leading axes — for the
    (n, m, ...) pubkey coordinate arrays on a 2-D mesh."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(
        mesh, PartitionSpec(SET_AXIS, PK_AXIS, *([None] * (ndim - 2)))
    )


def put_sets(a, mesh=None):
    """Place an array with its leading axis sharded over the mesh; plain
    device_put when no mesh. The leading dimension must divide the mesh
    size (callers pad the set axis with masked entries — see pad_sets)."""
    import jax

    if mesh is None:
        mesh = get_mesh()
    if mesh is None:
        return jax.device_put(a)
    import numpy as np

    return jax.device_put(a, sets_sharding(mesh, np.ndim(a)))


def put_pk_grid(a, mesh=None):
    """Place an (n_sets, n_pks, ...) pubkey array: set axis sharded always;
    pubkey axis additionally sharded on a 2-D mesh."""
    import jax

    if mesh is None:
        mesh = get_mesh()
    if mesh is None:
        return jax.device_put(a)
    import numpy as np

    if PK_AXIS in mesh.axis_names:
        return jax.device_put(a, pks_sharding(mesh, np.ndim(a)))
    return jax.device_put(a, sets_sharding(mesh, np.ndim(a)))


def _axis_size(mesh, axis: str) -> int:
    return mesh.shape[axis] if mesh is not None and axis in mesh.axis_names else 1


def _pad_pow2_multiple(n: int, size: int) -> int:
    """Smallest power of two >= n that is also a multiple of `size` — the
    kernels' tree reductions are pow2-structured AND sharded axes must
    divide the mesh axis, so both constraints apply together."""
    p = 1
    while p < max(n, 1):
        p *= 2
    while p % size:
        p *= 2
    return p


def pad_sets(n: int, mesh=None) -> int:
    """Round a set count up so it divides evenly across the mesh (and stays
    a power of two for the signature tree-sum)."""
    if mesh is None:
        mesh = get_mesh()
    if mesh is None:
        return n
    return _pad_pow2_multiple(n, _axis_size(mesh, SET_AXIS))


def pad_pks(m: int, mesh=None) -> int:
    """Round a per-set pubkey count up to a pow2 multiple of the pks axis
    (the pubkey aggregation is a pow2 halving tree)."""
    if mesh is None:
        mesh = get_mesh()
    if mesh is None:
        return m
    return _pad_pow2_multiple(m, _axis_size(mesh, PK_AXIS))
