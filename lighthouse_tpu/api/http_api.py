"""Beacon HTTP API server (stdlib http.server; warp analog).

Parity surface: the load-bearing route families of
/root/reference/beacon_node/http_api/src/lib.rs —
  /eth/v1/beacon/genesis | states/{id}/root | states/{id}/finality_checkpoints
  /eth/v1/beacon/states/{id}/validators[/{vid}] | headers/{id} | blocks/{id}/root
  /eth/v2/beacon/blocks/{id}   POST /eth/v1/beacon/pool/attestations
  POST /eth/v2/beacon/blocks (publish: broadcast-then-import semantics)
  /eth/v1/node/health | version | syncing      /eth/v1/config/spec
  /eth/v1/validator/duties/attester/{epoch} (POST) | duties/proposer/{epoch}
  /eth/v1/validator/attestation_data           /eth/v1/events (SSE)
plus /lighthouse-style extras under /lighthouse_tpu/*.

JSON encoding follows the beacon-api conventions: quoted integers, 0x-hex
byte strings.
"""

from __future__ import annotations

import json
import os
import queue
import re
import select
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer
from time import perf_counter

from ..observability.propagation import decode_ctx, encode_ctx
from ..observability.trace import set_current_wire_ctx
from ..state_transition import accessors as acc
from ..state_transition.slot import types_for_slot
from ..types import helpers as h
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY

log = get_logger("http_api")

VERSION = "lighthouse-tpu/0.1.0"

# request latency by route family (handler name, stable across path params
# — `get_validators` not `/eth/v1/.../states/head/validators`) and method:
# the http_api/src/metrics.rs HTTP_API_PATHS_TOTAL idiom with a histogram
_REQUEST_SECONDS = REGISTRY.histogram_vec(
    "http_api_request_seconds",
    "Beacon API request latency, by route family and method",
    ("route", "method"),
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0, 30.0),
)

# saturation SLIs for the bounded worker pool: how much work is in the
# house (workers busy / sockets queued / connections parked), what was
# turned away and why, and which read stage ate a deadline. All labeled
# families — an unlabeled aggregate cannot answer "was that shed a
# saturation event or a shutdown drain", and lint_metrics enforces it.
_INFLIGHT = REGISTRY.gauge_vec(
    "http_api_inflight",
    "Beacon API work in flight, by kind (workers busy / queued / parked)",
    ("kind",),
)
_SHED_TOTAL = REGISTRY.counter_vec(
    "http_api_shed_total",
    "Beacon API connections shed by the admission gate, by reason",
    ("reason",),
)
_TIMEOUTS_TOTAL = REGISTRY.counter_vec(
    "http_api_timeouts_total",
    "Beacon API per-request read-deadline expiries, by stage",
    ("stage",),
)
_ERRORS_TOTAL = REGISTRY.counter_vec(
    "http_api_errors_total",
    "Beacon API handler errors, by stage",
    ("stage",),
)


def resolve_http_threads(explicit=None) -> int:
    """Worker-pool size: explicit flag > LIGHTHOUSE_TPU_HTTP_THREADS env >
    default 8 (the `bn --http-threads` knob, resolve_call_timeout idiom)."""
    if explicit is not None:
        return max(1, int(explicit))
    env = os.environ.get("LIGHTHOUSE_TPU_HTTP_THREADS")
    if env:
        return max(1, int(env))
    return 8


def resolve_http_request_timeout(explicit=None) -> float:
    """Per-request header/body read deadline: explicit flag >
    LIGHTHOUSE_TPU_HTTP_REQUEST_TIMEOUT env > default 10s — a slow-loris
    peer costs one worker at most this long (`bn --http-request-timeout`)."""
    if explicit is not None:
        return float(explicit)
    env = os.environ.get("LIGHTHOUSE_TPU_HTTP_REQUEST_TIMEOUT")
    if env:
        return float(env)
    return 10.0


def _hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _u(x: int) -> str:
    return str(int(x))


def _checkpoint(cp) -> dict:
    return {"epoch": _u(cp.epoch), "root": _hex(cp.root)}


def _validator_json(i, v, balance) -> dict:
    return {
        "index": _u(i),
        "balance": _u(balance),
        "status": "active_ongoing",
        "validator": {
            "pubkey": _hex(v.pubkey),
            "withdrawal_credentials": _hex(v.withdrawal_credentials),
            "effective_balance": _u(v.effective_balance),
            "slashed": bool(v.slashed),
            "activation_eligibility_epoch": _u(v.activation_eligibility_epoch),
            "activation_epoch": _u(v.activation_epoch),
            "exit_epoch": _u(v.exit_epoch),
            "withdrawable_epoch": _u(v.withdrawable_epoch),
        },
    }


class ApiError(Exception):
    def __init__(self, code: int, message: str):
        self.code = code
        self.message = message


from contextlib import contextmanager


@contextmanager
def _client_input():
    """Context manager for BODY PARSING in submission handlers: a malformed
    client payload (missing keys, wrong types, bad hex) maps to 400, while
    the same exception types escaping chain internals stay 500 faults."""
    try:
        yield
    except (KeyError, TypeError, ValueError) as e:
        raise ApiError(400, f"malformed body: {type(e).__name__}: {e}") from e


class BeaconApiHandler(BaseHTTPRequestHandler):
    """Routes are matched with regexes against (method, path)."""

    server_version = VERSION
    # HTTP/1.1: keep-alive by default, so the pooled client's reused
    # connections survive between requests (every response path sends
    # Content-Length — _json, _rate_limited, get_health)
    protocol_version = "HTTP/1.1"
    # TCP_NODELAY: the response goes out as header-flush + body write;
    # with Nagle on, the body write waits on the peer's delayed ACK
    # (~40ms per keep-alive request)
    disable_nagle_algorithm = True
    chain = None           # injected by serve()
    op_pool = None
    event_bus = None
    allow_origin = None    # --http-allow-origin: CORS on every response
    # optional Tracer: each served request records an `http_serve` trace,
    # adopting the caller's X-LH-Trace-Ctx wire context so an HTTP-served
    # duty's spans carry the producer's causal id in the merged timeline
    tracer = None
    # QoS token bucket over the whole API (lighthouse_tpu/qos/ratelimit.py,
    # scope "http_api"): requests over quota are answered 429 with a
    # Retry-After header instead of queuing work behind an overloaded
    # chain. None (the default) disables limiting; `bn --http-rate-limit`
    # wires it. /eth/v1/node/health is exempt — liveness probes must answer
    # precisely when the node is busiest.
    rate_limiter = None
    RATE_LIMIT_EXEMPT = ("/eth/v1/node/health",)

    def end_headers(self):
        if self.allow_origin:
            self.send_header("Access-Control-Allow-Origin", self.allow_origin)
        ctx = getattr(self, "_wire_ctx", None)
        if ctx is not None:
            # echo the adopted context so the caller can confirm the causal
            # join (hex — header-safe encoding of the wire bytes)
            self.send_header("X-LH-Trace-Ctx", encode_ctx(ctx).hex())
        super().end_headers()

    def handle(self):
        """One request per pool dispatch: the worker decides afterwards
        whether to park the connection for keep-alive re-admission or
        close it — a handler thread never loops on one peer's socket."""
        self.close_connection = True
        self.handle_one_request()

    def log_error(self, fmt, *args):
        # handle_one_request swallows TimeoutError internally (discarding
        # the connection) and this hook is the only signal it leaves —
        # count the header-stage deadline here; body-stage deadlines are
        # counted in _read_body where the stage is known precisely
        if str(fmt).startswith("Request timed out"):
            _TIMEOUTS_TOTAL.labels("header").inc()
    # Backpressure for the HEAVY publish paths (block/attestation/sync-
    # committee import runs verification inline in the handler thread):
    # bounded gates — work beyond the limit gets 503 immediately, like the
    # reference sheds API work when the beacon-processor queues are full
    # (Work::ApiRequestP0/P1 bounded queues). Two deliberate properties:
    #   * permits are acquired AFTER the request body is read/parsed, so a
    #     slow client holds only its own handler thread, never a permit
    #     (and the 503 is written with the body already drained — no RST
    #     racing the response on big block bodies);
    #   * block publishes (the proposal path — P0 in the reference) have
    #     their OWN gate, so a burst of attestation/sync-committee posts
    #     can never 503 a proposer's block.
    _block_publish_gate = threading.BoundedSemaphore(
        int(os.environ.get("LIGHTHOUSE_TPU_MAX_CONCURRENT_BLOCK_PUBLISHES", "2"))
    )
    _bulk_publish_gate = threading.BoundedSemaphore(
        int(os.environ.get("LIGHTHOUSE_TPU_MAX_CONCURRENT_PUBLISHES", "8"))
    )

    @contextmanager
    def _publish_permit(self, gate):
        """Call only AFTER the body is fully read (see class comment)."""
        if not gate.acquire(blocking=False):
            raise ApiError(503, "publish pipeline overloaded; retry")
        try:
            yield
        finally:
            gate.release()

    def log_message(self, *args):  # silence default stderr logging
        pass

    # ------------------------------------------------------------- plumbing

    def _json(self, payload, code=200):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code, message):
        self._json({"code": code, "message": message}, code=code)

    def _read_body(self):
        length = int(self.headers.get("Content-Length", 0))
        if length == 0:
            return None
        try:
            raw = self.rfile.read(length)
        except (TimeoutError, socket.timeout):
            # mid-body stall: the read deadline freed this worker — the
            # connection is poisoned (partial body unread), so close it
            _TIMEOUTS_TOTAL.labels("body").inc()
            self.close_connection = True
            raise ApiError(408, "body read timed out") from None
        if len(raw) < length:
            self.close_connection = True
            raise ApiError(400, "truncated body")
        return json.loads(raw)

    def _state_by_id(self, state_id: str):
        chain = self.chain
        if state_id == "head":
            return chain.head_state()
        if state_id == "genesis":
            state_id = "0"
        if state_id == "finalized":
            # best-effort: finalized state if cached, else head
            froot = chain.fork_choice.store.finalized_checkpoint[1]
            sroot = chain.state_root_by_block.get(froot)
            if sroot and sroot in chain.state_cache:
                return chain.state_cache[sroot]
            return chain.head_state()
        if state_id.startswith("0x"):
            root = bytes.fromhex(state_id[2:])
            st = chain.state_cache.get(root)
            if st is None:
                raise ApiError(404, "state not found")
            return st
        # slot number: search cache
        slot = int(state_id)
        for st in chain.state_cache.values():
            if st.slot == slot:
                return st
        raise ApiError(404, "state not found")

    def _block_root_by_id(self, block_id: str) -> bytes:
        chain = self.chain
        if block_id == "head":
            return chain.head_root
        if block_id == "genesis":
            return chain.genesis_block_root
        if block_id == "finalized":
            return chain.fork_choice.store.finalized_checkpoint[1]
        if block_id.startswith("0x"):
            return bytes.fromhex(block_id[2:])
        slot = int(block_id)
        for root, s in chain.block_slots.items():
            if s == slot:
                return root
        raise ApiError(404, "block not found")

    # ------------------------------------------------------------- dispatch

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def _rate_limited(self):
        retry = self.rate_limiter.retry_after_secs("http_api")
        body = json.dumps(
            {"code": 429, "message": "rate limit exceeded; retry later"}
        ).encode()
        self.send_response(429)
        self.send_header("Content-Type", "application/json")
        self.send_header("Retry-After", str(retry))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _dispatch(self, method):
        path = self.path.split("?")[0].rstrip("/")
        # caller-propagated wire context (X-LH-Trace-Ctx, hex of the gossip
        # envelope encoding): tolerant decode — a malformed context must
        # never fail the request it rode in on
        ctx = None
        raw_ctx = self.headers.get("X-LH-Trace-Ctx")
        if raw_ctx:
            try:
                ctx = decode_ctx(bytes.fromhex(raw_ctx))
            except ValueError:
                ctx = None
        self._wire_ctx = ctx
        if (
            self.rate_limiter is not None
            and path not in self.RATE_LIMIT_EXEMPT
            and not self.rate_limiter.allow("http_api")
        ):
            return self._rate_limited()
        tr = None
        if self.tracer is not None:
            if ctx is not None:
                set_current_wire_ctx(ctx)
            tr = self.tracer.begin("http_serve")
        try:
            for pattern, meth, fn in _ROUTES:
                m = re.fullmatch(pattern, path)
                if m and meth == method:
                    t0 = perf_counter()
                    try:
                        return fn(self, *m.groups())
                    finally:
                        t1 = perf_counter()
                        _REQUEST_SECONDS.labels(fn.__name__, method).observe(
                            t1 - t0
                        )
                        if tr is not None:
                            tr.add_span(fn.__name__, t0, t1,
                                        path=path, method=method)
            self._error(404, f"unknown route {path}")
        except ApiError as e:
            self._error(e.code, e.message)
        except ConnectionError:
            # the PEER died mid-exchange (reset/broken pipe while we wrote
            # the response) — not a handler fault, and there is no socket
            # left to send an error envelope on
            self.close_connection = True
        except Exception as e:  # noqa: BLE001
            from ..chain.beacon_chain import BlockError

            if isinstance(e, BlockError):
                # invalid submissions are client errors, not server faults
                # (publish_blocks.rs maps verification failures to 400)
                self._error(400, f"BlockError: {e}")
            elif isinstance(e, (ValueError, json.JSONDecodeError)):
                # malformed ids/params are client errors (warp's invalid-
                # param rejections map to 400); submission handlers wrap
                # body parsing in _client_input() for the KeyError/TypeError
                # shapes so internal faults keep surfacing as 500s
                self._error(400, f"invalid request: {type(e).__name__}: {e}")
            else:
                _ERRORS_TOTAL.labels("handler").inc()
                log.warn(
                    "handler fault", route=path, method=method,
                    error=f"{type(e).__name__}: {e}",
                )
                self._error(500, f"{type(e).__name__}: {e}")
        finally:
            if tr is not None:
                self.tracer.finish(tr)
            if ctx is not None:
                set_current_wire_ctx(None)

    # ------------------------------------------------------------- handlers

    def get_genesis(self):
        st = self.chain.head_state()
        self._json(
            {
                "data": {
                    "genesis_time": _u(st.genesis_time),
                    "genesis_validators_root": _hex(st.genesis_validators_root),
                    "genesis_fork_version": _hex(self.chain.spec.genesis_fork_version),
                }
            }
        )

    def get_state_root(self, state_id):
        st = self._state_by_id(state_id)
        types = types_for_slot(self.chain.spec, st.slot)
        self._json({"data": {"root": _hex(types.BeaconState.hash_tree_root(st))}})

    def get_finality_checkpoints(self, state_id):
        st = self._state_by_id(state_id)
        self._json(
            {
                "data": {
                    "previous_justified": _checkpoint(st.previous_justified_checkpoint),
                    "current_justified": _checkpoint(st.current_justified_checkpoint),
                    "finalized": _checkpoint(st.finalized_checkpoint),
                }
            }
        )

    def get_validators(self, state_id):
        st = self._state_by_id(state_id)
        self._json(
            {
                "data": [
                    _validator_json(i, v, st.balances[i])
                    for i, v in enumerate(st.validators)
                ]
            }
        )

    def get_validator(self, state_id, vid):
        st = self._state_by_id(state_id)
        if vid.startswith("0x"):
            pkb = bytes.fromhex(vid[2:])
            if len(pkb) != 48:
                raise ApiError(400, "validator pubkey must be 48 bytes")
            for i, v in enumerate(st.validators):
                if bytes(v.pubkey) == pkb:
                    return self._json({"data": _validator_json(i, v, st.balances[i])})
            raise ApiError(404, "validator not found")
        i = int(vid)
        if i >= len(st.validators):
            raise ApiError(404, "validator not found")
        self._json({"data": _validator_json(i, st.validators[i], st.balances[i])})

    def get_block_root(self, block_id):
        self._json({"data": {"root": _hex(self._block_root_by_id(block_id))}})

    def get_block(self, block_id):
        root = self._block_root_by_id(block_id)
        chain = self.chain
        slot = chain.block_slots.get(root)
        if slot is None:
            raise ApiError(404, "block not found")
        types = types_for_slot(chain.spec, slot)
        blk = chain.store.get_block(root, types)
        if blk is None:
            raise ApiError(404, "block not found")
        self._json(
            {
                "version": chain.spec.fork_name_at_slot(slot).value,
                "data": {"message": {"slot": _u(blk.message.slot),
                                      "proposer_index": _u(blk.message.proposer_index),
                                      "parent_root": _hex(blk.message.parent_root),
                                      "state_root": _hex(blk.message.state_root)},
                          "signature": _hex(blk.signature),
                          "ssz": _hex(types.SignedBeaconBlock.serialize(blk))},
            }
        )

    def get_header(self, block_id):
        root = self._block_root_by_id(block_id)
        chain = self.chain
        slot = chain.block_slots.get(root)
        if slot is None:
            raise ApiError(404, "block not found")
        types = types_for_slot(chain.spec, slot)
        blk = chain.store.get_block(root, types)
        self._json(
            {
                "data": {
                    "root": _hex(root),
                    "canonical": True,
                    "header": {
                        "message": {
                            "slot": _u(blk.message.slot),
                            "proposer_index": _u(blk.message.proposer_index),
                            "parent_root": _hex(blk.message.parent_root),
                            "state_root": _hex(blk.message.state_root),
                            "body_root": _hex(
                                types.BeaconBlockBody.hash_tree_root(blk.message.body)
                            ),
                        },
                        "signature": _hex(blk.signature),
                    },
                }
            }
        )

    def get_health(self):
        """GET /eth/v1/node/health — reflects real signal instead of an
        unconditional 200: while the SLO burn rate exceeds its threshold or
        the device breaker is open the node is serving degraded, and a load
        balancer probing here should know (206 = serving-but-degraded, the
        beacon-api code the reference uses for a syncing-but-usable node).
        Stays rate-limit exempt; the check is two in-memory reads."""
        from ..observability import slo as obs_slo

        h = obs_slo.health()
        self.send_response(206 if h["degraded"] else 200)
        if h["degraded"]:
            # machine-visible reason without a body (health probes often
            # discard bodies): a header names what degraded
            self.send_header("X-Node-Degraded", ",".join(h["reasons"]))
        # bodyless response still needs an explicit length under HTTP/1.1
        # or the keep-alive peer would wait for a body that never comes
        self.send_header("Content-Length", "0")
        self.end_headers()

    def get_version(self):
        self._json({"data": {"version": VERSION}})

    def get_syncing(self):
        chain = self.chain
        head_slot = chain.head_state().slot
        current = chain.current_slot
        self._json(
            {
                "data": {
                    "head_slot": _u(head_slot),
                    "sync_distance": _u(max(0, current - head_slot)),
                    "is_syncing": current > head_slot + 1,
                    "is_optimistic": False,
                    "el_offline": True,
                }
            }
        )

    def get_spec(self):
        spec = self.chain.spec
        p = spec.preset
        self._json(
            {
                "data": {
                    "CONFIG_NAME": spec.config_name,
                    "PRESET_BASE": p.name,
                    "SLOTS_PER_EPOCH": _u(p.SLOTS_PER_EPOCH),
                    "SECONDS_PER_SLOT": _u(spec.seconds_per_slot),
                    "MAX_COMMITTEES_PER_SLOT": _u(p.MAX_COMMITTEES_PER_SLOT),
                    "TARGET_COMMITTEE_SIZE": _u(p.TARGET_COMMITTEE_SIZE),
                    "MAX_EFFECTIVE_BALANCE": _u(spec.max_effective_balance),
                    "GENESIS_FORK_VERSION": _hex(spec.genesis_fork_version),
                }
            }
        )

    def post_attester_duties(self, epoch):
        body = self._read_body() or []
        with _client_input():
            indices = [int(i) for i in body]
        from ..validator.beacon_node import InProcessBeaconNode

        node = InProcessBeaconNode(self.chain)
        duties = node.attester_duties(int(epoch), indices)
        self._json(
            {
                "dependent_root": _hex(self.chain.head_root),
                "execution_optimistic": False,
                "data": [
                    {
                        "pubkey": _hex(d.pubkey),
                        "validator_index": _u(d.validator_index),
                        "committee_index": _u(d.committee_index),
                        "committee_length": _u(d.committee_length),
                        "committees_at_slot": _u(d.committees_at_slot),
                        "validator_committee_index": _u(d.committee_position),
                        "slot": _u(d.slot),
                    }
                    for d in duties
                ],
            }
        )

    def get_proposer_duties(self, epoch):
        from ..validator.beacon_node import InProcessBeaconNode

        node = InProcessBeaconNode(self.chain)
        duties = node.proposer_duties(int(epoch))
        self._json(
            {
                "dependent_root": _hex(self.chain.head_root),
                "data": [
                    {
                        "pubkey": _hex(d.pubkey),
                        "validator_index": _u(d.validator_index),
                        "slot": _u(d.slot),
                    }
                    for d in duties
                ],
            }
        )

    def post_pool_attestations(self):
        body = self._read_body() or []
        chain = self.chain
        types = types_for_slot(chain.spec, chain.head_state().slot)
        atts = []
        with _client_input():
            for a in body:
                data = a["data"]
                att = types.Attestation.make(
                    aggregation_bits=_bits_from_hex(a["aggregation_bits"]),
                    data=types.AttestationData.make(
                        slot=int(data["slot"]),
                        index=int(data["index"]),
                        beacon_block_root=bytes.fromhex(data["beacon_block_root"][2:]),
                        source=types.Checkpoint.make(
                            epoch=int(data["source"]["epoch"]),
                            root=bytes.fromhex(data["source"]["root"][2:]),
                        ),
                        target=types.Checkpoint.make(
                            epoch=int(data["target"]["epoch"]),
                            root=bytes.fromhex(data["target"]["root"][2:]),
                        ),
                    ),
                    signature=bytes.fromhex(a["signature"][2:]),
                )
                atts.append(att)
        with self._publish_permit(self._bulk_publish_gate):
            verified = chain.verify_unaggregated_attestations(atts)
            for att, indices in verified:
                chain.apply_attestation_to_fork_choice(att, indices)
                if self.op_pool is not None:
                    self.op_pool.insert_attestation(att, indices, types)
        if len(verified) != len(atts):
            raise ApiError(400, f"{len(atts)-len(verified)} attestations failed")
        self._json({})

    def post_publish_block(self):
        body = self._read_body()
        chain = self.chain
        ssz_hex = body.get("ssz") if isinstance(body, dict) else None
        if not ssz_hex:
            raise ApiError(400, "expected {'ssz': '0x...'} body")
        # decode via head-fork types; forks with identical layouts decode fine
        types = types_for_slot(chain.spec, chain.current_slot)
        try:
            raw = bytes.fromhex(ssz_hex[2:])
            signed = types.SignedBeaconBlock.deserialize(raw)
        except Exception as e:  # noqa: BLE001
            _ERRORS_TOTAL.labels("block_ssz_decode").inc()
            log.warn("undecodable published block",
                     stage="block_ssz_decode",
                     error=f"{type(e).__name__}: {e}")
            raise ApiError(400, f"undecodable block SSZ: {e}") from e
        with self._publish_permit(self._block_publish_gate):
            self._import_published_block(signed)

    def _import_published_block(self, signed):
        """Shared import path for full + blinded publishes
        (publish_blocks.rs broadcast-then-import)."""
        chain = self.chain
        root = chain.verify_block_for_gossip(signed)
        # locally-produced deneb blocks: rebuild sidecars from the blobs
        # bundle the EL returned at production time (publish_blocks.rs)
        sidecars = chain.sidecars_for_produced_block(signed)
        chain.process_block(
            signed,
            block_root=root,
            proposal_already_verified=True,
            blobs=sidecars or None,
        )
        if self.event_bus is not None:
            self.event_bus.publish("block", {"slot": _u(signed.message.slot), "block": _hex(root)})
        self._json({})

    # -------------------------------------------------- route expansion r2

    def _query(self) -> dict:
        from urllib.parse import parse_qs, urlsplit

        q = parse_qs(urlsplit(self.path).query)
        return {k: v[0] for k, v in q.items()}

    def get_blob_sidecars(self, block_id):
        """GET /eth/v1/beacon/blob_sidecars/{block_id}."""
        root = self._block_root_by_id(block_id)
        sidecars = self.chain.get_blobs(root)
        out = []
        for sc in sidecars:
            out.append(
                {
                    "index": _u(sc.index),
                    "blob": _hex(sc.blob),
                    "kzg_commitment": _hex(sc.kzg_commitment),
                    "kzg_proof": _hex(sc.kzg_proof),
                    "kzg_commitment_inclusion_proof": [
                        _hex(b) for b in sc.kzg_commitment_inclusion_proof
                    ],
                }
            )
        self._json({"data": out})

    def get_committees(self, state_id):
        st = self._state_by_id(state_id)
        spec = self.chain.spec
        epoch = acc.get_current_epoch(st, spec)
        q = self._query()
        if "epoch" in q:
            epoch = int(q["epoch"])
        cache = acc.build_committee_cache(st, spec, epoch)
        out = []
        start = h.compute_start_slot_at_epoch(epoch, spec)
        for slot in range(start, start + spec.preset.SLOTS_PER_EPOCH):
            if "slot" in q and int(q["slot"]) != slot:
                continue
            for cidx in range(cache.committees_per_slot):
                if "index" in q and int(q["index"]) != cidx:
                    continue
                out.append(
                    {
                        "index": _u(cidx),
                        "slot": _u(slot),
                        "validators": [_u(v) for v in cache.committee(slot, cidx)],
                    }
                )
        self._json({"data": out})

    def get_sync_committees(self, state_id):
        st = self._state_by_id(state_id)
        if not hasattr(st, "current_sync_committee"):
            raise ApiError(400, "pre-altair state")
        pk_to_idx = {bytes(v.pubkey): i for i, v in enumerate(st.validators)}
        try:
            indices = [
                pk_to_idx[bytes(pk)] for pk in st.current_sync_committee.pubkeys
            ]
        except KeyError:
            raise ApiError(500, "sync committee pubkey missing from registry")
        self._json({"data": {"validators": [_u(i) for i in indices]}})

    def get_fork_schedule(self):
        spec = self.chain.spec
        from ..types.spec import ForkName

        out = []
        prev = spec.genesis_fork_version
        for fork in ForkName:
            epoch = spec.fork_epoch(fork)
            if epoch is None:
                continue
            ver = spec.fork_version(fork)
            out.append(
                {
                    "previous_version": _hex(prev),
                    "current_version": _hex(ver),
                    "epoch": _u(epoch),
                }
            )
            prev = ver
        self._json({"data": out})

    def get_deposit_contract(self):
        spec = self.chain.spec
        self._json(
            {
                "data": {
                    "chain_id": _u(spec.deposit_chain_id),
                    "address": _hex(spec.deposit_contract_address),
                }
            }
        )

    def get_identity(self):
        net = getattr(self.chain, "_network_node", None)
        self._json(
            {
                "data": {
                    "peer_id": net.node_id if net else "in-process",
                    "enr": "",
                    "p2p_addresses": (
                        [f"/ip4/{net.host.listen_addr[0]}/tcp/{net.host.listen_addr[1]}"]
                        if net
                        else []
                    ),
                    "metadata": {"seq_number": "1", "attnets": "0x00"},
                }
            }
        )

    def get_peers(self):
        net = getattr(self.chain, "_network_node", None)
        peers = []
        if net is not None:
            for pid in net.host.connections:
                peers.append(
                    {
                        "peer_id": pid,
                        "state": "connected",
                        "direction": "outbound",
                        "score": net.peer_manager.score(pid),
                    }
                )
        self._json({"data": peers, "meta": {"count": len(peers)}})

    def post_sync_duties(self, epoch):
        body = self._read_body() or []
        with _client_input():
            indices = [int(i) for i in body]
        duties = []
        st = self.chain.head_state()
        for vi in indices:
            positions = self.chain.sync_subcommittee_positions(vi)
            if positions:
                duties.append(
                    {
                        "pubkey": _hex(st.validators[vi].pubkey),
                        "validator_index": _u(vi),
                        "validator_sync_committee_indices": [
                            _u(s * (self.chain.spec.preset.SYNC_COMMITTEE_SIZE
                                    // self.chain.spec.sync_committee_subnet_count) + p)
                            for s, p in positions
                        ],
                    }
                )
        self._json({"data": duties})

    def get_aggregate_attestation(self):
        q = self._query()
        slot = int(q.get("slot", 0))
        root = bytes.fromhex(q.get("attestation_data_root", "0x")[2:])
        types = types_for_slot(self.chain.spec, slot)
        agg = self.chain.naive_attestation_pool.get_aggregate(slot, root, types)
        if agg is None:
            raise ApiError(404, "no aggregate known")
        from ..ssz.core import Bitlist

        bits = list(agg.aggregation_bits)
        bits_ssz = Bitlist(max(len(bits), 1)).serialize(bits)
        self._json(
            {
                "data": {
                    "aggregation_bits": _hex(bits_ssz),
                    "signature": _hex(agg.signature),
                    "data": {
                        "slot": _u(agg.data.slot),
                        "index": _u(agg.data.index),
                        "beacon_block_root": _hex(agg.data.beacon_block_root),
                        "source": _checkpoint(agg.data.source),
                        "target": _checkpoint(agg.data.target),
                    },
                }
            }
        )

    def post_liveness(self, epoch):
        """POST /eth/v1/validator/liveness/{epoch}: seen-on-chain/gossip
        indicator per validator (the reference answers from its liveness
        cache; here the observed-attesters gossip dedup set)."""
        body = self._read_body() or []
        epoch = int(epoch)
        with _client_input():
            data = [
                {
                    "index": _u(int(i)),
                    "is_live": (epoch, int(i)) in self.chain.observed_attesters,
                }
                for i in body
            ]
        self._json({"data": data})

    def post_prepare_proposer(self):
        body = self._read_body()
        with _client_input():
            for item in body:
                self.chain.proposer_preparations[int(item["validator_index"])] = bytes.fromhex(
                    item["fee_recipient"][2:]
                )
        self._json({}, 200)

    def post_subscriptions(self):
        # beacon_committee/sync_committee subscriptions: acknowledged; subnet
        # topic management is the network node's job
        self._read_body()
        self._json({}, 200)

    def get_debug_state(self, state_id):
        st = self._state_by_id(state_id)
        types = types_for_slot(self.chain.spec, st.slot)
        self._json(
            {
                "version": self.chain.spec.fork_name_at_slot(st.slot).name,
                "data": _hex(types.BeaconState.serialize(st)),
            }
        )

    def get_block_ssz(self, block_id):
        """Full SSZ of a signed block (hex-wrapped) — the checkpoint-sync
        companion to get_debug_state: `bn --checkpoint-sync-url` fetches
        the finalized state + block pair from here (the reference fetches
        the same pair from a remote BN, client/src/builder.rs:366-390)."""
        root = self._block_root_by_id(block_id)
        chain = self.chain
        slot = chain.block_slots.get(root)
        if slot is None:
            raise ApiError(404, "block not found")
        types = types_for_slot(chain.spec, slot)
        blk = chain.store.get_block(root, types)
        if blk is None:
            raise ApiError(404, "block not found")
        self._json(
            {
                "version": chain.spec.fork_name_at_slot(slot).value,
                "data": _hex(types.SignedBeaconBlock.serialize(blk)),
            }
        )

    def get_lh_database_info(self):
        """/lighthouse_tpu/database/info (ops endpoint family analog)."""
        chain = self.chain
        store = chain.store
        counts = {}
        try:
            from ..store.kv import Column

            for col in Column:
                n = sum(1 for _ in store.hot.iter_column(col))
                if n:
                    counts[col.name] = n
        except Exception:  # noqa: BLE001 — memory stores may not iterate
            pass
        self._json(
            {
                "data": {
                    "split_slot": _u(store.split_slot),
                    "anchor_slot": _u(chain.anchor_slot),
                    "oldest_block_slot": _u(chain.oldest_block_slot),
                    "hot_columns": counts,
                }
            }
        )

    def get_lh_health(self):
        """/lighthouse_tpu/health: process+system snapshot."""
        from ..utils.monitoring import system_health

        self._json({"data": system_health()})

    def post_lh_validator_metrics(self):
        """/lighthouse_tpu/ui/validator-metrics: per-validator monitor
        summaries for the requested indices (http_api/src/ui.rs
        post_validator_monitor_metrics analog). Body:
        {"indices": [..], "epoch": optional} — epoch defaults to the last
        CLOSED epoch (current - 2: books for E close once E+1 ends).
        Read-only: registration is an operator decision
        (--monitor-validators), not a side effect of an unauthenticated
        query."""
        body = self._read_body() or {}
        if not isinstance(body, dict):
            raise ApiError(400, "body must be a JSON object")
        indices = [int(i) for i in body.get("indices", [])]
        spe = self.chain.spec.preset.SLOTS_PER_EPOCH
        epoch = int(body.get("epoch", max(0, self.chain.current_slot // spe - 2)))
        self._json(
            {
                "data": {
                    "validators": self.chain.monitor.metrics_for(indices, epoch),
                    "epoch": epoch,
                }
            }
        )

    def get_lh_pipeline(self):
        """/lighthouse_tpu/pipeline: stage-timing snapshot of the
        verification dataflow — aggregate per-stage/per-kind timings, live
        scheduler queue state, and the most recent completed traces
        (lighthouse_tpu/observability). The scrape-time analog of a
        `--trace-out` Perfetto export."""
        from ..observability import snapshot

        self._json({"data": snapshot()})

    def get_lh_slo(self):
        """/lighthouse_tpu/slo: the slot-level SLO accountant's snapshot —
        per-slot reports, the rolling 5-slot / 32-slot windows with burn
        rate, and the degraded verdict (observability/slo.py). This is the
        live SLI surface a closed-loop capacity controller consumes."""
        from ..observability import flight_recorder as obs_fr
        from ..observability import slo as obs_slo

        data = obs_slo.ACCOUNTANT.snapshot()
        data["health"] = obs_slo.health()
        data["flight_recorder"] = {
            "events_recorded": obs_fr.RECORDER.events_recorded,
            "breaker_states": dict(obs_fr.RECORDER.breaker_states),
            "incidents_written": list(obs_fr.RECORDER.incidents_written),
        }
        self._json({"data": data})

    def get_lh_peers_scores(self):
        net = getattr(self.chain, "_network_node", None)
        out = []
        if net is not None:
            for pid in net.peer_manager.connected_peers():
                out.append({"peer_id": pid, "score": net.peer_manager.score(pid)})
        self._json({"data": out})

    def get_lh_logs(self):
        """/lighthouse_tpu/logs: recent structured log records (the SSE
        log-streaming idiom of common/logging, served as a snapshot)."""
        from ..utils.logging import RECENT

        self._json(
            {
                "data": [
                    {
                        "ts": ts,
                        "level": level,
                        "component": component,
                        "msg": msg,
                        **{k: str(v) for k, v in fields.items()},
                    }
                    for ts, level, component, msg, fields in list(RECENT)[-128:]
                ]
            }
        )

    def get_attestation_data(self):
        """GET /eth/v1/validator/attestation_data?slot=&committee_index=."""
        from ..validator.beacon_node import InProcessBeaconNode

        q = self._query()
        slot = int(q["slot"])
        cidx = int(q.get("committee_index", 0))
        data = InProcessBeaconNode(self.chain).attestation_data(slot, cidx)
        self._json(
            {
                "data": {
                    "slot": _u(data.slot),
                    "index": _u(data.index),
                    "beacon_block_root": _hex(data.beacon_block_root),
                    "source": _checkpoint(data.source),
                    "target": _checkpoint(data.target),
                }
            }
        )

    def get_produce_block(self, slot):
        """GET /eth/v3/validator/blocks/{slot}?randao_reveal=0x... — returns
        the unsigned block as SSZ hex (the VC signs and POSTs it back)."""
        q = self._query()
        reveal_hex = q.get("randao_reveal")
        if not reveal_hex:
            raise ApiError(400, "randao_reveal required")
        slot = int(slot)
        graffiti = bytes.fromhex(q["graffiti"][2:]) if "graffiti" in q else None
        block = self.chain.produce_block(
            slot, bytes.fromhex(reveal_hex[2:]),
            op_pool=self.op_pool, graffiti=graffiti,
        )
        types = types_for_slot(self.chain.spec, slot)
        self._json(
            {
                "version": self.chain.spec.fork_name_at_slot(slot).name,
                "execution_payload_blinded": False,
                "data": _hex(types.BeaconBlock.serialize(block)),
            }
        )

    def get_lc_bootstrap(self, block_root_hex):
        """GET /eth/v1/beacon/light_client/bootstrap/{block_root}."""
        lc = getattr(self.chain, "light_client_cache", None)
        if lc is None:
            raise ApiError(404, "light client server not enabled")
        root = bytes.fromhex(block_root_hex[2:])
        bs = lc.bootstraps.get(root)
        if bs is None:
            raise ApiError(404, "no bootstrap for block")
        self._json(
            {
                "data": {
                    "header": {"beacon": {"slot": _u(bs.header.slot)}},
                    "current_sync_committee_branch": [
                        _hex(b) for b in bs.current_sync_committee_branch
                    ],
                }
            }
        )

    def get_lc_optimistic(self):
        lc = getattr(self.chain, "light_client_cache", None)
        if lc is None or lc.latest_optimistic_update is None:
            raise ApiError(404, "no optimistic update")
        u = lc.latest_optimistic_update
        self._json(
            {
                "data": {
                    "attested_header": {"beacon": {"slot": _u(u.attested_header.slot)}},
                    "signature_slot": _u(u.signature_slot),
                }
            }
        )

    def get_lc_finality(self):
        lc = getattr(self.chain, "light_client_cache", None)
        if lc is None or lc.latest_finality_update is None:
            raise ApiError(404, "no finality update")
        u = lc.latest_finality_update
        self._json(
            {
                "data": {
                    "attested_header": {"beacon": {"slot": _u(u.attested_header.slot)}},
                    "finalized_header": {"beacon": {"slot": _u(u.finalized_header.slot)}},
                    "signature_slot": _u(u.signature_slot),
                }
            }
        )

    # ---------------------------------------------------------- rewards

    def get_rewards_blocks(self, block_id):
        """GET /eth/v1/beacon/rewards/blocks/{block_id}
        (standard_block_rewards.rs)."""
        from . import rewards as rw

        root = self._block_root_by_id(block_id)
        try:
            data = rw.compute_block_rewards(self.chain, root)
        except KeyError as e:
            raise ApiError(404, str(e)) from e
        self._json(
            {
                "execution_optimistic": False,
                "finalized": self._is_finalized_root(root),
                "data": {k: str(v) for k, v in data.items()},
            }
        )

    def post_rewards_attestations(self, epoch):
        """POST /eth/v1/beacon/rewards/attestations/{epoch} with an optional
        JSON array of validator indices/pubkeys (attestation_rewards.rs)."""
        from . import rewards as rw

        validators = self._read_body() or []
        if not isinstance(validators, list):
            raise ApiError(400, "body must be a JSON array")
        try:
            data = rw.compute_attestation_rewards(self.chain, int(epoch), validators)
        except KeyError as e:
            raise ApiError(404, str(e)) from e
        except ValueError as e:
            raise ApiError(400, str(e)) from e

        def quoted(row):
            return {k: str(v) for k, v in row.items()}

        self._json(
            {
                "execution_optimistic": False,
                "finalized": False,
                "data": {
                    "ideal_rewards": [quoted(r) for r in data["ideal_rewards"]],
                    "total_rewards": [quoted(r) for r in data["total_rewards"]],
                },
            }
        )

    def post_rewards_sync_committee(self, block_id):
        """POST /eth/v1/beacon/rewards/sync_committee/{block_id}
        (sync_committee_rewards.rs)."""
        from . import rewards as rw

        root = self._block_root_by_id(block_id)
        validators = self._read_body() or []
        if not isinstance(validators, list):
            raise ApiError(400, "body must be a JSON array")
        try:
            data = rw.compute_sync_committee_rewards(self.chain, root, validators)
        except KeyError as e:
            raise ApiError(404, str(e)) from e
        except ValueError as e:
            raise ApiError(400, str(e)) from e
        self._json(
            {
                "execution_optimistic": False,
                "finalized": self._is_finalized_root(root),
                "data": [
                    {"validator_index": str(r["validator_index"]),
                     "reward": str(r["reward"])}
                    for r in data
                ],
            }
        )

    def _is_finalized_root(self, root: bytes) -> bool:
        slot = self.chain.block_slots.get(root)
        if slot is None:
            return False
        fin_epoch = self.chain.fork_choice.store.finalized_checkpoint[0]
        return slot <= fin_epoch * self.chain.spec.preset.SLOTS_PER_EPOCH

    # ------------------------------------------------- blinded production

    def get_produce_blinded_block(self, slot):
        """GET /eth/v1/validator/blinded_blocks/{slot} — the block with its
        execution payload replaced by the payload HEADER (produce_block.rs
        blinded path; the VC signs it and POSTs to blinded_blocks)."""
        q = self._query()
        reveal_hex = q.get("randao_reveal")
        if not reveal_hex:
            raise ApiError(400, "randao_reveal required")
        slot = int(slot)
        graffiti = bytes.fromhex(q["graffiti"][2:]) if "graffiti" in q else None
        block = self.chain.produce_block(
            slot, bytes.fromhex(reveal_hex[2:]),
            op_pool=self.op_pool, graffiti=graffiti,
        )
        types = types_for_slot(self.chain.spec, slot)
        payload_header_json = None
        payload = getattr(block.body, "execution_payload", None)
        if payload is not None:
            tx_type = next(
                f.type for f in types.ExecutionPayload.fields
                if f.name == "transactions"
            )
            payload_header_json = {
                "block_hash": _hex(payload.block_hash),
                "parent_hash": _hex(payload.parent_hash),
                "block_number": _u(payload.block_number),
                "transactions_root": _hex(tx_type.hash_tree_root(payload.transactions)),
            }
        self._json(
            {
                "version": self.chain.spec.fork_name_at_slot(slot).name,
                "execution_payload_blinded": True,
                "data": {
                    "message": {
                        "slot": _u(block.slot),
                        "proposer_index": _u(block.proposer_index),
                        "parent_root": _hex(block.parent_root),
                        "state_root": _hex(block.state_root),
                        "body": {"execution_payload_header": payload_header_json},
                    },
                    # full SSZ so the in-process publish path can reuse it
                    "ssz": _hex(types.BeaconBlock.serialize(block)),
                },
            }
        )

    def post_publish_blinded_block(self):
        """POST /eth/v1/beacon/blinded_blocks — accepts the signed blinded
        block; the payload is recovered from the local production cache
        (publish_blocks.rs ProvenancedBlock::Builder path, with the local-EL
        unblinding shortcut)."""
        body = self._read_body()
        raw = body.get("ssz") if isinstance(body, dict) else None
        if raw is None:
            raise ApiError(400, "expected {'ssz': block hex, 'signature': sig hex}")
        sig = body.get("signature")
        if sig is None:
            raise ApiError(400, "signature required")
        # same fork resolution as the full publish path (types_for_slot of
        # the CURRENT slot; re-resolved below once the real slot is known)
        types = types_for_slot(self.chain.spec, self.chain.current_slot)
        try:
            block = types.BeaconBlock.deserialize(bytes.fromhex(raw[2:]))
        except Exception as e:  # noqa: BLE001
            _ERRORS_TOTAL.labels("blinded_ssz_decode").inc()
            log.warn("undecodable blinded block",
                     stage="blinded_ssz_decode",
                     error=f"{type(e).__name__}: {e}")
            raise ApiError(400, f"undecodable block SSZ: {e}") from e
        types = types_for_slot(self.chain.spec, block.slot)
        signed = types.SignedBeaconBlock.make(
            message=block, signature=bytes.fromhex(sig[2:])
        )
        with self._publish_permit(self._block_publish_gate):
            self._import_published_block(signed)

    # ------------------------------------------------- deposit snapshot

    def get_deposit_snapshot(self):
        """GET /eth/v1/beacon/deposit_snapshot (EIP-4881; the reference
        serves it from the eth1 service cache)."""
        eth1 = getattr(self.chain, "eth1_cache", None)
        if eth1 is None:
            raise ApiError(404, "no eth1 deposit cache")
        tree = eth1.tree
        count = len(tree)
        latest = eth1.blocks[-1] if eth1.blocks else None
        self._json(
            {
                "data": {
                    "finalized": [_hex(tree.root(count))],
                    "deposit_root": _hex(tree.root(count)),
                    "deposit_count": _u(count),
                    "execution_block_hash": _hex(
                        latest.hash if latest else b"\x00" * 32
                    ),
                    "execution_block_height": _u(latest.number if latest else 0),
                }
            }
        )

    # ------------------------------------------------- LC updates by range

    def get_lc_updates(self):
        """GET /eth/v1/beacon/light_client/updates?start_period=&count=
        (http_api light_client updates-by-range)."""
        lc = getattr(self.chain, "light_client_cache", None)
        if lc is None:
            raise ApiError(404, "light client server not enabled")
        q = self._query()
        try:
            start = int(q["start_period"])
            count = int(q["count"])
        except (KeyError, ValueError) as e:
            raise ApiError(400, "start_period and count required") from e
        count = min(count, 128)  # MAX_REQUEST_LIGHT_CLIENT_UPDATES
        out = []
        for period in range(start, start + count):
            u = lc.best_updates.get(period)
            if u is None:
                continue
            out.append(
                {
                    "version": self.chain.spec.fork_name_at_slot(
                        u.attested_header.slot
                    ).value,
                    "data": {
                        "attested_header": {
                            "beacon": {"slot": _u(u.attested_header.slot)}
                        },
                        "finalized_header": {
                            "beacon": {"slot": _u(u.finalized_header.slot)}
                        },
                        "signature_slot": _u(u.signature_slot),
                        "next_sync_committee_branch": [
                            _hex(b) for b in u.next_sync_committee_branch
                        ],
                    },
                }
            )
        self._json(out)

    def post_pool_voluntary_exits(self):
        body = self._read_body()
        types = types_for_slot(self.chain.spec, self.chain.current_slot)
        with _client_input():
            exit_ = types.SignedVoluntaryExit.make(
                message=types.VoluntaryExit.make(
                    epoch=int(body["message"]["epoch"]),
                    validator_index=int(body["message"]["validator_index"]),
                ),
                signature=bytes.fromhex(body["signature"][2:]),
            )
        if self.op_pool is not None:
            self.op_pool.insert_voluntary_exit(exit_)
        if self.event_bus is not None:
            self.event_bus.publish(
                "voluntary_exit",
                {"validator_index": body["message"]["validator_index"]},
            )
        self._json({})

    def get_pool_voluntary_exits(self):
        out = []
        if self.op_pool is not None:
            for e in self.op_pool.voluntary_exits.values():
                out.append(
                    {
                        "message": {
                            "epoch": _u(e.message.epoch),
                            "validator_index": _u(e.message.validator_index),
                        },
                        "signature": _hex(e.signature),
                    }
                )
        self._json({"data": out})

    # ---------------------------------------------- pool: slashings/changes

    def post_pool_bls_changes(self):
        body = self._read_body() or []
        types = types_for_slot(self.chain.spec, self.chain.current_slot)
        if isinstance(body, dict):
            body = [body]
        with _client_input():
            for c in body:
                change = types.SignedBLSToExecutionChange.make(
                    message=types.BLSToExecutionChange.make(
                        validator_index=int(c["message"]["validator_index"]),
                        from_bls_pubkey=bytes.fromhex(c["message"]["from_bls_pubkey"][2:]),
                        to_execution_address=bytes.fromhex(
                            c["message"]["to_execution_address"][2:]
                        ),
                    ),
                    signature=bytes.fromhex(c["signature"][2:]),
                )
                if self.op_pool is not None:
                    self.op_pool.insert_bls_change(change)
        self._json({})

    def get_pool_bls_changes(self):
        out = []
        if self.op_pool is not None:
            for c in self.op_pool.bls_changes.values():
                out.append(
                    {
                        "message": {
                            "validator_index": _u(c.message.validator_index),
                            "from_bls_pubkey": _hex(c.message.from_bls_pubkey),
                            "to_execution_address": _hex(
                                c.message.to_execution_address
                            ),
                        },
                        "signature": _hex(c.signature),
                    }
                )
        self._json({"data": out})

    def get_pool_attester_slashings(self):
        def indexed(a):
            return {
                "attesting_indices": [_u(i) for i in a.attesting_indices],
                "data": {
                    "slot": _u(a.data.slot),
                    "index": _u(a.data.index),
                    "beacon_block_root": _hex(a.data.beacon_block_root),
                    "source": _checkpoint(a.data.source),
                    "target": _checkpoint(a.data.target),
                },
                "signature": _hex(a.signature),
            }

        out = []
        if self.op_pool is not None:
            for sl in self.op_pool.attester_slashings:
                out.append(
                    {
                        "attestation_1": indexed(sl.attestation_1),
                        "attestation_2": indexed(sl.attestation_2),
                    }
                )
        self._json({"data": out})

    def post_pool_attester_slashings(self):
        body = self._read_body()
        types = types_for_slot(self.chain.spec, self.chain.current_slot)
        ssz_hex = body.get("ssz") if isinstance(body, dict) else None
        if not ssz_hex:
            raise ApiError(400, "expected {'ssz': '0x...'} body")
        slashing = types.AttesterSlashing.deserialize(bytes.fromhex(ssz_hex[2:]))
        try:
            self.chain.verify_slashing_for_pool(slashing, "attester")
        except Exception as e:  # noqa: BLE001
            _ERRORS_TOTAL.labels("attester_slashing_verify").inc()
            log.warn("rejected attester slashing",
                     stage="attester_slashing_verify",
                     error=f"{type(e).__name__}: {e}")
            raise ApiError(400, f"invalid attester slashing: {e}") from e
        if self.op_pool is not None:
            self.op_pool.insert_attester_slashing(slashing)
        if self.event_bus is not None:
            self.event_bus.publish("attester_slashing", {})
        self._json({})

    def get_pool_proposer_slashings(self):
        def header(sh):
            m = sh.message
            return {
                "message": {
                    "slot": _u(m.slot),
                    "proposer_index": _u(m.proposer_index),
                    "parent_root": _hex(m.parent_root),
                    "state_root": _hex(m.state_root),
                    "body_root": _hex(m.body_root),
                },
                "signature": _hex(sh.signature),
            }

        out = []
        if self.op_pool is not None:
            for sl in self.op_pool.proposer_slashings.values():
                out.append(
                    {
                        "signed_header_1": header(sl.signed_header_1),
                        "signed_header_2": header(sl.signed_header_2),
                    }
                )
        self._json({"data": out})

    def post_pool_proposer_slashings(self):
        body = self._read_body()
        types = types_for_slot(self.chain.spec, self.chain.current_slot)
        ssz_hex = body.get("ssz") if isinstance(body, dict) else None
        if not ssz_hex:
            raise ApiError(400, "expected {'ssz': '0x...'} body")
        slashing = types.ProposerSlashing.deserialize(bytes.fromhex(ssz_hex[2:]))
        try:
            self.chain.verify_slashing_for_pool(slashing, "proposer")
        except Exception as e:  # noqa: BLE001
            _ERRORS_TOTAL.labels("proposer_slashing_verify").inc()
            log.warn("rejected proposer slashing",
                     stage="proposer_slashing_verify",
                     error=f"{type(e).__name__}: {e}")
            raise ApiError(400, f"invalid proposer slashing: {e}") from e
        if self.op_pool is not None:
            self.op_pool.insert_proposer_slashing(slashing)
        if self.event_bus is not None:
            self.event_bus.publish("proposer_slashing", {})
        self._json({})

    def post_pool_sync_committees(self):
        """POST /eth/v1/beacon/pool/sync_committees: verified in one batch
        and fed to the naive contribution pool (the VC's sync-message
        publish path)."""
        body = self._read_body() or []
        types = types_for_slot(self.chain.spec, self.chain.current_slot)
        with _client_input():
            msgs = [
                types.SyncCommitteeMessage.make(
                    slot=int(m["slot"]),
                    beacon_block_root=bytes.fromhex(m["beacon_block_root"][2:]),
                    validator_index=int(m["validator_index"]),
                    signature=bytes.fromhex(m["signature"][2:]),
                )
                for m in body
            ]
        with self._publish_permit(self._bulk_publish_gate):
            accepted = self.chain.process_sync_committee_messages(msgs)
        if accepted != len(msgs):
            raise ApiError(400, f"{len(msgs) - accepted} messages failed")
        self._json({})

    # ---------------------------------------------- states: balances/randao

    def get_state_validator_balances(self, state_id):
        st = self._state_by_id(state_id)
        q = self._query()
        wanted = None
        if "id" in q:
            wanted = set()
            by_pubkey = None
            for ident in q["id"].split(","):
                if ident.startswith("0x"):
                    if by_pubkey is None:
                        by_pubkey = {
                            bytes(v.pubkey): i for i, v in enumerate(st.validators)
                        }
                    idx = by_pubkey.get(bytes.fromhex(ident[2:]))
                    if idx is not None:
                        wanted.add(idx)
                elif ident.isdigit():
                    wanted.add(int(ident))
                else:
                    raise ApiError(400, f"bad validator id {ident!r}")
        self._json(
            {
                "data": [
                    {"index": _u(i), "balance": _u(b)}
                    for i, b in enumerate(st.balances)
                    if wanted is None or i in wanted
                ]
            }
        )

    def get_state_randao(self, state_id):
        from ..state_transition import accessors as acc

        st = self._state_by_id(state_id)
        spec = self.chain.spec
        current = acc.get_current_epoch(st, spec)
        epoch = current
        q = self._query()
        if "epoch" in q:
            epoch = int(q["epoch"])
        # get_randao_mix indexes modulo EPOCHS_PER_HISTORICAL_VECTOR: an
        # out-of-range epoch would silently alias an unrelated mix
        lo = max(0, current - spec.preset.EPOCHS_PER_HISTORICAL_VECTOR + 1)
        if not (lo <= epoch <= current):
            raise ApiError(400, f"epoch {epoch} outside stored randao range")
        mix = h.get_randao_mix(st, spec, epoch)
        self._json({"data": {"randao": _hex(mix)}})

    def get_node_peer_count(self):
        net = getattr(self.chain, "_network_node", None)
        connected = len(net.peer_manager.connected_peers()) if net else 0
        self._json(
            {
                "data": {
                    "disconnected": "0",
                    "connecting": "0",
                    "connected": str(connected),
                    "disconnecting": "0",
                }
            }
        )


def _bits_from_hex(hex_str: str):
    from ..ssz.core import Bitlist

    data = bytes.fromhex(hex_str[2:])
    # decode SSZ bitlist bytes (with delimiter)
    last = data[-1]
    total = (len(data) - 1) * 8 + (last.bit_length() - 1)
    return [bool((data[i // 8] >> (i % 8)) & 1) for i in range(total)]


_ROUTES = [
    (r"/eth/v1/beacon/genesis", "GET", BeaconApiHandler.get_genesis),
    (r"/eth/v1/beacon/states/([^/]+)/root", "GET", BeaconApiHandler.get_state_root),
    (r"/eth/v1/beacon/states/([^/]+)/finality_checkpoints", "GET", BeaconApiHandler.get_finality_checkpoints),
    (r"/eth/v1/beacon/states/([^/]+)/validators", "GET", BeaconApiHandler.get_validators),
    (r"/eth/v1/beacon/states/([^/]+)/validators/([^/]+)", "GET", BeaconApiHandler.get_validator),
    (r"/eth/v1/beacon/blocks/([^/]+)/root", "GET", BeaconApiHandler.get_block_root),
    (r"/eth/v2/beacon/blocks/([^/]+)", "GET", BeaconApiHandler.get_block),
    (r"/eth/v1/beacon/headers/([^/]+)", "GET", BeaconApiHandler.get_header),
    (r"/eth/v1/node/health", "GET", BeaconApiHandler.get_health),
    (r"/eth/v1/node/version", "GET", BeaconApiHandler.get_version),
    (r"/eth/v1/node/syncing", "GET", BeaconApiHandler.get_syncing),
    (r"/eth/v1/config/spec", "GET", BeaconApiHandler.get_spec),
    (r"/eth/v1/validator/duties/attester/(\d+)", "POST", BeaconApiHandler.post_attester_duties),
    (r"/eth/v1/validator/duties/proposer/(\d+)", "GET", BeaconApiHandler.get_proposer_duties),
    (r"/eth/v1/beacon/pool/attestations", "POST", BeaconApiHandler.post_pool_attestations),
    (r"/eth/v2/beacon/blocks", "POST", BeaconApiHandler.post_publish_block),
    (r"/eth/v1/beacon/blob_sidecars/([^/]+)", "GET", BeaconApiHandler.get_blob_sidecars),
    (r"/eth/v1/beacon/states/([^/]+)/committees", "GET", BeaconApiHandler.get_committees),
    (r"/eth/v1/beacon/states/([^/]+)/sync_committees", "GET", BeaconApiHandler.get_sync_committees),
    (r"/eth/v1/config/fork_schedule", "GET", BeaconApiHandler.get_fork_schedule),
    (r"/eth/v1/config/deposit_contract", "GET", BeaconApiHandler.get_deposit_contract),
    (r"/eth/v1/node/identity", "GET", BeaconApiHandler.get_identity),
    (r"/eth/v1/node/peers", "GET", BeaconApiHandler.get_peers),
    (r"/eth/v1/validator/duties/sync/(\d+)", "POST", BeaconApiHandler.post_sync_duties),
    (r"/eth/v1/validator/aggregate_attestation", "GET", BeaconApiHandler.get_aggregate_attestation),
    (r"/eth/v1/validator/liveness/(\d+)", "POST", BeaconApiHandler.post_liveness),
    (r"/eth/v1/validator/prepare_beacon_proposer", "POST", BeaconApiHandler.post_prepare_proposer),
    (r"/eth/v1/validator/beacon_committee_subscriptions", "POST", BeaconApiHandler.post_subscriptions),
    (r"/eth/v1/validator/sync_committee_subscriptions", "POST", BeaconApiHandler.post_subscriptions),
    (r"/eth/v2/debug/beacon/states/([^/]+)", "GET", BeaconApiHandler.get_debug_state),
    (r"/lighthouse_tpu/blocks/([^/]+)/ssz", "GET", BeaconApiHandler.get_block_ssz),
    (r"/eth/v1/beacon/pool/bls_to_execution_changes", "GET", BeaconApiHandler.get_pool_bls_changes),
    (r"/eth/v1/beacon/pool/bls_to_execution_changes", "POST", BeaconApiHandler.post_pool_bls_changes),
    (r"/eth/v1/beacon/pool/attester_slashings", "GET", BeaconApiHandler.get_pool_attester_slashings),
    (r"/eth/v1/beacon/pool/attester_slashings", "POST", BeaconApiHandler.post_pool_attester_slashings),
    (r"/eth/v1/beacon/pool/proposer_slashings", "GET", BeaconApiHandler.get_pool_proposer_slashings),
    (r"/eth/v1/beacon/pool/proposer_slashings", "POST", BeaconApiHandler.post_pool_proposer_slashings),
    (r"/eth/v1/beacon/pool/sync_committees", "POST", BeaconApiHandler.post_pool_sync_committees),
    (r"/eth/v1/beacon/states/([^/]+)/validator_balances", "GET", BeaconApiHandler.get_state_validator_balances),
    (r"/eth/v1/beacon/states/([^/]+)/randao", "GET", BeaconApiHandler.get_state_randao),
    (r"/eth/v1/node/peer_count", "GET", BeaconApiHandler.get_node_peer_count),
    (r"/lighthouse_tpu/database/info", "GET", BeaconApiHandler.get_lh_database_info),
    (r"/lighthouse_tpu/health", "GET", BeaconApiHandler.get_lh_health),
    (r"/lighthouse_tpu/peers/scores", "GET", BeaconApiHandler.get_lh_peers_scores),
    (r"/lighthouse_tpu/ui/validator-metrics", "POST", BeaconApiHandler.post_lh_validator_metrics),
    (r"/lighthouse_tpu/logs", "GET", BeaconApiHandler.get_lh_logs),
    (r"/lighthouse_tpu/pipeline", "GET", BeaconApiHandler.get_lh_pipeline),
    (r"/lighthouse_tpu/slo", "GET", BeaconApiHandler.get_lh_slo),
    (r"/eth/v1/validator/attestation_data", "GET", BeaconApiHandler.get_attestation_data),
    (r"/eth/v3/validator/blocks/(\d+)", "GET", BeaconApiHandler.get_produce_block),
    (r"/eth/v1/beacon/light_client/bootstrap/(0x[0-9a-f]+)", "GET", BeaconApiHandler.get_lc_bootstrap),
    (r"/eth/v1/beacon/light_client/optimistic_update", "GET", BeaconApiHandler.get_lc_optimistic),
    (r"/eth/v1/beacon/light_client/finality_update", "GET", BeaconApiHandler.get_lc_finality),
    (r"/eth/v1/beacon/pool/voluntary_exits", "POST", BeaconApiHandler.post_pool_voluntary_exits),
    (r"/eth/v1/beacon/pool/voluntary_exits", "GET", BeaconApiHandler.get_pool_voluntary_exits),
    (r"/eth/v1/beacon/rewards/blocks/([^/]+)", "GET", BeaconApiHandler.get_rewards_blocks),
    (r"/eth/v1/beacon/rewards/attestations/(\d+)", "POST", BeaconApiHandler.post_rewards_attestations),
    (r"/eth/v1/beacon/rewards/sync_committee/([^/]+)", "POST", BeaconApiHandler.post_rewards_sync_committee),
    (r"/eth/v1/validator/blinded_blocks/(\d+)", "GET", BeaconApiHandler.get_produce_blinded_block),
    (r"/eth/v1/beacon/blinded_blocks", "POST", BeaconApiHandler.post_publish_blinded_block),
    (r"/eth/v1/beacon/deposit_snapshot", "GET", BeaconApiHandler.get_deposit_snapshot),
    (r"/eth/v1/beacon/light_client/updates", "GET", BeaconApiHandler.get_lc_updates),
]


class EventBus:
    """SSE topics (events.rs analog), minimal pub-sub."""

    def __init__(self):
        self.subscribers: list = []
        self._lock = threading.Lock()

    def publish(self, topic: str, payload: dict):
        with self._lock:
            for q in self.subscribers:
                q.append((topic, payload))


class WorkerPoolHTTPServer(HTTPServer):
    """Bounded worker pool behind an admission gate (the ThreadingHTTPServer
    replacement: thread-per-connection is unbounded — a connection flood IS
    a thread flood, and a slow-loris peer pins a thread forever).

    Topology: the accept loop never reads a byte — it only moves the
    accepted socket into a bounded work queue. `--http-threads` workers pop
    sockets, arm the per-request read deadline (`--http-request-timeout`),
    and serve exactly ONE request per dispatch; keep-alive connections are
    then *parked* and re-admitted through the same gate when they turn
    readable, so an idle pool of hundreds of keep-alive VC connections
    costs one select() set, not hundreds of threads. When the work queue is
    full, a small shed lane answers 503 + Retry-After (health stays exempt:
    `/eth/v1/node/health` is served inline off the shed lane so liveness
    probes answer precisely when the node is busiest) — counted in
    `http_api_shed_total{reason}` with a flight-recorder event on the
    saturation edge.

    `stats` (accepted / handled / shed / requeued / health_shed_path) are
    plain monotonic counters; the fleet's wedge check reads `handled` —
    a saturated-but-alive server keeps making progress as deadlines free
    workers, a wedged one does not."""

    allow_reuse_address = True
    request_queue_size = 128  # listen(2) backlog under accept bursts

    #: deadline for the shed lane's header read — sheds must stay cheap
    #: even against a slow-loris peer aimed at the shed lane itself
    SHED_READ_TIMEOUT = 1.0

    def __init__(self, addr, handler_cls, http_threads=None,
                 request_timeout=None, queue_depth=None):
        super().__init__(addr, handler_cls)
        self.http_threads = resolve_http_threads(http_threads)
        self.request_timeout = resolve_http_request_timeout(request_timeout)
        depth = (int(queue_depth) if queue_depth is not None
                 else max(16, 2 * self.http_threads))
        self._queue: queue.Queue = queue.Queue(depth)
        self._shed_queue: queue.Queue = queue.Queue(max(8, self.http_threads))
        self._parked: dict = {}  # socket -> parked_at (time.monotonic)
        self._park_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._stop = threading.Event()
        self._saturated = False  # hysteresis for the flight-recorder event
        self.stats = {
            "accepted": 0, "handled": 0, "shed": 0, "requeued": 0,
            "health_shed_path": 0,
        }
        self._workers = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"http-worker-{i}")
            for i in range(self.http_threads)
        ]
        self._shedder = threading.Thread(
            target=self._shedder_loop, daemon=True, name="http-shedder"
        )
        # self-pipe: _park() wakes the parker so a connection reused
        # immediately after its response re-admits in microseconds, not
        # at the next poll tick
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._parker = threading.Thread(
            target=self._parker_loop, daemon=True, name="http-parker"
        )
        for t in self._workers:
            t.start()
        self._shedder.start()
        self._parker.start()

    def _bump(self, key, n=1):
        with self._stats_lock:
            self.stats[key] += n

    # --------------------------------------------------------- admission

    def process_request(self, request, client_address):
        # accept-loop override: hand off, never read — accept progress
        # must not depend on any peer's send rate
        self._bump("accepted")
        self._admit(request, client_address)

    def _admit(self, sock, addr, requeued=False):
        if self._stop.is_set():
            self._shed_now(sock, "shutdown")
            return
        try:
            self._queue.put_nowait((sock, addr))
        except queue.Full:
            self._note_saturated(addr)
            try:
                self._shed_queue.put_nowait((sock, addr))
            except queue.Full:
                # even the shed lane is full: close without a response —
                # spending accept-thread time on this peer is the DoS
                _SHED_TOTAL.labels("overflow").inc()
                self._bump("shed")
                self._close_sock(sock)
            return
        if requeued:
            self._bump("requeued")
        self._saturated = False
        _INFLIGHT.labels("queue").set(self._queue.qsize())

    def _note_saturated(self, addr):
        if self._saturated:
            return
        self._saturated = True
        from ..observability.flight_recorder import RECORDER

        RECORDER.record(
            "http_api_saturated", severity="warn",
            queue_depth=self._queue.maxsize, workers=self.http_threads,
            peer=str(addr[0]) if addr else "",
        )

    # --------------------------------------------------------- shed lane

    def _shedder_loop(self):
        while True:
            item = self._shed_queue.get()
            if item is None:
                return
            sock, _addr = item
            self._shed_now(sock, "saturated")

    def _shed_now(self, sock, reason):
        try:
            self._shed_one(sock, reason)
        except (OSError, TimeoutError):
            # peer trickling headers at the shed lane, or gone: the shed
            # still counts — the connection was turned away either way
            _SHED_TOTAL.labels(reason).inc()
            self._bump("shed")
        finally:
            self._close_sock(sock)

    def _shed_one(self, sock, reason):
        sock.settimeout(min(self.SHED_READ_TIMEOUT, self.request_timeout))
        rfile = sock.makefile("rb", -1)
        try:
            line = rfile.readline(4096)
            for _ in range(128):  # drain headers (bounded)
                hline = rfile.readline(4096)
                if hline in (b"\r\n", b"\n", b""):
                    break
        finally:
            rfile.close()
        parts = line.split()
        path = (parts[1].split(b"?")[0].decode("latin-1", "replace")
                if len(parts) > 1 else "")
        if (parts and parts[0] == b"GET"
                and path in BeaconApiHandler.RATE_LIMIT_EXEMPT):
            from ..observability import slo as obs_slo

            degraded = obs_slo.health()["degraded"]
            status = "206 Partial Content" if degraded else "200 OK"
            sock.sendall(
                (f"HTTP/1.1 {status}\r\nContent-Length: 0\r\n"
                 "Connection: close\r\n\r\n").encode()
            )
            self._bump("health_shed_path")
            return
        body = b'{"code": 503, "message": "worker pool saturated; retry"}'
        sock.sendall(
            b"HTTP/1.1 503 Service Unavailable\r\n"
            b"Content-Type: application/json\r\n"
            b"Retry-After: 1\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n"
            b"Connection: close\r\n\r\n" + body
        )
        _SHED_TOTAL.labels(reason).inc()
        self._bump("shed")

    # ----------------------------------------------------------- workers

    def _worker(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            sock, addr = item
            _INFLIGHT.labels("queue").set(self._queue.qsize())
            _INFLIGHT.labels("workers").inc()
            keep = False
            try:
                sock.settimeout(self.request_timeout)
                handler = self.RequestHandlerClass(sock, addr, self)
                keep = not handler.close_connection
            except Exception:  # noqa: BLE001 — a dead peer must not kill a worker
                keep = False
            finally:
                _INFLIGHT.labels("workers").dec()
                self._bump("handled")
            if keep and not self._stop.is_set():
                self._park(sock)
            else:
                self._close_sock(sock)

    # ----------------------------------------------------------- parking

    def _park(self, sock):
        with self._park_lock:
            self._parked[sock] = time.monotonic()
            _INFLIGHT.labels("parked").set(len(self._parked))
        try:
            self._wake_w.send(b"p")
        except OSError:
            pass

    def _parker_loop(self):
        while not self._stop.is_set():
            with self._park_lock:
                socks = list(self._parked)
            try:
                readable, _, errored = select.select(
                    socks + [self._wake_r], [], socks, 0.25
                )
            except (OSError, ValueError):
                with self._park_lock:
                    for s in list(self._parked):
                        if s.fileno() < 0:
                            del self._parked[s]
                continue
            if self._wake_r in readable:
                readable.remove(self._wake_r)
                try:
                    self._wake_r.recv(4096)
                except OSError:
                    pass
            for s in set(readable) | set(errored):
                with self._park_lock:
                    self._parked.pop(s, None)
                if s in errored:
                    self._close_sock(s)
                    continue
                try:
                    if not s.recv(1, socket.MSG_PEEK):
                        self._close_sock(s)  # peer sent FIN while parked
                        continue
                    addr = s.getpeername()
                except OSError:
                    self._close_sock(s)
                    continue
                # next request arrived: back through the admission gate —
                # a parked connection has no standing claim on a worker
                self._admit(s, addr, requeued=True)
            now = time.monotonic()
            with self._park_lock:
                idle = [s for s, t0 in self._parked.items()
                        if now - t0 > self.request_timeout]
                for s in idle:
                    del self._parked[s]
                _INFLIGHT.labels("parked").set(len(self._parked))
            for s in idle:
                self._close_sock(s)

    # ---------------------------------------------------------- teardown

    @staticmethod
    def _close_sock(sock):
        """Close with FIN, not RST: half-close the send side, then drain
        briefly so unread peer bytes cannot flip the close into a reset."""
        try:
            try:
                sock.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            sock.settimeout(0.05)
            try:
                while sock.recv(4096):
                    pass
            except (OSError, TimeoutError):
                pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def shutdown(self):
        """Graceful stop: accept halts, queued + in-flight requests
        complete (sentinels ride BEHIND queued sockets in the FIFO), late
        arrivals get a clean 503, every pool thread joins — repeated
        start/stop cycles leak no worker threads."""
        self._stop.set()
        super().shutdown()  # blocks until the accept loop exits
        for _ in self._workers:
            self._queue.put(None)
        for w in self._workers:
            w.join(timeout=max(5.0, 2 * self.request_timeout))
        self._shed_queue.put(None)
        self._shedder.join(timeout=5.0)
        try:
            self._wake_w.send(b"s")
        except OSError:
            pass
        self._parker.join(timeout=5.0)
        for ws in (self._wake_r, self._wake_w):
            try:
                ws.close()
            except OSError:
                pass
        with self._park_lock:
            parked = list(self._parked)
            self._parked.clear()
        for s in parked:
            self._close_sock(s)
        while True:  # anything that raced past the sentinels
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                self._shed_now(item[0], "shutdown")
        self.server_close()


def serve(chain, op_pool=None, host="127.0.0.1", port=0, allow_origin=None,
          rate_limit=None, http_threads=None, request_timeout=None,
          tracer=None):
    """Start the API server; returns (server, thread, actual_port).
    `rate_limit` (requests/second, burst 2x) enables the QoS token bucket —
    over-quota requests get 429 + Retry-After instead of queued work.
    `http_threads`/`request_timeout` size the bounded worker pool and the
    per-request read deadline (None = env/default via the resolvers);
    `tracer` records per-request `http_serve` traces that adopt the
    caller's X-LH-Trace-Ctx wire context."""
    limiter = None
    if rate_limit is not None:
        from ..qos.ratelimit import RateLimiter

        limiter = RateLimiter().configure(
            "http_api", float(rate_limit), burst=2 * float(rate_limit)
        )
    handler = type(
        "BoundHandler",
        (BeaconApiHandler,),
        {"chain": chain, "op_pool": op_pool, "event_bus": EventBus(),
         "allow_origin": allow_origin, "rate_limiter": limiter,
         "tracer": tracer},
    )
    server = WorkerPoolHTTPServer(
        (host, port), handler, http_threads=http_threads,
        request_timeout=request_timeout,
    )
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, thread, server.server_address[1]
