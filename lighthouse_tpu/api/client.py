"""Typed Beacon-API HTTP client (common/eth2 BeaconNodeHttpClient analog).

Implements the same duck-typed surface as
validator.beacon_node.InProcessBeaconNode so the validator client can run
against a remote beacon node over HTTP exactly as it runs in-process."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from ..state_transition.slot import types_for_slot
from ..validator.beacon_node import (
    AttesterDuty,
    BeaconNodeError,
    NodeRateLimited,
    ProposerDuty,
)


def _http_error(verb: str, path: str, e: urllib.error.HTTPError) -> BeaconNodeError:
    """429s become the TYPED rate-limit shape so the fallback retries
    without demoting the node (classification by type, not text)."""
    if e.code == 429:
        try:
            retry_after = float(e.headers.get("Retry-After", 0) or 0)
        except (TypeError, ValueError):
            retry_after = 0.0
        return NodeRateLimited(
            f"{verb} {path}: 429 rate limited", retry_after=retry_after
        )
    return BeaconNodeError(f"{verb} {path}: {e.code} {e.read()[:200]}")


class BeaconNodeHttpClient:
    def __init__(self, base_url: str, timeout: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    # ------------------------------------------------------------ plumbing

    def _get(self, path: str):
        try:
            with urllib.request.urlopen(self.base_url + path, timeout=self.timeout) as r:
                body = r.read()
                return json.loads(body) if body else {}
        except urllib.error.HTTPError as e:
            raise _http_error("GET", path, e) from e
        except urllib.error.URLError as e:
            raise BeaconNodeError(f"GET {path}: {e}") from e

    def _post(self, path: str, payload):
        data = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.base_url + path, data=data, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                body = r.read()
                return json.loads(body) if body else {}
        except urllib.error.HTTPError as e:
            raise _http_error("POST", path, e) from e
        except urllib.error.URLError as e:
            raise BeaconNodeError(f"POST {path}: {e}") from e

    # ------------------------------------------------------------ node

    def is_healthy(self) -> bool:
        try:
            self._get("/eth/v1/node/health")
            return True
        except BeaconNodeError:
            return False

    def version(self) -> str:
        return self._get("/eth/v1/node/version")["data"]["version"]

    def syncing(self) -> dict:
        return self._get("/eth/v1/node/syncing")["data"]

    def genesis(self) -> dict:
        return self._get("/eth/v1/beacon/genesis")["data"]

    def genesis_validators_root(self) -> bytes:
        return bytes.fromhex(self.genesis()["genesis_validators_root"][2:])

    def spec(self) -> dict:
        return self._get("/eth/v1/config/spec")["data"]

    # ------------------------------------------------------------ beacon

    def state_root(self, state_id: str = "head") -> bytes:
        return bytes.fromhex(
            self._get(f"/eth/v1/beacon/states/{state_id}/root")["data"]["root"][2:]
        )

    def finality_checkpoints(self, state_id: str = "head") -> dict:
        return self._get(f"/eth/v1/beacon/states/{state_id}/finality_checkpoints")["data"]

    def validators(self, state_id: str = "head") -> list[dict]:
        return self._get(f"/eth/v1/beacon/states/{state_id}/validators")["data"]

    def block_root(self, block_id: str = "head") -> bytes:
        return bytes.fromhex(
            self._get(f"/eth/v1/beacon/blocks/{block_id}/root")["data"]["root"][2:]
        )

    def header(self, block_id: str = "head") -> dict:
        return self._get(f"/eth/v1/beacon/headers/{block_id}")["data"]

    def debug_state_ssz(self, state_id: str = "finalized") -> bytes:
        """Full SSZ BeaconState (checkpoint-sync download)."""
        return bytes.fromhex(
            self._get(f"/eth/v2/debug/beacon/states/{state_id}")["data"][2:]
        )

    def block_ssz(self, block_id: str = "finalized") -> bytes:
        """Full SSZ SignedBeaconBlock (checkpoint-sync companion)."""
        return bytes.fromhex(
            self._get(f"/lighthouse_tpu/blocks/{block_id}/ssz")["data"][2:]
        )

    # ------------------------------------------------------------ duties

    def attester_duties(self, epoch: int, indices: list[int]) -> list[AttesterDuty]:
        resp = self._post(
            f"/eth/v1/validator/duties/attester/{epoch}", [str(i) for i in indices]
        )
        return [
            AttesterDuty(
                pubkey=bytes.fromhex(d["pubkey"][2:]),
                validator_index=int(d["validator_index"]),
                slot=int(d["slot"]),
                committee_index=int(d["committee_index"]),
                committee_length=int(d["committee_length"]),
                committee_position=int(d["validator_committee_index"]),
                committees_at_slot=int(d["committees_at_slot"]),
            )
            for d in resp["data"]
        ]

    def proposer_duties(self, epoch: int) -> list[ProposerDuty]:
        resp = self._get(f"/eth/v1/validator/duties/proposer/{epoch}")
        return [
            ProposerDuty(
                pubkey=bytes.fromhex(d["pubkey"][2:]),
                validator_index=int(d["validator_index"]),
                slot=int(d["slot"]),
            )
            for d in resp["data"]
        ]

    # ------------------------------------------------------------ publish

    def publish_attestations(self, attestations, types) -> int:
        payload = []
        for att in attestations:
            from ..ssz.core import Bitlist

            bl = None
            for f in types.Attestation.fields:
                if f.name == "aggregation_bits":
                    bl = f.type
            payload.append(
                {
                    "aggregation_bits": "0x" + bl.serialize(att.aggregation_bits).hex(),
                    "data": {
                        "slot": str(att.data.slot),
                        "index": str(att.data.index),
                        "beacon_block_root": "0x" + bytes(att.data.beacon_block_root).hex(),
                        "source": {
                            "epoch": str(att.data.source.epoch),
                            "root": "0x" + bytes(att.data.source.root).hex(),
                        },
                        "target": {
                            "epoch": str(att.data.target.epoch),
                            "root": "0x" + bytes(att.data.target.root).hex(),
                        },
                    },
                    "signature": "0x" + bytes(att.signature).hex(),
                }
            )
        self._post("/eth/v1/beacon/pool/attestations", payload)
        return len(attestations)

    def attestation_data(self, slot: int, committee_index: int, types=None):
        got = self._get(
            f"/eth/v1/validator/attestation_data?slot={slot}"
            f"&committee_index={committee_index}"
        )["data"]
        if types is None:
            from ..state_transition.slot import types_for_slot

            types = types_for_slot(self.spec_obj, slot) if hasattr(self, "spec_obj") else None
        if types is None:
            return got
        return types.AttestationData.make(
            slot=int(got["slot"]),
            index=int(got["index"]),
            beacon_block_root=bytes.fromhex(got["beacon_block_root"][2:]),
            source=types.Checkpoint.make(
                epoch=int(got["source"]["epoch"]),
                root=bytes.fromhex(got["source"]["root"][2:]),
            ),
            target=types.Checkpoint.make(
                epoch=int(got["target"]["epoch"]),
                root=bytes.fromhex(got["target"]["root"][2:]),
            ),
        )

    def produce_block(self, slot: int, randao_reveal: bytes, types, graffiti: bytes | None = None):
        path = (
            f"/eth/v3/validator/blocks/{slot}?randao_reveal=0x{randao_reveal.hex()}"
        )
        if graffiti is not None:
            path += f"&graffiti=0x{graffiti.hex()}"
        got = self._get(path)
        return types.BeaconBlock.deserialize(bytes.fromhex(got["data"][2:]))

    def publish_block(self, signed_block, types) -> None:
        self._post(
            "/eth/v2/beacon/blocks",
            {"ssz": "0x" + types.SignedBeaconBlock.serialize(signed_block).hex()},
        )
