"""Typed Beacon-API HTTP client (common/eth2 BeaconNodeHttpClient analog).

Implements the same duck-typed surface as
validator.beacon_node.InProcessBeaconNode so the validator client can run
against a remote beacon node over HTTP exactly as it runs in-process."""

from __future__ import annotations

import http.client
import itertools
import json
import math
import socket
import threading
import time as _time
from time import perf_counter
from urllib.parse import urlsplit

from ..observability.propagation import WireTraceContext, encode_ctx
from ..observability.trace import current_wire_ctx, next_trace_id
from ..state_transition.slot import types_for_slot
from ..utils.metrics import REGISTRY
from ..validator.beacon_node import (
    AttesterDuty,
    BeaconNodeError,
    NodeRateLimited,
    NodeTimeout,
    ProposerDuty,
)

# timeout classification feeds the fallback's health scoring: a connect
# failure, a silent server, and a mid-body stall are different diseases
# and must demote differently — so each phase is its own series
HTTP_CLIENT_TIMEOUTS = REGISTRY.counter_vec(
    "http_client_timeouts_total",
    "beacon-API client timeouts, by phase (connect / read / body)",
    ("phase",),
)
HTTP_CLIENT_CONNECTIONS = REGISTRY.counter_vec(
    "http_client_connections_total",
    "beacon-API client connection events (new / reused / stale_retry)",
    ("event",),
)

#: a 429 with no usable Retry-After still deserves SOME backoff floor
RETRY_AFTER_DEFAULT = 1.0
#: and no server gets to park a validator client past this — a huge
#: Retry-After must never out-sleep a duty deadline
RETRY_AFTER_CAP = 30.0


def parse_retry_after(raw) -> float:
    """Clamp a Retry-After header to a sane bounded range: non-numeric,
    NaN, or missing values fall back to RETRY_AFTER_DEFAULT; negatives
    clamp to 0; huge values clamp to RETRY_AFTER_CAP. The old behavior
    (malformed -> 0.0, huge -> unbounded sleep) turned one bad header into
    either a hot retry loop or a missed slot."""
    try:
        v = float(raw)
    except (TypeError, ValueError):
        return RETRY_AFTER_DEFAULT
    if not math.isfinite(v):
        return RETRY_AFTER_DEFAULT
    return min(max(v, 0.0), RETRY_AFTER_CAP)


def _http_error(verb: str, path: str, status: int, headers, body: bytes):
    """429s — and 503s carrying Retry-After, the admission gate's shed
    shape — become the TYPED rate-limit error so the fallback retries
    without demoting the node and honors the header as a backoff floor
    (classification by type, not text)."""
    if status == 429 or (status == 503 and headers.get("Retry-After")):
        return NodeRateLimited(
            f"{verb} {path}: {status} rate limited",
            retry_after=parse_retry_after(headers.get("Retry-After")),
        )
    return BeaconNodeError(f"{verb} {path}: {status} {body[:200]!r}")


#: process-wide publish offsets for contexts minted at the client seam
_ctx_seq = itertools.count()


class BeaconNodeHttpClient:
    """Keep-alive pooled HTTP client: requests reuse per-node
    `http.client` connections instead of paying a TCP handshake per call
    (the reference's reqwest pool). A reused socket that the server closed
    between requests surfaces as RemoteDisconnected on the next write —
    retried ONCE on a fresh connection (stale-socket semantics), never for
    sockets that failed while fresh.

    Every request carries an `X-LH-Trace-Ctx` wire context: the caller's
    current context when one is bound to the thread (so a duty driven by a
    producer's publish joins its causal chain), else a context minted here
    — and the optional `tracer` records the serialization + socket cost as
    an `http_client` trace keyed on that context, which the cluster merge
    links to the server's `http_serve` span."""

    #: idle sockets kept per client; the fleet runs hundreds of clients
    #: per node, so each keeps a tiny pool rather than a deep one
    MAX_IDLE = 2

    def __init__(self, base_url: str, timeout: float = 5.0, tracer=None,
                 origin: str | None = None):
        self.base_url = base_url.rstrip("/")
        parts = urlsplit(self.base_url)
        self._host = parts.hostname or "127.0.0.1"
        self._port = parts.port or 80
        self.timeout = timeout
        self.tracer = tracer
        self.origin = origin or f"http_client@{self._host}:{self._port}"
        self._idle: list = []
        self._pool_lock = threading.Lock()

    # ------------------------------------------------------------ plumbing

    def _checkout(self) -> tuple[http.client.HTTPConnection, bool]:
        with self._pool_lock:
            if self._idle:
                HTTP_CLIENT_CONNECTIONS.labels("reused").inc()
                return self._idle.pop(), True
        HTTP_CLIENT_CONNECTIONS.labels("new").inc()
        return (
            http.client.HTTPConnection(
                self._host, self._port, timeout=self.timeout
            ),
            False,
        )

    def _checkin(self, conn) -> None:
        with self._pool_lock:
            if len(self._idle) < self.MAX_IDLE:
                self._idle.append(conn)
                return
        conn.close()

    def close(self) -> None:
        with self._pool_lock:
            idle, self._idle = self._idle, []
        for conn in idle:
            conn.close()

    def _mint_ctx(self) -> WireTraceContext:
        return WireTraceContext(
            origin=self.origin, trace_id=next_trace_id(), slot=0,
            seq=next(_ctx_seq), sent_at=_time.time(),
        )

    def _request(self, method: str, path: str, payload=None):
        data = None
        headers = {}
        if payload is not None:
            data = json.dumps(payload).encode()
            headers["Content-Type"] = "application/json"
        ctx = current_wire_ctx() or self._mint_ctx()
        headers["X-LH-Trace-Ctx"] = encode_ctx(ctx).hex()
        tr = None
        if self.tracer is not None:
            tr = self.tracer.begin("http_client")
            tr.adopt(ctx)
        t0 = perf_counter()
        try:
            status, resp_headers, body = self._roundtrip(
                method, path, data, headers
            )
        finally:
            if tr is not None:
                tr.add_span("http_request", t0, perf_counter(),
                            path=path, method=method)
                self.tracer.finish(tr)
        if status >= 400:
            raise _http_error(method, path, status, resp_headers, body)
        return json.loads(body) if body else {}

    def _roundtrip(self, method: str, path: str, data, headers):
        """One HTTP exchange over a pooled connection; returns (status,
        headers, body). Timeouts classify by phase — connect (no listener
        reachable in time), read (request sent, no response line), body
        (response started, then stalled) — because the fallback's health
        scoring treats them differently from hard errors."""
        last_exc = None
        for attempt in (0, 1):
            conn, reused = self._checkout()
            try:
                if conn.sock is None:
                    try:
                        conn.connect()
                    except (TimeoutError, socket.timeout) as e:
                        HTTP_CLIENT_TIMEOUTS.labels("connect").inc()
                        raise NodeTimeout(
                            f"{method} {path}: connect timed out"
                        ) from e
                try:
                    conn.request(method, path, body=data, headers=headers)
                    resp = conn.getresponse()
                except (TimeoutError, socket.timeout) as e:
                    HTTP_CLIENT_TIMEOUTS.labels("read").inc()
                    raise NodeTimeout(
                        f"{method} {path}: response timed out"
                    ) from e
                except (http.client.RemoteDisconnected,
                        http.client.BadStatusLine,
                        ConnectionResetError, BrokenPipeError) as e:
                    if reused and attempt == 0:
                        # server closed the pooled socket between requests
                        # (keep-alive expiry): retry once, fresh
                        HTTP_CLIENT_CONNECTIONS.labels("stale_retry").inc()
                        last_exc = e
                        conn.close()
                        continue
                    raise BeaconNodeError(f"{method} {path}: {e}") from e
                try:
                    body = resp.read()
                except (TimeoutError, socket.timeout) as e:
                    HTTP_CLIENT_TIMEOUTS.labels("body").inc()
                    raise NodeTimeout(
                        f"{method} {path}: response body stalled"
                    ) from e
                except (http.client.IncompleteRead,
                        ConnectionResetError) as e:
                    raise BeaconNodeError(
                        f"{method} {path}: truncated response: {e}"
                    ) from e
            except (NodeTimeout, BeaconNodeError):
                conn.close()
                raise
            except OSError as e:
                # anything unclassified above (refused, unreachable, DNS)
                conn.close()
                raise BeaconNodeError(f"{method} {path}: {e}") from e
            if resp.will_close:
                conn.close()
            else:
                self._checkin(conn)
            return resp.status, resp.headers, body
        raise BeaconNodeError(f"{method} {path}: {last_exc}") from last_exc

    def _get(self, path: str):
        return self._request("GET", path)

    def _post(self, path: str, payload):
        return self._request("POST", path, payload)

    # ------------------------------------------------------------ node

    def is_healthy(self) -> bool:
        try:
            self._get("/eth/v1/node/health")
            return True
        except BeaconNodeError:
            return False

    def version(self) -> str:
        return self._get("/eth/v1/node/version")["data"]["version"]

    def syncing(self) -> dict:
        return self._get("/eth/v1/node/syncing")["data"]

    def genesis(self) -> dict:
        return self._get("/eth/v1/beacon/genesis")["data"]

    def genesis_validators_root(self) -> bytes:
        return bytes.fromhex(self.genesis()["genesis_validators_root"][2:])

    def spec(self) -> dict:
        return self._get("/eth/v1/config/spec")["data"]

    # ------------------------------------------------------------ beacon

    def state_root(self, state_id: str = "head") -> bytes:
        return bytes.fromhex(
            self._get(f"/eth/v1/beacon/states/{state_id}/root")["data"]["root"][2:]
        )

    def finality_checkpoints(self, state_id: str = "head") -> dict:
        return self._get(f"/eth/v1/beacon/states/{state_id}/finality_checkpoints")["data"]

    def validators(self, state_id: str = "head") -> list[dict]:
        return self._get(f"/eth/v1/beacon/states/{state_id}/validators")["data"]

    def block_root(self, block_id: str = "head") -> bytes:
        return bytes.fromhex(
            self._get(f"/eth/v1/beacon/blocks/{block_id}/root")["data"]["root"][2:]
        )

    def header(self, block_id: str = "head") -> dict:
        return self._get(f"/eth/v1/beacon/headers/{block_id}")["data"]

    def debug_state_ssz(self, state_id: str = "finalized") -> bytes:
        """Full SSZ BeaconState (checkpoint-sync download)."""
        return bytes.fromhex(
            self._get(f"/eth/v2/debug/beacon/states/{state_id}")["data"][2:]
        )

    def block_ssz(self, block_id: str = "finalized") -> bytes:
        """Full SSZ SignedBeaconBlock (checkpoint-sync companion)."""
        return bytes.fromhex(
            self._get(f"/lighthouse_tpu/blocks/{block_id}/ssz")["data"][2:]
        )

    # ------------------------------------------------------------ duties

    def attester_duties(self, epoch: int, indices: list[int]) -> list[AttesterDuty]:
        resp = self._post(
            f"/eth/v1/validator/duties/attester/{epoch}", [str(i) for i in indices]
        )
        return [
            AttesterDuty(
                pubkey=bytes.fromhex(d["pubkey"][2:]),
                validator_index=int(d["validator_index"]),
                slot=int(d["slot"]),
                committee_index=int(d["committee_index"]),
                committee_length=int(d["committee_length"]),
                committee_position=int(d["validator_committee_index"]),
                committees_at_slot=int(d["committees_at_slot"]),
            )
            for d in resp["data"]
        ]

    def proposer_duties(self, epoch: int) -> list[ProposerDuty]:
        resp = self._get(f"/eth/v1/validator/duties/proposer/{epoch}")
        return [
            ProposerDuty(
                pubkey=bytes.fromhex(d["pubkey"][2:]),
                validator_index=int(d["validator_index"]),
                slot=int(d["slot"]),
            )
            for d in resp["data"]
        ]

    # ------------------------------------------------------------ publish

    def publish_attestations(self, attestations, types) -> int:
        payload = []
        for att in attestations:
            from ..ssz.core import Bitlist

            bl = None
            for f in types.Attestation.fields:
                if f.name == "aggregation_bits":
                    bl = f.type
            payload.append(
                {
                    "aggregation_bits": "0x" + bl.serialize(att.aggregation_bits).hex(),
                    "data": {
                        "slot": str(att.data.slot),
                        "index": str(att.data.index),
                        "beacon_block_root": "0x" + bytes(att.data.beacon_block_root).hex(),
                        "source": {
                            "epoch": str(att.data.source.epoch),
                            "root": "0x" + bytes(att.data.source.root).hex(),
                        },
                        "target": {
                            "epoch": str(att.data.target.epoch),
                            "root": "0x" + bytes(att.data.target.root).hex(),
                        },
                    },
                    "signature": "0x" + bytes(att.signature).hex(),
                }
            )
        self._post("/eth/v1/beacon/pool/attestations", payload)
        return len(attestations)

    def attestation_data(self, slot: int, committee_index: int, types=None):
        got = self._get(
            f"/eth/v1/validator/attestation_data?slot={slot}"
            f"&committee_index={committee_index}"
        )["data"]
        if types is None:
            from ..state_transition.slot import types_for_slot

            types = types_for_slot(self.spec_obj, slot) if hasattr(self, "spec_obj") else None
        if types is None:
            return got
        return types.AttestationData.make(
            slot=int(got["slot"]),
            index=int(got["index"]),
            beacon_block_root=bytes.fromhex(got["beacon_block_root"][2:]),
            source=types.Checkpoint.make(
                epoch=int(got["source"]["epoch"]),
                root=bytes.fromhex(got["source"]["root"][2:]),
            ),
            target=types.Checkpoint.make(
                epoch=int(got["target"]["epoch"]),
                root=bytes.fromhex(got["target"]["root"][2:]),
            ),
        )

    def produce_block(self, slot: int, randao_reveal: bytes, types, graffiti: bytes | None = None):
        path = (
            f"/eth/v3/validator/blocks/{slot}?randao_reveal=0x{randao_reveal.hex()}"
        )
        if graffiti is not None:
            path += f"&graffiti=0x{graffiti.hex()}"
        got = self._get(path)
        return types.BeaconBlock.deserialize(bytes.fromhex(got["data"][2:]))

    def publish_block(self, signed_block, types) -> None:
        self._post(
            "/eth/v2/beacon/blocks",
            {"ssz": "0x" + types.SignedBeaconBlock.serialize(signed_block).hex()},
        )
