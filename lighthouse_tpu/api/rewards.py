"""Beacon-API reward computations.

Parity surface:
  - /root/reference/beacon_node/http_api/src/standard_block_rewards.rs +
    beacon_chain/src/beacon_block_reward.rs (GET beacon/rewards/blocks)
  - beacon_chain/src/attestation_rewards.rs (POST beacon/rewards/attestations)
  - http_api/src/sync_committee_rewards.rs (POST beacon/rewards/sync_committee)

Block rewards are measured, not re-derived: each operation class is applied
to a clone of the pre-state and the proposer-balance delta read off — by
construction this agrees with the state transition for every fork.
"""

from __future__ import annotations

from ..state_transition import accessors as acc
from ..state_transition import epoch as ep
from ..state_transition.block import (
    _default_pubkey_getter,
    process_attestation,
    process_attester_slashing,
    process_proposer_slashing,
    process_sync_aggregate,
)
from ..state_transition.slot import process_slots, types_for_slot
from ..types.spec import ForkName
from ..types.state_util import clone_state


def _noop_handle(_s):
    return None


def compute_block_rewards(chain, block_root: bytes) -> dict:
    """StandardBlockReward for an imported block (all amounts in gwei)."""
    spec = chain.spec
    slot = chain.block_slots.get(block_root)
    if slot is None:
        raise KeyError("block not found")
    types = types_for_slot(spec, slot)
    signed = chain.store.get_block(block_root, types)
    if signed is None:
        raise KeyError("block not found")
    block = signed.message
    proposer = int(block.proposer_index)
    fork = spec.fork_name_at_slot(slot)

    state = chain._state_for_block(bytes(block.parent_root), slot)
    state = clone_state(state, spec)
    if state.slot < slot:
        process_slots(state, spec, slot)

    def bal() -> int:
        return int(state.balances[proposer])

    get_pubkey = _default_pubkey_getter(state)
    rewards = {}
    before = bal()
    for ps in block.body.proposer_slashings:
        process_proposer_slashing(
            state, spec, types, ps, fork, _noop_handle, get_pubkey
        )
    rewards["proposer_slashings"] = bal() - before

    before = bal()
    for asl in block.body.attester_slashings:
        process_attester_slashing(
            state, spec, types, asl, fork, _noop_handle, get_pubkey
        )
    rewards["attester_slashings"] = bal() - before

    before = bal()
    att_cache: dict = {}
    for att in block.body.attestations:
        process_attestation(
            state, spec, types, att, fork, _noop_handle, get_pubkey, att_cache
        )
    rewards["attestations"] = bal() - before

    rewards["sync_aggregate"] = 0
    if fork >= ForkName.altair:
        before = bal()
        process_sync_aggregate(state, spec, types, block, _noop_handle, get_pubkey)
        rewards["sync_aggregate"] = bal() - before

    total = (
        rewards["attestations"]
        + rewards["sync_aggregate"]
        + rewards["proposer_slashings"]
        + rewards["attester_slashings"]
    )
    return {
        "proposer_index": proposer,
        "total": total,
        "attestations": rewards["attestations"],
        "sync_aggregate": rewards["sync_aggregate"],
        "proposer_slashings": rewards["proposer_slashings"],
        "attester_slashings": rewards["attester_slashings"],
    }


def _canonical_state_at_slot(chain, slot: int):
    """Post-state of the canonical block at/below `slot`, advanced to
    `slot` — walks the head lineage so the answer is exact even when the
    head has moved far past it."""
    spec = chain.spec
    root = chain.head_root
    while chain.block_slots.get(root, 0) > slot:
        blk = chain.store.get_block(
            root, types_for_slot(spec, chain.block_slots[root])
        )
        if blk is None:
            raise KeyError(f"canonical chain walk broke at {root.hex()[:8]}")
        root = bytes(blk.message.parent_root)
    state_root = chain.state_root_by_block.get(root)
    if state_root is None or state_root not in chain.state_cache:
        raise KeyError(f"state at slot {slot} unavailable")
    state = clone_state(chain.state_cache[state_root], spec)
    if state.slot < slot:
        process_slots(state, spec, slot)
    return state


def compute_attestation_rewards(chain, epoch: int, validators: list | None) -> dict:
    """StandardAttestationRewards for `epoch` (altair+ accounting).

    Judged on the state at the END slot of epoch+1 (late attestations for
    `epoch` can land through all of epoch+1 — attestation_rewards.rs:44
    uses the same slot) — resolved from the canonical lineage, not the
    head, so the answer stays pinned to `epoch` as the chain advances."""
    spec = chain.spec
    sp_epoch = spec.preset.SLOTS_PER_EPOCH
    judge_slot = (epoch + 2) * sp_epoch - 1
    head = chain.head_state()
    if int(head.slot) < judge_slot:
        raise KeyError(f"epoch {epoch} not yet judgeable")
    state = _canonical_state_at_slot(chain, judge_slot)
    fork = spec.fork_name_at_slot(state.slot)
    if fork < ForkName.altair:
        raise ValueError("attestation rewards endpoint serves altair+ epochs")

    n = len(state.validators)
    per_flag = []
    for flag_index in range(len(acc.PARTICIPATION_FLAG_WEIGHTS)):
        per_flag.append(ep.get_flag_index_deltas(state, spec, flag_index, fork))
    inact_rw, inact_pen = ep.get_inactivity_penalty_deltas(state, spec, fork)

    # ideal rewards per effective-balance tier present in the registry
    base_per_incr = acc.get_base_reward_per_increment(state, spec)
    total_active = acc.get_total_active_balance(state, spec)
    incr = spec.effective_balance_increment
    leaking = acc.is_in_inactivity_leak(state, spec)
    prev = acc.get_previous_epoch(state, spec)
    flag_balances = [
        acc.get_total_balance(
            state, spec,
            acc.get_unslashed_participating_indices(state, spec, i, prev),
        )
        for i in range(len(acc.PARTICIPATION_FLAG_WEIGHTS))
    ]
    ideal = []
    for eff in sorted({int(v.effective_balance) for v in state.validators}):
        base_reward = (eff // incr) * base_per_incr
        row = {"effective_balance": eff, "head": 0, "target": 0, "source": 0,
               "inactivity": 0}
        for flag_index, name in ((0, "source"), (1, "target"), (2, "head")):
            if leaking:
                continue
            weight = acc.PARTICIPATION_FLAG_WEIGHTS[flag_index]
            num = base_reward * weight * (flag_balances[flag_index] // incr)
            row[name] = num // ((total_active // incr) * acc.WEIGHT_DENOMINATOR)
        ideal.append(row)

    wanted = None
    if validators:
        wanted = set()
        for v in validators:
            if isinstance(v, str) and v.startswith("0x"):
                pkb = bytes.fromhex(v[2:])
                for i, val in enumerate(state.validators):
                    if bytes(val.pubkey) == pkb:
                        wanted.add(i)
                        break
            else:
                wanted.add(int(v))
    eligible = set(ep._eligible_validator_indices(state, spec))
    total = []
    for i in range(n):
        if i not in eligible:
            continue
        if wanted is not None and i not in wanted:
            continue
        (src_r, src_p), (tgt_r, tgt_p), (head_r, _head_p) = (
            per_flag[0], per_flag[1], per_flag[2],
        )
        total.append(
            {
                "validator_index": i,
                "head": head_r[i],
                "target": tgt_r[i] - tgt_p[i],
                "source": src_r[i] - src_p[i],
                "inactivity": inact_rw[i] - inact_pen[i],
            }
        )
    return {"ideal_rewards": ideal, "total_rewards": total}


def compute_sync_committee_rewards(chain, block_root: bytes,
                                   validators: list | None) -> list[dict]:
    """Per-participant sync-committee rewards for one block
    (sync_committee_rewards.rs)."""
    spec = chain.spec
    slot = chain.block_slots.get(block_root)
    if slot is None:
        raise KeyError("block not found")
    types = types_for_slot(spec, slot)
    signed = chain.store.get_block(block_root, types)
    if signed is None:
        raise KeyError("block not found")
    fork = spec.fork_name_at_slot(slot)
    if fork < ForkName.altair:
        raise ValueError("no sync committee before altair")

    state = chain._state_for_block(bytes(signed.message.parent_root), slot)
    state = clone_state(state, spec)
    if state.slot < slot:
        process_slots(state, spec, slot)

    # participant reward exactly as process_sync_aggregate computes it
    total_active = acc.get_total_active_balance(state, spec)
    incr = spec.effective_balance_increment
    base_per_incr = acc.get_base_reward_per_increment(state, spec)
    total_base_rewards = base_per_incr * (total_active // incr)
    max_participant_rewards = (
        total_base_rewards
        * acc.SYNC_REWARD_WEIGHT
        // acc.WEIGHT_DENOMINATOR
        // spec.preset.SLOTS_PER_EPOCH
    )
    participant_reward = max_participant_rewards // spec.preset.SYNC_COMMITTEE_SIZE

    index_by_pk = {bytes(v.pubkey): i for i, v in enumerate(state.validators)}
    committee = [
        index_by_pk.get(bytes(pk)) for pk in state.current_sync_committee.pubkeys
    ]
    bits = list(signed.message.body.sync_aggregate.sync_committee_bits)
    wanted = {int(v) for v in validators} if validators else None
    out = []
    for pos, vidx in enumerate(committee):
        if vidx is None:
            continue
        if wanted is not None and vidx not in wanted:
            continue
        out.append(
            {
                "validator_index": vidx,
                "reward": participant_reward if bits[pos] else -participant_reward,
            }
        )
    return out
