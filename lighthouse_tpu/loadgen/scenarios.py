"""Scenario definitions: mainnet-shaped traffic mixes, seeded + deterministic.

Mainnet shape (the ratios, not the absolute scale): every active validator
attests exactly once per epoch, so a subscribed-to-everything node sees
roughly `n_validators / 32` single-bit attestations per slot; each of the
up-to-64 committees elects ~16 aggregators, so aggregates arrive at
`committees * 16` per slot; and there is one block per slot. The generator
jitters each count ±10% from the scenario seed so queues see realistic
unevenness while staying bit-reproducible.

`stale_fraction` mixes in attestations stamped with a slot older than the
propagation window — replayed/late gossip whose deadline has already
passed, which MUST be shed `expired` at pop, never verified.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, replace

SLOTS_PER_EPOCH = 32          # mainnet shape
AGGREGATORS_PER_COMMITTEE = 16
MAX_COMMITTEES_PER_SLOT = 64


@dataclass(frozen=True)
class SlotTraffic:
    attestations: int
    aggregates: int
    blocks: int
    stale_attestations: int = 0


@dataclass
class Scenario:
    name: str
    n_validators: int = 16384
    slots: int = 8
    seed: int = 0xC0FFEE
    # open-loop multiplier over the mainnet-shaped per-slot counts
    flood_factor: float = 1.0
    # fraction of attestations stamped past the propagation window
    stale_fraction: float = 0.0
    # fault injections: "device_stall" stalls the device backend over
    # stall_slots; "slow_host" adds per-batch host latency; "storage_crash"
    # tears the durable head write at crash_slot and kills the node, then
    # the runner restarts it from the same datadir (crash_restart scenario);
    # "mesh_stall" stalls ONE chip's shard of the mesh device over
    # stall_slots (the collective blocks — loadgen/meshsim.py)
    faults: tuple = ()
    stall_slots: tuple = (2, 4)      # [start, end) in scenario slots
    crash_slot: int | None = None    # storage_crash: slot whose head write tears
    # mesh serving (loadgen/meshsim.py): mesh=True routes batches through
    # a real PipelinedDispatcher over an N-chip mesh device sim whose chip
    # count resolves against parallel.get_mesh() (mesh_devices overrides —
    # the --mesh-devices sweep's points); mesh_stall_chip names the chip
    # the "mesh_stall" fault wedges (chip 1 by default: the urgent lane is
    # pinned to chip 0 and must keep serving through the stall)
    mesh: bool = False
    mesh_devices: int | None = None
    mesh_stall_chip: int = 1
    # queue bounds for the attestation/aggregate queues (None = processor
    # defaults); flood scenarios shrink them so shedding is observable in
    # a few seconds instead of at mainnet scale
    att_queue_cap: int | None = None
    agg_queue_cap: int | None = None
    seconds_per_slot: float = 1.0    # logical (manual-clock) seconds


def mainnet_mix(n_validators: int, rng: random.Random) -> SlotTraffic:
    atts = max(1, n_validators // SLOTS_PER_EPOCH)
    committees = max(1, min(MAX_COMMITTEES_PER_SLOT, atts // 128))
    aggs = committees * AGGREGATORS_PER_COMMITTEE

    def jitter(n: int) -> int:
        return max(1, int(n * (0.9 + 0.2 * rng.random())))

    return SlotTraffic(jitter(atts), jitter(aggs), 1)


def traffic_schedule(sc: Scenario) -> list[SlotTraffic]:
    """Per-slot traffic for the whole scenario — pure function of the
    scenario (seeded RNG), so a report is reproducible from (name, seed)."""
    rng = random.Random(sc.seed)
    out = []
    for _slot in range(sc.slots):
        base = mainnet_mix(sc.n_validators, rng)
        atts = int(base.attestations * sc.flood_factor)
        stale = int(atts * sc.stale_fraction)
        out.append(
            SlotTraffic(
                attestations=atts - stale,
                aggregates=int(base.aggregates * sc.flood_factor),
                blocks=base.blocks,
                stale_attestations=stale,
            )
        )
    return out


SCENARIOS: dict[str, Scenario] = {
    # ~5 s CPU-only sanity pass: modest traffic, every code path exercised
    # (flood over the shrunk queue caps -> oldest-first sheds; stale mix ->
    # expiry; device stall mid-run -> full breaker cycle)
    "smoke": Scenario(
        name="smoke", n_validators=4096, slots=6, flood_factor=3.0,
        stale_fraction=0.1, faults=("device_stall",), stall_slots=(2, 4),
        att_queue_cap=256, agg_queue_cap=64,
    ),
    # steady mainnet-shaped mix, no faults — the control run
    "steady": Scenario(
        name="steady", n_validators=16384, slots=8,
    ),
    # 4x open-loop flood over deliberately small queues: oldest-first
    # shedding + admission refusals under pressure
    "flood": Scenario(
        name="flood", n_validators=16384, slots=8, flood_factor=4.0,
        stale_fraction=0.05, att_queue_cap=512, agg_queue_cap=128,
    ),
    # device stalls mid-run while the flood continues: the circuit breaker
    # must open, the host path serve, and the breaker close after recovery
    "device_stall": Scenario(
        name="device_stall", n_validators=16384, slots=10, flood_factor=2.0,
        faults=("device_stall",), stall_slots=(3, 6),
        att_queue_cap=1024, agg_queue_cap=256,
    ),
    # slow host verification under flood: queues stay hot, deadlines bite
    "slow_host": Scenario(
        name="slow_host", n_validators=8192, slots=8, flood_factor=2.0,
        faults=("slow_host",), stale_fraction=0.1,
        att_queue_cap=512, agg_queue_cap=128,
    ),
    # one chip of the mesh wedges mid-run while the flood continues: the
    # collective blocks every SHARDED batch, the breaker must open and the
    # host path serve (SLO ratio dips), the urgent lane (pinned to chip 0)
    # keeps serving, and the heal must close the breaker — the multichip
    # analog of device_stall, proving a stalled shard degrades gracefully
    # instead of wedging the pipeline window
    "mesh_stall": Scenario(
        name="mesh_stall", n_validators=16384, slots=10, flood_factor=2.0,
        mesh=True, faults=("mesh_stall",), stall_slots=(3, 6),
        att_queue_cap=1024, agg_queue_cap=256,
    ),
    # crash recovery proof: mainnet-shaped load over a DURABLE store whose
    # head write tears mid-record at crash_slot (the node "dies"); the
    # runner restarts from the same datadir, asserts the recovered head is
    # the last durably persisted one, and finishes the run — conservation
    # extends to published == processed + dropped + expired + lost_to_crash
    "crash_restart": Scenario(
        name="crash_restart", n_validators=4096, slots=8, flood_factor=2.0,
        stale_fraction=0.1, faults=("storage_crash",), crash_slot=4,
        att_queue_cap=256, agg_queue_cap=64,
    ),
}


def smoke_variant(sc: Scenario) -> Scenario:
    """Any scenario shrunk to smoke scale (CPU-only, seconds) without
    changing its SHAPE: same faults, same mix, clamped size. This is what
    `--smoke` combined with an explicit `--scenario` runs."""
    out = replace(
        sc,
        n_validators=min(sc.n_validators, 4096),
        slots=min(sc.slots, 8),
    )
    if out.crash_slot is not None:
        out.crash_slot = max(1, min(out.crash_slot, out.slots - 2))
    s0, s1 = out.stall_slots
    out.stall_slots = (min(s0, max(0, out.slots - 2)), min(s1, out.slots))
    return out


def get_scenario(name: str, **overrides) -> Scenario:
    """A named scenario, optionally with field overrides (CLI flags)."""
    base = SCENARIOS.get(name)
    if base is None:
        have = (
            sorted(SCENARIOS) + sorted(CAPACITY_SCENARIOS)
            + sorted(STATE_ROOT_SCENARIOS) + sorted(MIXED_DUTY_SCENARIOS)
            + sorted(MULTINODE_SCENARIOS) + sorted(_ensure_fleet())
        )
        raise KeyError(
            f"unknown scenario {name!r} (have: {', '.join(have)})"
        )
    overrides = {k: v for k, v in overrides.items() if v is not None}
    return replace(base, **overrides) if overrides else replace(base)


# --------------------------------------------------------------- capacity


@dataclass
class CapacityScenario:
    """The closed-loop capacity-control proof (loadgen/capacity.py): a
    deterministic device-time-ledger sim where batch sizing genuinely
    matters (padded pow2 lane costs + per-batch base overhead, the jaxbls
    padding-bucket economics) driven through the REAL BeaconProcessor +
    AdmissionController + CapacityScheduler + SlotAccountant. The driver
    runs the controller leg (NO pre-installed profile, scheduler retuning
    live) against a static-optimal reference (the best fixed-cap plan
    found by sweeping a pow2 ladder with retuning disabled — the plan an
    oracle `autotune calibrate` would have installed) and FAILS unless
    the controller's deadline-credited throughput lands within
    `gate_ratio` of it."""

    name: str
    n_validators: int = 16384
    slots: int = 24
    seed: int = 0xC0FFEE
    #: per-slot demand curve shape: "ramp" sweeps factor_low -> factor_high
    #: -> back (the diurnal arc); "crowd" holds factor_low with a
    #: factor_high burst over crowd_slots
    profile: str = "ramp"
    factor_low: float = 0.25
    factor_high: float = 2.4
    crowd_slots: tuple = (8, 12)     # [start, end) for profile="crowd"
    #: BULK-class work (chain_segment) submitted per slot: what the
    #: admission watermarks shed when the controller tightens them
    bulk_per_slot: int = 24
    bulk_queue_cap: int = 64
    #: device cost model: a batch of n sets pays
    #: base_ms + per_set_ms * pow2ceil(n) logical milliseconds
    base_ms: float = 25.0
    per_set_ms: float = 0.65
    #: logical device seconds available per slot (the ledger)
    seconds_per_slot: int = 1
    #: extra traffic-free slots that drain backlog before the force-drain
    epilogue_slots: int = 4
    #: controller throughput floor vs the static-optimal reference
    gate_ratio: float = 0.9
    att_queue_cap: int | None = None
    agg_queue_cap: int | None = None


CAPACITY_SCENARIOS: dict[str, CapacityScenario] = {
    # demand sweeps a diurnal arc (0.25x -> 3x mainnet shape -> back),
    # overloading the ledger around the peak: the controller must track
    # the moving knee — pow2-aligned caps per demand phase — from a cold
    # start, with no profile installed
    "diurnal_ramp": CapacityScenario(
        name="diurnal_ramp", profile="ramp", factor_high=3.0,
    ),
    # steady 0.8x with a 5x crowd over slots [8,12): overload is real
    # (the ledger cannot serve the burst), so the controller's job is to
    # widen caps for the backlog, tighten watermarks while burn is over
    # 1x, and recover — and still out-serve (or match) every fixed plan
    "flash_crowd": CapacityScenario(
        name="flash_crowd", profile="crowd", slots=20,
        factor_low=0.8, factor_high=5.0, crowd_slots=(8, 12),
    ),
}


def is_capacity(name: str) -> bool:
    return name in CAPACITY_SCENARIOS


def get_capacity_scenario(name: str, **overrides) -> CapacityScenario:
    base = CAPACITY_SCENARIOS.get(name)
    if base is None:
        raise KeyError(f"unknown capacity scenario {name!r}")
    overrides = {k: v for k, v in overrides.items() if v is not None}
    return replace(base, **overrides) if overrides else replace(base)


def capacity_smoke_variant(sc: CapacityScenario) -> CapacityScenario:
    """Seconds-sized clamp, same demand SHAPE (profile + factors are the
    scenario; only scale shrinks). Shrinking the validator count scales
    the per-set cost UP by the same ratio so the demand-to-ledger ratio
    — the saturation physics the gate measures — is preserved; without
    that a smoke run would never stress the ledger and every plan would
    tie. The crowd window slides inside the clamped run so the burst is
    never cut."""
    n_small = min(sc.n_validators, 8192)
    out = replace(
        sc,
        n_validators=n_small,
        per_set_ms=sc.per_set_ms * (sc.n_validators / n_small),
        slots=min(sc.slots, 12),
        epilogue_slots=min(sc.epilogue_slots, 3),
    )
    if out.profile == "crowd":
        s0, s1 = out.crowd_slots
        width = max(1, min(s1 - s0, out.slots - 2))
        s0 = min(s0, out.slots - width - 1)
        out = replace(out, crowd_slots=(s0, s0 + width))
    return out


def capacity_slot_factors(sc: CapacityScenario) -> list[float]:
    """The per-slot demand multipliers — a pure function of the scenario
    (no RNG: jitter stays in mainnet_mix's seeded draw)."""
    import math

    if sc.profile == "crowd":
        s0, s1 = sc.crowd_slots
        return [
            sc.factor_high if s0 <= i < s1 else sc.factor_low
            for i in range(sc.slots)
        ]
    span = max(1, sc.slots - 1)
    return [
        sc.factor_low
        + (sc.factor_high - sc.factor_low) * math.sin(math.pi * i / span)
        for i in range(sc.slots)
    ]


# ------------------------------------------------------------- state root


@dataclass
class StateRootScenario:
    """The second workload's soak: seeded mutate-and-reroot churn over a
    validator-scale BeaconState (loadgen/state_root.py). Every slot
    mutates a block's worth of validators/balances and re-roots through
    the ACTIVE hash backend (bn --hash-backend / the scenario override);
    the run is conservation-checked — the balance ledger must sum and the
    final root must equal a cache-free ground-truth rehash — so a soak
    that passes proves the device path bit-exact under churn, not just on
    a fixture."""

    name: str
    n_validators: int = 16384
    slots: int = 8
    seed: int = 0xC0FFEE
    #: validators whose effective balance (and balance) mutate per slot
    churn_validators: int = 8
    #: additional balance-only mutations per slot
    churn_balances: int = 32
    #: override the process hash backend for the run (None = whatever
    #: bn --hash-backend / env resolved)
    hash_backend: str | None = None


STATE_ROOT_SCENARIOS: dict[str, StateRootScenario] = {
    "state_root": StateRootScenario(name="state_root"),
    # mainnet scale: the CowList-backed registry (ssz/cow.py) — fewer
    # slots because each carries the same churn shape but the fixture
    # build and ground-truth rehash dominate the wall clock
    "state_root_1m": StateRootScenario(
        name="state_root_1m", n_validators=1_048_576, slots=4
    ),
}


def is_state_root(name: str) -> bool:
    return name in STATE_ROOT_SCENARIOS


def get_state_root_scenario(name: str, **overrides) -> StateRootScenario:
    base = STATE_ROOT_SCENARIOS.get(name)
    if base is None:
        raise KeyError(f"unknown state-root scenario {name!r}")
    overrides = {k: v for k, v in overrides.items() if v is not None}
    return replace(base, **overrides) if overrides else replace(base)


def state_root_smoke_variant(sc: StateRootScenario) -> StateRootScenario:
    """Seconds-sized clamp, same churn shape (the --smoke modifier)."""
    return replace(
        sc,
        n_validators=min(sc.n_validators, 2048),
        slots=min(sc.slots, 4),
    )


# ------------------------------------------------------------- multi-node


@dataclass
class MultiNodeScenario:
    """A scenario over N full BeaconChain+NetworkNode stacks under a
    network fault plan (loadgen/multinode.py + netfaults.py). These run on
    the MINIMAL spec with the fake BLS backend: the subject is the
    network — forks, partitions, sync, slashing — not the device path, so
    every family is CPU-sized (seconds) by construction and `--smoke` is a
    clamp, not a reshape."""

    name: str
    n_nodes: int = 4
    n_validators: int = 64
    slots: int = 12
    seed: int = 0xC0FFEE
    subnets: int = 2
    #: publish per-validator attestations (the weight fork choice needs to
    #: resolve competing forks); off for families that don't fork
    attest: bool = True
    #: attach a SlasherService to every node (equivocation_storm)
    slasher: bool = False
    #: K: slots after the last heal within which all alive nodes must
    #: agree on one head, or the scenario FAILS
    converge_slots: int = 4
    #: fault plan pieces (loadgen/netfaults.py dataclasses)
    partitions: tuple = ()
    links: tuple = ()
    rpc_faults: tuple = ()
    churn: tuple = ()
    equivocations: tuple = ()
    #: sync_catchup: this node starts detached and range-syncs after
    #: `slots`, then `post_slots` more live slots run with it attached
    catchup_node: int | None = None
    post_slots: int = 2
    #: p2p Req/Resp budget for the in-sim nodes (small: injected faults
    #: raise immediately, real requests are localhost)
    rpc_timeout: float = 2.0
    #: validators owned per node (None = even split); must sum to
    #: n_validators — fork_reorg uses an uneven split so the healed fork
    #: race has a decisive majority
    validator_split: tuple | None = None
    #: fail the run unless >=1 produced block ends up orphaned (the
    #: fork_reorg acceptance: a reorg actually happened)
    expect_reorg: bool = False
    #: route gossip verification through the REAL BeaconProcessor +
    #: CapacityScheduler (harness-pumped, multinode._tick): the capacity
    #: controller under a heal-driven reorg storm — e.g.
    #: `partition_heal` with the controller active must still converge
    #: within K of heal with burn recovering
    batch_gossip: bool = False


def _multinode_scenarios() -> dict[str, MultiNodeScenario]:
    from .netfaults import Equivocation, Partition, RpcFault

    return {
        # 3-vs-1 partition mid-run: the minority node forks or stalls,
        # the heal must reconverge every head within K slots through
        # parent lookups + attestation-weighted fork choice
        "partition_heal": MultiNodeScenario(
            name="partition_heal", n_nodes=4, n_validators=64, slots=12,
            partitions=(Partition(start_slot=4, heal_slot=8,
                                  groups=((0, 1, 2), (3,))),),
            converge_slots=4,
        ),
        # 2-vs-2 node split with UNEVEN stake (48 vs 16 validators) held
        # long enough that BOTH sides grow a fork: the heal forces a real
        # reorg — the minority fork's blocks end up orphaned (the run
        # fails unless >=1 block is reorged out) before convergence
        "fork_reorg": MultiNodeScenario(
            name="fork_reorg", n_nodes=4, n_validators=64, slots=16,
            validator_split=(24, 24, 8, 8),
            partitions=(Partition(start_slot=4, heal_slot=10,
                                  groups=((0, 1), (2, 3))),),
            converge_slots=5, expect_reorg=True,
        ),
        # a node started behind range-syncs to head while the first peer
        # it targets stalls silently mid-range: SyncManager must time out,
        # blame, back off, and fail over to an alternate peer
        "sync_catchup": MultiNodeScenario(
            name="sync_catchup", n_nodes=4, n_validators=32, slots=8,
            attest=False, catchup_node=3, post_slots=2,
            rpc_faults=(RpcFault(
                server=0, start_slot=0, end_slot=10**9, mode="silent",
                protocols=(
                    "/eth2/beacon_chain/req/beacon_blocks_by_range/2/"
                    "ssz_snappy",
                ),
            ),),
        ),
        # repeated double-proposals: every honest node must reject the
        # second block, route both signed headers through its slasher,
        # and the assembled ProposerSlashings must reach later blocks
        "equivocation_storm": MultiNodeScenario(
            name="equivocation_storm", n_nodes=4, n_validators=64,
            slots=12, attest=False, slasher=True,
            equivocations=(Equivocation(slot=3), Equivocation(slot=6),
                           Equivocation(slot=9)),
        ),
    }


# ------------------------------------------------------------------ fleet


@dataclass
class FleetScenario:
    """A validator-fleet soak over the multi-node harness (loadgen/
    fleet.py): real VC stacks (slashing-protected stores, duty services,
    hardened BeaconNodeFallback) drive every duty through the nodes'
    rate-limited API surfaces while the fault axes compose. Minimal spec,
    fake BLS, CPU-sized; `--smoke` clamps size, never the fault shape."""

    name: str
    n_nodes: int = 4
    #: thousands of keys at full scale; smoke clamps (FLEET_SMOKE_*)
    n_validators: int = 2048
    #: each node's keys split UNEVENLY (seeded) across this many VCs
    vcs_per_node: int = 4
    slots: int = 16
    seed: int = 0xC0FFEE
    subnets: int = 2
    converge_slots: int = 4
    #: network fault axes (loadgen/netfaults.py dataclasses)
    partitions: tuple = ()
    links: tuple = ()
    churn: tuple = ()
    #: fleet fault axes (loadgen/fleet.py dataclasses)
    node_stalls: tuple = ()
    node_crashes: tuple = ()
    flash_crowds: tuple = ()
    #: token-bucket rate/burst on every node's VC-facing API surface
    #: (logical tokens/second — the HTTP API's --http-rate-limit shape)
    node_rate: float = 4096.0
    node_burst: float = 8192.0
    #: hardened-fallback knobs (validator/beacon_node.py resolution)
    vc_timeout: float = 2.0
    vc_retries: int = 2
    #: sign + aggregate sync-committee duties too
    sync_duties: bool = True
    #: fail unless performed/scheduled reaches this (None = no floor)
    min_performed_ratio: float | None = None
    #: fail unless >=1 incident dumped during the run
    expect_incident: bool = False
    #: route every node's gossip attestation/aggregate/block work through
    #: the REAL BeaconProcessor + CapacityScheduler (harness-pumped at
    #: phase barriers, multinode._tick) instead of inline verification —
    #: the capacity controller under realistic VC duty demand
    batch_gossip: bool = False
    #: fail unless the capacity scheduler actually made batch-formation
    #: decisions on the nodes (the scheduler-active proof)
    expect_scheduler: bool = False
    seconds_per_slot: float = 1.0
    #: -------- the real-socket HTTP leg (loadgen/fleet.py HttpLeg): this
    #: many VCs per node talk to a REAL localhost `api.http_api.serve()`
    #: server through pooled `api.client` connections (0 = leg off)
    http_vcs_per_node: int = 0
    #: duty-shaped GET requests each HTTP VC issues per slot (seeded
    #: deterministic schedule — the scheduled counts join the cluster
    #: rollup; socket outcomes/latencies stay wall-clock observations)
    http_requests_per_slot: int = 1
    #: server hardening knobs (api.http_api.WorkerPoolHTTPServer)
    http_threads: int = 4
    http_request_timeout: float = 1.0
    #: token-bucket rate on the real servers (None = unlimited)
    http_rate_limit: float | None = None
    #: socket-seam attacker schedule (netfaults.HttpFault)
    http_faults: tuple = ()
    #: fail unless the admission gate actually shed (http_api_shed_total
    #: + flight-recorder proof that saturation was reached and survived)
    expect_http_shed: bool = False


FLEET_SMOKE_VALIDATORS = 96
FLEET_SMOKE_SLOTS = 20


def _fleet_scenarios() -> dict[str, FleetScenario]:
    from .fleet import FlashCrowd, NodeCrash, NodeStall
    from .netfaults import HttpFault, Partition

    return {
        # the control run: no faults, the fleet must perform >=99% of its
        # duties (the remainder: genuinely empty aggregation pools)
        "fleet_steady": FleetScenario(
            name="fleet_steady", min_performed_ratio=0.99,
        ),
        # a 3v1 partition while the fleet signs: both sides keep serving
        # their forks (zero slashable signatures!), heads reconverge
        # within K of heal, every missed duty carries a reason
        "fleet_partition": FleetScenario(
            name="fleet_partition",
            partitions=(Partition(start_slot=4, heal_slot=8,
                                  groups=((0, 1, 2), (3,))),),
            converge_slots=4, expect_incident=True,
        ),
        # a torn store write kills node 1 mid-epoch: its VCs time out,
        # demote it, and fail over — the fleet keeps meeting duties
        "fleet_crash": FleetScenario(
            name="fleet_crash",
            node_crashes=(NodeCrash(node=1, slot=5),),
            converge_slots=4, expect_incident=True,
            min_performed_ratio=0.9,
        ),
        # fleet_steady's duty traffic as the capacity controller's demand
        # curve (the ROADMAP fleet item's follow-up): every node's gossip
        # verification work rides the REAL BeaconProcessor + capacity
        # scheduler, and the run fails unless the >=99% performed floor
        # STILL holds with the scheduler forming every batch — plus
        # nonzero scheduler decisions on the nodes (controller provably
        # active, not vacuously bypassed)
        "fleet_capacity": FleetScenario(
            name="fleet_capacity", min_performed_ratio=0.99,
            batch_gossip=True, expect_scheduler=True,
        ),
        # everything at once: 3-way partition x node-0 API stall x flash
        # crowd x one torn-write crash — PLUS the real-socket lane:
        # hundreds of HTTP VCs per node drive duty-shaped reads through
        # REAL localhost servers while an RST window bites the sockets.
        # The duty path must degrade with counted reasons and recover —
        # zero slashable messages, heads converge after heal, burn back
        # under 1x by the end, and the cluster rollup carries the leg's
        # per-route scheduled counts
        "combined_chaos": FleetScenario(
            name="combined_chaos", slots=20,
            partitions=(Partition(start_slot=4, heal_slot=8,
                                  groups=((0, 1), (2,), (3,))),),
            node_stalls=(NodeStall(node=0, start_slot=5, end_slot=7),),
            node_crashes=(NodeCrash(node=1, slot=6),),
            flash_crowds=(FlashCrowd(start_slot=10, end_slot=12),),
            converge_slots=5, expect_incident=True,
            http_vcs_per_node=128, http_requests_per_slot=1,
            http_threads=4, http_request_timeout=1.0,
            http_faults=(
                HttpFault(kind="reset", start_slot=10, end_slot=13,
                          clients=2),
            ),
        ),
        # the socket-seam siege: slow-loris header trickle occupies every
        # worker, a fire-and-forget storm overflows the admission queue,
        # and mid-body stalls eat read deadlines — the gate MUST shed
        # typed 503s (counted, flight-recorded), the health-exempt route
        # MUST keep answering, and the in-process duty path must not
        # notice (the performed floor still holds)
        "http_slowloris": FleetScenario(
            name="http_slowloris", n_nodes=2, n_validators=256,
            vcs_per_node=2, slots=8, converge_slots=4,
            http_vcs_per_node=3, http_requests_per_slot=1,
            http_threads=2, http_request_timeout=0.4,
            http_faults=(
                HttpFault(kind="slow_loris", start_slot=2, end_slot=5,
                          clients=4),
                HttpFault(kind="storm_429", start_slot=2, end_slot=5,
                          clients=40),
                HttpFault(kind="body_stall", start_slot=3, end_slot=5,
                          clients=2),
            ),
            expect_http_shed=True, min_performed_ratio=0.97,
        ),
    }


FLEET_SCENARIOS: dict[str, FleetScenario] = {}


def _ensure_fleet() -> dict[str, FleetScenario]:
    if not FLEET_SCENARIOS:
        FLEET_SCENARIOS.update(_fleet_scenarios())
    return FLEET_SCENARIOS


def is_fleet(name: str) -> bool:
    return name in _ensure_fleet()


def get_fleet_scenario(name: str, **overrides) -> FleetScenario:
    base = _ensure_fleet().get(name)
    if base is None:
        raise KeyError(f"unknown fleet scenario {name!r}")
    overrides = {k: v for k, v in overrides.items() if v is not None}
    return replace(base, **overrides) if overrides else replace(base)


def fleet_smoke_variant(sc: FleetScenario) -> FleetScenario:
    """Seconds-sized clamp: fewer keys and VCs, same fault plan (the
    plan IS the scenario's shape — slots are NOT clamped below the last
    fault window)."""
    return replace(
        sc,
        n_validators=min(sc.n_validators, FLEET_SMOKE_VALIDATORS),
        vcs_per_node=min(sc.vcs_per_node, 2),
        slots=min(sc.slots, FLEET_SMOKE_SLOTS),
        http_vcs_per_node=min(sc.http_vcs_per_node, 4),
    )


#: lazily built (netfaults imports the metrics registry; keep module
#: import as light as the CLI parser expects)
MULTINODE_SCENARIOS: dict[str, MultiNodeScenario] = {}


def _ensure_multinode() -> dict[str, MultiNodeScenario]:
    if not MULTINODE_SCENARIOS:
        MULTINODE_SCENARIOS.update(_multinode_scenarios())
    return MULTINODE_SCENARIOS


def is_multinode(name: str) -> bool:
    return name in _ensure_multinode()


def get_multinode_scenario(name: str, **overrides) -> MultiNodeScenario:
    base = _ensure_multinode().get(name)
    if base is None:
        raise KeyError(f"unknown multi-node scenario {name!r}")
    overrides = {k: v for k, v in overrides.items() if v is not None}
    return replace(base, **overrides) if overrides else replace(base)


def multinode_smoke_variant(sc: MultiNodeScenario) -> MultiNodeScenario:
    """Multi-node scenarios are CPU-sized by construction; `--smoke` only
    clamps an operator override back into the seconds range without
    changing the fault plan (the plan IS the scenario's shape)."""
    return replace(
        sc,
        n_validators=min(sc.n_validators, 64),
        slots=min(sc.slots, 16),
    )


# -------------------------------------------------------------- mixed duty


@dataclass
class MixedDutyScenario:
    """One device, many tenants (loadgen/mixed_duty.py): BLS attestation
    batches, tree-hash state-root jobs, and epoch-vector work all drive
    ONE per-chip device ledger through the process-wide device-occupancy
    ledger (observability/device_ledger.py). The run is the measurement
    substrate for the ROADMAP's "one device, many tenants" arbiter: it
    fails unless per-chip ledger conservation (busy + idle +
    contention-wait = wall) holds exactly, every workload's SLO block
    lands in the report, and the injected mid-run stall produces at
    least one schema-valid `device_contention` incident naming victim
    and occupant. The deterministic core is bit-identical across reruns
    — this run IS the workloads-isolated baseline the arbiter item's
    acceptance clause compares against."""

    name: str
    n_validators: int = 8192
    slots: int = 12
    seed: int = 0x7E9A27
    #: chip universe of the logical device (the meshsim shape)
    n_chips: int = 4
    #: BLS demand scale over mainnet_mix's seeded draw
    demand_factor: float = 1.0
    #: tree-hash tenant: state-root jobs per slot, leaves per job
    roots_per_slot: int = 6
    root_leaves: int = 4096
    #: epoch tenant: cadence (every k-th slot) and batches per firing
    epoch_every: int = 8
    epoch_batches: int = 2
    #: logical device cost model per tenant: a batch of n units pays
    #: base_ms + per_unit_ms * pow2ceil(n) ms (the padding-bucket
    #: economics shared with the capacity ledger); BLS shards across
    #: every chip, state-root jobs pin one chip round-robin
    bls_base_ms: float = 25.0
    bls_per_set_ms: float = 0.65
    hash_base_ms: float = 8.0
    hash_per_leaf_ms: float = 0.004
    epoch_base_ms: float = 60.0
    epoch_per_val_ms: float = 0.012
    seconds_per_slot: int = 1
    #: traffic-free drain slots before the final force-drain
    epilogue_slots: int = 2
    #: injected mid-run stall: over [start, end) slots BLS batches serve
    #: stall_factor x slower (a wedged collective holding the device),
    #: so the other tenants' admitted work queues behind the occupant —
    #: the contention episode the incident trigger must catch and name
    stall_slots: tuple = (5, 7)
    stall_factor: float = 8.0
    #: accountant device_contention trigger threshold (logical seconds
    #: of cross-tenant contention accrued per slot)
    contention_threshold: float = 0.25


MIXED_DUTY_SCENARIOS: dict[str, MixedDutyScenario] = {
    # steady mainnet-shaped BLS + 6 state-roots/slot + epoch vectors on
    # the epoch boundary, with a mid-run 8x BLS stall: the three tenants
    # genuinely contend for the 4-chip ledger around the stall window
    "mixed_duty": MixedDutyScenario(name="mixed_duty"),
}


def is_mixed_duty(name: str) -> bool:
    return name in MIXED_DUTY_SCENARIOS


def get_mixed_duty_scenario(name: str, **overrides) -> MixedDutyScenario:
    base = MIXED_DUTY_SCENARIOS.get(name)
    if base is None:
        raise KeyError(f"unknown mixed-duty scenario {name!r}")
    overrides = {k: v for k, v in overrides.items() if v is not None}
    return replace(base, **overrides) if overrides else replace(base)


def mixed_duty_smoke_variant(sc: MixedDutyScenario) -> MixedDutyScenario:
    """Seconds-sized clamp preserving the contention physics: shrinking
    the validator count scales the BLS per-set cost up by the same ratio
    (the capacity_smoke_variant rule), and the stall window slides inside
    the clamped run so the contention episode is never cut."""
    n_small = min(sc.n_validators, 4096)
    out = replace(
        sc,
        n_validators=n_small,
        bls_per_set_ms=sc.bls_per_set_ms * (sc.n_validators / n_small),
        slots=min(sc.slots, 10),
        epilogue_slots=min(sc.epilogue_slots, 2),
        roots_per_slot=min(sc.roots_per_slot, 4),
    )
    s0, s1 = out.stall_slots
    width = max(1, min(s1 - s0, out.slots - 3))
    s0 = max(1, min(s0, out.slots - width - 1))
    return replace(out, stall_slots=(s0, s0 + width))
