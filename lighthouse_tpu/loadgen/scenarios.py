"""Scenario definitions: mainnet-shaped traffic mixes, seeded + deterministic.

Mainnet shape (the ratios, not the absolute scale): every active validator
attests exactly once per epoch, so a subscribed-to-everything node sees
roughly `n_validators / 32` single-bit attestations per slot; each of the
up-to-64 committees elects ~16 aggregators, so aggregates arrive at
`committees * 16` per slot; and there is one block per slot. The generator
jitters each count ±10% from the scenario seed so queues see realistic
unevenness while staying bit-reproducible.

`stale_fraction` mixes in attestations stamped with a slot older than the
propagation window — replayed/late gossip whose deadline has already
passed, which MUST be shed `expired` at pop, never verified.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, replace

SLOTS_PER_EPOCH = 32          # mainnet shape
AGGREGATORS_PER_COMMITTEE = 16
MAX_COMMITTEES_PER_SLOT = 64


@dataclass(frozen=True)
class SlotTraffic:
    attestations: int
    aggregates: int
    blocks: int
    stale_attestations: int = 0


@dataclass
class Scenario:
    name: str
    n_validators: int = 16384
    slots: int = 8
    seed: int = 0xC0FFEE
    # open-loop multiplier over the mainnet-shaped per-slot counts
    flood_factor: float = 1.0
    # fraction of attestations stamped past the propagation window
    stale_fraction: float = 0.0
    # fault injections: "device_stall" stalls the device backend over
    # stall_slots; "slow_host" adds per-batch host latency; "storage_crash"
    # tears the durable head write at crash_slot and kills the node, then
    # the runner restarts it from the same datadir (crash_restart scenario)
    faults: tuple = ()
    stall_slots: tuple = (2, 4)      # [start, end) in scenario slots
    crash_slot: int | None = None    # storage_crash: slot whose head write tears
    # queue bounds for the attestation/aggregate queues (None = processor
    # defaults); flood scenarios shrink them so shedding is observable in
    # a few seconds instead of at mainnet scale
    att_queue_cap: int | None = None
    agg_queue_cap: int | None = None
    seconds_per_slot: float = 1.0    # logical (manual-clock) seconds


def mainnet_mix(n_validators: int, rng: random.Random) -> SlotTraffic:
    atts = max(1, n_validators // SLOTS_PER_EPOCH)
    committees = max(1, min(MAX_COMMITTEES_PER_SLOT, atts // 128))
    aggs = committees * AGGREGATORS_PER_COMMITTEE

    def jitter(n: int) -> int:
        return max(1, int(n * (0.9 + 0.2 * rng.random())))

    return SlotTraffic(jitter(atts), jitter(aggs), 1)


def traffic_schedule(sc: Scenario) -> list[SlotTraffic]:
    """Per-slot traffic for the whole scenario — pure function of the
    scenario (seeded RNG), so a report is reproducible from (name, seed)."""
    rng = random.Random(sc.seed)
    out = []
    for _slot in range(sc.slots):
        base = mainnet_mix(sc.n_validators, rng)
        atts = int(base.attestations * sc.flood_factor)
        stale = int(atts * sc.stale_fraction)
        out.append(
            SlotTraffic(
                attestations=atts - stale,
                aggregates=int(base.aggregates * sc.flood_factor),
                blocks=base.blocks,
                stale_attestations=stale,
            )
        )
    return out


SCENARIOS: dict[str, Scenario] = {
    # ~5 s CPU-only sanity pass: modest traffic, every code path exercised
    # (flood over the shrunk queue caps -> oldest-first sheds; stale mix ->
    # expiry; device stall mid-run -> full breaker cycle)
    "smoke": Scenario(
        name="smoke", n_validators=4096, slots=6, flood_factor=3.0,
        stale_fraction=0.1, faults=("device_stall",), stall_slots=(2, 4),
        att_queue_cap=256, agg_queue_cap=64,
    ),
    # steady mainnet-shaped mix, no faults — the control run
    "steady": Scenario(
        name="steady", n_validators=16384, slots=8,
    ),
    # 4x open-loop flood over deliberately small queues: oldest-first
    # shedding + admission refusals under pressure
    "flood": Scenario(
        name="flood", n_validators=16384, slots=8, flood_factor=4.0,
        stale_fraction=0.05, att_queue_cap=512, agg_queue_cap=128,
    ),
    # device stalls mid-run while the flood continues: the circuit breaker
    # must open, the host path serve, and the breaker close after recovery
    "device_stall": Scenario(
        name="device_stall", n_validators=16384, slots=10, flood_factor=2.0,
        faults=("device_stall",), stall_slots=(3, 6),
        att_queue_cap=1024, agg_queue_cap=256,
    ),
    # slow host verification under flood: queues stay hot, deadlines bite
    "slow_host": Scenario(
        name="slow_host", n_validators=8192, slots=8, flood_factor=2.0,
        faults=("slow_host",), stale_fraction=0.1,
        att_queue_cap=512, agg_queue_cap=128,
    ),
    # crash recovery proof: mainnet-shaped load over a DURABLE store whose
    # head write tears mid-record at crash_slot (the node "dies"); the
    # runner restarts from the same datadir, asserts the recovered head is
    # the last durably persisted one, and finishes the run — conservation
    # extends to published == processed + dropped + expired + lost_to_crash
    "crash_restart": Scenario(
        name="crash_restart", n_validators=4096, slots=8, flood_factor=2.0,
        stale_fraction=0.1, faults=("storage_crash",), crash_slot=4,
        att_queue_cap=256, agg_queue_cap=64,
    ),
}


def smoke_variant(sc: Scenario) -> Scenario:
    """Any scenario shrunk to smoke scale (CPU-only, seconds) without
    changing its SHAPE: same faults, same mix, clamped size. This is what
    `--smoke` combined with an explicit `--scenario` runs."""
    out = replace(
        sc,
        n_validators=min(sc.n_validators, 4096),
        slots=min(sc.slots, 8),
    )
    if out.crash_slot is not None:
        out.crash_slot = max(1, min(out.crash_slot, out.slots - 2))
    s0, s1 = out.stall_slots
    out.stall_slots = (min(s0, max(0, out.slots - 2)), min(s1, out.slots))
    return out


def get_scenario(name: str, **overrides) -> Scenario:
    """A named scenario, optionally with field overrides (CLI flags)."""
    base = SCENARIOS.get(name)
    if base is None:
        raise KeyError(
            f"unknown scenario {name!r} (have: {', '.join(sorted(SCENARIOS))})"
        )
    overrides = {k: v for k, v in overrides.items() if v is not None}
    return replace(base, **overrides) if overrides else replace(base)
