"""Mesh-aware device simulation: the multichip harness behind the
`bn loadtest --mesh-devices` sweep and the `mesh_stall` scenario.

`MeshShardedBackend` stands in for the device leg of an N-chip mesh with
the COLLECTIVE cost semantics the real sharded pipeline has
(crypto/jaxbls/backend.py + parallel/mesh.py):

  - a batch of n sets shards over the set axis: each chip serves
    ceil(n / D) sets, so the batch's device time is
    `base_ms + per_set_ms * ceil(n / D)` — near-linear sets/s scaling
    1 -> D is the shape the sweep asserts;
  - the cross-set reductions are collectives: EVERY chip must arrive, so
    one stalled chip stalls the WHOLE batch (`stall_chip(i)` — the
    mesh_stall scenario's fault). A stalled batch waits a bounded
    `wait_secs` then raises DeviceStallError, exactly the signal the
    breaker/hybrid router sees from a wedged chip;
  - the urgent lane is PINNED SINGLE-CHIP (the jaxbls contract): urgent
    submissions cost the full single-chip time and only stall when chip 0
    (the pinned one) is stalled.

Every submission rides a REAL `PipelinedDispatcher`
(crypto/jaxbls/pipeline.py — jax-free at import), so the loadgen mesh
scenarios drive the production FIFO window, urgent bypass and
jaxbls_pipeline_* accounting end to end; the simulated part is only the
per-chip cost model. The chip count resolves against the REAL mesh layer
(`parallel.get_mesh()` under the forced-host-device harness,
XLA_FLAGS=--xla_force_host_platform_device_count=8) unless pinned
explicitly, so mesh bring-up, axis gauges and flight-recorder events are
the production ones.

Wall-clock observations (sets/s, p50) are kept OUT of the deterministic
report core — they land in the report's `mesh` block and, via the
--mesh-devices sweep, in BENCH_MATRIX rows tagged `source: loadtest`
(observability/perf.write_loadtest_rows).
"""

from __future__ import annotations

import math
import threading
import time

from ..observability.device_ledger import LEDGER
from ..utils.metrics import REGISTRY
from .faults import DeviceStallError

# mesh_* series are labeled families (scripts/lint_metrics.py enforces
# it): per-chip breakdowns are the whole point of the harness
_CHIP_BUSY = REGISTRY.counter_vec(
    "mesh_chip_busy_seconds_total",
    "simulated per-chip compute seconds served by the mesh harness",
    ("chip",),
)
_CHIP_STALLS = REGISTRY.counter_vec(
    "mesh_chip_stalls_total",
    "batches that hit a stalled chip's shard at the collective barrier, "
    "by the chip that stalled them",
    ("chip",),
)
_COLLECTIVE_WAIT = REGISTRY.histogram_vec(
    "mesh_collective_wait_seconds",
    "simulated wait at the collective barrier, by outcome (arrived = all "
    "chips on time, stalled = a chip never arrived within the budget)",
    ("outcome",),
    buckets=(0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
)


def resolve_mesh_devices(explicit: int | None = None) -> int:
    """Chip count for a mesh scenario: explicit override (the sweep's
    points) > the REAL resolved mesh's total device count > 1. Resolving
    through parallel.get_mesh() is deliberate — it exercises production
    mesh bring-up (env seams, axis gauges, flight-recorder event) under
    the forced-host-device harness."""
    if explicit is not None:
        return max(1, int(explicit))
    try:
        from ..parallel import get_mesh

        mesh = get_mesh()
        return int(mesh.devices.size) if mesh is not None else 1
    except Exception:
        return 1


class MeshShardedBackend:
    """Scriptable N-chip device stand-in with collective semantics."""

    name = "loadgen_mesh"

    def __init__(self, n_devices: int, *, base_ms: float = 0.5,
                 per_set_ms: float = 0.02, wait_secs: float = 0.02,
                 verdict: bool = True):
        self.n_devices = max(1, int(n_devices))
        self.base_secs = base_ms / 1e3
        self.per_set_secs = per_set_ms / 1e3
        self.wait_secs = wait_secs
        self.verdict = verdict
        self.calls = 0
        self.stall_hits = 0
        LEDGER.register("meshsim", dispatcher=self)
        # simulated compute seconds per chip (the occupancy ledger the
        # report's mesh block summarizes)
        self.chip_busy = [0.0] * self.n_devices
        self._stalled: set = set()
        self._released = threading.Event()
        self._released.set()
        self._lock = threading.Lock()

    # ------------------------------------------------------------ faults

    def stall_chip(self, chip: int) -> None:
        """Stall one chip's shard: every sharded batch (and urgent work
        when chip 0 is hit) now blocks at the collective barrier."""
        with self._lock:
            self._stalled.add(int(chip))
        self._released.clear()
        try:
            from ..observability.flight_recorder import RECORDER

            RECORDER.record("mesh_chip_stall", severity="warn",
                            chip=int(chip), devices=self.n_devices)
        except Exception:
            pass

    def release_chip(self, chip: int | None = None) -> None:
        """Heal one chip (or all with None)."""
        with self._lock:
            if chip is None:
                self._stalled.clear()
            else:
                self._stalled.discard(int(chip))
            clear = not self._stalled
        if clear:
            self._released.set()
        try:
            from ..observability.flight_recorder import RECORDER

            RECORDER.record("mesh_chip_release",
                            chip=-1 if chip is None else int(chip))
        except Exception:
            pass

    def release(self) -> None:
        """StallingBackend-compatible blanket heal (the runner's epilogue
        releases whatever is still armed)."""
        self.release_chip(None)

    @property
    def stalled_chips(self) -> tuple:
        with self._lock:
            return tuple(sorted(self._stalled))

    @property
    def stalled(self) -> bool:
        """Any chip stalled (the StallingBackend-compatible flag the
        runner's route accounting reads)."""
        with self._lock:
            return bool(self._stalled)

    # ------------------------------------------------------------- serve

    def _serve(self, n_sets: int, single_chip: bool) -> bool:
        with self._lock:
            self.calls += 1
            stalled = set(self._stalled)
        d = 1 if single_chip else self.n_devices
        share = max(1, math.ceil(max(1, n_sets) / d))
        compute = self.base_secs + self.per_set_secs * share
        # book the serve into the process-wide device ledger: the mesh
        # harness is a tenant ("meshsim") like any other, so sweeps show
        # up on the merged device timeline and in contention attribution
        iv = LEDGER.open(
            "meshsim", lane="urgent" if single_chip else "batch",
            bucket=share, est_cost=compute,
            chips=(0,) if single_chip else None,
        ).start()
        try:
            return self._serve_booked(single_chip, stalled, compute)
        except DeviceStallError:
            iv.close("stalled")
            raise
        finally:
            iv.close("ok")        # no-op when the stall path closed it

    def _serve_booked(self, single_chip, stalled, compute) -> bool:
        time.sleep(compute)
        chips = (0,) if single_chip else tuple(range(self.n_devices))
        with self._lock:
            # the busy ledger is read by occupancy() and written from
            # concurrent worker threads (urgent vs batch verifies): the
            # read-modify-write must not lose increments
            for c in chips:
                self.chip_busy[c] += compute
        for c in chips:
            _CHIP_BUSY.labels(c).inc(compute)
        # the collective barrier: a stalled chip in this batch's shard set
        # means the reduction never completes within the stall budget
        blocking = sorted(stalled.intersection(chips))
        if blocking:
            t0 = time.perf_counter()
            if not self._released.wait(self.wait_secs):
                _COLLECTIVE_WAIT.labels("stalled").observe(
                    time.perf_counter() - t0
                )
                with self._lock:
                    self.stall_hits += 1
                for c in blocking:
                    _CHIP_STALLS.labels(c).inc()
                raise DeviceStallError(
                    f"mesh collective stalled on chip(s) {blocking} past "
                    f"{self.wait_secs}s wait"
                )
        _COLLECTIVE_WAIT.labels("arrived").observe(0.0)
        return self.verdict

    def verify_signature_sets(self, sets, rands) -> bool:
        return self._serve(len(sets), single_chip=False)

    def verify_signature_sets_urgent(self, sets, rands) -> bool:
        # the urgent lane is pinned to chip 0 (jaxbls contract): it pays
        # single-chip compute and only chip 0's stall can block it
        return self._serve(len(sets), single_chip=True)

    def verify_signature_sets_async(self, sets, rands):
        outer = self
        n = len(sets)

        class _Handle:
            def result(self) -> bool:
                return outer._serve(n, single_chip=False)

        return _Handle()

    def occupancy(self) -> dict:
        """Per-chip busy seconds + the busy-balance summary for reports."""
        with self._lock:
            busy = [round(b, 6) for b in self.chip_busy]
        peak = max(busy) if busy else 0.0
        return {
            "devices": self.n_devices,
            "chip_busy_secs": busy,
            "busy_balance": (
                round(min(busy) / peak, 4) if peak > 0 else None
            ),
            "stall_hits": self.stall_hits,
            "stalled_chips": list(self.stalled_chips),
        }
