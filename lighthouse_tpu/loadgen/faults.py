"""Fault injection: device stall, slow host verify, scheduled triggers.

`StallingBackend` stands in for the device leg of the hybrid router: it
verifies instantly (fake-crypto semantics — loadgen measures the QoS
machinery, not pairings) until `stall()` is called, after which every
verify blocks for a bounded `wait_secs` and then raises `DeviceStallError`
— the shape of a wedged remote-TPU tunnel as seen by a caller with a
timeout. Async handles block in `result()` the same way, so the processor's
in-flight resolution path is exercised too. `release()` restores instant
service.

`FaultInjector` is the slot-driven trigger board: the runner registers
actions at scenario slots and calls `on_slot` as the manual clock advances,
keeping every fault deterministic.
"""

from __future__ import annotations

import threading
import time


class DeviceStallError(RuntimeError):
    """A stalled device verify gave up after its bounded wait."""


class StallingBackend:
    """Scriptable device stand-in: instant verifies, stallable on demand."""

    name = "loadgen_stall"

    def __init__(self, verdict: bool = True, wait_secs: float = 0.02):
        self.verdict = verdict
        self.wait_secs = wait_secs
        self.calls = 0
        self.stall_hits = 0
        self._released = threading.Event()
        self._released.set()
        self._lock = threading.Lock()

    @property
    def stalled(self) -> bool:
        return not self._released.is_set()

    def stall(self) -> None:
        self._released.clear()

    def release(self) -> None:
        self._released.set()

    def _serve(self) -> bool:
        with self._lock:
            self.calls += 1
        if not self._released.wait(self.wait_secs):
            with self._lock:
                self.stall_hits += 1
            raise DeviceStallError(
                f"device stalled past {self.wait_secs}s wait"
            )
        return self.verdict

    def verify_signature_sets(self, sets, rands) -> bool:
        return self._serve()

    def verify_signature_sets_async(self, sets, rands):
        outer = self

        class _Handle:
            def result(self) -> bool:
                return outer._serve()

        return _Handle()


class SlowHostVerify:
    """Host-path fault: a fixed per-batch delay (GIL-released sleep), the
    shape of a host CPU saturated by competing verification work."""

    def __init__(self, delay_secs: float = 0.005):
        self.delay_secs = delay_secs
        self.calls = 0

    def __call__(self, n_sets: int) -> bool:
        self.calls += 1
        time.sleep(self.delay_secs)
        return True


class FaultInjector:
    """Deterministic slot-triggered actions. Register with `at(slot, fn)`;
    the runner calls `on_slot(slot)` once per simulated slot and every
    not-yet-fired action scheduled at or before it runs, in slot order."""

    def __init__(self):
        # per-entry fired flag (NOT index-keyed: registering a new action
        # after some have fired must not remap what already ran)
        self._actions: list[list] = []   # [slot, fn, fired]

    def at(self, slot: int, fn) -> "FaultInjector":
        self._actions.append([int(slot), fn, False])
        self._actions.sort(key=lambda x: x[0])
        return self

    def on_slot(self, slot: int) -> int:
        fired = 0
        for entry in self._actions:
            at_slot, fn, done = entry
            if done or at_slot > slot:
                continue
            entry[2] = True
            fn()
            fired += 1
        return fired
