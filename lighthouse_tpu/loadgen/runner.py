"""Scenario runner: synthetic gossip through the real QoS-protected path.

One `LoadgenNode` is the serving path of a beacon node with the chain
swapped for counters: an `InProcessGossipRouter` delivers synthetic
attestation/aggregate/block messages (snappy-compressed, deduped by real
message ids) into topic handlers that submit `WorkItem`s to a real
`BeaconProcessor` guarded by a real `AdmissionController` — the exact
submit/coalesce/shed/expire machinery gossip exercises in production. The
verification leg is a `StallingBackend` device behind a `CircuitBreaker`
with an instant host fallback, so device-stall scenarios drive the
closed→open→half_open cycle exactly as the hybrid BLS router would.

Time is a `ManualSlotClock` advanced slot by slot; the breaker reads the
same logical clock. Within a slot the generator is open-loop (everything
publishes whether or not the pipeline keeps up), then the pump drains, so
every count in the report is a pure function of (scenario, seed).

Service-level accounting: each run drives a PRIVATE SlotAccountant
(observability/slo.py — the global one belongs to the node) whose slot
reports close after every drained slot, so the report's `slo` block shows
the per-slot deadline-hit ratio degrading through a device stall and
recovering after. The global flight recorder is reset per run and pointed
at `<datadir>/incidents`: the breaker opening (or a burn-rate/miss-streak
trigger) dumps a real incident snapshot, which the report lists and
`bn debug-bundle --datadir` packages.
"""

from __future__ import annotations

import json
import os
import random
import tempfile
import time

from ..chain.beacon_processor import (
    BeaconProcessor,
    BeaconProcessorConfig,
    WorkItem,
    WorkKind,
)
from ..network import gossip as gs
from ..network import snappy
from ..observability.flight_recorder import RECORDER
from ..observability.slo import SlotAccountant
from ..qos.admission import AdmissionController
from ..qos.breaker import CircuitBreaker
from ..utils.slot_clock import ManualSlotClock
from .faults import DeviceStallError, FaultInjector, SlowHostVerify, StallingBackend
from .scenarios import Scenario, traffic_schedule

# stale gossip is stamped this many slots in the past: past the propagation
# window (32), so its deadline has already expired on arrival
STALE_AGE_SLOTS = 40

_FORK_DIGEST = b"\x00" * 4


class LoadgenNode:
    """Router topics -> QoS-guarded BeaconProcessor -> counting verifiers."""

    def __init__(self, sc: Scenario, clock: ManualSlotClock, store=None,
                 slo_acct: SlotAccountant | None = None):
        self.scenario = sc
        self.clock = clock
        # optional durable store: the block handler persists the head slot
        # through it (a loadgen-scale BeaconChain.persist()), so storage
        # faults injected there crash the node exactly where a real one
        # would crash — inside its durable-write path
        self.store = store
        self.admission = AdmissionController(clock)
        self.processor = BeaconProcessor(
            BeaconProcessorConfig(), admission=self.admission
        )
        # private per-run accountant (export_metrics=False keeps the
        # process-global slo_* gauges owned by the node's accountant);
        # crash_restart passes ONE accountant across both node phases
        self.slo = slo_acct if slo_acct is not None else SlotAccountant(
            export_metrics=False
        )
        self.slo.bind_clock(clock)
        self.processor.slo = self.slo
        if sc.att_queue_cap is not None:
            self.processor.max_lengths[WorkKind.gossip_attestation] = (
                sc.att_queue_cap
            )
        if sc.agg_queue_cap is not None:
            self.processor.max_lengths[WorkKind.gossip_aggregate] = (
                sc.agg_queue_cap
            )
        if sc.mesh:
            # mesh scenario: an N-chip device sim with collective
            # semantics (one stalled chip stalls the whole batch) behind
            # a REAL PipelinedDispatcher — chip count resolves against
            # parallel.get_mesh() unless the sweep pins it
            from ..crypto.jaxbls.pipeline import PipelinedDispatcher
            from .meshsim import MeshShardedBackend, resolve_mesh_devices

            self.mesh_devices = resolve_mesh_devices(sc.mesh_devices)
            self.device = MeshShardedBackend(self.mesh_devices)
            self.dispatcher = PipelinedDispatcher(workload="meshsim")
        else:
            self.mesh_devices = None
            self.device = StallingBackend()
            self.dispatcher = None
        # breaker on the scenario's logical clock: one-slot cooldown, so
        # recovery is observable within the run
        self.breaker = CircuitBreaker(
            "loadgen_device", failure_threshold=3,
            reset_timeout=float(sc.seconds_per_slot), time_fn=clock._time,
            workload="meshsim",
        )
        # wall-clock verify observations for mesh runs (device-served
        # batches only): the sweep's sets/s + p50 numbers — kept OUT of
        # the deterministic report core
        self.batch_verify_obs: list = []  # (n_sets, secs)
        self.slow_host = (
            SlowHostVerify() if "slow_host" in sc.faults else None
        )
        self.router = gs.InProcessGossipRouter()
        self.att_topic = gs.attestation_subnet_topic(_FORK_DIGEST, 0)
        self.agg_topic = gs.topic_name(_FORK_DIGEST, "beacon_aggregate_and_proof")
        self.block_topic = gs.topic_name(_FORK_DIGEST, "beacon_block")
        self.router.subscribe("node", self.att_topic, self._on_att)
        self.router.subscribe("node", self.agg_topic, self._on_agg)
        self.router.subscribe("node", self.block_topic, self._on_block)
        self._seq = 0
        self.published = {"attestations": 0, "aggregates": 0, "blocks": 0,
                          "stale_attestations": 0}
        self.verified_sets = 0
        self.batches = {"device": 0, "host": 0, "device_stalls": 0,
                        "circuit_refusals": 0}
        self.block_slot_lag: list[int] = []
        self.shed_callbacks = 0

    # --------------------------------------------------------- payloads

    def _payload(self, slot: int, rng: random.Random) -> bytes:
        """Unique synthetic message: stamped slot + sequence + seeded noise
        (the router dedups by real message id; every payload must differ)."""
        self._seq += 1
        return (
            # signed: stale stamps near genesis go negative (slot - 40)
            int(slot).to_bytes(8, "little", signed=True)
            + self._seq.to_bytes(8, "little")
            + rng.getrandbits(128).to_bytes(16, "little")
        )

    @staticmethod
    def _stamped_slot(msg) -> int:
        return int.from_bytes(
            snappy.decompress(msg.payload)[:8], "little", signed=True
        )

    # --------------------------------------------------------- handlers

    def _on_shed(self, _reason: str) -> None:
        self.shed_callbacks += 1

    def _on_att(self, msg) -> bool:
        slot = self._stamped_slot(msg)
        return self.processor.submit(WorkItem(
            kind=WorkKind.gossip_attestation,
            payload=slot,
            run_batch=self._run_verify_batch,
            deadline_slot=self.admission.attestation_deadline_slot(slot),
            on_shed=self._on_shed,
        ))

    def _on_agg(self, msg) -> bool:
        slot = self._stamped_slot(msg)
        return self.processor.submit(WorkItem(
            kind=WorkKind.gossip_aggregate,
            payload=slot,
            run_batch=self._run_verify_batch,
            deadline_slot=self.admission.attestation_deadline_slot(slot),
            on_shed=self._on_shed,
        ))

    def _on_block(self, msg) -> bool:
        slot = self._stamped_slot(msg)

        def run():
            # blocks verify on the host path unconditionally (the hybrid
            # urgent path); what matters here is WHEN they run. Mesh runs
            # additionally push the proposer check through the REAL
            # dispatcher's urgent BYPASS lane, pinned to chip 0 — the
            # mesh_stall scenario (chip 1 wedged) proves the urgent path
            # keeps serving while every sharded batch stalls
            now = self.clock.now() or 0
            self.block_slot_lag.append(now - slot)
            if self.dispatcher is not None:
                from ..crypto.bls.api import _ReadyHandle

                try:
                    # pre-resolved handle (the bypass lane resolves
                    # in-band; crypto/bls/api owns the handle contract)
                    self.dispatcher.submit(
                        lambda: _ReadyHandle(
                            self.device.verify_signature_sets_urgent(
                                [None], [1]
                            )
                        ),
                        urgent=True,
                    ).result()
                    self.batches["urgent"] = self.batches.get("urgent", 0) + 1
                except Exception:
                    # a stalled chip 0 fails the urgent verify; the block
                    # still imports (host fallback semantics) — count it
                    self.batches["urgent_stalled"] = (
                        self.batches.get("urgent_stalled", 0) + 1
                    )
            if self.store is not None:
                # the durable head record (BeaconChain.persist() at loadgen
                # scale): one CRC-framed fsynced append per imported block —
                # a SimulatedCrash raised here kills the whole node run
                from ..store.kv import Column

                self.store.put(
                    Column.beacon_chain, b"head-slot",
                    int(slot).to_bytes(8, "little", signed=True),
                )

        return self.processor.submit(
            WorkItem(kind=WorkKind.gossip_block, run=run)
        )

    def _run_verify_batch(self, payloads) -> None:
        """Coalesced batch verifier: device behind the breaker, host
        fallback — the hybrid router's routing shape with counters for
        crypto (fake semantics; loadgen measures QoS, not pairings)."""
        n = len(payloads)
        self.verified_sets += n
        t0 = time.perf_counter()
        if self.breaker.allow():
            try:
                if self.dispatcher is not None:
                    # mesh lane: the REAL pipelined dispatcher owns the
                    # submission (FIFO window + jaxbls_pipeline_* series);
                    # resolution stays in-band so reports remain
                    # deterministic functions of (scenario, seed)
                    self.dispatcher.submit(
                        lambda: self.device.verify_signature_sets_async(
                            [None] * n, [1] * n
                        )
                    ).result()
                else:
                    self.device.verify_signature_sets([None] * n, [1] * n)
                dt = time.perf_counter() - t0
                self.breaker.record_success()
                self.batches["device"] += 1
                self.batch_verify_obs.append((n, dt))
                self.slo.record_route("device", n)
                self.slo.record_verify_latency(dt)
                RECORDER.note_route("loadgen_device", "device", "ok")
                return None
            except DeviceStallError:
                self.breaker.record_failure()
                self.batches["device_stalls"] += 1
                # the host serves the batch below, but it already blew the
                # device stall budget: these items verified LATE — counted
                # processed for conservation, deadline MISSES for the SLI
                self.slo.record_late(n)
        else:
            self.batches["circuit_refusals"] += 1
        if self.slow_host is not None:
            self.slow_host(n)
        self.batches["host"] += 1
        self.slo.record_route("host", n)
        self.slo.record_verify_latency(time.perf_counter() - t0)
        RECORDER.note_route(
            "loadgen_device", "host",
            "device_stall" if self.device.stalled else "circuit_open",
        )
        return None

    # --------------------------------------------------------- publishing

    def publish_slot(self, slot: int, traffic, rng: random.Random) -> None:
        for _ in range(traffic.attestations):
            self.router.publish(
                "loadgen", self.att_topic, self._payload(slot, rng)
            )
        self.published["attestations"] += traffic.attestations
        stale_slot = slot - STALE_AGE_SLOTS
        for _ in range(traffic.stale_attestations):
            self.router.publish(
                "loadgen", self.att_topic, self._payload(stale_slot, rng)
            )
        self.published["stale_attestations"] += traffic.stale_attestations
        for _ in range(traffic.aggregates):
            self.router.publish(
                "loadgen", self.agg_topic, self._payload(slot, rng)
            )
        self.published["aggregates"] += traffic.aggregates
        for _ in range(traffic.blocks):
            self.router.publish(
                "loadgen", self.block_topic, self._payload(slot, rng)
            )
        self.published["blocks"] += traffic.blocks


def _prepare_recorder(datadir: str | None, clock, slo_acct) -> str:
    """Reset the global flight recorder for a deterministic run and point
    it at this run's incident directory; returns that directory."""
    datadir = datadir or tempfile.mkdtemp(prefix="loadgen-")
    incident_dir = os.path.join(datadir, "incidents")
    RECORDER.reset()
    RECORDER.configure(incident_dir=incident_dir, clock=clock,
                       slo_provider=slo_acct.snapshot)
    return incident_dir


def _slo_block(slo_acct: SlotAccountant, incident_dir: str) -> dict:
    """The report's service-level block: per-slot deadline-hit ratios, the
    rolling windows, and the incidents the run dumped."""
    reports = [r for r in slo_acct.recent if not r.empty]
    hits = sum(r.hits for r in reports)
    misses = sum(r.misses for r in reports)
    total = hits + misses
    return {
        "target": slo_acct.target,
        "deadline_hits": hits,
        "deadline_misses": misses,
        "deadline_hit_ratio": round(hits / total, 4) if total else None,
        "per_slot": [
            {
                "slot": r.slot,
                "deadline_hit_ratio": (
                    None if r.hit_ratio() is None else round(r.hit_ratio(), 4)
                ),
                "hits": r.hits,
                "misses": r.misses,
                "late": r.late,
                "routes": r.routes,
            }
            for r in reports
        ],
        "windows": {
            name: slo_acct.window_summary(name) for name in slo_acct.windows
        },
        "incident_dir": incident_dir,
        "incidents": [
            os.path.basename(p) for p in RECORDER.incidents_written
        ],
        "flight_recorder_events": RECORDER.events_recorded,
    }


def _verify_obs_block(node: LoadgenNode) -> dict:
    """Wall-clock verify observations (EVERY run): sets/s + p50 over the
    device-served batches — what `bn loadtest --bench-matrix` and the
    --mesh-devices sweep snapshot into BENCH_MATRIX rows. Deliberately
    OUTSIDE the deterministic report core — these are measurements, not
    seed functions."""
    obs = node.batch_verify_obs
    total_sets = sum(n for n, _ in obs)
    total_secs = sum(s for _, s in obs)
    lats = sorted(s for _, s in obs)
    p50 = lats[len(lats) // 2] if lats else None
    return {
        "device_batches": len(obs),
        "sets_per_sec": (
            round(total_sets / total_secs, 2) if total_secs > 0 else None
        ),
        "verify_p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
    }


def _mesh_block(node: LoadgenNode) -> dict:
    """Mesh runs additionally report per-chip occupancy + the urgent-lane
    ledger next to the verify observations."""
    block = dict(node.device.occupancy())
    block.update(
        _verify_obs_block(node),
        urgent_served=node.batches.get("urgent", 0),
        urgent_stalled=node.batches.get("urgent_stalled", 0),
    )
    return block


def run_scenario(sc: Scenario, out_path: str | None = None,
                 log_fn=None, datadir: str | None = None) -> dict:
    """Run one scenario to completion; returns (and optionally writes) the
    machine-readable report."""
    if "storage_crash" in sc.faults:
        return run_crash_restart(sc, out_path=out_path, log_fn=log_fn,
                                 datadir=datadir)
    t_wall = time.time()
    clock = ManualSlotClock(0, max(1, int(sc.seconds_per_slot)))
    slo_acct = SlotAccountant(export_metrics=False)
    incident_dir = _prepare_recorder(datadir, clock, slo_acct)
    node = LoadgenNode(sc, clock, slo_acct=slo_acct)
    injector = FaultInjector()
    if "device_stall" in sc.faults:
        start, end = sc.stall_slots
        injector.at(start, node.device.stall)
        injector.at(end, node.device.release)
    if "mesh_stall" in sc.faults:
        start, end = sc.stall_slots
        chip = sc.mesh_stall_chip % max(1, node.mesh_devices or 1)
        injector.at(start, lambda: node.device.stall_chip(chip))
        injector.at(end, lambda: node.device.release_chip(chip))
    schedule = traffic_schedule(sc)
    rng = random.Random(sc.seed ^ 0x10AD6E4)
    for slot, traffic in enumerate(schedule):
        clock.set_slot(slot)
        injector.on_slot(slot)
        node.publish_slot(slot, traffic, rng)
        node.processor.run_until_idle()
        slo_acct.close_slot(slot)
        if log_fn is not None:
            log_fn(f"slot {slot}: published "
                   f"{traffic.attestations + traffic.stale_attestations} att "
                   f"/ {traffic.aggregates} agg / {traffic.blocks} block; "
                   f"breaker={node.breaker.state()}")
    # epilogue slot: release any still-armed faults, drain what remains
    clock.set_slot(sc.slots)
    injector.on_slot(sc.slots + max(0, sc.stall_slots[1] - sc.slots))
    node.device.release()
    node.processor.run_until_idle()
    slo_acct.close_slot(sc.slots)
    proc = node.processor
    report = {
        "scenario": sc.name,
        "seed": sc.seed,
        "slots": sc.slots,
        "n_validators": sc.n_validators,
        "flood_factor": sc.flood_factor,
        "faults": list(sc.faults),
        "published": dict(node.published),
        "processed": {k.name: v for k, v in proc.processed.items() if v},
        "dropped": {k.name: v for k, v in proc.dropped.items() if v},
        "expired": {k.name: v for k, v in proc.expired.items() if v},
        "shed_admission": {
            k.name: v for k, v in proc.shed_admission.items() if v
        },
        "qos_totals": proc.qos_totals(),
        "shed_callbacks": node.shed_callbacks,
        "verified_sets": node.verified_sets,
        "batches": dict(node.batches),
        "breaker_transitions": list(node.breaker.transitions),
        "blocks_processed_in_slot": bool(node.block_slot_lag)
        and max(node.block_slot_lag) == 0,
        "slo": _slo_block(slo_acct, incident_dir),
        "elapsed_secs": round(time.time() - t_wall, 3),
    }
    report["verify_observations"] = _verify_obs_block(node)
    if node.mesh_devices is not None:
        report["mesh"] = _mesh_block(node)
    # the deadline-hit ratio rides next to the loss accounting so one
    # glance answers both "was work conserved" and "was it in time"
    report["deadline_hit_ratio"] = report["slo"]["deadline_hit_ratio"]
    # fully detach the run's wiring: a later incident in this process
    # must not be stamped by the dead manual clock or carry this run's
    # private accountant windows
    RECORDER.configure(incident_dir=None, clock=None, slo_provider=None)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
    return report


def _merge_counts(a: dict, b: dict) -> dict:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


def run_crash_restart(sc: Scenario, out_path: str | None = None,
                      log_fn=None, datadir: str | None = None) -> dict:
    """The crash-recovery proof: mainnet-shaped load over a DURABLE store,
    a torn head write at `crash_slot` that kills the node mid-slot, then a
    restart over the same datadir that must resume from the last durably
    persisted head and finish the run.

    Phase 1 runs on a `FaultyKVStore` (fsync=always) whose fault plan
    tears the crash slot's head record mid-write; the `SimulatedCrash`
    propagates out of the processor pump — everything still queued at that
    instant is `lost_to_crash`, exactly the work a real power loss eats.
    Phase 2 reopens the path with the healthy pure-Python engine: replay
    truncates the torn record (store-level crash recovery), the recovered
    head MUST be crash_slot - 1, and the remaining slots run on a fresh
    node. The report's conservation invariant extends to
    published == processed + dropped + expired + lost_to_crash."""
    from ..store.kv import Column
    from ..store.native_kv import PurePythonKVStore
    from .storefaults import FaultPlan, FaultyKVStore, SimulatedCrash

    t_wall = time.time()
    datadir = datadir or tempfile.mkdtemp(prefix="loadgen-crash-")
    path = os.path.join(datadir, "hot.db")
    crash_slot = sc.crash_slot if sc.crash_slot is not None else sc.slots // 2
    # one head record per slot -> the crash slot's record is write #crash_slot+1;
    # keep 9 bytes (header + 1 payload byte): a torn record the CRC must catch
    store = FaultyKVStore(
        path, plan=FaultPlan(tear_at=crash_slot + 1, tear_keep_bytes=9),
        fsync="always",
    )
    clock = ManualSlotClock(0, max(1, int(sc.seconds_per_slot)))
    # ONE accountant across the crash and the restart: the scenario's
    # service level is what the OPERATOR saw, node identity aside
    slo_acct = SlotAccountant(export_metrics=False)
    incident_dir = _prepare_recorder(datadir, clock, slo_acct)
    node = LoadgenNode(sc, clock, store=store, slo_acct=slo_acct)
    schedule = traffic_schedule(sc)
    rng = random.Random(sc.seed ^ 0x10AD6E4)

    crash_msg = None
    resume_at = sc.slots
    for slot, traffic in enumerate(schedule):
        clock.set_slot(slot)
        node.publish_slot(slot, traffic, rng)
        try:
            node.processor.run_until_idle()
        except SimulatedCrash as e:
            crash_msg = str(e)
            resume_at = slot + 1   # the node is down for the rest of the slot
            RECORDER.record("node_crash", severity="error", slot=slot,
                            fault=str(e))
            if log_fn is not None:
                log_fn(f"slot {slot}: CRASH — {e}")
            break
        slo_acct.close_slot(slot)
        if log_fn is not None:
            log_fn(f"slot {slot}: published "
                   f"{traffic.attestations + traffic.stale_attestations} att "
                   f"/ {traffic.aggregates} agg / {traffic.blocks} block")
    proc1 = node.processor
    # work lost with the process: the unit being executed when the store
    # died (the block — its processed count never ticked) plus everything
    # still queued. Loadgen batches resolve synchronously, so there are no
    # in-flight device handles to account.
    lost_to_crash = 0
    if crash_msg is not None:
        lost_to_crash = 1 + sum(len(q) for q in proc1.queues.values())

    # ---- restart over the SAME datadir with the healthy engine: replay +
    # tail truncation recover the crash-consistent prefix
    store2 = PurePythonKVStore(path, fsync="always")
    raw = store2.get(Column.beacon_chain, b"head-slot")
    recovered_head = (
        int.from_bytes(raw, "little", signed=True) if raw is not None else None
    )
    expected_head = crash_slot - 1 if crash_msg is not None else sc.slots - 1
    node2 = LoadgenNode(sc, clock, store=store2, slo_acct=slo_acct)
    for slot in range(resume_at, sc.slots):
        clock.set_slot(slot)
        node2.publish_slot(slot, schedule[slot], rng)
        node2.processor.run_until_idle()
        slo_acct.close_slot(slot)
        if log_fn is not None:
            log_fn(f"slot {slot}: resumed node published "
                   f"{schedule[slot].attestations} att")
    clock.set_slot(sc.slots)
    node2.processor.run_until_idle()
    slo_acct.close_slot(sc.slots)
    store2.close()
    proc2 = node2.processor

    published = _merge_counts(node.published, node2.published)
    pub_total = sum(published.values())
    processed = _merge_counts(
        {k.name: v for k, v in proc1.processed.items() if v},
        {k.name: v for k, v in proc2.processed.items() if v},
    )
    dropped = _merge_counts(
        {k.name: v for k, v in proc1.dropped.items() if v},
        {k.name: v for k, v in proc2.dropped.items() if v},
    )
    expired = _merge_counts(
        {k.name: v for k, v in proc1.expired.items() if v},
        {k.name: v for k, v in proc2.expired.items() if v},
    )
    conservation = {
        "published": pub_total,
        "processed": sum(processed.values()),
        "dropped": sum(dropped.values()),
        "expired": sum(expired.values()),
        "lost_to_crash": lost_to_crash,
    }
    conservation["ok"] = conservation["published"] == (
        conservation["processed"] + conservation["dropped"]
        + conservation["expired"] + conservation["lost_to_crash"]
    )
    slo_block = _slo_block(slo_acct, incident_dir)
    # the deadline-hit ratio sits INSIDE the conservation block: "was work
    # conserved" and "was it in time" are the two halves of one verdict
    conservation["deadline_hit_ratio"] = slo_block["deadline_hit_ratio"]
    lag = node.block_slot_lag + node2.block_slot_lag
    report = {
        "scenario": sc.name,
        "seed": sc.seed,
        "slots": sc.slots,
        "n_validators": sc.n_validators,
        "flood_factor": sc.flood_factor,
        "faults": list(sc.faults),
        "crash": {
            "slot": crash_slot if crash_msg is not None else None,
            "fault": crash_msg,
            "datadir": datadir,
            "store_writes_at_crash": store.writes,
            "lost_to_crash": lost_to_crash,
            "recovered_head_slot": recovered_head,
            "expected_head_slot": expected_head,
            "resumed_from_persisted_head": recovered_head == expected_head,
            "resumed_at_slot": resume_at,
        },
        "published": published,
        "processed": processed,
        "dropped": dropped,
        "expired": expired,
        "conservation": conservation,
        "qos_totals": {
            "shed": proc1.qos_totals()["shed"] + proc2.qos_totals()["shed"],
            "expired": proc1.qos_totals()["expired"]
            + proc2.qos_totals()["expired"],
        },
        "shed_callbacks": node.shed_callbacks + node2.shed_callbacks,
        "verified_sets": node.verified_sets + node2.verified_sets,
        "batches": _merge_counts(node.batches, node2.batches),
        "breaker_transitions": list(node.breaker.transitions)
        + list(node2.breaker.transitions),
        "blocks_processed_in_slot": bool(lag) and max(lag) == 0,
        "slo": slo_block,
        "deadline_hit_ratio": slo_block["deadline_hit_ratio"],
        "elapsed_secs": round(time.time() - t_wall, 3),
    }
    # fully detach the run's wiring: a later incident in this process
    # must not be stamped by the dead manual clock or carry this run's
    # private accountant windows
    RECORDER.configure(incident_dir=None, clock=None, slo_provider=None)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
    return report
