"""Network fault injection: partitions, lossy links, silent peers, churn,
equivocation — the network analog of `storefaults.FaultyKVStore`.

A `NetFaultPlan` declares WHEN and HOW the network misbehaves, keyed on
logical slots so every run of a seeded scenario sees the identical fault
sequence. A `NetFaultInjector` evaluates the plan as the slot clock
advances and exposes decision surfaces the real networking layers consult:

  - `FaultyGossipSend` wraps a node's gossipsub send callback (the
    function `Gossipsub` hands encoded RPC frames to — in production the
    transport's `send_gossip`, i.e. a real TCP frame write). A frame to an
    unreachable peer (partition / churned-down node) or one eaten by a
    lossy link is dropped BEFORE the wire with a counted reason; a delayed
    link queues the frame and the injector flushes it at the next slot
    tick (slot-quantized latency, deterministic by construction).
  - `FaultyPeer` wraps any Req/Resp `handle()` surface (RpcHandler,
    transport.RemotePeer) with the plan's RPC faults: a "silent" peer
    raises the same `TransportError("request timeout")` a wedged socket
    produces (without consuming wall-clock), a "torn" peer serves half its
    response chunks then goes silent, an "empty" peer answers cleanly with
    nothing. This is what forces `SyncManager`'s retry/backoff/failover
    and `BackFillSync`'s window widening for real.
  - `gossip.InProcessGossipRouter(fault_filter=...)` takes the injector's
    `router_filter` for single-process rigs that never open a socket.

Every eaten/delayed message is counted in the labeled `netfault_*` metric
families and in the injector's deterministic per-run `counts` dict; every
partition/heal/churn transition lands as a flight-recorder event — "no
message lost without a counted reason" is the invariant the multi-node
scenarios assert.

Node identity is by INDEX into the harness's node list; `id_map` maps the
transport-level peer ids (node_id strings) back to indices.
"""

from __future__ import annotations

import socket
import struct
from dataclasses import dataclass

from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY
from .faults import FaultInjector

log = get_logger("netfaults")

NETFAULT_MESSAGES = REGISTRY.counter_vec(
    "netfault_messages_total",
    "messages the fault injector acted on, by fault kind "
    "(partition / churn / drop / delay / rpc_silent / rpc_torn / "
    "rpc_empty) and scope (gossip / rpc)",
    ("fault", "scope"),
)
NETFAULT_EVENTS = REGISTRY.counter_vec(
    "netfault_events_total",
    "fault-plan transitions fired, by kind (partition_start / "
    "partition_heal / churn_down / churn_up / equivocation)",
    ("kind",),
)
NETFAULT_HTTP = REGISTRY.counter_vec(
    "netfault_http_injections_total",
    "HTTP socket-seam fault injections against real API servers, by kind "
    "(slow_loris / body_stall / reset / storm_429)",
    ("kind",),
)


class InjectedTimeout(Exception):
    """Raised by FaultyPeer for a silent/stalled peer — duck-types the
    transport's request-timeout failure without consuming wall-clock."""


@dataclass(frozen=True)
class Partition:
    """Nodes split into isolated groups over [start_slot, heal_slot).
    Nodes not listed in any group form one implicit extra group."""

    start_slot: int
    heal_slot: int
    groups: tuple[tuple[int, ...], ...]


@dataclass(frozen=True)
class LinkFault:
    """A lossy/slow directed link over [start_slot, end_slot). `src`/`dst`
    None match any node. Deterministic by construction: `drop_every=k`
    drops every k-th frame crossing the link in its window (counter-based,
    no RNG in the hot path), `delay_slots` holds frames until that many
    slot ticks later."""

    src: int | None = None
    dst: int | None = None
    start_slot: int = 0
    end_slot: int | None = None
    drop_every: int = 0
    delay_slots: int = 0

    def active(self, slot: int) -> bool:
        return slot >= self.start_slot and (
            self.end_slot is None or slot < self.end_slot
        )

    def matches(self, src: int, dst: int) -> bool:
        return (self.src is None or self.src == src) and (
            self.dst is None or self.dst == dst
        )


@dataclass(frozen=True)
class RpcFault:
    """Node `server`'s served Req/Resp misbehaves over [start_slot,
    end_slot): "silent" = request times out (stalled peer), "torn" = half
    the response chunks then silence, "empty" = clean empty response
    (exercises BackFillSync widening / lying-peer ejection)."""

    server: int
    start_slot: int
    end_slot: int
    mode: str = "silent"            # silent | torn | empty
    protocols: tuple[str, ...] = () # empty = all protocols
    max_hits: int | None = None     # stop faulting after N requests

    def active(self, slot: int) -> bool:
        return self.start_slot <= slot < self.end_slot


@dataclass(frozen=True)
class Churn:
    """Node drops off the network at down_slot, redials at up_slot."""

    node: int
    down_slot: int
    up_slot: int


@dataclass(frozen=True)
class Equivocation:
    """The proposer of `slot` signs and publishes TWO conflicting blocks;
    honest nodes must reject the second and route both signed headers
    through the slasher."""

    slot: int


@dataclass(frozen=True)
class HttpFault:
    """Socket-seam misbehavior against a node's REAL HTTP API server over
    [start_slot, end_slot): "slow_loris" = attacker connections that send
    the request line then trickle one header byte per slot, "body_stall" =
    full headers with a large Content-Length then a stalled body,
    "reset" = full request followed by an SO_LINGER-0 close (RST on the
    wire), "storm_429" = a burst of cheap fire-and-forget GETs that burn
    the server's rate-limit tokens so honest clients see 429s."""

    kind: str                       # slow_loris | body_stall | reset | storm_429
    start_slot: int
    end_slot: int
    nodes: tuple[int, ...] = ()     # empty = every node running an HTTP server
    clients: int = 4                # attacker connections per node per slot

    def active(self, slot: int) -> bool:
        return self.start_slot <= slot < self.end_slot

    def matches(self, node: int) -> bool:
        return not self.nodes or node in self.nodes


@dataclass
class NetFaultPlan:
    """The full declarative fault schedule for one scenario run."""

    partitions: tuple[Partition, ...] = ()
    links: tuple[LinkFault, ...] = ()
    rpc_faults: tuple[RpcFault, ...] = ()
    churn: tuple[Churn, ...] = ()
    equivocations: tuple[Equivocation, ...] = ()
    http_faults: tuple[HttpFault, ...] = ()

    def as_dict(self) -> dict:
        """JSON-serializable plan description for the scenario report."""
        return {
            "partitions": [
                {"start_slot": p.start_slot, "heal_slot": p.heal_slot,
                 "groups": [list(g) for g in p.groups]}
                for p in self.partitions
            ],
            "links": [
                {"src": lf.src, "dst": lf.dst, "start_slot": lf.start_slot,
                 "end_slot": lf.end_slot, "drop_every": lf.drop_every,
                 "delay_slots": lf.delay_slots}
                for lf in self.links
            ],
            "rpc_faults": [
                {"server": r.server, "start_slot": r.start_slot,
                 "end_slot": r.end_slot, "mode": r.mode,
                 "protocols": list(r.protocols), "max_hits": r.max_hits}
                for r in self.rpc_faults
            ],
            "churn": [
                {"node": c.node, "down_slot": c.down_slot,
                 "up_slot": c.up_slot}
                for c in self.churn
            ],
            "equivocations": [
                {"slot": e.slot} for e in self.equivocations
            ],
            "http_faults": [
                {"kind": h.kind, "start_slot": h.start_slot,
                 "end_slot": h.end_slot, "nodes": list(h.nodes),
                 "clients": h.clients}
                for h in self.http_faults
            ],
        }


class NetFaultInjector:
    """Evaluates a NetFaultPlan against the advancing slot clock.

    `on_slot(slot)` drives the schedule: it flushes due delayed frames,
    emits partition/heal/churn transition events (flight recorder +
    netfault_events_total), and leaves the injector's decision surfaces
    (`reachable`, `gossip_decision`, `rpc_mode`) answering for the new
    slot. All counters in `counts` are deterministic per (plan, drive
    sequence)."""

    def __init__(self, plan: NetFaultPlan, n_nodes: int, recorder=None):
        self.plan = plan
        self.n_nodes = n_nodes
        self.recorder = recorder
        self.slot = -1
        self.down: set[int] = set()
        # per-fault per-link frame counters for drop_every, keyed
        # (fault index, src, dst): two overlapping LinkFaults matching the
        # same link must each keep their own cadence
        self._link_seen: dict[tuple[int, int, int], int] = {}
        # delayed frames: release_slot -> [thunk]
        self._delayed: dict[int, list] = {}
        # per-RpcFault hit counters (index into plan.rpc_faults)
        self._rpc_hits: dict[int, int] = {}
        self.counts = {
            "gossip": {},       # reason -> frames eaten/delayed pre-wire
            "rpc": {},          # reason -> requests faulted
            "events": [],       # ordered (slot, kind, detail) transitions
        }
        # the event board reuses loadgen's slot-triggered one-shot engine
        self._board = FaultInjector()
        for p in plan.partitions:
            self._board.at(p.start_slot, lambda p=p: self._event(
                "partition_start", groups=[list(g) for g in p.groups]))
            self._board.at(p.heal_slot, lambda p=p: self._event(
                "partition_heal", groups=[list(g) for g in p.groups]))
        for c in plan.churn:
            self._board.at(c.down_slot, lambda c=c: self._event(
                "churn_down", node=c.node))
            self._board.at(c.up_slot, lambda c=c: self._event(
                "churn_up", node=c.node))
        for e in plan.equivocations:
            self._board.at(e.slot, lambda e=e: self._event(
                "equivocation", slot=e.slot))

    # ------------------------------------------------------------ schedule

    def _event(self, kind: str, **detail) -> None:
        NETFAULT_EVENTS.labels(kind).inc()
        self.counts["events"].append({"slot": self.slot, "kind": kind,
                                      **detail})
        log.warn("netfault transition", kind=kind, at_slot=self.slot, **{
            k: str(v) for k, v in detail.items() if k != "slot"})
        if self.recorder is not None:
            self.recorder.record(f"netfault_{kind}", severity="warn",
                                 **detail)

    def on_slot(self, slot: int) -> None:
        """Advance the schedule to `slot`: transition events fire, churned
        node state updates, and due delayed frames flush (in send order —
        slot-quantized latency, not reordering; a link that should reorder
        can use two different delay_slots)."""
        self.slot = slot
        self._board.on_slot(slot)
        self.down = {
            c.node for c in self.plan.churn
            if c.down_slot <= slot < c.up_slot
        }
        for release in sorted(s for s in self._delayed if s <= slot):
            for thunk in self._delayed.pop(release):
                try:
                    thunk()
                except Exception as e:  # noqa: BLE001 — a dead conn is fine
                    log.warn("delayed frame delivery failed",
                             error=f"{type(e).__name__}: {e}")

    # ----------------------------------------------------------- decisions

    def partition_of(self, node: int, slot: int | None = None) -> int:
        """Group index of `node` under the partition active at `slot`
        (-1 = no partition active)."""
        slot = self.slot if slot is None else slot
        for p in self.plan.partitions:
            if p.start_slot <= slot < p.heal_slot:
                for gi, group in enumerate(p.groups):
                    if node in group:
                        return gi
                return len(p.groups)        # implicit leftover group
        return -1

    def reachable(self, a: int, b: int, slot: int | None = None) -> bool:
        """Can a frame flow between nodes a and b right now? False while
        either is churned down or a partition separates them."""
        if a in self.down or b in self.down:
            return False
        return self.partition_of(a, slot) == self.partition_of(b, slot)

    def _count(self, scope: str, reason: str) -> None:
        NETFAULT_MESSAGES.labels(reason, scope).inc()
        bucket = self.counts[scope]
        bucket[reason] = bucket.get(reason, 0) + 1

    def gossip_decision(self, src: int, dst: int):
        """Decision for one gossip frame src -> dst: None = deliver,
        ("drop", reason) = eat it, ("delay", slots) = queue it."""
        if src in self.down or dst in self.down:
            self._count("gossip", "churn")
            return ("drop", "churn")
        if self.partition_of(src) != self.partition_of(dst):
            self._count("gossip", "partition")
            return ("drop", "partition")
        # every active matching fault OBSERVES every frame (its cadence
        # counter advances) before any decision returns, so overlapping
        # faults on one link keep independent, seed-stable cadences
        decision = None
        for li, lf in enumerate(self.plan.links):
            if not (lf.active(self.slot) and lf.matches(src, dst)):
                continue
            if lf.drop_every:
                key = (li, src, dst)
                self._link_seen[key] = self._link_seen.get(key, 0) + 1
                if self._link_seen[key] % lf.drop_every == 0:
                    decision = ("drop", "drop")
            if lf.delay_slots and decision is None:
                decision = ("delay", lf.delay_slots)
        if decision is not None:
            self._count(
                "gossip", "drop" if decision[0] == "drop" else "delay"
            )
        return decision

    def queue_delayed(self, release_slot: int, thunk) -> None:
        self._delayed.setdefault(release_slot, []).append(thunk)

    def rpc_mode(self, server: int, protocol: str) -> str | None:
        """Active RPC fault mode for a request SERVED by `server`, or None.
        Partition/churn unreachability is the caller's (FaultyPeer's)
        concern — this answers only for the scripted server faults."""
        for i, rf in enumerate(self.plan.rpc_faults):
            if rf.server != server or not rf.active(self.slot):
                continue
            if rf.protocols and str(protocol) not in rf.protocols:
                continue
            hits = self._rpc_hits.get(i, 0)
            if rf.max_hits is not None and hits >= rf.max_hits:
                continue
            self._rpc_hits[i] = hits + 1
            return rf.mode
        return None

    # -------------------------------------------------- router integration

    def router_filter(self, id_map: dict[str, int]):
        """A `fault_filter` for gossip.InProcessGossipRouter: maps the
        router's peer-id strings through `id_map` and answers drop reasons
        (the in-process rigs have no delay queue — delays degrade to
        delivery, partitions/drops are honored)."""

        def fault_filter(source_peer: str, dest_peer: str, topic: str):
            src, dst = id_map.get(source_peer), id_map.get(dest_peer)
            if src is None or dst is None:
                return None
            decision = self.gossip_decision(src, dst)
            if decision is not None and decision[0] == "drop":
                return decision[1]
            return None

        return fault_filter


class HttpNetFaults:
    """Drives HttpFaults at the raw-socket seam against real localhost
    HTTP API servers.

    The attacker never goes through api.client — each injection is a bare
    TCP connection speaking just enough HTTP to land in the server's
    vulnerable phase: header read (slow_loris), body read (body_stall),
    worker write/read (reset), or the rate-limit gate (storm_429).
    slow_loris and body_stall connections persist across slots (topped up
    to `clients` per node each tick, one trickle byte per slot keeps the
    header read alive); reset and storm_429 are fire-and-forget per slot.
    """

    def __init__(self, faults, ports, recorder=None):
        self.faults = tuple(faults)
        self.ports = dict(ports)        # node index -> localhost port
        self.recorder = recorder
        self.counts: dict[str, int] = {}
        # (fault_idx, node) -> live attacker sockets for persistent kinds
        self._held: dict[tuple[int, int], list[socket.socket]] = {}
        # storm sockets from the previous tick: closed AFTER their
        # responses/sheds landed, so the burst pressures the admission
        # queue without turning every close into an RST
        self._pending_close: list[socket.socket] = []
        self._announced: set[int] = set()

    def on_slot(self, slot: int) -> None:
        for s in self._pending_close:
            try:
                s.close()
            except OSError:
                pass
        self._pending_close = []
        for fi, fault in enumerate(self.faults):
            targets = [n for n in sorted(self.ports) if fault.matches(n)]
            if not fault.active(slot):
                for node in targets:
                    self._release(fi, node)
                continue
            if fi not in self._announced:
                self._announced.add(fi)
                log.warn("http fault window opens", kind=fault.kind,
                         slot=slot, nodes=targets or "all")
                if self.recorder is not None:
                    self.recorder.record(
                        "netfault_http_start", severity="warn",
                        fault_kind=fault.kind, slot=slot,
                    )
            for node in targets:
                port = self.ports.get(node)
                if port is None:
                    continue
                if fault.kind in ("slow_loris", "body_stall"):
                    self._sustain(fi, fault, node, port)
                else:
                    for _ in range(max(1, fault.clients)):
                        self._fire_once(fault.kind, port)

    def close(self) -> None:
        for key in list(self._held):
            self._release(*key)
        for s in self._pending_close:
            try:
                s.close()
            except OSError:
                pass
        self._pending_close = []

    # -- internals -------------------------------------------------------

    def _count(self, kind: str) -> None:
        NETFAULT_HTTP.labels(kind).inc()
        self.counts[kind] = self.counts.get(kind, 0) + 1

    def _release(self, fi: int, node: int) -> None:
        for s in self._held.pop((fi, node), ()):
            try:
                s.close()
            except OSError:
                pass

    def _connect(self, port: int):
        try:
            return socket.create_connection(("127.0.0.1", port), timeout=0.5)
        except OSError:
            return None

    def _sustain(self, fi: int, fault: HttpFault, node: int,
                 port: int) -> None:
        held = self._held.setdefault((fi, node), [])
        # Trickle a header byte on survivors; drop sockets the server
        # already timed out or reset.
        alive = []
        for s in held:
            try:
                if fault.kind == "slow_loris":
                    s.sendall(b"x")
                alive.append(s)
            except OSError:
                try:
                    s.close()
                except OSError:
                    pass
        held[:] = alive
        while len(held) < max(1, fault.clients):
            s = self._connect(port)
            if s is None:
                break
            try:
                if fault.kind == "slow_loris":
                    # Request line + an unterminated header: the worker
                    # blocks in the header read until its deadline.
                    s.sendall(b"GET /eth/v1/node/syncing HTTP/1.1\r\n"
                              b"Host: lh\r\nX-Drip: ")
                else:  # body_stall
                    # Complete headers, oversized Content-Length, then
                    # silence mid-body: the worker stalls in _read_body.
                    s.sendall(b"POST /eth/v1/beacon/pool/attestations "
                              b"HTTP/1.1\r\nHost: lh\r\n"
                              b"Content-Type: application/json\r\n"
                              b"Content-Length: 4096\r\n\r\n[{\"agg")
            except OSError:
                try:
                    s.close()
                except OSError:
                    pass
                break
            self._count(fault.kind)
            held.append(s)

    def _fire_once(self, kind: str, port: int) -> None:
        s = self._connect(port)
        if s is None:
            return
        self._count(kind)
        try:
            s.sendall(b"GET /eth/v1/node/version HTTP/1.1\r\nHost: lh\r\n"
                      b"Connection: close\r\n\r\n")
        except OSError:
            try:
                s.close()
            except OSError:
                pass
            return
        if kind == "reset":
            # Abortive close: RST instead of FIN.
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        else:
            # storm_429: never read the response — the whole burst lands
            # on the admission gate at once; closed next tick
            self._pending_close.append(s)


class FaultyGossipSend:
    """Wraps one node's gossipsub send callback with the fault plan.

    Install with `FaultyGossipSend.install(node, injector, idx, id_map)`:
    the node's `Gossipsub._send_raw` is replaced, so every encoded RPC
    frame — publishes, forwards, control traffic — passes the injector
    before reaching the real TCP connection. Dropped frames never hit the
    wire; delayed frames are queued on the injector and flushed at a later
    slot tick."""

    def __init__(self, injector: NetFaultInjector, src_idx: int,
                 id_map: dict[str, int], inner_send):
        self.injector = injector
        self.src_idx = src_idx
        self.id_map = id_map
        self.inner_send = inner_send

    def __call__(self, peer_id: str, rpc_bytes: bytes) -> None:
        dst = self.id_map.get(peer_id)
        if dst is None:
            return self.inner_send(peer_id, rpc_bytes)
        decision = self.injector.gossip_decision(self.src_idx, dst)
        if decision is None:
            return self.inner_send(peer_id, rpc_bytes)
        kind, arg = decision
        if kind == "delay":
            inner, pid, data = self.inner_send, peer_id, rpc_bytes
            self.injector.queue_delayed(
                self.injector.slot + arg, lambda: inner(pid, data)
            )
        # "drop": the frame is eaten with its reason already counted

    @classmethod
    def install(cls, node, injector: NetFaultInjector, src_idx: int,
                id_map: dict[str, int]):
        wrapped = cls(injector, src_idx, id_map, node.gossipsub._send_raw)
        node.gossipsub._send_raw = wrapped
        return wrapped


class FaultyPeer:
    """Wraps a Req/Resp peer handle with the plan's RPC faults — the
    `FaultyKVStore` of the network: same interface, scriptable failure.

    `server_idx`/`client_idx` locate the link: partition/churn
    unreachability raises the injected timeout exactly like a dead socket,
    and the server's scripted fault modes apply on top."""

    def __init__(self, inner, injector: NetFaultInjector, server_idx: int,
                 client_idx: int):
        self.inner = inner
        self.injector = injector
        self.server_idx = server_idx
        self.client_idx = client_idx

    def handle(self, peer_id: str, protocol, request_bytes: bytes,
               timeout: float | None = None) -> list[bytes]:
        inj = self.injector
        if not inj.reachable(self.client_idx, self.server_idx):
            reason = (
                "churn" if (self.server_idx in inj.down
                            or self.client_idx in inj.down)
                else "partition"
            )
            inj._count("rpc", reason)
            raise InjectedTimeout(
                f"request timeout (injected: {reason} blocks "
                f"node{self.client_idx} -> node{self.server_idx})"
            )
        proto = protocol.value if hasattr(protocol, "value") else str(protocol)
        mode = inj.rpc_mode(self.server_idx, proto)
        if mode == "silent":
            inj._count("rpc", "rpc_silent")
            raise InjectedTimeout(
                f"request timeout (injected: node{self.server_idx} "
                f"silent on {proto})"
            )
        chunks = self.inner.handle(peer_id, protocol, request_bytes,
                                   timeout=timeout)
        if mode == "torn":
            inj._count("rpc", "rpc_torn")
            # the peer streamed half the response then went silent: the
            # caller's read deadline fires with partial data lost
            raise InjectedTimeout(
                f"request timeout (injected: node{self.server_idx} "
                f"stalled mid-response after {len(chunks) // 2}/"
                f"{len(chunks)} chunks on {proto})"
            )
        if mode == "empty":
            inj._count("rpc", "rpc_empty")
            return []
        return chunks
