"""Capacity-control proving ground: the closed-loop scheduler vs the best
static plan, on a deterministic device-time ledger.

The question PR 14's controller must answer is "can a node with NO
autotune profile reach the throughput an oracle-tuned static plan gets?"
— and the answer has to be provable on CPU, bit-reproducibly. So this
harness replaces wall-clock with a LOGICAL device-time ledger:

  - a batch of n sets costs `base_ms + per_set_ms * pow2ceil(n)` logical
    milliseconds — the jaxbls padding-bucket economics (a 640-set batch
    under a 1024 cap pays 1024 lanes; 512+128 under a 512 cap pays 640),
    which is exactly what makes batch-cap choice a real optimization
    problem instead of "bigger is always better";
  - the device is a serial timeline (`busy_until`): a batch may START
    only while the device frees up inside the current slot — the
    scheduler's budget gate holds everything else, so backlog carries
    across slots like a saturated accelerator's queue would;
  - work verified after its publish slot is LATE (deadline miss for the
    SLO, processed for conservation), so throughput is measured in
    deadline-credited hits, not raw sets.

Everything else is the REAL serving machinery: a `BeaconProcessor` whose
batch formation is the `CapacityScheduler`'s call, a real
`AdmissionController` on a `ManualSlotClock` (whose watermarks the
controller retunes live), a private `SlotAccountant` closing real slot
reports (the control loop's tick), and the global flight recorder
collecting retune events and burn incidents. No RNG outside the seeded
traffic draw, no wall-clock in any decision: reruns are bit-identical in
the deterministic core.

The driver (loadgen/driver.py `_drive_capacity`) runs the CONTROLLER leg
(defaults, retune on) against a STATIC sweep (pow2 cap ladder, retune
off — the plans an oracle calibrate could have installed) and exits
nonzero unless controller hits >= gate_ratio * best static hits.
"""

from __future__ import annotations

import json
import os
import random
import tempfile
import time

from ..chain.beacon_processor import (
    BeaconProcessor,
    BeaconProcessorConfig,
    WorkItem,
    WorkKind,
)
from ..chain.scheduler import pow2ceil
from ..observability.flight_recorder import RECORDER
from ..observability.slo import SlotAccountant
from ..qos.admission import AdmissionController
from ..utils.slot_clock import ManualSlotClock
from .scenarios import (
    CapacityScenario,
    capacity_slot_factors,
    mainnet_mix,
)

#: pow2 cap candidates for the static-optimal reference sweep (the same
#: ladder the controller chooses from, minus the degenerate extremes)
STATIC_CAP_SWEEP = (128, 256, 512, 1024, 2048)


class DeviceLedger:
    """Serial logical device timeline + the cost model."""

    def __init__(self, sc: CapacityScenario):
        self.base_secs = sc.base_ms / 1e3
        self.per_set_secs = sc.per_set_ms / 1e3
        self.busy_until = 0.0
        self.batches = 0
        self.lanes_padded = 0
        self.sets_served = 0

    def cost(self, n_sets: int) -> float:
        return self.base_secs + self.per_set_secs * pow2ceil(n_sets)

    def serve(self, n_sets: int, now: float) -> tuple[float, float]:
        """Run one batch: returns (start, end) on the logical timeline."""
        start = max(self.busy_until, now)
        end = start + self.cost(n_sets)
        self.busy_until = end
        self.batches += 1
        self.lanes_padded += pow2ceil(n_sets)
        self.sets_served += n_sets
        return start, end


def _capacity_traffic(sc: CapacityScenario) -> list[tuple[int, int]]:
    """Per-slot (attestations, aggregates) — seeded, profile-scaled."""
    rng = random.Random(sc.seed)
    factors = capacity_slot_factors(sc)
    out = []
    for f in factors:
        base = mainnet_mix(sc.n_validators, rng)
        out.append(
            (max(1, int(base.attestations * f)),
             max(1, int(base.aggregates * f)))
        )
    return out


def run_capacity_leg(sc: CapacityScenario, *, static_caps=None,
                     datadir: str | None = None, log_fn=None) -> dict:
    """One full run of the scenario. `static_caps=(att, agg)` pins the
    caps (explicit config — the scheduler never retunes a pinned knob)
    and disables the control loop entirely: the static-plan reference.
    `static_caps=None` is the controller leg: default knobs, no profile,
    retuning live."""
    t_wall = time.time()
    clock = ManualSlotClock(0, max(1, int(sc.seconds_per_slot)))
    sps = float(max(1, int(sc.seconds_per_slot)))
    slo_acct = SlotAccountant(export_metrics=False)
    admission = AdmissionController(clock)
    if static_caps is not None:
        cfg = BeaconProcessorConfig(
            max_attestation_batch=int(static_caps[0]),
            max_aggregate_batch=int(static_caps[1]),
        )
    else:
        cfg = BeaconProcessorConfig()
    proc = BeaconProcessor(cfg, admission=admission)
    proc.slo = slo_acct
    slo_acct.bind_clock(clock)
    sched = proc.scheduler
    if static_caps is not None:
        sched.retune_enabled = False
    if sc.att_queue_cap is not None:
        proc.max_lengths[WorkKind.gossip_attestation] = sc.att_queue_cap
    if sc.agg_queue_cap is not None:
        proc.max_lengths[WorkKind.gossip_aggregate] = sc.agg_queue_cap
    proc.max_lengths[WorkKind.chain_segment] = sc.bulk_queue_cap

    datadir = datadir or tempfile.mkdtemp(prefix="loadgen-capacity-")
    incident_dir = os.path.join(datadir, "incidents")
    RECORDER.reset()
    RECORDER.configure(incident_dir=incident_dir, clock=clock,
                       slo_provider=slo_acct.snapshot)

    ledger = DeviceLedger(sc)
    state = {"slot": 0}
    counts = {
        "published_att": 0, "published_agg": 0, "late_sets": 0,
        "bulk_submitted": 0, "bulk_processed": 0, "bulk_refused": 0,
    }

    def _slot_t0() -> float:
        """Current slot's start on the ABSOLUTE logical timeline — the
        ledger, the clock and the lateness rule all speak seconds, so
        slot indices convert through seconds_per_slot exactly once here
        (mixing the two is only coincidentally right at sps == 1)."""
        return state["slot"] * sps

    def gate(_kind: str, n: int) -> bool:
        # the device may START a batch only while it frees up inside the
        # current slot; a backlogged timeline holds batch work to the
        # next slot — the continuous-batching ledger semantics
        return max(ledger.busy_until, _slot_t0()) < _slot_t0() + sps

    sched.set_budget_gate(gate)

    def mk_verify(kind_name: str):
        def verify(payloads):
            n = len(payloads)
            start, end = ledger.serve(n, _slot_t0())
            # the visible clock tracks device progress inside the slot so
            # admission expiry and SLO attribution see intra-slot time;
            # it never crosses the boundary (close_slot owns that)
            clock.set_time(min(end, _slot_t0() + sps * 0.999))
            late = sum(1 for s in payloads if end > (s + 1) * sps)
            if late:
                counts["late_sets"] += late
                slo_acct.record_late(late)
            slo_acct.record_route("device", n)
            slo_acct.record_verify_latency(end - start)
            sched.observe_verify(kind_name, n, end - start)
            return None

        return verify

    verify_att = mk_verify("gossip_attestation")
    verify_agg = mk_verify("gossip_aggregate")

    def bulk_run():
        # host-side bulk work (a chain segment import): no device time,
        # but a queue the admission watermarks protect under pressure
        counts["bulk_processed"] += 1

    traffic = _capacity_traffic(sc)
    per_slot: list[dict] = []
    # run totals accumulate from every close_slot() return, NOT from the
    # accountant's `recent` ring (bounded at 64 reports — a 100-slot run
    # would silently count only its tail)
    totals = {"hits": 0, "misses": 0}

    def _tally(reports) -> None:
        for r in reports:
            totals["hits"] += r.hits
            totals["misses"] += r.misses

    def publish(slot: int, atts: int, aggs: int) -> None:
        for _ in range(atts):
            proc.submit(WorkItem(
                kind=WorkKind.gossip_attestation, payload=slot,
                run_batch=verify_att,
                deadline_slot=admission.attestation_deadline_slot(slot),
            ))
        counts["published_att"] += atts
        for _ in range(aggs):
            proc.submit(WorkItem(
                kind=WorkKind.gossip_aggregate, payload=slot,
                run_batch=verify_agg,
                deadline_slot=admission.attestation_deadline_slot(slot),
            ))
        counts["published_agg"] += aggs
        for _ in range(sc.bulk_per_slot):
            counts["bulk_submitted"] += 1
            if not proc.submit(WorkItem(
                kind=WorkKind.chain_segment, run=bulk_run,
            )):
                counts["bulk_refused"] += 1

    total_slots = sc.slots + sc.epilogue_slots
    for slot in range(total_slots):
        state["slot"] = slot
        clock.set_slot(slot)
        if slot < sc.slots:
            atts, aggs = traffic[slot]
            publish(slot, atts, aggs)
        proc.run_available()
        reports = slo_acct.close_slot(slot)
        _tally(reports)
        rep = reports[-1] if reports else None
        entry = {
            "slot": slot,
            "published": (traffic[slot] if slot < sc.slots else (0, 0)),
            "caps": dict(sched.caps),
            "watermarks": {
                "bulk": round(admission.bulk_watermark, 3),
                "backfill": round(admission.backfill_watermark, 3),
            },
            "busy_carry": round(
                max(0.0, ledger.busy_until - (slot + 1) * sps), 6
            ),
        }
        if rep is not None:
            entry.update(
                hits=rep.hits, misses=rep.misses, late=rep.late,
                processed=dict(rep.processed), shed=dict(rep.shed),
            )
        per_slot.append(entry)
        if log_fn is not None and slot < sc.slots:
            log_fn(
                f"slot {slot}: att={entry['published'][0]} "
                f"agg={entry['published'][1]} caps={entry['caps']} "
                f"hits={entry.get('hits')} late={entry.get('late')}"
            )
    # force-drain whatever the ledger still holds: it verifies LATE by
    # construction (the run is over), so it lands as misses, never lost
    sched.set_budget_gate(None)
    state["slot"] = total_slots
    clock.set_slot(total_slots)
    proc.run_until_idle()
    _tally(slo_acct.close_slot(total_slots))

    hits = totals["hits"]
    misses = totals["misses"]
    published = counts["published_att"] + counts["published_agg"]
    processed = sum(
        v for k, v in proc.processed.items()
        if k in (WorkKind.gossip_attestation, WorkKind.gossip_aggregate)
    )
    dropped = sum(proc.dropped.values())
    expired = sum(proc.expired.values())
    shed_admission = sum(
        v for k, v in proc.shed_admission.items()
        if k in (WorkKind.gossip_attestation, WorkKind.gossip_aggregate)
    )
    conservation = {
        "published": published,
        "processed": processed,
        "dropped": dropped,
        "expired": expired,
        "shed_admission": shed_admission,
        "ok": published == processed + dropped + expired + shed_admission,
    }
    deterministic = {
        "per_slot": per_slot,
        "deadline_hits": hits,
        "deadline_misses": misses,
        "late_sets": counts["late_sets"],
        "published": {
            "attestations": counts["published_att"],
            "aggregates": counts["published_agg"],
        },
        "bulk": {
            "submitted": counts["bulk_submitted"],
            "processed": counts["bulk_processed"],
            "refused": counts["bulk_refused"],
        },
        "conservation": conservation,
        "device": {
            "batches": ledger.batches,
            "lanes_padded": ledger.lanes_padded,
            "sets_served": ledger.sets_served,
            "busy_secs": round(ledger.busy_until, 6),
            "lane_efficiency": (
                round(ledger.sets_served / ledger.lanes_padded, 4)
                if ledger.lanes_padded else None
            ),
        },
        "scheduler": sched.stats(),
    }
    leg = {
        "static_caps": list(static_caps) if static_caps else None,
        "deterministic": deterministic,
        "slo": {
            "windows": {
                name: slo_acct.window_summary(name)
                for name in slo_acct.windows
            },
            "incident_dir": incident_dir,
            "incidents": [
                os.path.basename(p) for p in RECORDER.incidents_written
            ],
        },
        "elapsed_secs": round(time.time() - t_wall, 3),
    }
    RECORDER.configure(incident_dir=None, clock=None, slo_provider=None)
    return leg


def run_capacity_scenario(sc: CapacityScenario, out_path: str | None = None,
                          log_fn=None, datadir: str | None = None) -> dict:
    """The full proof: the controller leg (cold start, NO profile) vs the
    static-optimal reference (best fixed-cap plan from the pow2 sweep,
    retuning disabled). The gate verdict rides in the report; exit-code
    semantics live in loadgen/driver.py."""
    t_wall = time.time()
    # ONE base dir per run (the other scenario runners' pattern): the
    # sweep legs get subdirs so their incident dumps never collide with
    # (or overwrite) the controller leg's, and a default-tmpdir run
    # leaves a single directory behind, not six
    datadir = datadir or tempfile.mkdtemp(prefix="loadgen-capacity-")
    controller = run_capacity_leg(
        sc, datadir=os.path.join(datadir, "controller"), log_fn=log_fn
    )
    sweep: dict[str, dict] = {}
    best_caps, best_hits = None, -1
    for cap in STATIC_CAP_SWEEP:
        caps = (cap, max(64, cap // 2))
        leg = run_capacity_leg(
            sc, static_caps=caps,
            datadir=os.path.join(datadir, f"static_{cap}"),
        )
        det = leg["deterministic"]
        sweep[str(cap)] = {
            "caps": list(caps),
            "deadline_hits": det["deadline_hits"],
            "deadline_misses": det["deadline_misses"],
            "lane_efficiency": det["device"]["lane_efficiency"],
        }
        if det["deadline_hits"] > best_hits:
            best_hits = det["deadline_hits"]
            best_caps = caps
    controller_hits = controller["deterministic"]["deadline_hits"]
    ratio = (
        round(controller_hits / best_hits, 4) if best_hits > 0 else None
    )
    gate = {
        "controller_hits": controller_hits,
        "static_optimal_hits": best_hits,
        "static_optimal_caps": list(best_caps) if best_caps else None,
        "ratio": ratio,
        "gate_ratio": sc.gate_ratio,
        "ok": (
            ratio is not None and ratio >= sc.gate_ratio
            and controller["deterministic"]["conservation"]["ok"]
        ),
    }
    report = {
        "scenario": sc.name,
        "seed": sc.seed,
        "slots": sc.slots,
        "n_validators": sc.n_validators,
        "profile": sc.profile,
        "capacity": True,
        "controller": controller,
        "static_sweep": sweep,
        "gate": gate,
        "deterministic": controller["deterministic"],
        "slo": controller["slo"],
        "elapsed_secs": round(time.time() - t_wall, 3),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
    return report
